"""Chaos-hardened serving plane (ISSUE 10): deterministic fault
injection + transparent in-flight failover.

Fast tier: the injection layer's units (schedule grammar, trigger
semantics, the seeded-determinism contract, journal/metric plumbing),
the host store's crc32 integrity, the allocator-pressure and clock-skew
points, the gateway client's retry-after honoring, and THE failover
acceptance (a replica crash injected mid-decode on a 2-replica pool
completes every in-flight greedy request token-identically, with
``failover`` timeline events and zero stuck requests).

Slow tier: the engine-level restore-failure fallback (the PR 4 path,
now provokable on demand), host-tier corruption detection end to end,
and the budget-exhaust -> UNAVAILABLE + retry-after surface over live
gRPC.
"""

import threading
import time

import numpy as np
import pytest

from aios_tpu import faults
from aios_tpu.faults.inject import _parse
from aios_tpu.obs import flightrec


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with no schedule armed — a leaked plan
    would inject faults into unrelated tests."""
    faults.deactivate()
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# schedule grammar + trigger semantics (fast)
# ---------------------------------------------------------------------------


def test_parse_schedule_grammar():
    sched, seed = _parse(
        "seed=42;pool.scheduler_crash=nth:3;"
        "dispatch.delay=prob:0.25,delay_ms=20;"
        "admission.clock_skew=after:5,skew_ms=2000"
    )
    assert seed == 42
    assert sched["pool.scheduler_crash"].mode == "nth"
    assert sched["pool.scheduler_crash"].arg == 3
    assert sched["dispatch.delay"].params == {"delay_ms": 20}
    assert sched["admission.clock_skew"].params == {"skew_ms": 2000}


def test_parse_is_lenient():
    """Malformed entries drop with a warning — a typo'd chaos knob must
    not take down a boot (the env-parser convention)."""
    sched, seed = _parse(
        "seed=oops;no.such.point=nth:1;pool.scheduler_crash=never:1;"
        "dispatch.delay=nth:x;host_store.corrupt=nth:2,bad=param;"
        "rpc.unavailable=nth:1"
    )
    assert seed == 0
    assert list(sched) == ["rpc.unavailable"]


def test_nth_trigger_fires_exactly_once():
    plan = faults.activate("pool.scheduler_crash=nth:3")
    hits = [faults.point("pool.scheduler_crash") for _ in range(6)]
    fired = [a for a in hits if a is not None]
    assert len(fired) == 1
    assert hits[2] is not None and fired[0].hit == 3
    assert plan.journal() == [{
        "point": "pool.scheduler_crash", "mode": "nth", "hit": 3,
        "model": "",
    }]


def test_prob_trigger_is_a_pure_function_of_seed_and_hit_index():
    """THE determinism contract: the k-th hit's fire decision depends
    only on (seed, point, k) — the same seed + schedule + call pattern
    reproduce the identical injected-fault sequence."""
    def run(seed):
        faults.activate(f"seed={seed};dispatch.delay=prob:0.4")
        return [
            faults.point("dispatch.delay") is not None for _ in range(64)
        ]

    a, b, other = run(11), run(11), run(12)
    assert a == b
    assert a != other  # a different seed is a different storm
    assert any(a) and not all(a)


def test_per_point_rngs_are_independent():
    """Interleaving a second point's hits must not perturb the first
    point's decisions (per-point PRNGs seeded by (seed, point))."""
    faults.activate("seed=5;dispatch.delay=prob:0.4")
    alone = [faults.point("dispatch.delay") is not None for _ in range(32)]
    faults.activate(
        "seed=5;dispatch.delay=prob:0.4;rpc.unavailable=prob:0.4"
    )
    mixed = []
    for _ in range(32):
        mixed.append(faults.point("dispatch.delay") is not None)
        faults.point("rpc.unavailable")
    assert mixed == alone


def test_after_trigger_gates_on_elapsed_time():
    plan = faults.activate("admission.clock_skew=after:30,skew_ms=500")
    assert faults.point("admission.clock_skew") is None
    plan.activated_at -= 31  # fast-forward the drill clock
    act = faults.point("admission.clock_skew")
    assert act is not None and act.skew_s == 0.5


def test_disabled_point_is_none_with_no_side_effects():
    assert not faults.active()
    assert faults.point("pool.scheduler_crash") is None
    assert faults.fired() == []


def test_fired_fault_counts_metric_and_records_model_event():
    from aios_tpu.obs import instruments as obs

    child = obs.FAULTS_INJECTED.labels(
        point="allocator.pressure", mode="nth"
    )
    before = child.value
    faults.activate("allocator.pressure=nth:1")
    assert faults.point("allocator.pressure", "faultmodel") is not None
    assert child.value == before + 1
    events = [
        (m, kind, f)
        for _, m, kind, f in flightrec.RECORDER.model_events("faultmodel")
        if kind == "fault"
    ]
    assert events and events[-1][2]["point"] == "allocator.pressure"


def test_activate_seed_override_and_env_install(monkeypatch):
    plan = faults.activate("dispatch.delay=prob:0.5", seed=99)
    assert plan.seed == 99
    monkeypatch.setenv("AIOS_TPU_FAULTS", "seed=3;rpc.unavailable=nth:1")
    faults.install_from_env()
    assert faults.active()
    assert faults.point("rpc.unavailable") is not None
    monkeypatch.setenv("AIOS_TPU_FAULTS", "")
    faults.install_from_env()
    assert not faults.active()


# ---------------------------------------------------------------------------
# injection points: allocator pressure + clock skew (fast, no jit)
# ---------------------------------------------------------------------------


def test_allocator_pressure_point_raises_pool_exhausted():
    from aios_tpu.engine.paged import PageAllocator, PoolExhausted

    a = PageAllocator(num_pages=8, page_size=16, num_slots=2, max_blocks=4)
    a.ensure(0, 16)  # sanity: works un-faulted
    faults.activate("allocator.pressure=nth:1")
    with pytest.raises(PoolExhausted):
        a.ensure(1, 16)
    a.ensure(1, 16)  # one-shot: the pool recovers


def test_clock_skew_point_drives_deadline_sheds():
    from aios_tpu.serving.admission import AdmissionController, AdmissionError
    from aios_tpu.serving.config import ServingConfig

    adm = AdmissionController(ServingConfig(), "skewmodel")
    # feasible: 100 tokens at 100 tok/s inside a 10 s deadline
    adm.check_deadline(10.0, 0, 100, 100.0)
    faults.activate("admission.clock_skew=nth:1,skew_ms=9500")
    with pytest.raises(AdmissionError) as err:
        adm.check_deadline(10.0, 0, 100, 100.0)
    assert err.value.cause == "deadline"
    adm.check_deadline(10.0, 0, 100, 100.0)  # one-shot


# ---------------------------------------------------------------------------
# host store crc32 integrity (fast, pure numpy)
# ---------------------------------------------------------------------------


def test_store_corruption_detected_and_dropped():
    from aios_tpu.engine.paged import HostPageStore

    s = HostPageStore(max_bytes=1 << 20)
    for h in (b"a", b"b", b"c"):
        s.put(h, {"k": np.arange(64, dtype=np.int8),
                  "v": np.arange(64, dtype=np.int8)})
    # silent bit-rot (no fault layer): flip a stored byte by hand
    s._entries[b"b"]["k"][3] ^= 1
    got = s.match_chain([b"a", b"b", b"c"])
    assert [h for h, _ in got] == [b"a"]  # chain truncates at the rot
    assert s.corruptions == 1
    assert s.peek_chain([b"b"]) == 0  # dropped, not served again
    assert s.peek_chain([b"c"]) == 1  # innocent bystander survives


def test_store_corrupt_fault_point_drives_the_detection_path():
    from aios_tpu.engine.paged import HostPageStore

    s = HostPageStore(max_bytes=1 << 20)
    s.put(b"a", {"k": np.zeros(64, np.int8), "v": np.zeros(64, np.int8)})
    faults.activate("host_store.corrupt=nth:1")
    assert s.match_chain([b"a"]) == []
    assert s.corruptions == 1 and s.misses == 1
    assert len(s) == 0


def test_store_failed_restore_counts_a_miss():
    from aios_tpu.engine.paged import HostPageStore

    s = HostPageStore(max_bytes=1 << 20)
    s.put(b"a", {"k": np.zeros(8, np.int8), "v": np.zeros(8, np.int8)})
    assert len(s.match_chain([b"a"])) == 1
    assert (s.hits, s.misses) == (1, 0)
    s.note_failed_restore()
    assert (s.hits, s.misses) == (1, 1)


# ---------------------------------------------------------------------------
# gateway client honors retry-after (fast, fake stub)
# ---------------------------------------------------------------------------


class _FakeRpcError(Exception):
    def __init__(self, code, trailing=()):
        self._code = code
        self._trailing = tuple(trailing)

    def code(self):
        return self._code

    def details(self):
        return "fake"

    def trailing_metadata(self):
        return self._trailing


def _mk_fake_error(code, trailing=()):
    import grpc

    # a real grpc.RpcError subclass so the client's except clause matches
    err = _FakeRpcError.__new__(
        type("FakeRpcError", (grpc.RpcError,), dict(_FakeRpcError.__dict__))
    )
    err.__init__(code, trailing)
    return err


def test_gateway_client_retries_on_retry_after(monkeypatch):
    import grpc

    from aios_tpu.gateway.providers import LocalRuntimeClient

    client = LocalRuntimeClient(address="127.0.0.1:1")
    calls = {"n": 0}

    class _Resp:
        text = "ok"
        tokens_used = 3
        model_used = "tiny"

    class _Stub:
        def Infer(self, request, timeout):
            calls["n"] += 1
            if calls["n"] < 3:
                raise _mk_fake_error(
                    grpc.StatusCode.UNAVAILABLE,
                    (("retry-after-ms", "5"),),
                )
            return _Resp()

    slept = []
    monkeypatch.setattr(client, "_get_stub", lambda: _Stub())
    monkeypatch.setattr(
        LocalRuntimeClient, "_backoff",
        staticmethod(lambda ms: slept.append(ms)),
    )
    out = client.infer("p", "s", 16, 0.0)
    assert out.text == "ok" and calls["n"] == 3
    assert slept == [5, 5]  # honored the hint on both failures


def test_gateway_client_no_hint_fails_fast(monkeypatch):
    import grpc

    from aios_tpu.gateway.providers import LocalRuntimeClient, ProviderError

    client = LocalRuntimeClient(address="127.0.0.1:1")
    calls = {"n": 0}

    class _Stub:
        def Infer(self, request, timeout):
            calls["n"] += 1
            raise _mk_fake_error(grpc.StatusCode.NOT_FOUND)

    monkeypatch.setattr(client, "_get_stub", lambda: _Stub())
    with pytest.raises(ProviderError):
        client.infer("p", "s", 16, 0.0)
    assert calls["n"] == 1  # no blind retry without the hint


def test_gateway_client_bounded_attempts(monkeypatch):
    import grpc

    from aios_tpu.gateway.providers import LocalRuntimeClient, ProviderError

    monkeypatch.setenv("AIOS_TPU_RUNTIME_RETRY_ATTEMPTS", "1")
    client = LocalRuntimeClient(address="127.0.0.1:1")
    calls = {"n": 0}

    class _Stub:
        def Infer(self, request, timeout):
            calls["n"] += 1
            raise _mk_fake_error(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                (("retry-after-ms", "1"),),
            )

    monkeypatch.setattr(client, "_get_stub", lambda: _Stub())
    monkeypatch.setattr(
        LocalRuntimeClient, "_backoff", staticmethod(lambda ms: None)
    )
    with pytest.raises(ProviderError):
        client.infer("p", "s", 16, 0.0)
    assert calls["n"] == 2  # 1 try + 1 retry, then surface


# ---------------------------------------------------------------------------
# no pycache-only package dirs (the orphan that squatted on faults/)
# ---------------------------------------------------------------------------


def test_no_pycache_only_package_dirs():
    """A directory under aios_tpu/ whose only content is __pycache__ is
    a ghost package: stale bytecode squatting on a name (the pre-PR-10
    state of aios_tpu/faults/)."""
    from pathlib import Path

    import aios_tpu

    root = Path(aios_tpu.__file__).parent
    for cache in root.rglob("__pycache__"):
        siblings = [p for p in cache.parent.iterdir()
                    if p.name != "__pycache__"]
        assert siblings, (
            f"{cache.parent} contains ONLY __pycache__ — delete the "
            f"stale bytecode or give the package sources"
        )


# ---------------------------------------------------------------------------
# 2-replica pool: THE failover acceptance (fast tier — tiny engines)
# ---------------------------------------------------------------------------


MODEL = "failover-test"


@pytest.fixture(scope="module")
def crash_pool():
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine.batching import ContinuousBatcher
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.serving import ReplicaPool, ServingConfig

    cfg = TINY_TEST.scaled(name=MODEL, max_context=256)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    engines = [
        TPUEngine(cfg, params, num_slots=2, max_context=256,
                  cache_dtype=jnp.float32)
        for _ in range(2)
    ]
    pool = ReplicaPool(
        MODEL, engines,
        lambda e: ContinuousBatcher(e, chunk_steps=2, admit_chunk_steps=2),
        ServingConfig(replicas=2, failover_retries=2),
    )
    yield pool
    pool.shutdown()


def _wave(pool, tag, n=4, max_tokens=24):
    from aios_tpu.engine.batching import Request

    handles = [
        pool.submit(
            Request(prompt_ids=[3 + i, 7, 11], max_tokens=max_tokens,
                    temperature=0.0, request_id=f"{tag}-{i}"),
            tenant="chaos-tenant",
        )
        for i in range(n)
    ]
    streams = {}
    threads = []
    for i, h in enumerate(handles):
        t = threading.Thread(
            target=lambda i=i, h=h: streams.__setitem__(i, h.tokens()),
            daemon=True,
        )
        t.start()
        threads.append(t)
    stuck = 0
    for t in threads:
        t.join(timeout=120)
        stuck += int(t.is_alive())
    return [streams.get(i) for i in range(n)], handles, stuck


def test_failover_crash_mid_decode_streams_identical(crash_pool):
    """ISSUE 10 acceptance: a replica crash injected mid-decode on a
    2-replica pool completes every in-flight greedy request with a token
    stream identical to a fault-free run, zero stuck requests,
    ``failover`` timeline events, and a counted respawn — the client
    never sees the crash."""
    pool = crash_pool
    ref, ref_handles, stuck = _wave(pool, "ref")
    assert stuck == 0 and all(len(s) == 24 for s in ref)
    assert not any(h.aborted for h in ref_handles)

    restarts_before = pool.restarts
    faults.activate("seed=2;pool.scheduler_crash=nth:6")
    try:
        out, handles, stuck = _wave(pool, "crash")
    finally:
        faults.deactivate()
    assert stuck == 0, "a request leaked through the crash"
    assert out == ref, "failover streams must be token-identical"
    assert not any(h.aborted for h in handles)
    assert pool.restarts == restarts_before + 1
    # the timelines carry the failover story: at least one request
    # crossed replicas, every one retired normally
    tls = [
        t for t in flightrec.RECORDER.recent(model=MODEL, limit=64)
        if t.request_id.startswith("crash-")
    ]
    assert len(tls) == 4
    assert all(t.state == "retired" for t in tls)
    fo = [t for t in tls
          if any(k == "failover" for _, k, _ in t.events)]
    assert fo, "no failover event recorded on any timeline"
    ev = next(
        f for t in fo for _, k, f in t.events if k == "failover"
    )
    assert ev["cause"] == "scheduler_failed" and ev["attempt"] == 1
    # tokens_out accumulated across attempts == what the client got
    assert all(t.tokens_out == 24 for t in tls)


def test_failover_budget_exhausts_as_retryable_abort(crash_pool):
    """Every retry crashes (prob:1.0): the abort surfaces with a
    retry-after hint — UNAVAILABLE at the service mapping — and the
    timeline finishes aborted with the failover attempts on record."""
    pool = crash_pool
    faults.activate("seed=3;pool.scheduler_crash=prob:1.0")
    try:
        out, handles, stuck = _wave(pool, "exhaust", n=2)
    finally:
        faults.deactivate()
    assert stuck == 0
    assert all(h.aborted for h in handles)
    assert all(h.retry_after_ms > 0 for h in handles), (
        "an exhausted failover budget must hand the client a backoff "
        "hint, not a dead end"
    )
    assert all("scheduler" in h.abort_reason for h in handles)
    tls = [
        t for t in flightrec.RECORDER.recent(model=MODEL, limit=64)
        if t.request_id.startswith("exhaust-")
    ]
    assert len(tls) == 2
    assert all(t.state == "aborted" for t in tls)
    assert all(t.abort_cause == "scheduler_failed" for t in tls)
    for t in tls:
        assert sum(
            1 for _, k, _ in t.events if k == "failover"
        ) == 2, "both budget attempts must be on the record"


def test_cancel_after_claimed_abort_finishes_timeline(crash_pool):
    """A crash and a client disconnect are correlated (the stalled
    stream is why the client gives up): when the batcher deferred the
    terminal event to the failover controller and the consumer then
    cancels instead of resuming, the timeline must still finish — no
    request may vanish with no terminal event, ring entry, or SLO
    sample."""
    from aios_tpu.engine.batching import Request

    pool = crash_pool
    faults.activate("seed=5;pool.scheduler_crash=prob:1.0")
    try:
        h = pool.submit(
            Request(prompt_ids=[9, 8, 7], max_tokens=24, temperature=0.0,
                    request_id="orphan-1"),
            tenant="chaos-tenant",
        )
        deadline = time.time() + 60
        while time.time() < deadline and not h._inner._live.abort_reason:
            time.sleep(0.02)
        assert h._inner._live.abort_reason, "the crash never landed"
        h.cancel()  # the client gave up without consuming the stream
    finally:
        faults.deactivate()
    tls = [
        t for t in flightrec.RECORDER.recent(model=MODEL, limit=64)
        if t.request_id == "orphan-1"
    ]
    assert tls, "the claimed timeline was never finished into the ring"
    assert tls[0].state == "aborted"


def test_faults_disabled_streams_and_compiles_pinned(crash_pool):
    """The PR 6/7/8 invariant extended to the instrumented hot paths:
    with no schedule armed, the same wave twice is token-identical, no
    fault fires, and the engines compile NOTHING new (the injection
    points are no-ops, not graph changes)."""
    pool = crash_pool
    a, _, _ = _wave(pool, "quiet-a")
    compiles = [r.engine.stats()["xla_compiles"] for r in pool.replicas]
    b, _, _ = _wave(pool, "quiet-b")
    assert a == b
    assert faults.fired() == []
    assert [
        r.engine.stats()["xla_compiles"] for r in pool.replicas
    ] == compiles


def test_constrained_requests_are_not_wrapped(crash_pool):
    """json_mode/json_schema requests keep the plain handle (a resume
    cannot reproduce the grammar-forced first token) — they abort with
    a retryable status instead of failing over."""
    from aios_tpu.engine.batching import Request, RequestHandle

    pool = crash_pool
    req = Request(prompt_ids=[5, 6, 7], max_tokens=4, temperature=0.0,
                  json_mode=True, request_id="constrained-1")
    # the pool refuses to wrap; whether submit succeeds depends on the
    # tokenizer (TINY_TEST batchers have none), and THAT error must
    # surface on the caller, not a failover controller
    try:
        h = pool.submit(req, tenant="chaos-tenant")
    except ValueError:
        assert req.failover is None
        return
    assert isinstance(h, RequestHandle)
    assert req.failover is None
    h.cancel()


def test_evicted_not_retryable_on_single_replica_pool():
    """A 1-replica pool must not re-route an eviction back onto the
    replica that just evicted it — only scheduler crashes retry."""
    from aios_tpu.serving.failover import FailoverHandle

    class _Pool:
        replicas = [object()]
        name = "one"
        _draining = False
        _closed = False

    fo = FailoverHandle(_Pool(), None, "t", retries=2, backoff_ms=1.0)
    assert fo.claims("scheduler failed: boom")
    assert not fo.claims("evicted: KV pool exhausted")
    assert not fo.claims("model unloading")

    class _Pool2(_Pool):
        replicas = [object(), object()]

    fo2 = FailoverHandle(_Pool2(), None, "t", retries=2, backoff_ms=1.0)
    assert fo2.claims("evicted: KV pool exhausted")


def test_failover_handle_cancel_stops_retries():
    from aios_tpu.serving.failover import FailoverHandle

    class _Pool:
        replicas = [object(), object()]
        name = "c"
        _draining = False
        _closed = False

    fo = FailoverHandle(_Pool(), None, "t", retries=2, backoff_ms=1.0)
    fo.cancel()
    assert not fo.claims("scheduler failed: boom")


# ---------------------------------------------------------------------------
# failover with a DRAFT MODEL attached (ISSUE 11): the draft KV is
# replica-local derived state — a crash mid-spec-round must fail over
# with the resumed stream token-identical and the surviving replica's
# draft rebuilt from history (bulk ingest) or cleanly reset.
# ---------------------------------------------------------------------------

DRAFT_MODEL = "draft-failover-test"


@pytest.fixture(scope="module")
def draft_pool():
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model as model_mod
    from aios_tpu.engine import spec as spec_mod
    from aios_tpu.engine.batching import ContinuousBatcher
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.serving import ReplicaPool, ServingConfig

    cfg = TINY_TEST.scaled(name=DRAFT_MODEL, max_context=256)
    params = model_mod.init_params(cfg, jax.random.PRNGKey(0),
                                   dtype=jnp.float32)
    draft = spec_mod.DraftModel(cfg, params, quantize=None)
    engines = [
        TPUEngine(cfg, params, num_slots=2, max_context=256,
                  cache_dtype=jnp.float32, draft=draft)
        for _ in range(2)
    ]
    pool = ReplicaPool(
        DRAFT_MODEL, engines,
        lambda e: ContinuousBatcher(e, chunk_steps=2, admit_chunk_steps=2,
                                    speculative=True, spec_draft_len=3),
        ServingConfig(replicas=2, failover_retries=2),
    )
    yield pool
    pool.shutdown()


def test_draft_failover_crash_mid_spec_round_streams_identical(draft_pool):
    """A scheduler crash injected mid-SPEC-round on a draft-enabled
    2-replica pool: the failover controller resumes every greedy stream
    token-identically on the surviving replica — whose draft KV for the
    resumed slot starts empty and rebuilds from the re-prefilled history
    via bulk ingest — with zero stuck requests and a counted respawn."""
    pool = draft_pool
    ref, ref_handles, stuck = _wave(pool, "dref")
    assert stuck == 0 and all(len(s) == 24 for s in ref)
    assert not any(h.aborted for h in ref_handles)
    # the reference wave really served through the draft proposer
    assert any(
        r.engine.spec_proposer_rounds["draft"] > 0 for r in pool.replicas
    )

    restarts_before = pool.restarts
    # nth:4 counts DECODE ticks — with chunk_steps=2 and spec rounds the
    # 4th live tick lands mid-stream, well inside the spec-serving phase
    faults.activate("seed=11;pool.scheduler_crash=nth:4")
    try:
        out, handles, stuck = _wave(pool, "dcrash")
    finally:
        faults.deactivate()
    assert stuck == 0, "a request leaked through the crash"
    assert out == ref, (
        "draft-mode failover streams must be token-identical"
    )
    assert not any(h.aborted for h in handles)
    assert pool.restarts == restarts_before + 1
    tls = [
        t for t in flightrec.RECORDER.recent(model=DRAFT_MODEL, limit=64)
        if t.request_id.startswith("dcrash-")
    ]
    assert len(tls) == 4
    assert all(t.state == "retired" for t in tls)
    assert any(
        k == "failover" for t in tls for _, k, _ in t.events
    ), "no failover event recorded on any timeline"
    # every replica's draft mirror is back in a clean state (all slots
    # released after the wave -> lengths zeroed)
    for r in pool.replicas:
        assert (r.engine._draft_host_lengths == 0).all()
        assert (np.asarray(r.engine.draft_state["lengths"]) == 0).all()


def test_draft_faults_disabled_streams_and_compiles_pinned(draft_pool):
    """The PR 8/10 pinned invariant re-asserted with a draft model
    attached: no schedule armed -> the same wave twice is
    token-identical, no fault fires, and the engines compile NOTHING new
    (the draft graphs were built on the first wave's dispatch sizes and
    stay warm)."""
    pool = draft_pool
    a, _, _ = _wave(pool, "dquiet-a")
    compiles = [r.engine.stats()["xla_compiles"] for r in pool.replicas]
    b, _, _ = _wave(pool, "dquiet-b")
    assert a == b
    assert faults.fired() == []
    assert [
        r.engine.stats()["xla_compiles"] for r in pool.replicas
    ] == compiles


# ---------------------------------------------------------------------------
# engine-level restore fallback + corruption (slow tier — real spills)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def params():
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import model

    from aios_tpu.engine.config import TINY_TEST

    return model.init_params(TINY_TEST, jax.random.PRNGKey(1),
                             dtype=jnp.float32)


def make_engine(params, host_bytes=64 << 20, **kw):
    import jax.numpy as jnp

    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    kw.setdefault("num_slots", 2)
    kw.setdefault("max_context", 256)
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("paged_pool_rows", 256)
    kw.setdefault("page_size", 32)
    return TPUEngine(TINY_TEST, params, prefix_host_bytes=host_bytes, **kw)


def _force_spill(eng, rng, min_entries=2, blocks=6):
    pressure = [int(t) for t in rng.integers(1, 500, blocks * 32 + 8)]
    eng.prefill(0, pressure, temperature=0.0)
    eng.release(0)
    deadline = time.time() + 20
    while (len(eng.host_store) < min_entries or eng._spill_pending) \
            and time.time() < deadline:
        time.sleep(0.02)
    assert len(eng.host_store) >= min_entries, "spill worker never drained"
    assert eng._spill_pending == 0


def _assert_page_invariants(eng):
    """No page simultaneously free-listed and mapped/indexed (the PR 4
    interleaving invariant, asserted after every faulted run)."""
    alloc = eng.allocator
    free = set(alloc._free[0])
    indexed = set(eng.prefix_index.snapshot().values())
    mapped = set()
    for s in range(eng.num_slots):
        used = int(alloc._blocks_used[s])
        mapped.update(int(p) for p in alloc.tables[s, :used])
    assert not (free & indexed), (free, indexed)
    assert not (free & mapped), (free, mapped)
    for p in free:
        assert alloc.refcount(p) == 0


@pytest.mark.slow
def test_restore_fail_falls_back_to_prefill_token_identical(params):
    """ISSUE 10 satellite: fault-inject ``host_store.restore_fail`` and
    the engine falls back to normal prefill with token-identical output,
    the failed restore counted as a host-tier miss, nothing restored,
    and no page leaked between the free list and the tables."""
    rng = np.random.default_rng(7)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]
    eng = make_engine(params)
    ref = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    _force_spill(eng, rng)
    misses0 = eng.host_store.misses
    hits0 = eng.host_store.hits
    faults.activate("host_store.restore_fail=nth:1")
    try:
        again = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    finally:
        faults.deactivate()
    assert again == ref  # fallback prefill, token-identical
    assert eng.prefix_rows_restored == 0  # the restore never happened
    assert eng.host_store.hits == hits0 + 1  # the probe DID hit
    assert eng.host_store.misses == misses0 + 1, (
        "a failed restore must count as a miss — "
        "aios_tpu_prefix_host_misses_total is the recompute predictor"
    )
    assert eng.stats()["host_tier_misses"] == eng.host_store.misses
    # the fallback prefill re-registered the blocks in the HBM index:
    # the NEXT submit is a plain prefix hit — no restore, no recompute
    reused0 = eng.prefix_rows_reused
    third = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    assert third == ref
    assert eng.prefix_rows_reused > reused0
    assert eng.prefix_rows_restored == 0
    _assert_page_invariants(eng)
    eng.close()


@pytest.mark.slow
def test_corrupt_spill_detected_end_to_end(params):
    """``host_store.corrupt`` flips a spilled byte; the crc32 check at
    the restore probe drops the page, the prompt recomputes token-
    identically, and the corruption is counted (engine stats +
    aios_tpu_prefix_host_corrupt_total plumbing)."""
    rng = np.random.default_rng(8)
    prompt = [int(t) for t in rng.integers(1, 500, 100)]
    eng = make_engine(params)
    ref = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    _force_spill(eng, rng)
    faults.activate("host_store.corrupt=nth:1")
    try:
        again = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    finally:
        faults.deactivate()
    assert again == ref
    assert eng.host_store.corruptions == 1
    assert eng.stats()["host_tier_corrupt"] == 1
    _assert_page_invariants(eng)
    eng.close()


# ---------------------------------------------------------------------------
# gRPC surface: crash aborts are retryable; rpc.unavailable injects
# (slow tier — live server)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_crash_abort_surfaces_unavailable_with_retry_after(monkeypatch):
    """ISSUE 10 satellite: a crash that exhausts the failover budget
    reaches the client as UNAVAILABLE + retry-after-ms trailing
    metadata (the admission-shed convention), never a truncated stream
    presented as a completion."""
    import grpc as grpc_mod

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    monkeypatch.delenv("AIOS_TPU_REPLICAS", raising=False)
    monkeypatch.setenv("AIOS_TPU_FAILOVER_RETRIES", "1")
    monkeypatch.setenv("AIOS_TPU_FAILOVER_BACKOFF_MS", "5")
    mgr = ModelManager(num_slots=2, warm_compile=False)
    mgr.load_model("crashtiny", "synthetic://tiny-test",
                   context_length=128)
    server, _, port = serve(address="127.0.0.1:0", manager=mgr,
                            block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        stub = services.AIRuntimeStub(channel)
        # warm the path un-faulted so the crash lands mid-decode
        stub.Infer(runtime_pb2.InferRequest(
            prompt="warm", max_tokens=4, temperature=0.0
        ))
        faults.activate("seed=4;pool.scheduler_crash=prob:1.0")
        with pytest.raises(grpc_mod.RpcError) as err:
            stub.Infer(runtime_pb2.InferRequest(
                prompt="hello", max_tokens=64, temperature=0.0
            ))
        faults.deactivate()
        assert err.value.code() == grpc_mod.StatusCode.UNAVAILABLE
        md = dict(err.value.trailing_metadata() or ())
        assert int(md.get("retry-after-ms", 0)) > 0
        # and the pool recovers: the next request serves normally
        resp = stub.Infer(runtime_pb2.InferRequest(
            prompt="after", max_tokens=4, temperature=0.0
        ))
        assert resp.tokens_used > 0
    finally:
        faults.deactivate()
        channel.close()
        server.stop(grace=None)
        mgr.unload_model("crashtiny")


@pytest.mark.slow
def test_rpc_unavailable_point_aborts_with_retry_after(monkeypatch):
    """The rpc.unavailable point makes ANY server RPC abort UNAVAILABLE
    + retry-after-ms — the injected shape of a process mid-restart —
    and service resumes on the next call."""
    import grpc as grpc_mod

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import common_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    monkeypatch.delenv("AIOS_TPU_REPLICAS", raising=False)
    mgr = ModelManager(num_slots=2, warm_compile=False)
    server, _, port = serve(address="127.0.0.1:0", manager=mgr,
                            block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        stub = services.AIRuntimeStub(channel)
        stub.HealthCheck(common_pb2.Empty())  # un-faulted: serves
        faults.activate("rpc.unavailable=nth:1,retry_after_ms=250")
        with pytest.raises(grpc_mod.RpcError) as err:
            stub.HealthCheck(common_pb2.Empty())
        faults.deactivate()
        assert err.value.code() == grpc_mod.StatusCode.UNAVAILABLE
        md = dict(err.value.trailing_metadata() or ())
        assert md.get("retry-after-ms") == "250"
        stub.HealthCheck(common_pb2.Empty())  # one-shot: recovered
    finally:
        faults.deactivate()
        channel.close()
        server.stop(grace=None)
