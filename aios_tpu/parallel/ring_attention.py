"""Ring attention: causal sequence-parallel attention over the `sp` mesh axis.

Long-context path for training and bulk prefill: Q/K/V are sharded along the
sequence dimension; each device keeps its query block resident and the K/V
blocks rotate around the ring via `lax.ppermute` (ICI neighbor exchange),
with a numerically-stable online-softmax accumulation — so the full T x T
score matrix never materializes and max sequence length scales linearly with
the number of chips.

The reference has nothing comparable (fixed 2048-8192 contexts, SURVEY.md
section 2.4); this is the "long-context is first-class" component of the TPU
build. Blockwise/ring formulation follows the public ring-attention papers
(PAPERS.md); implementation is GQA-aware and runs as shard_map nested inside
jit, composing with the dp/tp axes of the same mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = jnp.float32(-1e30)


def _block_scores(q, k, scale):
    """q [B,Tq,KH,G,D] x k [B,Tk,KH,D] -> fp32 scores [B,KH,G,Tq,Tk]."""
    return jnp.einsum("btkgd,bskd->bkgts", q, k).astype(jnp.float32) * scale


def visibility(rows, cols, window):
    """Causal (optionally sliding-window) visibility in GLOBAL positions —
    the one mask rule both sequence-parallel attentions apply per tile."""
    vis = rows[:, None] >= cols[None, :]
    if window is not None:
        vis = vis & (cols[None, :] > rows[:, None] - window)
    return vis


def fold_tile(carry, scores, visible, v_tile):
    """One online-softmax (flash) accumulation step over a KV tile, shared
    by ring and Ulysses sequence parallelism. carry = (m, l, acc) with
    shapes [B,KH,G,Tq] / [B,KH,G,Tq] / [B,KH,G,Tq,D]; scores [B,KH,G,Tq,Tk]
    fp32; visible [Tq, Tk]; v_tile [B,Tk,KH,D]."""
    m, l, acc = carry
    scores = jnp.where(visible[None, None, None], scores, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(visible[None, None, None], p, 0.0)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bkgts,bskd->bkgtd", p, v_tile.astype(jnp.float32))
    return m_new, l_new, acc * alpha[..., None] + pv


def ring_attention(
    q: jnp.ndarray,  # [B, T, H, D]   T sharded over `axis`
    k: jnp.ndarray,  # [B, T, KH, D]
    v: jnp.ndarray,  # [B, T, KH, D]
    mesh: Mesh,
    axis: str = "sp",
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Causal (optionally sliding-window) GQA ring attention; returns
    [B, T, H, D] sharded like q."""
    B, T, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    scale = 1.0 / np.sqrt(D)
    n_ring = mesh.shape[axis]

    spec = P(None, axis, None, None)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    def _ring(q_blk, k_blk, v_blk):
        # local shapes: q [B, Tq, H, D], k/v [B, Tk, KH, D]
        Tq = q_blk.shape[1]
        Tk = k_blk.shape[1]
        my = jax.lax.axis_index(axis)
        qg = q_blk.reshape(B, Tq, KH, G, D)

        rows = my * Tq + jnp.arange(Tq)  # global query positions

        def step(carry, s):
            k_cur, v_cur, m, l, acc = carry
            src_blk = (my - s) % n_ring  # which global block we hold now
            cols = src_blk * Tk + jnp.arange(Tk)
            vis = visibility(rows, cols, window)  # global coords

            scores = _block_scores(qg, k_cur, scale)  # [B,KH,G,Tq,Tk]
            new_m, new_l, new_acc = fold_tile((m, l, acc), scores, vis, v_cur)

            # rotate k/v one hop around the ring (device d -> d+1)
            perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (k_nxt, v_nxt, new_m, new_l, new_acc), None

        m0 = jnp.full((B, KH, G, Tq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, KH, G, Tq), jnp.float32)
        acc0 = jnp.zeros((B, KH, G, Tq, D), jnp.float32)
        (_, _, _, l, acc), _ = jax.lax.scan(
            step, (k_blk, v_blk, m0, l0, acc0), jnp.arange(n_ring)
        )
        out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,KH,G,Tq,D]
        out = out.transpose(0, 3, 1, 2, 4).reshape(B, Tq, H, D)
        return out.astype(q_blk.dtype)

    return _ring(q, k, v)


def make_ring_attn_fn(mesh: Mesh, axis: str = "sp",
                      window: Optional[int] = None):
    """Adapter matching model.py's attention signature (the causal /
    sliding-window mask is recomputed internally from GLOBAL positions, so
    the passed local mask is ignored — callers must forward the model's
    window here, as make_train_step does)."""

    def attn(q, k, v, mask):  # noqa: ARG001 — masking handled in-ring
        return ring_attention(q, k, v, mesh, axis, window=window)

    return attn
