"""Orchestrator gRPC surface + management console over live sockets."""

import json
import time
import urllib.error
import urllib.request

import pytest

from aios_tpu import rpc, services
from aios_tpu.orchestrator.management import ManagementConsole
from aios_tpu.orchestrator.service import OrchestratorService, serve
from aios_tpu.proto_gen import common_pb2, orchestrator_pb2


@pytest.fixture(scope="module")
def orch():
    server, service, port = serve(address="127.0.0.1:0", block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.OrchestratorStub(channel), service
    channel.close()
    server.stop(grace=None)


def test_goal_submit_status_cancel(orch):
    stub, service = orch
    gid = stub.SubmitGoal(
        orchestrator_pb2.SubmitGoalRequest(
            description="check disk usage", priority=6, source="test"
        )
    )
    assert gid.id
    status = stub.GetGoalStatus(common_pb2.GoalId(id=gid.id))
    assert status.goal.description == "check disk usage"
    goals = stub.ListGoals(orchestrator_pb2.ListGoalsRequest())
    assert goals.total >= 1
    cancelled = stub.CancelGoal(common_pb2.GoalId(id=gid.id))
    assert cancelled.success


def test_agent_register_poll_report_cycle(orch):
    stub, service = orch
    stub.RegisterAgent(common_pb2.AgentRegistration(
        agent_id="system_agent-t1",
        agent_type="system",
        tool_namespaces=["service", "monitor"],
    ))
    hb = stub.Heartbeat(orchestrator_pb2.HeartbeatRequest(
        agent_id="system_agent-t1", status="idle"))
    assert hb.success
    agents = stub.ListAgents(common_pb2.Empty())
    assert any(a.agent_id == "system_agent-t1" for a in agents.agents)

    # plant a routed task and poll it back
    gid = stub.SubmitGoal(orchestrator_pb2.SubmitGoalRequest(
        description="restart the cron service"))
    from aios_tpu.orchestrator.goal_engine import Task

    t = Task(id="tt-1", goal_id=gid.id, description="restart cron",
             required_tools=["service"])
    service.engine.add_tasks(gid.id, [t])
    assert service.router.route_task(t) == "system_agent-t1"

    polled = stub.GetAssignedTask(common_pb2.AgentId(id="system_agent-t1"))
    assert polled.id == "tt-1"
    report = stub.ReportTaskResult(common_pb2.TaskResult(
        task_id="tt-1", success=True,
        output_json=json.dumps({"restarted": True}).encode(),
        duration_ms=42, model_used="none",
    ))
    assert report.success
    assert service.engine.tasks["tt-1"].status == "completed"
    assert service.aggregator.summary(gid.id).succeeded == 1


def test_empty_poll_returns_empty_task(orch):
    stub, _ = orch
    polled = stub.GetAssignedTask(common_pb2.AgentId(id="system_agent-t1"))
    assert polled.id == ""


def test_capability_auto_grant_quirk(orch):
    stub, _ = orch
    resp = stub.RequestCapability(orchestrator_pb2.CapabilityRequest(
        agent_id="x", capabilities=["fs.write", "sec.admin"]))
    assert resp.granted  # reference auto-grants everything (main.rs:395-411)
    assert list(resp.capabilities) == ["fs.write", "sec.admin"]


def test_schedules_actually_wired(orch):
    stub, _ = orch
    created = stub.CreateSchedule(orchestrator_pb2.CreateScheduleRequest(
        cron_expr="0 3 * * *", goal_template="nightly backup", priority=4))
    assert created.success
    listed = stub.ListSchedules(common_pb2.Empty())
    assert any(s.goal_template == "nightly backup" for s in listed.schedules)
    deleted = stub.DeleteSchedule(orchestrator_pb2.DeleteScheduleRequest(
        schedule_id=created.schedule_id))
    assert deleted.success


def test_cluster_node_rpcs(orch):
    stub, _ = orch
    reg = stub.RegisterNode(orchestrator_pb2.NodeRegistration(
        node_id="node-b", hostname="b", address="10.0.0.2:50051",
        max_tasks=5))
    assert reg.success
    hb = stub.NodeHeartbeat(orchestrator_pb2.NodeStatus(
        node_id="node-b", cpu_usage=12.5, active_tasks=1))
    assert hb.success
    nodes = stub.ListNodes(orchestrator_pb2.ListNodesRequest())
    assert nodes.nodes[0].node_id == "node-b"
    assert nodes.nodes[0].healthy


def test_system_status(orch):
    stub, _ = orch
    s = stub.GetSystemStatus(common_pb2.Empty())
    assert s.memory_total_mb > 0
    assert s.uptime_seconds >= 0


# ---------------------------------------------------------------------------
# Management console
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def console(orch):
    _, service = orch
    c = ManagementConsole(
        service, port=0,
        serving_stats=lambda: {
            "tinyllama": {"active_slots": 2, "num_slots": 8,
                          "decode_steps": 41, "waiting": 0}
        },
        service_health=lambda: {"runtime": True, "memory": True},
    )
    c.start()
    yield f"http://127.0.0.1:{c.bound_port}"
    c.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read())


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())


def test_console_dashboard_and_api(console):
    with urllib.request.urlopen(console + "/", timeout=5) as r:
        html = r.read().decode()
    assert "aiOS-TPU" in html and "<script>" in html

    health = _get(console + "/api/health")
    assert health["healthy"]

    status = _get(console + "/api/status")
    assert "active_goals" in status

    out = _post(console + "/api/chat", {"message": "check cpu please"})
    assert out["goal_id"]
    goals = _get(console + "/api/goals")
    assert any(g["id"] == out["goal_id"] for g in goals["goals"])
    msgs = _get(console + f"/api/goals/{out['goal_id']}/messages")
    assert msgs["messages"][0]["content"] == "check cpu please"
    tasks = _get(console + f"/api/goals/{out['goal_id']}/tasks")
    assert "tasks" in tasks
    agents = _get(console + "/api/agents")
    assert "agents" in agents

    # reference-parity dashboard surfaces (management.rs:757+): goal
    # drill-down + conversation thread + serving/health panels all have a
    # UI path and the new /api/serving route serves the counters
    assert "openGoal" in html and "subscribe_goal" in html
    assert "cancelGoal" in html  # operator kill switch in the drill-down
    assert "TPU serving" in html and "Service health" in html
    serving = _get(console + "/api/serving")
    assert serving["models"]["tinyllama"]["decode_steps"] == 41
    health2 = _get(console + "/api/health")
    assert health2["services"] == {"runtime": True, "memory": True}

    # operator cancel route: cancels through the same path as the
    # CancelGoal RPC (in-flight AI abort included); repeat -> 409
    out2 = _post(console + "/api/chat", {"message": "please cancel me"})
    cancelled = _post(console + f"/api/goals/{out2['goal_id']}/cancel", {})
    assert cancelled["cancelled"] is True
    goals2 = _get(console + "/api/goals")
    st = {g["id"]: g["status"] for g in goals2["goals"]}
    assert st[out2["goal_id"]] == "cancelled"
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(console + f"/api/goals/{out2['goal_id']}/cancel", {})
    assert err.value.code == 409
    # unknown id is 404, not the already-terminal 409
    with pytest.raises(urllib.error.HTTPError) as err2:
        _post(console + "/api/goals/not-a-goal/cancel", {})
    assert err2.value.code == 404


# ---------------------------------------------------------------------------
# Standalone client + CLI (reference orchestrator_client.py:33-100)
# ---------------------------------------------------------------------------


def _client_for(orch_port):
    from aios_tpu.orchestrator.client import ClientConfig, OrchestratorClient

    return OrchestratorClient(
        ClientConfig(address=f"127.0.0.1:{orch_port}", timeout_s=10,
                     retry_delay_s=0.05)
    )


@pytest.fixture(scope="module")
def orch_port():
    server, service, port = serve(address="127.0.0.1:0", block=False)
    yield port
    server.stop(grace=None)


def test_client_submit_status_cancel_roundtrip(orch_port):
    with _client_for(orch_port) as client:
        gid = client.submit_goal("client roundtrip goal", priority=4,
                                 tags=["cli"], metadata={"k": "v"})
        assert gid
        status = client.get_goal_status(gid)
        assert status["description"] == "client roundtrip goal"
        goals = client.list_goals()
        assert any(g["id"] == gid for g in goals)
        assert client.cancel_goal(gid)
        assert client.get_goal_status(gid)["status"] == "cancelled"
        # wait_for_goal returns immediately on a terminal state
        done = client.wait_for_goal(gid, timeout_s=5, poll_s=0.05)
        assert done["status"] == "cancelled"
        sysinfo = client.get_system_status()
        assert "active_goals" in sysinfo
        assert isinstance(client.list_agents(), list)


def test_client_retries_then_raises_on_dead_server():
    import grpc

    from aios_tpu.orchestrator.client import ClientConfig, OrchestratorClient

    client = OrchestratorClient(
        ClientConfig(address="127.0.0.1:1", timeout_s=0.3, max_retries=2,
                     retry_delay_s=0.01)
    )
    t0 = time.time()
    with pytest.raises(grpc.RpcError):
        client.get_system_status()
    assert time.time() - t0 >= 0.01  # at least one retry delay elapsed


def test_client_cli_submit_and_status(orch_port, capsys):
    from aios_tpu.orchestrator import client as client_mod

    rc = client_mod.main(
        ["--address", f"127.0.0.1:{orch_port}", "submit", "cli goal"]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["goal_id"]
    rc = client_mod.main(
        ["--address", f"127.0.0.1:{orch_port}", "status", out["goal_id"]]
    )
    assert rc == 0
    status = json.loads(capsys.readouterr().out)
    assert status["description"] == "cli goal"


def test_run_boots_console_with_serving_feed(tmp_path):
    """run() (the module entrypoint the boot supervisor spawns) must wire
    the console's serving feed from build_orchestrator's closure —
    regression: a NameError here failed the whole stack's boot gate while
    every test that used build_orchestrator directly stayed green."""
    from aios_tpu.orchestrator.main import run

    server, service, console, autonomy, spawner, shutdown = run(
        data_dir=str(tmp_path), grpc_address="127.0.0.1:0",
        console_port=0, spawn_agents=False, block=False,
    )
    try:
        assert console.bound_port
        # the serving feed survived the build->run handoff (empty dict is
        # fine — no runtime is up in this test)
        assert console.serving_stats is not None
        assert _get(f"http://127.0.0.1:{console.bound_port}/api/serving") == {
            "models": {}
        }
    finally:
        # stops EVERY loop run() started (scheduler/proactive/health too —
        # a leaked health prober would spend the rest of the suite
        # submitting service.unhealthy goals into the tmp_path db)
        shutdown()
