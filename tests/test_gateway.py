"""API gateway: provider selection, fallback, budget, cache, RPC surface.

Cloud providers are stubbed with a local HTTP server speaking both the
Claude Messages and OpenAI chat-completions protocols; the `local` provider
is a stub AIRuntime gRPC server. The suite runs fully offline.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import grpc
import pytest

from aios_tpu import rpc, services
from aios_tpu.gateway.budget import BudgetManager
from aios_tpu.gateway.providers import ProviderError
from aios_tpu.gateway.router import RequestRouter, ResponseCache
from aios_tpu.proto_gen import api_gateway_pb2 as pb
from aios_tpu.proto_gen import common_pb2, runtime_pb2


class _StubCloud(BaseHTTPRequestHandler):
    fail_providers: set = set()
    calls: list = []

    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        type(self).calls.append(self.path)
        if self.path == "/v1/messages":  # Claude protocol
            if "claude" in self.fail_providers:
                self.send_error(500, "claude down")
                return
            resp = {
                "model": body["model"],
                "content": [{"type": "text", "text": f"claude says: {body['messages'][0]['content'][:20]}"}],
                "usage": {"input_tokens": 100, "output_tokens": 50},
            }
        elif self.path == "/v1/chat/completions":  # OpenAI protocol
            name = "openai" if "gpt" in body["model"] else "qwen3"
            if name in self.fail_providers:
                self.send_error(500, f"{name} down")
                return
            resp = {
                "model": body["model"],
                "choices": [{"message": {"content": f"{name} says hi"}}],
                "usage": {"prompt_tokens": 80, "completion_tokens": 40},
            }
        else:
            self.send_error(404)
            return
        out = json.dumps(resp).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def log_message(self, *args):
        pass


class _StubRuntime(services.AIRuntimeServicer):
    stream_gate = threading.Event()

    def Infer(self, request, context):
        return runtime_pb2.InferResponse(
            text="local tpu response", tokens_used=10, model_used="tinyllama"
        )

    def StreamInfer(self, request, context):
        for i in range(3):
            yield runtime_pb2.InferChunk(text=f"tok{i} ", done=False)
        # block until the test releases us — proves the gateway relays
        # chunks live instead of buffering the whole response
        type(self).stream_gate.wait(timeout=10)
        yield runtime_pb2.InferChunk(text="end", done=False)
        yield runtime_pb2.InferChunk(text="", done=True)


@pytest.fixture(scope="module")
def stub_endpoints():
    http_server = HTTPServer(("127.0.0.1", 0), _StubCloud)
    threading.Thread(target=http_server.serve_forever, daemon=True).start()
    http_port = http_server.server_port

    grpc_server = rpc.create_server()
    rpc.add_to_server(services.RUNTIME, _StubRuntime(), grpc_server)
    grpc_port = grpc_server.add_insecure_port("127.0.0.1:0")
    grpc_server.start()
    yield f"http://127.0.0.1:{http_port}", f"127.0.0.1:{grpc_port}"
    http_server.shutdown()
    grpc_server.stop(grace=None)


@pytest.fixture()
def router(stub_endpoints, monkeypatch):
    base, runtime_addr = stub_endpoints
    for var, val in {
        "CLAUDE_API_KEY": "test-key",
        "OPENAI_API_KEY": "test-key",
        "QWEN3_API_KEY": "test-key",
        "CLAUDE_BASE_URL": base,
        "OPENAI_BASE_URL": base,
        "QWEN3_BASE_URL": base,
    }.items():
        monkeypatch.setenv(var, val)
    _StubCloud.fail_providers = set()
    _StubCloud.calls = []
    return RequestRouter(budget=BudgetManager(), runtime_address=runtime_addr)


def test_priority_selects_claude_first(router):
    result = router.route("hello world")
    assert result.provider == "claude"
    assert "claude says" in result.text


def test_fallback_chain_on_provider_error(router):
    _StubCloud.fail_providers = {"claude"}
    result = router.route("try again", preferred="claude", allow_fallback=True)
    assert result.provider == "openai"


def test_no_fallback_when_disallowed(router):
    _StubCloud.fail_providers = {"claude"}
    with pytest.raises(ProviderError):
        router.route("no fb", preferred="claude", allow_fallback=False,
                     use_cache=False)


def test_local_is_final_fallback(router):
    _StubCloud.fail_providers = {"claude", "openai", "qwen3"}
    result = router.route("anyone?", preferred="claude", allow_fallback=True)
    assert result.provider == "local"
    assert result.text == "local tpu response"


def test_missing_keys_route_local(stub_endpoints, monkeypatch):
    for var in ("CLAUDE_API_KEY", "OPENAI_API_KEY", "QWEN3_API_KEY"):
        monkeypatch.delenv(var, raising=False)
    r = RequestRouter(budget=BudgetManager(), runtime_address=stub_endpoints[1])
    result = r.route("local only")
    assert result.provider == "local"


def test_budget_exhaustion_skips_provider(router):
    router.budget.claude_budget = 0.0001
    router.budget.record("claude", "m", 1_000_000, 1_000_000)  # blow the budget
    result = router.route("over budget", use_cache=False)
    assert result.provider != "claude"


def test_budget_accounting_and_warning():
    b = BudgetManager(claude_budget=10.0, openai_budget=5.0)
    b.record("claude", "m", 1_000_000, 0)  # $3
    assert b.used("claude") == pytest.approx(3.0)
    assert b.warning("claude") == ""
    b.record("claude", "m", 2_000_000, 0)  # +$6 = $9 => 90%
    assert "90%" in b.warning("claude")
    s = b.status()
    assert not s["budget_exceeded"]
    b.record("claude", "m", 1_000_000, 0)  # $12 > $10
    assert b.status()["budget_exceeded"]


def test_response_cache_hit(router):
    r1 = router.route("cache me", temperature=0.0)
    n_calls = len(_StubCloud.calls)
    r2 = router.route("cache me", temperature=0.0)
    assert len(_StubCloud.calls) == n_calls  # no extra provider hit
    assert r1.text == r2.text
    assert router.cache.hits == 1


def test_cache_lru_eviction():
    c = ResponseCache(max_entries=3)
    from aios_tpu.gateway.providers import InferResult

    for i in range(5):
        c.put(c.key(f"p{i}", "", 10, 0.0), InferResult(f"t{i}", 0, 0, "m", "p"))
    assert c.get(c.key("p0", "", 10, 0.0)) is None  # evicted
    assert c.get(c.key("p4", "", 10, 0.0)) is not None


# ---------------------------------------------------------------------------
# gRPC surface
# ---------------------------------------------------------------------------


@pytest.fixture()
def gateway_stub(router):
    from aios_tpu.gateway.service import serve

    server, service, port = serve(address="127.0.0.1:0", router=router, block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    yield services.ApiGatewayStub(channel)
    channel.close()
    server.stop(grace=None)


def test_rpc_infer_and_usage(gateway_stub):
    resp = gateway_stub.Infer(
        pb.ApiInferRequest(prompt="hello rpc", requesting_agent="test-agent")
    )
    assert resp.text
    assert resp.model_used.startswith("claude/")
    usage = gateway_stub.GetUsage(pb.UsageRequest(provider="claude"))
    assert usage.total_requests >= 1
    assert usage.records[0].requesting_agent == "test-agent"
    budget = gateway_stub.GetBudget(common_pb2.Empty())
    assert budget.claude_monthly_budget_usd == 100.0


def test_rpc_stream_infer(gateway_stub):
    chunks = list(gateway_stub.StreamInfer(pb.ApiInferRequest(prompt="stream me")))
    assert chunks[-1].done
    assert "".join(c.text for c in chunks)


def test_rpc_stream_infer_local_is_live(gateway_stub):
    """True streaming (VERDICT r2 weak #6): the first chunk must reach the
    client while the runtime is still mid-generation — the stub blocks its
    final chunks on an event only the test sets after observing the first."""
    _StubRuntime.stream_gate.clear()
    stream = gateway_stub.StreamInfer(
        pb.ApiInferRequest(
            prompt="live stream", preferred_provider="local",
            allow_fallback=False,
        )
    )
    first = next(stream)
    assert first.text.startswith("tok") and not first.done
    assert not _StubRuntime.stream_gate.is_set()  # generation still blocked
    _StubRuntime.stream_gate.set()
    rest = list(stream)
    assert rest[-1].done
    assert "end" in "".join(c.text for c in rest)


def test_rpc_all_fail_unavailable(gateway_stub):
    _StubCloud.fail_providers = {"claude", "openai", "qwen3"}
    # local still works, so force preferred=qwen3 without fallback
    with pytest.raises(grpc.RpcError) as err:
        gateway_stub.Infer(
            pb.ApiInferRequest(prompt="x", preferred_provider="qwen3",
                               allow_fallback=False)
        )
    assert err.value.code() == grpc.StatusCode.UNAVAILABLE
