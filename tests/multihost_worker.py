"""Worker process for the multi-host e2e test (tests/test_multihost.py).

Each instance is "one host": it joins the process group via the
AIOS_TPU_* env contract, builds the global mesh, runs the cross-host
all-reduce probe, then one sharded train step whose gradient all-reduce
crosses the process boundary. Both ranks must print the identical loss —
that is the proof the data plane spans hosts.

Run: python tests/multihost_worker.py <pid> <nprocs> <coordinator>
(env JAX_PLATFORMS=cpu, 4 virtual devices per process, tunnel hook off —
the test sets these).
"""

import sys

import numpy as np


def main() -> int:
    pid, n, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    import os

    os.environ["AIOS_TPU_COORDINATOR"] = coord
    os.environ["AIOS_TPU_NUM_PROCESSES"] = str(n)
    os.environ["AIOS_TPU_PROCESS_ID"] = str(pid)

    from aios_tpu.parallel import multihost

    assert multihost.initialize_from_env(), "process group must initialize"

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    rank, nprocs, local = multihost.process_info()
    assert (rank, nprocs) == (pid, n)
    assert jax.device_count() == local * n

    mesh = multihost.build_global_mesh(sp=1, tp=2)
    local_dp = local // 2
    assert mesh.shape == {"dp": n * local_dp, "sp": 1, "ep": 1, "tp": 2}, mesh.shape
    # every host must see the same global sum: sum over ranks of
    # (rank+1) * local_dp
    total = multihost.cross_host_allreduce_check(mesh)
    expect = sum((r + 1) * local_dp for r in range(n))
    assert total == expect, (total, expect)

    from aios_tpu.engine import model
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.train import make_optimizer, make_train_step
    from aios_tpu.parallel.sharding import ShardingPlan

    plan = ShardingPlan(mesh)
    params = model.init_params(TINY_TEST, jax.random.PRNGKey(0), jnp.float32)
    init_state, train_step = make_train_step(
        TINY_TEST, mesh, optimizer=make_optimizer(1, 10)
    )
    state = init_state(plan.put_params(params))
    B = n * local_dp * 2  # 2 rows per dp shard
    rows = B // n
    rng = np.random.default_rng(0)  # same stream on every rank
    gtok = rng.integers(0, TINY_TEST.vocab_size, (B, 16)).astype(np.int32)
    sh = NamedSharding(mesh, P("dp"))
    batch = {
        "tokens": jax.make_array_from_process_local_data(
            sh, gtok[pid * rows : (pid + 1) * rows]
        ),
        "loss_mask": jax.make_array_from_process_local_data(
            sh, np.ones((rows, 16), np.float32)
        ),
    }
    state, metrics = jax.jit(train_step)(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    print(f"WORKER_OK {pid} allreduce={total:.1f} loss={loss:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
