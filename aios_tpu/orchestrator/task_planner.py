"""Goal -> task-DAG decomposition with the intelligence hierarchy.

Reference parity (agent-core/src/task_planner.rs):
  * classify_complexity keyword rules -> Reactive / Operational / Tactical /
    Strategic (task_planner.rs:493-546);
  * Reactive/Operational goals become a single task (549-598);
  * Tactical/Strategic goals get AI decomposition — api-gateway first, then
    runtime fallback — prompted to emit a JSON array of steps (117-223),
    parsed with <think>-tag stripping and markdown-fence extraction
    (226-353), then chained linearly via depends_on (313-341);
  * keyword multi-step fallbacks for restart/security/install/network goals
    when the AI path is unavailable (357-490);
  * infer_required_tools keyword -> tool-namespace map (601-676).
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Callable, List, Optional

from .goal_engine import Goal, Task

# ---------------------------------------------------------------------------
# Intelligence levels
# ---------------------------------------------------------------------------

REACTIVE = "reactive"
OPERATIONAL = "operational"
TACTICAL = "tactical"
STRATEGIC = "strategic"

_STRATEGIC_KW = (
    "design", "architect", "plan ", "migrate", "overhaul", "refactor",
    "build a", "create a system", "set up a", "deploy a", "research",
    "analyze and", "optimize the whole", "harden",
)
_TACTICAL_KW = (
    "investigate", "diagnose", "troubleshoot", "fix", "configure",
    "install and", "secure", "audit", "backup and", "update all",
    "clean up", "optimize", "scan",
)
_REACTIVE_KW = (
    "ping", "check cpu", "check memory", "check disk", "uptime", "status of",
    "list ", "show ", "read ", "get ",
)


def classify_complexity(description: str) -> str:
    """Keyword ladder, most-complex match wins (task_planner.rs:493-546)."""
    low = description.lower()
    if any(k in low for k in _STRATEGIC_KW):
        return STRATEGIC
    if any(k in low for k in _TACTICAL_KW):
        return TACTICAL
    if any(k in low for k in _REACTIVE_KW):
        return REACTIVE
    return OPERATIONAL


# ---------------------------------------------------------------------------
# Tool inference
# ---------------------------------------------------------------------------

_TOOL_KEYWORDS = [
    (("file", "directory", "folder", "read", "write", "disk space"), "fs"),
    (("process", "pid", "running"), "process"),
    (("service", "daemon", "systemd", "restart", "nginx", "sshd"), "service"),
    (("network", "ping", "dns", "connectivity", "interface", "port"), "net"),
    (("firewall", "nftables", "iptables", "block ip"), "firewall"),
    (("package", "install", "apt", "upgrade", "update"), "pkg"),
    (("security", "audit", "permission", "rootkit", "cert", "tls",
      "intrusion"), "sec"),
    (("cpu", "memory", "monitor", "metric", "log", "usage"), "monitor"),
    (("hardware", "device"), "hw"),
    (("http", "url", "website", "scrape", "download", "webhook", "api"), "web"),
    (("git", "repository", "commit", "clone"), "git"),
    (("scaffold", "generate code", "new project"), "code"),
    (("container", "podman", "docker"), "container"),
    (("email", "mail", "notify"), "email"),
    (("plugin",), "plugin"),
]


def infer_required_tools(description: str) -> List[str]:
    """Keyword -> tool-namespace map (task_planner.rs:601-676).

    Whole-word matching: plain substring matching misfires ("port" inside
    "report", "install" inside "reinstallation").
    """
    low = description.lower()
    namespaces = []
    for keywords, namespace in _TOOL_KEYWORDS:
        hit = any(
            re.search(r"\b" + re.escape(k) + r"\b", low) for k in keywords
        )
        if hit and namespace not in namespaces:
            namespaces.append(namespace)
    return namespaces


# ---------------------------------------------------------------------------
# AI response parsing
# ---------------------------------------------------------------------------


def strip_think_tags(text: str) -> str:
    """Remove <think>...</think> reasoning blocks (task_planner.rs:226-250)."""
    return re.sub(r"<think>.*?</think>", "", text, flags=re.S).strip()


def extract_json_array(text: str) -> Optional[list]:
    """JSON array from raw text, markdown fences, or embedded brackets."""
    text = strip_think_tags(text)
    candidates = [text]
    fence = re.search(r"```(?:json)?\s*(.*?)```", text, flags=re.S)
    if fence:
        candidates.insert(0, fence.group(1))
    bracket = re.search(r"\[.*\]", text, flags=re.S)
    if bracket:
        candidates.append(bracket.group(0))
    for cand in candidates:
        try:
            parsed = json.loads(cand.strip())
            if isinstance(parsed, list):
                return parsed
        except ValueError:
            continue
    return None


DECOMPOSE_PROMPT = """\
Decompose this goal into a short ordered list of concrete system tasks.

Goal: {goal}

Respond with ONLY a JSON array, each element:
{{"description": "...", "required_tools": ["namespace", ...]}}
Use tool namespaces from: fs, process, service, net, firewall, pkg, sec,
monitor, hw, web, git, code, container, email, plugin. 2-6 tasks.
"""


# ---------------------------------------------------------------------------
# Keyword multi-step fallbacks (task_planner.rs:357-490)
# ---------------------------------------------------------------------------


def _fallback_steps(description: str) -> List[dict]:
    low = description.lower()
    if "restart" in low and ("service" in low or "nginx" in low or "daemon" in low):
        return [
            {"description": f"Check status before restart: {description}",
             "required_tools": ["service"]},
            {"description": f"Restart the service: {description}",
             "required_tools": ["service"]},
            {"description": "Verify the service is healthy after restart",
             "required_tools": ["service", "monitor"]},
        ]
    if any(k in low for k in ("security", "audit", "harden", "intrusion")):
        return [
            {"description": "Scan for open ports and listening services",
             "required_tools": ["sec", "net"]},
            {"description": "Check file permissions and setuid binaries",
             "required_tools": ["sec", "fs"]},
            {"description": "Run rootkit indicators scan",
             "required_tools": ["sec"]},
            {"description": "Summarize security findings",
             "required_tools": ["monitor"]},
        ]
    if "install" in low:
        return [
            {"description": f"Search for the package: {description}",
             "required_tools": ["pkg"]},
            {"description": f"Install: {description}",
             "required_tools": ["pkg"]},
            {"description": "Verify installation", "required_tools": ["pkg"]},
        ]
    if any(k in low for k in ("network", "connectivity", "dns")):
        return [
            {"description": "List network interfaces and their state",
             "required_tools": ["net"]},
            {"description": "Test external connectivity (ping/dns)",
             "required_tools": ["net"]},
            {"description": "Summarize network diagnosis",
             "required_tools": ["monitor"]},
        ]
    return [{"description": description,
             "required_tools": infer_required_tools(description)}]


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


class TaskPlanner:
    """Decomposes goals; AI backends are injected as callables so the
    planner is testable without live services (mirrors the reference's
    gateway-then-runtime chain, task_planner.rs:143-223)."""

    def __init__(
        self,
        gateway_infer: Optional[Callable[[str], str]] = None,
        runtime_infer: Optional[Callable[[str], str]] = None,
    ):
        self.gateway_infer = gateway_infer
        self.runtime_infer = runtime_infer

    def _try_ai_decompose(self, goal: Goal) -> Optional[List[dict]]:
        prompt = DECOMPOSE_PROMPT.format(goal=goal.description)
        for backend in (self.gateway_infer, self.runtime_infer):
            if backend is None:
                continue
            try:
                raw = backend(prompt)
            except Exception:  # noqa: BLE001 — backend down, try next
                continue
            steps = extract_json_array(raw)
            if steps:
                cleaned = []
                for s in steps[:8]:
                    if isinstance(s, dict) and s.get("description"):
                        cleaned.append(
                            {
                                "description": str(s["description"]),
                                "required_tools": [
                                    str(t) for t in s.get("required_tools", [])
                                ],
                            }
                        )
                    elif isinstance(s, str):
                        cleaned.append(
                            {"description": s,
                             "required_tools": infer_required_tools(s)}
                        )
                if cleaned:
                    return cleaned
        return None

    def decompose_goal(self, goal: Goal) -> List[Task]:
        """Goal -> ordered task list with linear depends_on chaining."""
        level = classify_complexity(goal.description)

        if level in (REACTIVE, OPERATIONAL):
            steps = [
                {
                    "description": goal.description,
                    "required_tools": infer_required_tools(goal.description),
                }
            ]
        else:
            steps = self._try_ai_decompose(goal) or _fallback_steps(
                goal.description
            )

        tasks: List[Task] = []
        prev_id: Optional[str] = None
        for step in steps:
            task = Task(
                id=str(uuid.uuid4()),
                goal_id=goal.id,
                description=step["description"],
                intelligence_level=level,
                required_tools=step.get("required_tools", []),
                depends_on=[prev_id] if prev_id else [],
            )
            tasks.append(task)
            prev_id = task.id
        return tasks
