"""Serving layer: replica pools, cache-aware routing, admission control.

Sits between the runtime gRPC service and the decode engines —
``RuntimeService`` talks to a :class:`ReplicaPool` per managed model;
the pool routes each request to the replica most likely to hold its
prompt prefix (SGLang-style cache-aware routing, arXiv:2312.07104) and
sheds work a saturated pool cannot serve inside its deadline
(RTP-LLM-style admission, arXiv:2605.29639). See docs/SERVING.md.
"""

from .admission import AdmissionController, AdmissionError, TokenBucket, tenant_of
from .autoscale import AutoscaleConfig, AutoscaleController
from .config import ServingConfig
from .failover import FailoverHandle
from .pool import Replica, ReplicaPool
from .router import Router

__all__ = [
    "AdmissionController",
    "AdmissionError",
    "AutoscaleConfig",
    "AutoscaleController",
    "FailoverHandle",
    "Replica",
    "ReplicaPool",
    "Router",
    "ServingConfig",
    "TokenBucket",
    "tenant_of",
]
