"""Goal lifecycle engine with SQLite persistence and crash recovery.

Reference parity (agent-core/src/goal_engine.rs):
  * goal states pending -> planning -> in_progress -> completed/failed/
    cancelled; task states pending/assigned/in_progress/completed/failed;
  * in-memory cache + SQLite WAL persistence (tables goals/tasks/messages,
    goal_engine.rs:48-97) at a configurable path;
  * per-goal conversation threads (GoalMessage, goal_engine.rs:17-23) for
    the awaiting_input flow;
  * crash recovery: on restart, in_progress tasks reset to pending and
    unfinished goals reload into the planner (get_all_resumable_tasks,
    goal_engine.rs:493-518);
  * progress = fraction of completed tasks (goal_engine.rs:272-286).
"""

from __future__ import annotations

import json
import logging
import sqlite3
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger("aios.goals")

GOAL_STATES = ("pending", "planning", "in_progress", "completed", "failed",
               "cancelled")
TASK_STATES = ("pending", "assigned", "in_progress", "completed", "failed",
               "cancelled")
TERMINAL_GOAL = ("completed", "failed", "cancelled")
TERMINAL_TASK = ("completed", "failed", "cancelled")


def _now() -> int:
    return int(time.time())


@dataclass
class Goal:
    id: str
    description: str
    priority: int = 5
    source: str = "user"
    status: str = "pending"
    created_at: int = field(default_factory=_now)
    updated_at: int = field(default_factory=_now)
    tags: List[str] = field(default_factory=list)
    metadata: Dict = field(default_factory=dict)


@dataclass
class Task:
    id: str
    goal_id: str
    description: str
    assigned_agent: str = ""
    status: str = "pending"
    intelligence_level: str = "operational"
    required_tools: List[str] = field(default_factory=list)
    depends_on: List[str] = field(default_factory=list)
    input: Dict = field(default_factory=dict)
    output: Dict = field(default_factory=dict)
    created_at: int = field(default_factory=_now)
    started_at: int = 0
    completed_at: int = 0
    error: str = ""


@dataclass
class GoalMessage:
    goal_id: str
    role: str  # user | assistant | system
    content: str
    timestamp: int = field(default_factory=_now)


_SCHEMA = """
CREATE TABLE IF NOT EXISTS goals (
    id TEXT PRIMARY KEY, description TEXT, priority INTEGER, source TEXT,
    status TEXT, created_at INTEGER, updated_at INTEGER, tags TEXT,
    metadata TEXT
);
CREATE TABLE IF NOT EXISTS tasks (
    id TEXT PRIMARY KEY, goal_id TEXT, description TEXT, assigned_agent TEXT,
    status TEXT, intelligence_level TEXT, required_tools TEXT, depends_on TEXT,
    input TEXT, output TEXT, created_at INTEGER, started_at INTEGER,
    completed_at INTEGER, error TEXT
);
CREATE TABLE IF NOT EXISTS messages (
    seq INTEGER PRIMARY KEY AUTOINCREMENT, goal_id TEXT, role TEXT,
    content TEXT, timestamp INTEGER
);
CREATE INDEX IF NOT EXISTS idx_tasks_goal ON tasks(goal_id);
CREATE INDEX IF NOT EXISTS idx_messages_goal ON messages(goal_id);
"""


class GoalEngine:
    """In-memory cache over SQLite; all mutations write through."""

    def __init__(self, db_path: str = ":memory:"):
        self._conn = sqlite3.connect(db_path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.executescript(_SCHEMA)
        self._lock = threading.RLock()
        self.goals: Dict[str, Goal] = {}
        self.tasks: Dict[str, Task] = {}
        self._load()

    # -- persistence --------------------------------------------------------

    def _load(self) -> None:
        with self._lock:
            for row in self._conn.execute(
                "SELECT id, description, priority, source, status, created_at,"
                " updated_at, tags, metadata FROM goals"
            ):
                self.goals[row[0]] = Goal(
                    id=row[0], description=row[1], priority=row[2],
                    source=row[3], status=row[4], created_at=row[5],
                    updated_at=row[6], tags=json.loads(row[7] or "[]"),
                    metadata=json.loads(row[8] or "{}"),
                )
            for row in self._conn.execute(
                "SELECT id, goal_id, description, assigned_agent, status,"
                " intelligence_level, required_tools, depends_on, input,"
                " output, created_at, started_at, completed_at, error FROM tasks"
            ):
                self.tasks[row[0]] = Task(
                    id=row[0], goal_id=row[1], description=row[2],
                    assigned_agent=row[3], status=row[4],
                    intelligence_level=row[5],
                    required_tools=json.loads(row[6] or "[]"),
                    depends_on=json.loads(row[7] or "[]"),
                    input=json.loads(row[8] or "{}"),
                    output=json.loads(row[9] or "{}"),
                    created_at=row[10], started_at=row[11],
                    completed_at=row[12], error=row[13],
                )

    def _persist_goal(self, g: Goal) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO goals VALUES (?,?,?,?,?,?,?,?,?)",
            (g.id, g.description, g.priority, g.source, g.status, g.created_at,
             g.updated_at, json.dumps(g.tags), json.dumps(g.metadata)),
        )
        self._conn.commit()

    def _persist_task(self, t: Task) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO tasks VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (t.id, t.goal_id, t.description, t.assigned_agent, t.status,
             t.intelligence_level, json.dumps(t.required_tools),
             json.dumps(t.depends_on), json.dumps(t.input),
             json.dumps(t.output), t.created_at, t.started_at, t.completed_at,
             t.error),
        )
        self._conn.commit()

    # -- goals --------------------------------------------------------------

    def submit_goal(
        self,
        description: str,
        priority: int = 5,
        source: str = "user",
        tags: Optional[List[str]] = None,
        metadata: Optional[Dict] = None,
    ) -> Goal:
        goal = Goal(
            id=str(uuid.uuid4()),
            description=description,
            priority=priority,
            source=source,
            tags=tags or [],
            metadata=metadata or {},
        )
        with self._lock:
            self.goals[goal.id] = goal
            self._persist_goal(goal)
        return goal

    def set_goal_status(self, goal_id: str, status: str) -> None:
        assert status in GOAL_STATES, status
        with self._lock:
            g = self.goals.get(goal_id)
            if g is None:
                return
            if g.status in TERMINAL_GOAL:
                # terminal goals are final: CancelGoal can land during the
                # planner's slow AI decomposition, and the subsequent
                # add_tasks -> "in_progress" write must not resurrect the
                # cancelled goal (its tasks would start dispatching)
                log.info(
                    "ignoring %s -> %s for terminal goal %s",
                    g.status, status, goal_id,
                )
                return
            g.status = status
            g.updated_at = _now()
            self._persist_goal(g)

    def is_abandoned(self, task_id: str, goal_id: str) -> bool:
        """True when the goal or the task reached a terminal state — the
        signal a long-running executor (the reasoning loop) checks between
        rounds to stop working for a dead goal."""
        with self._lock:
            g = self.goals.get(goal_id)
            t = self.tasks.get(task_id)
        if g is not None and g.status in TERMINAL_GOAL:
            return True
        return t is not None and t.status in TERMINAL_TASK

    def cancel_goal(self, goal_id: str) -> bool:
        with self._lock:
            g = self.goals.get(goal_id)
            if g is None or g.status in TERMINAL_GOAL:
                return False
            g.status = "cancelled"
            g.updated_at = _now()
            self._persist_goal(g)
            for t in self.tasks_for_goal(goal_id):
                if t.status not in TERMINAL_TASK:
                    t.status = "cancelled"
                    self._persist_task(t)
            return True

    def list_goals(
        self, status_filter: str = "", limit: int = 100, offset: int = 0
    ) -> List[Goal]:
        with self._lock:
            goals = sorted(
                self.goals.values(), key=lambda g: g.created_at, reverse=True
            )
        if status_filter:
            goals = [g for g in goals if g.status == status_filter]
        return goals[offset : offset + limit]

    def active_goals(self) -> List[Goal]:
        with self._lock:
            return [
                g for g in self.goals.values() if g.status not in TERMINAL_GOAL
            ]

    def set_metadata(self, goal_id: str, key: str, value) -> None:
        with self._lock:
            g = self.goals.get(goal_id)
            if g is None:
                return
            g.metadata[key] = value
            self._persist_goal(g)

    def progress(self, goal_id: str) -> float:
        tasks = self.tasks_for_goal(goal_id)
        if not tasks:
            return 0.0
        done = sum(1 for t in tasks if t.status == "completed")
        return done / len(tasks) * 100.0

    # -- tasks --------------------------------------------------------------

    def add_tasks(self, goal_id: str, tasks: List[Task]) -> None:
        with self._lock:
            goal = self.goals.get(goal_id)
            dead = goal is not None and goal.status in TERMINAL_GOAL
            for t in tasks:
                if dead:
                    # the goal was cancelled while the planner decomposed
                    # it: record its tasks as cancelled, not as pending
                    # strays under a terminal goal
                    t.status = "cancelled"
                    t.completed_at = _now()
                self.tasks[t.id] = t
                self._persist_task(t)
            if goal is not None and tasks and not dead:
                self.set_goal_status(goal_id, "in_progress")

    def tasks_for_goal(self, goal_id: str) -> List[Task]:
        with self._lock:
            return sorted(
                (t for t in self.tasks.values() if t.goal_id == goal_id),
                key=lambda t: t.created_at,
            )

    def set_task_status(
        self, task_id: str, status: str, error: str = "",
        output: Optional[Dict] = None, agent: str = "",
    ) -> None:
        assert status in TASK_STATES, status
        with self._lock:
            t = self.tasks.get(task_id)
            if t is None:
                return
            if t.status in TERMINAL_TASK:
                # terminal states are final — name AND payload: a late or
                # duplicate ReportTaskResult (agent retry after a dropped
                # response) must neither resurrect a cancelled task nor
                # overwrite the first report's output/error/completed_at
                log.info(
                    "ignoring %s -> %s for terminal task %s",
                    t.status, status, task_id,
                )
                return
            t.status = status
            if agent:
                t.assigned_agent = agent
            if status == "in_progress" and not t.started_at:
                t.started_at = _now()
            if status in TERMINAL_TASK:
                t.completed_at = _now()
            if error:
                t.error = error
            if output is not None:
                t.output = output
            self._persist_task(t)

    def complete_task(self, task_id: str, output: Optional[Dict] = None) -> None:
        self.set_task_status(task_id, "completed", output=output)

    def unblocked_pending_tasks(self, limit: int = 3) -> List[Task]:
        """Pending tasks whose dependencies are all completed, priority order
        (task_planner.rs next_tasks:755-768)."""
        with self._lock:
            out = []
            for t in self.tasks.values():
                if t.status != "pending":
                    continue
                goal = self.goals.get(t.goal_id)
                if goal is None or goal.status in TERMINAL_GOAL:
                    continue
                deps_done = all(
                    self.tasks.get(d) is not None
                    and self.tasks[d].status == "completed"
                    for d in t.depends_on
                )
                if deps_done:
                    out.append(t)
            out.sort(
                key=lambda t: (
                    -(self.goals[t.goal_id].priority if t.goal_id in self.goals else 0),
                    t.created_at,
                )
            )
            return out[:limit]

    def check_goal_completion(self, goal_id: str) -> Optional[str]:
        """completed when all tasks done; failed if any task failed
        (autonomy.rs:709-733 housekeeping)."""
        tasks = self.tasks_for_goal(goal_id)
        if not tasks:
            return None
        if any(t.status == "failed" for t in tasks):
            self.set_goal_status(goal_id, "failed")
            return "failed"
        if all(t.status == "completed" for t in tasks):
            self.set_goal_status(goal_id, "completed")
            return "completed"
        return None

    # -- conversation threads ----------------------------------------------

    def add_message(self, goal_id: str, role: str, content: str) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO messages (goal_id, role, content, timestamp)"
                " VALUES (?,?,?,?)",
                (goal_id, role, content, _now()),
            )
            self._conn.commit()

    def messages_for_goal(self, goal_id: str, limit: int = 50) -> List[GoalMessage]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT goal_id, role, content, timestamp FROM messages"
                " WHERE goal_id=? ORDER BY seq DESC LIMIT ?",
                (goal_id, limit),
            ).fetchall()
        return [GoalMessage(*r) for r in reversed(rows)]

    def count_messages(self, goal_id: str, role: str = "") -> int:
        with self._lock:
            if role:
                row = self._conn.execute(
                    "SELECT COUNT(*) FROM messages WHERE goal_id=? AND role=?",
                    (goal_id, role),
                ).fetchone()
            else:
                row = self._conn.execute(
                    "SELECT COUNT(*) FROM messages WHERE goal_id=?", (goal_id,)
                ).fetchone()
        return row[0]

    # -- crash recovery -----------------------------------------------------

    def recover(self) -> int:
        """in_progress/assigned tasks -> pending on restart
        (goal_engine.rs:493-518)."""
        n = 0
        with self._lock:
            for t in self.tasks.values():
                if t.status in ("in_progress", "assigned"):
                    t.status = "pending"
                    t.assigned_agent = ""
                    self._persist_task(t)
                    n += 1
        return n
