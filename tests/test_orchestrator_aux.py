"""Orchestrator auxiliaries: scheduler/cron, event bus, cluster, telemetry.

Pure-state tests mirroring the reference's inline module tests
(scheduler.rs:228-256, cluster.rs:161-214, event_bus.rs, decision_logger.rs).
"""

import time

from aios_tpu.orchestrator.cluster import ClusterManager, ClusterNode
from aios_tpu.orchestrator.event_bus import Event, EventBus, Subscription
from aios_tpu.orchestrator.scheduler import GoalScheduler, matches_cron
from aios_tpu.orchestrator.telemetry import (
    Decision,
    DecisionLogger,
    ResultAggregator,
    TaskOutcome,
)


# ---------------------------------------------------------------------------
# Cron matcher (scheduler.rs:186-226)
# ---------------------------------------------------------------------------


def _t(minute=0, hour=0, mday=1, mon=1, wday=0):
    return time.struct_time((2026, mon, mday, hour, minute, 0, wday, 1, -1))


def test_cron_wildcards_and_values():
    assert matches_cron("* * * * *", _t())
    assert matches_cron("30 14 * * *", _t(minute=30, hour=14))
    assert not matches_cron("30 14 * * *", _t(minute=31, hour=14))


def test_cron_steps_and_lists():
    assert matches_cron("*/15 * * * *", _t(minute=45))
    assert not matches_cron("*/15 * * * *", _t(minute=46))
    assert matches_cron("0,30 * * * *", _t(minute=30))
    assert matches_cron("* * * * 0,4", _t(wday=4))
    assert matches_cron("0 9-17 * * *", _t(hour=12))
    assert not matches_cron("0 9-17 * * *", _t(hour=8))
    assert not matches_cron("bad cron", _t())


def test_scheduler_fires_and_debounces(tmp_db_path):
    fired = []
    s = GoalScheduler(lambda desc, prio: fired.append((desc, prio)),
                      db_path=tmp_db_path)
    sid = s.create("* * * * *", "periodic health sweep", priority=3)
    assert s.tick() == 1
    assert fired == [("periodic health sweep", 3)]
    assert s.tick() == 0  # same minute -> debounced via last_run
    assert len(s.list()) == 1
    assert s.delete(sid)
    assert s.tick() == 0


# ---------------------------------------------------------------------------
# Event bus
# ---------------------------------------------------------------------------


def test_event_bus_goal_creation_with_substitution():
    goals = []
    bus = EventBus(submit_goal=lambda d, p: goals.append((d, p)))
    bus.subscribe(Subscription(
        pattern="service.*",
        min_severity="error",
        goal_template="remediate {event_type} from {source}",
        priority=8,
    ))
    bus.publish(Event("service.crashed", "health-checker", severity="error"))
    bus.publish(Event("service.started", "init", severity="info"))  # below sev
    bus.publish(Event("disk.full", "monitor", severity="critical"))  # no match
    assert goals == [("remediate service.crashed from health-checker", 8)]
    assert bus.published == 3
    assert len(bus.recent_events()) == 3


def test_event_bus_callback_subscription():
    seen = []
    bus = EventBus()
    bus.subscribe(Subscription(pattern="*", callback=seen.append))
    bus.publish(Event("anything.goes", "test"))
    assert len(seen) == 1 and seen[0].event_type == "anything.goes"


# ---------------------------------------------------------------------------
# Cluster manager (cluster.rs:161-214)
# ---------------------------------------------------------------------------


def test_cluster_least_loaded_routing():
    c = ClusterManager()
    c.register(ClusterNode("n1", "host1", "10.0.0.1:50051", max_tasks=10))
    c.register(ClusterNode("n2", "host2", "10.0.0.2:50051", max_tasks=10))
    c.heartbeat("n1", cpu=80.0, memory=50.0, active_tasks=8)
    c.heartbeat("n2", cpu=20.0, memory=30.0, active_tasks=1)
    assert c.least_loaded().node_id == "n2"


def test_cluster_dead_node_pruning():
    c = ClusterManager()
    n = ClusterNode("n1", "h", "a:1")
    c.register(n)
    assert c.nodes() and not c.prune_dead()
    n.last_heartbeat -= 60  # exceed the 30 s timeout
    assert c.nodes() == []
    assert c.prune_dead() == ["n1"]


def test_cluster_full_nodes_not_routable():
    c = ClusterManager()
    c.register(ClusterNode("n1", "h", "a:1", max_tasks=2))
    c.heartbeat("n1", cpu=10, memory=10, active_tasks=2)
    assert c.least_loaded() is None


# ---------------------------------------------------------------------------
# Telemetry
# ---------------------------------------------------------------------------


def test_result_aggregator_summary():
    agg = ResultAggregator()
    agg.record("g1", TaskOutcome("t1", True, tokens_used=100,
                                 duration_ms=50, model_used="tinyllama"))
    agg.record("g1", TaskOutcome("t2", False, error="x", tokens_used=20,
                                 duration_ms=10, model_used="mistral"))
    s = agg.summary("g1")
    assert s.total_tasks == 2 and s.succeeded == 1 and s.failed == 1
    assert s.total_tokens == 120
    assert s.models_used == ["tinyllama", "mistral"]


def test_decision_logger_ring_and_success_rate():
    d = DecisionLogger(capacity=5)
    for i in range(8):
        d.log(Decision(context=f"c{i}", options=["a", "b"], chosen="a",
                       reasoning="r", outcome="success" if i % 2 else "failure"))
    assert len(d) == 5  # ring bounded
    rate = d.success_rate()
    assert rate is not None and 0.0 <= rate <= 1.0
    assert d.success_rate("no-such-context") is None


# ---------------------------------------------------------------------------
# Proactive serving escalations (VERDICT r3 item 7: engine.stats counters
# -> remediation goals, mirroring proactive.rs:144-159's health->goal path)
# ---------------------------------------------------------------------------


def _proactive(stats_fn):
    from aios_tpu.orchestrator.proactive import (
        ProactiveConfig,
        ProactiveGenerator,
    )

    goals = []
    gen = ProactiveGenerator(
        submit_goal=lambda d, p: goals.append((d, p)),
        active_goal_descriptions=lambda: [d for d, _ in goals],
        serving_stats=stats_fn,
        # thresholds nothing on this box can trip, so only the serving
        # rules fire
        config=ProactiveConfig(
            cpu_threshold=1000, memory_threshold=1000, disk_threshold=1000,
            cert_dir="/nonexistent", backup_dir="/nonexistent",
        ),
    )
    return gen, goals


def test_starved_pool_yields_remediation_goal():
    """Two consecutive starved passes (all slots busy + queued requests)
    create ONE slot-starvation goal; a recovered pass resets the count."""
    stats = {"tinyllama": {"active_slots": 8, "num_slots": 8, "waiting": 3}}
    gen, goals = _proactive(lambda: stats)
    assert gen.check_once() == []          # pass 1: armed, no goal yet
    assert gen.check_once() == ["starvation:tinyllama"]
    assert any("starvation" in d for d, _ in goals)
    assert goals[0][1] == 7
    # active goal dedupe: a third starved pass does not re-submit
    assert gen.check_once() == []
    # recovery resets the consecutive counter
    stats["tinyllama"]["waiting"] = 0
    gen.check_once()
    assert gen._starved_passes["tinyllama"] == 0


def test_pool_eviction_growth_yields_goal():
    """pool_evictions increasing between passes (live streams truncated to
    admit new work) creates a pool-exhaustion goal; a stable count does
    not re-fire."""
    stats = {"mistral": {"pool_evictions": 0, "active_slots": 1,
                         "num_slots": 8, "waiting": 0}}
    gen, goals = _proactive(lambda: stats)
    assert gen.check_once() == []          # baseline recorded
    stats["mistral"]["pool_evictions"] = 2
    assert gen.check_once() == ["pool:mistral"]
    assert any("page-pool exhaustion" in d for d, _ in goals)
    assert goals[0][1] == 8
    assert gen.check_once() == []          # stable count: no new goal


def test_pool_eviction_history_is_baseline_not_alarm():
    """pool_evictions is cumulative since RUNTIME start: a fresh
    orchestrator seeing days-old evictions records the baseline instead
    of paging anyone; two models escalate independently (the dedupe key
    includes the model name, not a 40-char shared prefix)."""
    stats = {"a-model": {"pool_evictions": 50, "active_slots": 0,
                         "num_slots": 8, "waiting": 0},
             "b-model": {"pool_evictions": 7, "active_slots": 0,
                         "num_slots": 8, "waiting": 0}}
    gen, goals = _proactive(lambda: stats)
    assert gen.check_once() == []          # history -> baseline only
    stats["a-model"]["pool_evictions"] = 51
    assert gen.check_once() == ["pool:a-model"]
    stats["b-model"]["pool_evictions"] = 9
    # a-model's active goal must NOT suppress b-model's escalation
    assert gen.check_once() == ["pool:b-model"]


def test_serving_stats_failure_is_silent():
    """A runtime that is down is the health checker's escalation, not a
    serving-rule crash."""
    def boom():
        raise RuntimeError("runtime unreachable")

    gen, goals = _proactive(boom)
    assert gen.check_once() == []
    assert goals == []
