#!/usr/bin/env bash
# Launch the full aiOS-TPU stack via the boot supervisor (foreground).
#
# TPU-native equivalent of /root/reference/scripts/run-qemu.sh: the reference
# boots its ISO in QEMU; here the five services boot as supervised host
# processes on the TPU VM (aios_tpu/boot/supervisor.py — topo order, health
# gates, restart caps).
#
# Usage: scripts/run-aios.sh [--data-dir DIR] [--model-dir DIR] [--cpu]
#
# Multi-host (one invocation per TPU-VM host; the runtimes join one JAX
# process group and serve over a single global mesh — dp across hosts on
# DCN, sp/tp inside each host on ICI; aios_tpu/parallel/multihost.py):
#   AIOS_TPU_COORDINATOR=host0:8476 AIOS_TPU_NUM_PROCESSES=4 \
#   AIOS_TPU_PROCESS_ID=$RANK scripts/run-aios.sh
# (on Cloud TPU pods set just AIOS_TPU_MULTIHOST=auto — the topology
#  self-describes and jax.distributed.initialize() needs no arguments)
set -euo pipefail

REPO_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
export PYTHONPATH="$REPO_DIR${PYTHONPATH:+:$PYTHONPATH}"

while [[ $# -gt 0 ]]; do
  case "$1" in
    --data-dir) export AIOS_DATA_DIR="$2"; shift 2 ;;
    --model-dir) export AIOS_MODEL_DIR="$2"; shift 2 ;;
    --cpu) export JAX_PLATFORMS=cpu; shift ;;
    *) echo "unknown flag: $1" >&2; exit 2 ;;
  esac
done

cd "$REPO_DIR"
exec "${PYTHON:-python3}" -m aios_tpu.boot.supervisor
