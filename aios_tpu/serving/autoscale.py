"""SLO-burn-driven autoscaling + degrade ladder for a ReplicaPool.

Closes the loop the observability PRs opened: the SLO engine (obs/slo.py)
already computes windowed burn rates per objective and the devprof ledger
(obs/devprof.py) already attributes device-seconds per replica — this
controller is the first consumer. Policy (RTP-LLM-style load-aware
engine management, PAPERS.md):

  * **scale out first** — while the pool is below the configured replica
    ceiling and an engine factory is attached, sustained burn adds a
    replica (reusing the pool's spawn lifecycle; the new replica starts
    cold and picks up overflow via least-loaded routing). When devprof
    is armed, the measured device-seconds-per-replica between ticks is
    the capacity denominator: per-replica utilization rides every action
    event so an operator can see whether the pool was actually
    compute-bound when the controller acted.
  * **degrade below the ceiling** — at the ceiling (or with no factory)
    the controller walks a deterministic ladder of optional-work sheds:
    rung 1 speculation off, rung 2 grammar jump-ahead off, rung 3 shed
    best-effort admissions (priority < 1; the reactive/operational tiers
    stay protected, and the batcher's priority-aware slot admission +
    pool-pressure eviction keep preempting in their favor). Every rung
    is token-identical for greedy streams by construction, so a ladder
    transition never perturbs an in-flight stream.
  * **hysteresis + cooldown** — an action needs ``hold_ticks``
    consecutive over/under-threshold evaluations AND ``cooldown_secs``
    since the previous action, so the controller cannot flap on a noisy
    window. Recovery walks the ladder back BEFORE scaling in (restoring
    work is free; giving up a replica is not).
  * **kill switch** — ``AIOS_TPU_AUTOSCALE_KILL=1`` (checked every
    tick) restores the pool to healthy and freezes the controller; the
    operator override documented in docs/RUNBOOK.md §8.

Every action increments the closed-enum
``aios_tpu_autoscale_actions_total{action,cause}`` family (children
pre-registered by iterating ACTIONS x CAUSES) and lands on the flight
recorder's model lane as an ``autoscale`` event with the evidence the
decision was made on (burn, level, replicas, utilization).
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ..analysis.locks import make_lock
from ..obs import flightrec
from ..obs import incidents as incidents_mod
from ..obs import instruments as obs
from ..obs import slo as slo_mod
from ..obs import tsdb as tsdb_mod

log = logging.getLogger("aios.serving")

# Closed enums — the only values the metric family's ``action`` and
# ``cause`` labels may carry (tests/test_obs_lint.py pins every call
# site and that registration iterates the tuples).
ACTIONS = ("scale_up", "scale_down", "degrade", "restore")
CAUSES = ("burn", "ceiling", "recovery", "kill_switch")

# The degrade ladder, in escalation order (pool.set_degrade_level maps
# rung index -> mechanism; docs/RUNBOOK.md §8 documents the order).
LADDER = ("spec_off", "jump_off", "shed_best_effort")

_MAX_JOURNAL = 256  # bounded action journal (state()/bench evidence)


def _env_float(name: str, default: float, minimum: float = 0.0) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
        if v < minimum:
            raise ValueError(f"must be >= {minimum}")
        return v
    except ValueError as exc:
        log.warning("%s=%r ignored (%s); using %s", name, raw, exc, default)
        return default


def enabled() -> bool:
    """Whether AIOS_TPU_AUTOSCALE arms a controller per loaded pool
    (read by ModelManager.load_model)."""
    return os.environ.get("AIOS_TPU_AUTOSCALE", "").lower() in (
        "1", "on", "true", "yes",
    )


def kill_switch() -> bool:
    """AIOS_TPU_AUTOSCALE_KILL=1: restore the pool and freeze the
    controller (checked every tick, so an operator can flip it on a
    live deployment without a restart)."""
    return os.environ.get("AIOS_TPU_AUTOSCALE_KILL", "").lower() in (
        "1", "on", "true", "yes",
    )


@dataclass(frozen=True)
class AutoscaleConfig:
    """Controller policy, read once at attach (the serving-config
    convention: a running controller's policy is immutable; the kill
    switch is the only live override)."""

    # replica ceiling the controller may scale up to (>= the pool's
    # starting size; scale-down never goes below the starting size)
    max_replicas: int = 4
    # control-loop period (the background thread's tick interval)
    interval_secs: float = 5.0
    # burn-rate thresholds: escalate when the worst watched objective
    # burns above up_burn for hold_ticks consecutive ticks; recover when
    # it stays below down_burn as long. 1.0 = burning exactly at the
    # error budget.
    up_burn: float = 1.0
    down_burn: float = 0.25
    hold_ticks: int = 2
    # minimum seconds between actions (flap damping on top of the hold)
    cooldown_secs: float = 30.0
    # objectives whose burn drives the loop. Availability is deliberately
    # excluded by default: ladder rung 3 sheds best-effort work, which
    # counts against availability — including it would let the
    # controller's own mitigation hold it at the ceiling forever.
    objectives: Tuple[str, ...] = ("ttft", "tpot")
    # devprof capacity denominator: target per-replica busy fraction
    # used for the suggested-replicas estimate on action events
    target_util: float = 0.7

    @classmethod
    def from_env(cls) -> "AutoscaleConfig":
        return cls(
            max_replicas=int(_env_float(
                "AIOS_TPU_AUTOSCALE_MAX_REPLICAS", 4, 1
            )),
            interval_secs=_env_float(
                "AIOS_TPU_AUTOSCALE_INTERVAL_SECS", 5.0, 0.05
            ),
            up_burn=_env_float("AIOS_TPU_AUTOSCALE_UP_BURN", 1.0, 0.0),
            down_burn=_env_float("AIOS_TPU_AUTOSCALE_DOWN_BURN", 0.25, 0.0),
            hold_ticks=int(_env_float("AIOS_TPU_AUTOSCALE_HOLD_TICKS", 2, 1)),
            cooldown_secs=_env_float(
                "AIOS_TPU_AUTOSCALE_COOLDOWN_SECS", 30.0, 0.0
            ),
        )


class AutoscaleController:
    """One controller per ReplicaPool. ``tick()`` is the whole control
    law (tests/bench drive it directly; ``start()`` runs it on a daemon
    thread every ``interval_secs``). The controller lock guards ONLY
    bookkeeping — engine builds, pool mutations, and metric increments
    all run outside it (an engine factory warms up for seconds)."""

    def __init__(
        self,
        pool,
        cfg: Optional[AutoscaleConfig] = None,
        engine_factory: Optional[Callable[[], object]] = None,
        slo_engine=None,
        start: bool = False,
    ) -> None:
        self.pool = pool
        self.cfg = cfg or AutoscaleConfig.from_env()
        self.engine_factory = engine_factory
        self.slo = slo_engine if slo_engine is not None else slo_mod.ENGINE
        self.min_replicas = len(pool.replicas)
        self._lock = make_lock("autoscale")
        self._hold_up = 0  #: guarded_by _lock
        self._hold_down = 0  #: guarded_by _lock
        self._last_action_t = 0.0  #: guarded_by _lock
        self._acted = False  #: guarded_by _lock
        self._journal: List[dict] = []  #: guarded_by _lock
        self._killed = False  #: guarded_by _lock
        # engines THIS controller built (scale-down closes only these;
        # baseline engines belong to the model manager)
        self._added: List = []  #: guarded_by _lock
        # devprof capacity denominator: last (t, total device-seconds)
        self._dev_mark: Optional[Tuple[float, float]] = None  #: guarded_by _lock
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # pre-register every (action, cause) child by iterating the
        # closed enums (the SLO-objectives registration pattern)
        self._obs_actions = {
            (a, c): obs.AUTOSCALE_ACTIONS.labels(
                model=pool.name, action=a, cause=c
            )
            for a in ACTIONS for c in CAUSES
        }
        pool.autoscaler = self
        if start:
            self.start()

    # -- control law --------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> str:
        """One evaluation + at most one action. Returns what happened:
        idle|hold|cooldown|kill|steady|saturated or an ACTIONS member."""
        t = time.monotonic() if now is None else now
        if kill_switch():
            with self._lock:
                was_killed = self._killed
                self._killed = True
            if not was_killed and self.pool.degrade_level > 0:
                self.pool.set_degrade_level(0)
                self._record("restore", "kill_switch", t, burn=None,
                             level=0)
            return "kill"
        with self._lock:
            self._killed = False
        burn = self.worst_burn(now=t)
        if burn is None:
            return "idle"  # no evaluable window yet: provably quiescent
        with self._lock:
            if burn > self.cfg.up_burn:
                self._hold_up += 1
                self._hold_down = 0
            elif burn < self.cfg.down_burn:
                self._hold_down += 1
                self._hold_up = 0
            else:
                self._hold_up = 0
                self._hold_down = 0
            want_up = self._hold_up >= self.cfg.hold_ticks
            want_down = self._hold_down >= self.cfg.hold_ticks
            cooling = (
                self._acted
                and t - self._last_action_t < self.cfg.cooldown_secs
            )
        if not (want_up or want_down):
            return "hold"
        if cooling:
            return "cooldown"
        return self._escalate(t, burn) if want_up \
            else self._deescalate(t, burn)

    def _escalate(self, t: float, burn: float) -> str:
        pool = self.pool
        n = len(pool.replicas)
        if n < self.cfg.max_replicas and self.engine_factory is not None:
            # engine build + warmup runs HERE, outside every lock —
            # seconds of compile must not block scrapes or submits
            engine = self.engine_factory()
            try:
                idx = pool.add_replica(engine)
            except BaseException:
                # a pool that started draining mid-build must not leak
                # the freshly-built engine's HBM
                engine.close()
                raise
            with self._lock:
                self._added.append(engine)
            self._record("scale_up", "burn", t, burn=burn, replica=idx,
                         replicas=idx + 1, level=pool.degrade_level)
            return "scale_up"
        level = pool.degrade_level
        if level < len(LADDER):
            new = pool.set_degrade_level(level + 1)
            cause = (
                "ceiling"
                if self.engine_factory is not None
                and n >= self.cfg.max_replicas
                else "burn"
            )
            self._record("degrade", cause, t, burn=burn, level=new,
                         rung=LADDER[new - 1], replicas=n)
            return "degrade"
        return "saturated"  # ceiling + fully degraded: nothing left

    def _deescalate(self, t: float, burn: float) -> str:
        pool = self.pool
        level = pool.degrade_level
        if level > 0:
            new = pool.set_degrade_level(level - 1)
            self._record("restore", "recovery", t, burn=burn, level=new,
                         rung=LADDER[level - 1],
                         replicas=len(pool.replicas))
            return "restore"
        if len(pool.replicas) > self.min_replicas:
            victim = pool.remove_replica()
            if victim is None:
                return "steady"
            engine = victim.engine
            with self._lock:
                ours = engine in self._added
                if ours:
                    self._added.remove(engine)
            if ours:
                # we built it, we free its HBM; baseline engines belong
                # to the model manager
                engine.close()
            self._record("scale_down", "recovery", t, burn=burn,
                         replica=victim.idx, replicas=len(pool.replicas),
                         level=pool.degrade_level)
            return "scale_down"
        return "steady"

    # -- signals ------------------------------------------------------------

    def worst_burn(self, now: Optional[float] = None) -> Optional[float]:
        """Max burn rate over the watched objectives, or None when no
        objective has an evaluable window yet (fewer than the SLO
        engine's min_samples — a cold pool never triggers actions).
        ``now`` (the tick's clock) bypasses the SLO engine's 1 s scrape
        cache so each control decision sees the live window."""
        if self.pool.name not in self.slo.models():
            return None
        ev = self.slo.evaluate(self.pool.name, now=now)
        burns = [
            v["burn_rate"]
            for o, v in ev.items()
            if o in self.cfg.objectives
            and v["samples"] >= self.slo.cfg.min_samples
        ]
        return max(burns) if burns else None

    def utilization(self, now: Optional[float] = None) -> Optional[dict]:
        """Devprof capacity denominator: device-seconds accrued per
        replica per wall-second since the previous reading, plus the
        replica count that busy fraction suggests at ``target_util``.
        None when devprof is unarmed / has no samples yet or on the
        first reading (no delta)."""
        from ..obs import devprof

        t = time.monotonic() if now is None else now
        busy = 0.0
        seen = False
        for led in devprof.ledgers_for(self.pool.name):
            for kind in devprof.GRAPH_KINDS:
                s = led.device_seconds(kind)
                if s:
                    seen = True
                    busy += s
        if not seen:
            return None
        with self._lock:
            mark, self._dev_mark = self._dev_mark, (t, busy)
        if mark is None or t <= mark[0]:
            return None
        elapsed = t - mark[0]
        n = max(len(self.pool.replicas), 1)
        per_replica = (busy - mark[1]) / elapsed / n
        return {
            "device_seconds_per_replica_per_sec": round(per_replica, 6),
            "replicas_suggested": max(
                1,
                math.ceil((busy - mark[1]) / elapsed
                          / max(self.cfg.target_util, 1e-6)),
            ),
        }

    # -- bookkeeping ---------------------------------------------------------

    def _record(self, action: str, cause: str, t: float, *,
                burn: Optional[float], **fields) -> None:
        util = self.utilization(t)
        if util is not None:
            fields.update(util)
        # When the tsdb ring is armed, annotate the decision with the
        # recent burn trend — the journal then records not just the
        # instantaneous burn the controller acted on but the direction
        # it was heading (None when unarmed: zero cost on the hot path).
        burn_trend = tsdb_mod.trend(
            "aios_tpu_slo_burn_rate_ratio", {"model": self.pool.name},
        )
        entry = dict(action=action, cause=cause,
                     burn=round(burn, 4) if burn is not None else None,
                     **fields)
        if burn_trend is not None:
            entry["burn_trend"] = burn_trend
        with self._lock:
            self._hold_up = 0
            self._hold_down = 0
            self._last_action_t = t
            self._acted = True
            self._journal.append(entry)
            del self._journal[:-_MAX_JOURNAL]
        self._obs_actions[(action, cause)].inc()
        flightrec.RECORDER.model_event(
            self.pool.name, "autoscale", **entry
        )
        incidents_mod.notify(
            self.pool.name, "autoscale",
            action=action, autoscale_cause=cause,
            burn=entry["burn"],
        )
        log.warning(
            "%s autoscale %s (%s): burn=%s level=%d replicas=%d",
            self.pool.name, action, cause, entry["burn"],
            self.pool.degrade_level, len(self.pool.replicas),
        )

    def actions(self) -> List[dict]:
        """The bounded action journal, oldest first (bench/tests read
        this as the controller's evidence trail)."""
        with self._lock:
            return list(self._journal)

    def state(self) -> dict:
        """Flat controller state for stats()/debug surfaces."""
        with self._lock:
            return {
                "level": self.pool.degrade_level,
                "replicas": len(self.pool.replicas),
                "min_replicas": self.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "actions": len(self._journal),
                "hold_up": self._hold_up,
                "hold_down": self._hold_down,
                "killed": self._killed,
            }

    # -- thread --------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"autoscale-{self.pool.name}",
            daemon=True,
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.cfg.interval_secs):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive a bad tick
                log.exception(
                    "%s autoscale tick failed", self.pool.name
                )

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10)
