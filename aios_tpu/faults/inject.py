"""Deterministic, seeded fault injection for the serving plane.

The reference aiOS survives component failure by design — the spawner
restarts crashed agents and the intelligence hierarchy degrades tier by
tier — but recovery code nobody can *provoke* is recovery code nobody
has tested. This module gives the TPU serving plane named injection
points compiled into its hot paths:

    pool.scheduler_crash    the batcher scheduler thread raises mid-tick
    dispatch.delay          the decode loop sleeps before a dispatch
    host_store.restore_fail the host-tier restore dies mid-scatter
    host_store.corrupt      a spilled page's bytes flip (crc32 catches it)
    rpc.unavailable         a server RPC aborts UNAVAILABLE + retry-after
    allocator.pressure      alloc_pages raises PoolExhausted
    admission.clock_skew    the deadline gate sees a skewed clock

Each point is a **near-zero-cost no-op** unless a schedule is active:
the hot-path call is one module-global ``None`` check. A schedule comes
from ``AIOS_TPU_FAULTS`` (or boot ``[faults]`` -> that env, or
:func:`activate` in tests/bench)::

    AIOS_TPU_FAULTS="seed=42;pool.scheduler_crash=nth:3;\
dispatch.delay=prob:0.25,delay_ms=20;admission.clock_skew=after:5,skew_ms=2000"

Triggers (the fire decision is a pure function of ``(seed, point,
hit-index)`` for ``nth``/``prob`` — the same seed and call pattern
reproduce the same injected-fault sequence, which is what makes a chaos
run a *regression test* instead of a dice roll):

  * ``nth:N``  — fire exactly on the Nth hit of the point (one-shot);
  * ``prob:P`` — fire each hit with probability P, drawn from a
    per-point PRNG seeded with ``(seed, point)`` — one draw per hit;
  * ``after:T`` — fire on every hit once T seconds have elapsed since
    activation (wall-clock; for live chaos drills, not determinism).

Optional ``key=value`` params ride after the trigger: ``delay_ms``
(dispatch.delay), ``skew_ms`` (admission.clock_skew), ``retry_after_ms``
(rpc.unavailable).

Every fired fault is counted by ``aios_tpu_faults_injected_total{point,
mode}``, recorded on the flight recorder's model lane as a ``fault``
event, and appended to a bounded in-process journal (:func:`fired`) so
a chaos harness can assert the injected sequence was identical across
re-runs. See docs/FAULTS.md.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..analysis.locks import make_lock
from ..obs import instruments as obs

log = logging.getLogger("aios.faults")

__all__ = [
    "POINTS", "MODES", "FaultAction", "InjectedFault", "activate",
    "deactivate", "active", "point", "fired", "install_from_env",
]

# The closed catalog of injection points. A schedule naming anything
# else logs and skips it (the lenient-env pattern) — a typo must not
# silently arm nothing while the operator believes chaos is running.
POINTS = (
    "pool.scheduler_crash",
    "dispatch.delay",
    "host_store.restore_fail",
    "host_store.corrupt",
    "rpc.unavailable",
    "allocator.pressure",
    "admission.clock_skew",
    # decode-host loss mid-handoff (aios_tpu/fleet/disagg.py): the
    # servicer aborts the stream — or, with exit=1, kills the whole
    # process (the disagg smoke's real host kill) — and the prefill
    # host re-hands the stream to a survivor
    "fleet.host_kill",
)

MODES = ("nth", "prob", "after")

# journal bound: a chaos storm fires tens of faults, not thousands; the
# cap only guards against a runaway prob:1.0 schedule on a hot point
_MAX_JOURNAL = 4096

# parameter defaults per point: a schedule that names the point but not
# its magnitude still injects SOMETHING — a fired fault that is secretly
# a no-op would count in the metric/journal while exercising nothing
_PARAM_DEFAULTS: Dict[str, Dict[str, float]] = {
    "dispatch.delay": {"delay_ms": 10.0},
    "admission.clock_skew": {"skew_ms": 1000.0},
    "rpc.unavailable": {"retry_after_ms": 1000.0},
}


class InjectedFault(RuntimeError):
    """The exception a crash-class injection point raises. Distinct type
    so recovery-path tests can assert the abort they observe is the one
    they injected, not an unrelated failure."""


@dataclass(frozen=True)
class FaultAction:
    """What a fired point tells its call site to do. ``hit`` is the
    1-based hit index at fire time (the journal's determinism anchor)."""

    point: str
    mode: str
    hit: int
    delay_s: float = 0.0
    skew_s: float = 0.0
    retry_after_ms: int = 1000
    # fleet.host_kill only: True = the call site should take the whole
    # PROCESS down (os._exit), not just abort the stream — the disagg
    # smoke's real host kill. Default False so in-process tests drive
    # the same recovery path without dying.
    exit: bool = False


@dataclass
class _PointSpec:
    mode: str
    arg: float  # N for nth, P for prob, T seconds for after
    params: Dict[str, float] = field(default_factory=dict)


class FaultPlan:
    """One activated schedule: per-point triggers, seeded PRNGs, hit
    counters, and the fired-fault journal."""

    def __init__(self, schedule: Dict[str, _PointSpec], seed: int) -> None:
        self.seed = seed
        self.schedule = schedule
        self.activated_at = time.monotonic()
        self._lock = make_lock("faults")
        #: guarded_by _lock
        self._hits: Dict[str, int] = {}
        #: guarded_by _lock
        self._journal: deque = deque(maxlen=_MAX_JOURNAL)
        # per-point PRNG seeded by (seed, point): the k-th draw decides
        # the k-th hit no matter how points interleave across threads
        self._rngs: Dict[str, random.Random] = {
            name: random.Random(f"{seed}:{name}") for name in schedule
        }

    def check(self, name: str, model: str = "") -> Optional[FaultAction]:
        spec = self.schedule.get(name)
        if spec is None:
            return None
        with self._lock:
            hit = self._hits.get(name, 0) + 1
            self._hits[name] = hit
            if spec.mode == "nth":
                fire = hit == int(spec.arg)
            elif spec.mode == "prob":
                fire = self._rngs[name].random() < spec.arg
            else:  # after
                fire = (
                    time.monotonic() - self.activated_at >= spec.arg
                )
            if not fire:
                return None
            act = FaultAction(
                point=name, mode=spec.mode, hit=hit,
                delay_s=spec.params.get("delay_ms", 0.0) / 1e3,
                skew_s=spec.params.get("skew_ms", 0.0) / 1e3,
                retry_after_ms=int(spec.params.get("retry_after_ms", 1000)),
                exit=bool(spec.params.get("exit", 0.0)),
            )
            self._journal.append(
                {"point": name, "mode": spec.mode, "hit": hit,
                 "model": model}
            )
        self._record(act, model)
        return act

    def _record(self, act: FaultAction, model: str) -> None:
        """Observability for a fired fault — outside the plan lock (the
        recorder and metric children take their own)."""
        obs.FAULTS_INJECTED.labels(point=act.point, mode=act.mode).inc()
        from ..obs import flightrec  # late: obs.__init__ import order

        flightrec.RECORDER.model_event(
            model or "faults", "fault",
            point=act.point, mode=act.mode, hit=act.hit,
        )
        log.warning(
            "fault injected: %s (%s, hit %d)%s",
            act.point, act.mode, act.hit,
            f" on {model}" if model else "",
        )

    def journal(self) -> List[dict]:
        with self._lock:
            return list(self._journal)


# The active plan. None = faults disabled; the hot-path cost of a
# disabled point() is one global load + is-None check.
_PLAN: Optional[FaultPlan] = None
_swap = threading.Lock()  # activate/deactivate only — never on hot paths


def point(name: str, model: str = "") -> Optional[FaultAction]:
    """The hot-path call: None when no schedule is active or the point
    does not fire; a :class:`FaultAction` telling the call site what to
    inject otherwise."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.check(name, model)


def active() -> bool:
    return _PLAN is not None


def fired() -> List[dict]:
    """The active plan's fired-fault journal (empty when inactive) —
    ordered ``{point, mode, hit, model}`` dicts, the determinism
    fingerprint chaos re-runs compare."""
    plan = _PLAN
    return plan.journal() if plan is not None else []


def activate(spec: str, seed: Optional[int] = None) -> FaultPlan:
    """Arm a schedule programmatically (tests, ``bench.py --chaos``).
    ``spec`` uses the ``AIOS_TPU_FAULTS`` grammar; an explicit ``seed``
    overrides the spec's ``seed=`` entry. Returns the plan (its
    ``journal()`` is the run's injected-fault sequence)."""
    global _PLAN
    schedule, spec_seed = _parse(spec)
    plan = FaultPlan(schedule, seed if seed is not None else spec_seed)
    with _swap:
        _PLAN = plan
    if schedule:
        log.warning(
            "fault injection ACTIVE (seed %d): %s", plan.seed,
            ", ".join(
                f"{n}={s.mode}:{s.arg:g}" for n, s in schedule.items()
            ),
        )
    return plan


def deactivate() -> None:
    global _PLAN
    with _swap:
        _PLAN = None


def install_from_env() -> None:
    """Arm (or disarm) from ``AIOS_TPU_FAULTS`` — called at import so a
    booted process carries its schedule from birth, and callable again
    after an env change (tests)."""
    raw = os.environ.get("AIOS_TPU_FAULTS", "").strip()
    if raw:
        activate(raw)
    else:
        deactivate()


def _parse(spec: str) -> Tuple[Dict[str, _PointSpec], int]:
    """``seed=42;point=mode:arg[,k=v...];...`` -> (schedule, seed).
    Malformed entries log and drop (never take down a boot)."""
    schedule: Dict[str, _PointSpec] = {}
    seed = 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, rest = entry.partition("=")
        name, rest = name.strip(), rest.strip()
        if name == "seed":
            try:
                seed = int(rest)
            except ValueError:
                log.warning("AIOS_TPU_FAULTS: bad seed %r ignored", rest)
            continue
        if name not in POINTS:
            log.warning(
                "AIOS_TPU_FAULTS: unknown point %r ignored (known: %s)",
                name, ", ".join(POINTS),
            )
            continue
        head, *params = rest.split(",")
        mode, _, arg = head.partition(":")
        mode = mode.strip()
        if mode not in MODES:
            log.warning(
                "AIOS_TPU_FAULTS: %s: unknown trigger %r ignored "
                "(known: %s)", name, mode, ", ".join(MODES),
            )
            continue
        try:
            argv = float(arg)
        except ValueError:
            log.warning(
                "AIOS_TPU_FAULTS: %s: bad trigger arg %r ignored",
                name, arg,
            )
            continue
        kv: Dict[str, float] = dict(_PARAM_DEFAULTS.get(name, ()))
        ok = True
        for p in params:
            k, _, v = p.partition("=")
            try:
                kv[k.strip()] = float(v)
            except ValueError:
                log.warning(
                    "AIOS_TPU_FAULTS: %s: bad param %r ignored — "
                    "dropping the whole entry", name, p,
                )
                ok = False
        if ok:
            schedule[name] = _PointSpec(mode, argv, kv)
    return schedule, seed


install_from_env()
