"""ctypes bindings for the native C++ runtime primitives.

Builds lazily (g++ via build.py) and degrades gracefully: when the shared
library is missing or the toolchain is absent, `available()` is False and
callers fall back to their pure-Python implementations — same semantics,
native speed when present. Wired consumers: the tool-registry rate limiter
(tools/ratelimit.py, NativeTokenBucket) and the audit ledger's record hash
(tools/audit.py, sha256_hex). NativeRing and chain_hash are standalone
primitives with parity tests; the memory service's operational ring keeps
its Python deque because its queries filter on event dict fields.
"""

from __future__ import annotations

import ctypes
import threading
from pathlib import Path
from typing import List, Optional

_LIB_PATH = Path(__file__).parent / "libaios_native.so"
_lib: Optional[ctypes.CDLL] = None
_load_lock = threading.Lock()
_load_failed = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.aios_sha256_hex.argtypes = [u8p, ctypes.c_uint64, ctypes.c_char_p]
    lib.aios_chain_hash.argtypes = [ctypes.c_char_p, u8p, ctypes.c_uint64,
                                    ctypes.c_char_p]
    lib.aios_ring_create.restype = ctypes.c_void_p
    lib.aios_ring_create.argtypes = [ctypes.c_uint64]
    lib.aios_ring_destroy.argtypes = [ctypes.c_void_p]
    lib.aios_ring_push.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]
    lib.aios_ring_size.restype = ctypes.c_uint64
    lib.aios_ring_size.argtypes = [ctypes.c_void_p]
    lib.aios_ring_total.restype = ctypes.c_uint64
    lib.aios_ring_total.argtypes = [ctypes.c_void_p]
    lib.aios_ring_get_recent.restype = ctypes.c_int64  # -1 = index absent
    lib.aios_ring_get_recent.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                         u8p, ctypes.c_uint64]
    lib.aios_bucket_create.restype = ctypes.c_void_p
    lib.aios_bucket_create.argtypes = [ctypes.c_double, ctypes.c_double]
    lib.aios_bucket_destroy.argtypes = [ctypes.c_void_p]
    lib.aios_bucket_try_acquire.restype = ctypes.c_int
    lib.aios_bucket_try_acquire.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.aios_bucket_tokens.restype = ctypes.c_double
    lib.aios_bucket_tokens.argtypes = [ctypes.c_void_p]
    return lib


def load(build_if_missing: bool = True) -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _load_lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not _LIB_PATH.exists() and build_if_missing:
            try:
                from .build import build

                build()
            except Exception:
                _load_failed = True
                return None
        if not _LIB_PATH.exists():
            _load_failed = True
            return None
        try:
            _lib = _configure(ctypes.CDLL(str(_LIB_PATH)))
        except OSError:
            _load_failed = True
            return None
        return _lib


def available() -> bool:
    return load() is not None


def _as_u8p(data: bytes):
    return ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))


def sha256_hex(data: bytes) -> str:
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(65)
    lib.aios_sha256_hex(_as_u8p(data), len(data), out)
    return out.value.decode()


def chain_hash(prev_hex: str, payload: bytes) -> str:
    lib = load()
    assert lib is not None, "native library unavailable"
    out = ctypes.create_string_buffer(65)
    lib.aios_chain_hash(prev_hex.encode(), _as_u8p(payload), len(payload), out)
    return out.value.decode()


class NativeRing:
    """Bounded event ring backed by the C++ deque (operational tier)."""

    def __init__(self, capacity: int):
        lib = load()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self._handle = lib.aios_ring_create(capacity)

    def push(self, item: bytes) -> None:
        self._lib.aios_ring_push(self._handle, _as_u8p(item), len(item))

    def __len__(self) -> int:
        return self._lib.aios_ring_size(self._handle)

    @property
    def total_pushed(self) -> int:
        return self._lib.aios_ring_total(self._handle)

    def recent(self, count: int) -> List[bytes]:
        out: List[bytes] = []
        buf = ctypes.create_string_buffer(64 * 1024)
        u8 = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
        for i in range(count):
            n = self._lib.aios_ring_get_recent(self._handle, i, u8, len(buf))
            if n < 0:  # index beyond ring (0 is a valid empty item)
                break
            if n > len(buf):  # grow and retry
                buf = ctypes.create_string_buffer(int(n))
                u8 = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
                n = self._lib.aios_ring_get_recent(self._handle, i, u8, len(buf))
            out.append(buf.raw[:n])
        return out

    def __del__(self):
        try:
            self._lib.aios_ring_destroy(self._handle)
        except Exception:
            pass


class NativeTokenBucket:
    """Token bucket backed by the C++ steady-clock implementation."""

    def __init__(self, rate: float, capacity: Optional[float] = None):
        lib = load()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self._handle = lib.aios_bucket_create(rate, capacity or 0.0)

    def try_acquire(self, n: float = 1.0) -> bool:
        return bool(self._lib.aios_bucket_try_acquire(self._handle, n))

    @property
    def tokens(self) -> float:
        return self._lib.aios_bucket_tokens(self._handle)

    def __del__(self):
        try:
            self._lib.aios_bucket_destroy(self._handle)
        except Exception:
            pass
