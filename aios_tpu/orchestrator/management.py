"""Management console: REST + WebSocket + embedded dashboard on :9090.

Reference parity (agent-core/src/management.rs:43-54 routes, 757+ dashboard):
  GET  /api/status            system summary
  GET  /api/goals             goal list        POST /api/goals  submit
  GET  /api/goals/{id}/tasks  task list
  GET  /api/goals/{id}/messages  conversation thread
  POST /api/chat              chat-style goal submission
  GET  /api/agents            live agents
  GET  /api/health            liveness
  WS   /ws                    event push with subscribe_goal
plus a single-file embedded HTML dashboard at /.

Implemented with aiohttp on a dedicated thread/event loop (the reference
uses axum inside tokio).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from typing import Optional, Set

from aiohttp import WSMsgType, web

log = logging.getLogger("aios.console")

DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>aiOS-TPU Console</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#0d1117;color:#e6edf3}
 header{padding:12px 20px;background:#161b22;border-bottom:1px solid #30363d}
 h1{font-size:16px;margin:0}
 main{display:grid;grid-template-columns:1fr 1fr;gap:16px;padding:16px}
 section{background:#161b22;border:1px solid #30363d;border-radius:8px;padding:12px}
 h2{font-size:13px;margin:0 0 8px;color:#7d8590;text-transform:uppercase}
 #goals div,#agents div{padding:6px;border-bottom:1px solid #21262d;font-size:13px}
 .status{float:right;font-size:11px;padding:1px 8px;border-radius:10px;background:#1f6feb33}
 .completed{background:#23863633}.failed{background:#da363333}
 form{display:flex;gap:8px;margin-top:8px}
 input{flex:1;background:#0d1117;border:1px solid #30363d;color:#e6edf3;
       padding:8px;border-radius:6px}
 button{background:#238636;color:#fff;border:0;padding:8px 16px;border-radius:6px}
 #chat{height:220px;overflow-y:auto;font-size:13px}
 #chat p{margin:4px 0}.role{color:#7d8590}
 #stats{font-size:13px;line-height:1.8}
</style></head><body>
<header><h1>aiOS-TPU — orchestrator console</h1></header>
<main>
 <section><h2>Submit goal / chat</h2>
  <div id="chat"></div>
  <form onsubmit="return send(event)">
   <input id="msg" placeholder="Describe a goal..." autocomplete="off">
   <button>Send</button></form>
 </section>
 <section><h2>System</h2><div id="stats">loading…</div></section>
 <section><h2>Goals</h2><div id="goals"></div></section>
 <section><h2>Agents</h2><div id="agents"></div></section>
</main>
<script>
async function refresh(){
 const s=await (await fetch('/api/status')).json();
 document.getElementById('stats').innerHTML=
  `goals: ${s.active_goals} active · tasks pending: ${s.pending_tasks}`+
  `<br>agents: ${s.active_agents} · models: ${s.loaded_models.join(', ')||'none'}`+
  `<br>cpu: ${s.cpu_percent.toFixed(0)}% · mem: ${(s.memory_used_mb/1024).toFixed(1)}GB`+
  `<br>uptime: ${s.uptime_seconds}s`;
 const gs=await (await fetch('/api/goals')).json();
 document.getElementById('goals').innerHTML=gs.goals.slice(0,12).map(g=>
  `<div>${g.description.slice(0,60)}<span class="status ${g.status}">${g.status}</span></div>`).join('');
 const ag=await (await fetch('/api/agents')).json();
 document.getElementById('agents').innerHTML=ag.agents.map(a=>
  `<div>${a.agent_id}<span class="status">${a.status}</span></div>`).join('')||'none';
}
async function send(e){
 e.preventDefault();
 const input=document.getElementById('msg');
 const text=input.value.trim(); if(!text)return false; input.value='';
 chatAdd('you',text);
 const r=await (await fetch('/api/chat',{method:'POST',
   headers:{'Content-Type':'application/json'},
   body:JSON.stringify({message:text})})).json();
 chatAdd('aios',r.reply);
 refresh(); return false;
}
function chatAdd(role,text){
 const c=document.getElementById('chat');
 c.innerHTML+=`<p><span class="role">${role}:</span> ${text}</p>`;
 c.scrollTop=c.scrollHeight;
}
refresh(); setInterval(refresh,3000);
try{
 const ws=new WebSocket(`ws://${location.host}/ws`);
 ws.onmessage=(m)=>{refresh();};
}catch(e){}
</script></body></html>
"""


class ManagementConsole:
    def __init__(self, orchestrator, host: str = "127.0.0.1", port: int = 9090):
        """``orchestrator`` is an OrchestratorService (shared state)."""
        self.orch = orchestrator
        self.host = host
        self.port = port
        self._ws_clients: Set[web.WebSocketResponse] = set()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.bound_port: Optional[int] = None

    # -- handlers -----------------------------------------------------------

    async def _index(self, request):
        return web.Response(text=DASHBOARD_HTML, content_type="text/html")

    async def _status(self, request):
        engine = self.orch.engine
        import psutil

        vm = psutil.virtual_memory()
        return web.json_response(
            {
                "active_goals": len(engine.active_goals()),
                "pending_tasks": len(engine.unblocked_pending_tasks(limit=1000)),
                "active_agents": sum(
                    1 for a in self.orch.router.agents() if a.alive
                ),
                "loaded_models": list(self.orch.loaded_models()),
                "cpu_percent": psutil.cpu_percent(interval=None),
                "memory_used_mb": vm.used / 1e6,
                "memory_total_mb": vm.total / 1e6,
                "uptime_seconds": int(time.time() - self.orch.started_at),
            }
        )

    async def _goals(self, request):
        goals = self.orch.engine.list_goals(limit=100)
        return web.json_response(
            {
                "goals": [
                    {
                        "id": g.id,
                        "description": g.description,
                        "status": g.status,
                        "priority": g.priority,
                        "progress": self.orch.engine.progress(g.id),
                        "created_at": g.created_at,
                    }
                    for g in goals
                ]
            }
        )

    async def _submit_goal(self, request):
        body = await request.json()
        goal = self.orch.engine.submit_goal(
            body.get("description", ""),
            priority=int(body.get("priority", 5)),
            source="console",
        )
        await self._broadcast({"event": "goal_submitted", "goal_id": goal.id})
        return web.json_response({"goal_id": goal.id})

    async def _goal_tasks(self, request):
        goal_id = request.match_info["goal_id"]
        tasks = self.orch.engine.tasks_for_goal(goal_id)
        return web.json_response(
            {
                "tasks": [
                    {
                        "id": t.id,
                        "description": t.description,
                        "status": t.status,
                        "agent": t.assigned_agent,
                        "error": t.error,
                    }
                    for t in tasks
                ]
            }
        )

    async def _goal_messages(self, request):
        goal_id = request.match_info["goal_id"]
        msgs = self.orch.engine.messages_for_goal(goal_id)
        return web.json_response(
            {
                "messages": [
                    {"role": m.role, "content": m.content,
                     "timestamp": m.timestamp}
                    for m in msgs
                ]
            }
        )

    async def _chat(self, request):
        body = await request.json()
        text = body.get("message", "").strip()
        if not text:
            return web.json_response({"error": "empty message"}, status=400)
        goal = self.orch.engine.submit_goal(text, source="chat")
        self.orch.engine.add_message(goal.id, "user", text)
        await self._broadcast({"event": "goal_submitted", "goal_id": goal.id})
        return web.json_response(
            {
                "goal_id": goal.id,
                "reply": f"Goal accepted ({goal.id[:8]}). I'll work on it.",
            }
        )

    async def _agents(self, request):
        return web.json_response(
            {
                "agents": [
                    {
                        "agent_id": a.agent_id,
                        "agent_type": a.agent_type,
                        "status": a.status if a.alive else "dead",
                        "tasks_completed": a.tasks_completed,
                    }
                    for a in self.orch.router.agents()
                ]
            }
        )

    async def _health(self, request):
        return web.json_response({"healthy": True, "service": "orchestrator"})

    async def _ws(self, request):
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        self._ws_clients.add(ws)
        try:
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    try:
                        data = json.loads(msg.data)
                    except ValueError:
                        continue
                    if data.get("action") == "subscribe_goal":
                        goal_id = data.get("goal_id", "")
                        goal = self.orch.engine.goals.get(goal_id)
                        if goal:
                            await ws.send_json(
                                {
                                    "event": "goal_status",
                                    "goal_id": goal_id,
                                    "status": goal.status,
                                    "progress": self.orch.engine.progress(goal_id),
                                }
                            )
        finally:
            self._ws_clients.discard(ws)
        return ws

    async def _broadcast(self, payload: dict) -> None:
        dead = []
        for ws in self._ws_clients:
            try:
                await ws.send_json(payload)
            except Exception:  # noqa: BLE001
                dead.append(ws)
        for ws in dead:
            self._ws_clients.discard(ws)

    def notify(self, payload: dict) -> None:
        """Thread-safe push to all WS clients."""
        if self._loop is not None:
            asyncio.run_coroutine_threadsafe(self._broadcast(payload), self._loop)

    # -- lifecycle ----------------------------------------------------------

    def _build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/status", self._status)
        app.router.add_get("/api/goals", self._goals)
        app.router.add_post("/api/goals", self._submit_goal)
        app.router.add_get("/api/goals/{goal_id}/tasks", self._goal_tasks)
        app.router.add_get("/api/goals/{goal_id}/messages", self._goal_messages)
        app.router.add_post("/api/chat", self._chat)
        app.router.add_get("/api/agents", self._agents)
        app.router.add_get("/api/health", self._health)
        app.router.add_get("/ws", self._ws)
        return app

    def start(self) -> None:
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def boot():
                self._runner = web.AppRunner(self._build_app())
                await self._runner.setup()
                site = web.TCPSite(self._runner, self.host, self.port)
                await site.start()
                for s in self._runner.sites:
                    sock = s._server.sockets[0]  # noqa: SLF001
                    self.bound_port = sock.getsockname()[1]
                self._started.set()

            self._loop.run_until_complete(boot())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, name="console", daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        if self._loop is None:
            return

        async def shutdown():
            if self._runner:
                await self._runner.cleanup()

        asyncio.run_coroutine_threadsafe(shutdown(), self._loop).result(timeout=5)
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread:
            self._thread.join(timeout=5)
