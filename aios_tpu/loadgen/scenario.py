"""Declarative storm scenarios: tenant mixes, curves, and SLO targets.

A scenario file (TOML or JSON; ``scenarios/storm_*.toml`` are the
committed references) declares WHAT the storm looks like; the trace
builder turns it into a deterministic call schedule. Validation is
strict — a misspelled tenant class or arrival curve fails the load, not
the gate (the chaos lesson: a storm that silently does nothing passes
vacuously).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Tuple

from .._compat import tomllib

TENANT_CLASSES = ("interactive", "agent", "batch", "abusive", "reactive")
ARRIVALS = ("poisson", "uniform", "diurnal", "burst")

# intelligence level per tenant class (the runtime service maps levels
# to admission priority: strategic 3, tactical 2, operational/reactive
# 1, unclassified 0 — so "batch" traffic is the best-effort tier the
# degrade ladder's rung 3 sheds)
CLASS_LEVELS = {
    "interactive": "operational",
    "agent": "tactical",
    "batch": "",
    "abusive": "",
    "reactive": "reactive",
}


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's traffic shape. Lengths are in CHARACTERS of prompt
    text (the storm models serve byte-level tokenizers, so chars ==
    tokens; real-tokenizer scenarios just mean "about this many
    tokens")."""

    name: str
    klass: str = "interactive"
    rps: float = 1.0  # base arrival rate (requests/sec of virtual time)
    arrival: str = "poisson"
    peak_ratio: float = 4.0  # diurnal/burst peak rate multiplier
    period_secs: float = 4.0  # diurnal period / burst cycle length
    burst_secs: float = 1.0  # burst on-window at the start of each cycle
    prompt_p50: int = 48  # lognormal median prompt length
    prompt_sigma: float = 0.5  # lognormal spread (the long tail)
    prompt_max: int = 400  # hard cap (keeps prompts inside the context)
    max_tokens: int = 16
    max_tokens_max: int = 0  # 0 = fixed; else uniform [max_tokens, this]
    temperature: float = 0.0  # greedy by default (the determinism contract)
    streaming: bool = False  # StreamInfer (TTFT measured at first chunk)
    shared_prefix: int = 0  # chars of shared per-tenant preamble
    fork_width: int = 0  # agent loops: children per parent call
    fork_gap_secs: float = 0.15  # child arrival offset after the parent
    deadline_ms: int = 0  # gRPC deadline (reactive tier); 0 = none
    quota_storm: bool = False  # fixed-cost hammering meant to trip quotas

    def __post_init__(self):
        if self.klass not in TENANT_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: unknown class {self.klass!r} "
                f"(one of {TENANT_CLASSES})"
            )
        if self.arrival not in ARRIVALS:
            raise ValueError(
                f"tenant {self.name!r}: unknown arrival {self.arrival!r} "
                f"(one of {ARRIVALS})"
            )
        if self.rps <= 0:
            raise ValueError(f"tenant {self.name!r}: rps must be > 0")

    @property
    def level(self) -> str:
        return CLASS_LEVELS[self.klass]


@dataclass(frozen=True)
class SLOTargets:
    """The storm's declared pass/fail line, judged from the driver's
    own measurements AND read back from the live /debug/slo surface."""

    ttft_ms: float = 30_000.0
    tpot_ms: float = 5_000.0
    attainment: float = 0.95  # min fraction of requests meeting each
    availability: float = 0.99  # min ok ratio over admitted+admissible work


@dataclass(frozen=True)
class StormScenario:
    name: str
    seed: int
    duration_secs: float
    model: str
    tenants: Tuple[TenantSpec, ...]
    slo: SLOTargets = field(default_factory=SLOTargets)
    # serving-plane env applied for the storm's pool (ReplicaPool knobs)
    replicas: int = 2
    context: int = 512
    num_slots: int = 4
    tenant_tokens_per_sec: float = 0.0  # 0 = quotas off
    tenant_burst_tokens: float = 0.0
    max_queue: int = 64
    # multi-target storms (the fleet driver): explicit runtime endpoints
    # to spread the trace over. Empty = single target supplied by the
    # harness at run time; the VERDICT then aggregates one fingerprint
    # per endpoint (tenant -> target routing is deterministic, so the
    # per-target counts are part of the determinism contract).
    endpoints: Tuple[str, ...] = ()

    def tenant(self, name: str) -> TenantSpec:
        for t in self.tenants:
            if t.name == name:
                return t
        raise KeyError(name)


def _build(data: dict, path: str) -> StormScenario:
    if "scenario" not in data:
        raise ValueError(f"{path}: missing [scenario] section")
    sc = dict(data["scenario"])
    slo = SLOTargets(**data.get("slo", {}))
    raw_tenants = data.get("tenants", [])
    if not raw_tenants:
        raise ValueError(f"{path}: a storm needs at least one [[tenants]]")
    tenants = []
    allowed = {f.name for f in fields(TenantSpec)}
    for row in raw_tenants:
        row = dict(row)
        # TOML has no "class" collision problem, python does
        if "class" in row:
            row["klass"] = row.pop("class")
        unknown = set(row) - allowed
        if unknown:
            raise ValueError(
                f"{path}: tenant {row.get('name', '?')!r} has unknown "
                f"keys {sorted(unknown)}"
            )
        tenants.append(TenantSpec(**row))
    names = [t.name for t in tenants]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate tenant names {names}")
    return StormScenario(
        name=str(sc.get("name", os.path.basename(path))),
        seed=int(sc.get("seed", 42)),
        duration_secs=float(sc.get("duration_secs", 5.0)),
        model=str(sc.get("model", "storm-tiny")),
        replicas=int(sc.get("replicas", 2)),
        context=int(sc.get("context", 512)),
        num_slots=int(sc.get("num_slots", 4)),
        tenant_tokens_per_sec=float(sc.get("tenant_tokens_per_sec", 0.0)),
        tenant_burst_tokens=float(sc.get("tenant_burst_tokens", 0.0)),
        max_queue=int(sc.get("max_queue", 64)),
        endpoints=tuple(str(e) for e in sc.get("endpoints", ())),
        tenants=tuple(tenants),
        slo=slo,
    )


def load_scenario(path: str) -> StormScenario:
    """Load + validate a scenario file (.toml or .json)."""
    if path.endswith(".json"):
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    else:
        with open(path, "rb") as f:
            data = tomllib.load(f)
    return _build(data, path)


def default_scenario_path(repo_root: str, smoke: bool = False) -> str:
    """The scenario ``bench.py --storm`` runs: AIOS_TPU_STORM_SCENARIO
    (CI matrices point at a site scenario without editing the command
    line) or the committed reference/smoke file."""
    override = os.environ.get("AIOS_TPU_STORM_SCENARIO", "").strip()
    if override:
        return override
    return os.path.join(
        repo_root, "scenarios",
        "storm_smoke.toml" if smoke else "storm_reference.toml",
    )


def time_scale_env() -> float:
    """AIOS_TPU_STORM_TIME_SCALE stretches the arrival clock on slow or
    oversubscribed containers (2.0 = half speed; floor 0.1). The trace
    — and so the deterministic verdict — is unchanged; only the
    wall-clock replay slows down."""
    raw = os.environ.get("AIOS_TPU_STORM_TIME_SCALE", "").strip()
    if not raw:
        return 1.0
    try:
        return max(float(raw), 0.1)
    except ValueError:
        return 1.0
