"""bench.py probe-budget behavior: an unreachable TPU backend must not
wedge the round (BENCH_r05 lost 2 h to a dead tunnel and produced
``parsed: null``) — the probe is capped and exhaustion yields one
parseable diagnostic JSON line PER planned config and exit code 0."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_unreachable_backend_emits_diagnostics_and_exits_zero():
    env = {
        **os.environ,
        # force the non-cpu probe path; this host has no usable TPU, so
        # the probe subprocess's backend init fails (or wedges on the
        # libtpu lockfile — the per-attempt timeout covers that) — the
        # "unreachable backend" condition without any tunnel involved
        "JAX_PLATFORMS": "tpu",
        "AIOS_BENCH_PROBE_ATTEMPTS": "1",
        "AIOS_BENCH_PROBE_SECS": "60",
        "AIOS_BENCH_PROBE_TIMEOUT": "15",
    }
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
        cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    # one diagnostic line per planned config (5 decode configs + the
    # serving-feature benches)
    assert len(lines) >= 5, r.stdout
    metrics = set()
    for ln in lines:
        obj = json.loads(ln)  # every line parseable
        assert obj["value"] == 0.0
        assert "unavailable" in obj["error"]
        metrics.add(obj["metric"])
    assert len(metrics) == len(lines)  # one line per config, no dupes
    assert any("tinyllama" in m for m in metrics)
    assert any("mistral" in m for m in metrics)


def test_fast_flag_limits_diagnostics_to_decode_configs():
    env = {
        **os.environ,
        "JAX_PLATFORMS": "tpu",
        "AIOS_BENCH_PROBE_ATTEMPTS": "1",
        "AIOS_BENCH_PROBE_SECS": "60",
        "AIOS_BENCH_PROBE_TIMEOUT": "15",
    }
    r = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--fast", "--skip-mistral"],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
        cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1  # tinyllama decode config only
    assert "tinyllama" in json.loads(lines[0])["metric"]
