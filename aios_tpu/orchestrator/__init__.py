"""The orchestrator — goal engine, task planner, agent router, autonomy loop,
scheduler, event bus, proactive generator, cluster manager, console.

Reference: agent-core/src/ (SURVEY.md section 2 rows 2a-2q).
"""
