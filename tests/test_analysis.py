"""The analyzer analyzes itself: seeded-violation fixtures prove every
rule in the catalog FIRES, waiver fixtures prove every rule can be
waived with a justification, and the tier-1 gate runs the real tree
through the same entry point as ``python -m aios_tpu.analysis``.

Plus the runtime half: DebugLock unit tests that provoke and detect an
AB/BA lock-order inversion from two threads, and trip the held-too-long
watchdog.
"""

import textwrap
import threading
import time

import pytest

from aios_tpu.analysis import __main__ as analysis_cli
from aios_tpu.analysis.core import ModuleInfo
from aios_tpu.analysis.locks import (
    DebugLock,
    LockOrderError,
    make_lock,
    watchdog_trips,
)
from aios_tpu.analysis.registry import LOCKS, LockDecl, Registry
from aios_tpu.analysis.rules import RULE_IDS, Analyzer

FIX = "aios_tpu.fixture"


def _registry(**kw):
    locks = kw.pop("locks", (
        LockDecl("fix", FIX, "Eng", "_lock"),
        LockDecl("other", FIX, "Other", "_lock"),
    ))
    field_types = kw.pop("field_types", {
        (FIX, "Eng", "other"): (FIX, "Other"),
        (FIX, "Other", "eng"): (FIX, "Eng"),
    })
    return Registry(
        locks=locks,
        field_types=field_types,
        global_types={},
        context_fns=kw.pop("context_fns", {}),
        hook_targets={},
        local_locks={},
        dispatch_hygiene_modules=kw.pop("dispatch_hygiene_modules", ()),
    )


def _analyze(src, registry=None, rules=None, doc=None):
    mi = ModuleInfo.from_source(
        textwrap.dedent(src), name=FIX, path="fixture.py"
    )
    return Analyzer(
        [mi], registry or _registry(), config_doc=doc
    ).run(rules)


def _unwaived(findings, rule=None):
    return [
        f for f in findings
        if not f.waived and (rule is None or f.rule == rule)
    ]


# -- rule 1: lock discipline -------------------------------------------------

DISPATCH_SRC = """
    class Eng:
        def f(self):
            with self._lock:
                fn = jax.jit(body)
"""

READBACK_SRC = """
    class Eng:
        def f(self):
            with self._lock:
                toks = np.asarray(device_tokens)
"""

RPC_SRC = """
    class Eng:
        def f(self):
            with self._lock:
                reply = self.runtime_stub.Infer(req)
"""


@pytest.mark.parametrize("src,rule", [
    (DISPATCH_SRC, "lock-dispatch"),
    (READBACK_SRC, "lock-readback"),
    (RPC_SRC, "lock-rpc"),
])
def test_lock_discipline_rules_fire(src, rule):
    found = _unwaived(_analyze(src), rule)
    assert len(found) == 1, f"{rule} did not fire"
    assert "fix" in found[0].message


@pytest.mark.parametrize("src,rule", [
    (DISPATCH_SRC, "lock-dispatch"),
    (READBACK_SRC, "lock-readback"),
    (RPC_SRC, "lock-rpc"),
])
def test_lock_discipline_waiver_honored(src, rule):
    waived = src.replace(
        "with self._lock:",
        f"with self._lock:  # aios: waive({rule}): fixture rationale",
    )
    findings = _analyze(waived)
    assert not _unwaived(findings, rule)
    assert any(
        f.rule == rule and f.waived
        and f.waive_reason == "fixture rationale"
        for f in findings
    )


def test_lock_discipline_engine_lock_allows_dispatch():
    """A lock declared with forbids=('readback', 'rpc') shelters
    dispatch by design (the engine lock's whole job)."""
    reg = _registry(locks=(
        LockDecl("fix", FIX, "Eng", "_lock", forbids=("readback", "rpc")),
    ))
    assert not _unwaived(_analyze(DISPATCH_SRC, reg), "lock-dispatch")
    assert _unwaived(_analyze(READBACK_SRC, reg), "lock-readback")


def test_lock_discipline_one_level_call_graph():
    src = """
        class Eng:
            def f(self):
                with self._lock:
                    self._helper()

            def _helper(self):
                toks = np.asarray(device_tokens)
    """
    found = _unwaived(_analyze(src), "lock-readback")
    assert len(found) == 1
    assert "_helper" in found[0].message


def test_lock_discipline_context_fn():
    """A function declared as running with a lock held (dynamic hook the
    AST can't follow) is scanned as if inside the lock body."""
    src = """
        class Eng:
            def hook(self):
                jax.block_until_ready(arrs)
    """
    reg = _registry(context_fns={(FIX, "Eng.hook"): ("fix",)})
    assert _unwaived(_analyze(src, reg), "lock-readback")


def test_waiver_without_reason_rejected():
    waived = DISPATCH_SRC.replace(
        "with self._lock:",
        "with self._lock:  # aios: waive(lock-dispatch)",
    )
    findings = _analyze(waived)
    # the hazard still fires AND the empty waiver is its own finding
    assert _unwaived(findings, "lock-dispatch")
    assert _unwaived(findings, "waiver-reason")


def test_waiver_unknown_rule_rejected():
    findings = _analyze("""
        class Eng:
            def f(self):
                x = 1  # aios: waive(made-up-rule): because
    """)
    assert _unwaived(findings, "waiver-reason")


def test_standalone_waiver_line_governs_next_code_line():
    src = """
        class Eng:
            def f(self):
                with self._lock:
                    # aios: waive(lock-readback): fixture rationale
                    toks = np.asarray(device_tokens)
    """
    assert not _unwaived(_analyze(src), "lock-readback")


# -- rule 2: lock-order cycles ----------------------------------------------

def test_lock_order_cycle_detected():
    # Eng holds fix -> takes other; Other holds other -> calls back into
    # Eng.grab which takes fix: a classic AB/BA
    src = """
        class Eng:
            def a(self):
                with self._lock:
                    self.other.take()

            def grab(self):
                with self._lock:
                    pass

        class Other:
            def take(self):
                with self._lock:
                    pass

            def b(self):
                with self._lock:
                    self.eng.grab()
    """
    found = _unwaived(_analyze(src), "lock-order")
    assert len(found) == 1
    assert "fix" in found[0].message and "other" in found[0].message


def test_lock_order_acyclic_is_clean():
    src = """
        class Eng:
            def a(self):
                with self._lock:
                    self.other.take()

        class Other:
            def take(self):
                with self._lock:
                    pass
    """
    assert not _unwaived(_analyze(src), "lock-order")


# -- rule 3: guarded-by ------------------------------------------------------

GUARDED_SRC = """
    class Eng:
        def __init__(self):
            self._live = {}  #: guarded_by _lock

        def good(self):
            with self._lock:
                self._live[1] = "x"

        def bad(self):
            self._live.clear()
"""


def test_guarded_by_fires_on_unlocked_mutation():
    found = _unwaived(_analyze(GUARDED_SRC), "guarded-by")
    assert len(found) == 1
    assert "_live" in found[0].message
    # only the unlocked mutation fires — __init__ and the locked write
    # are allowed
    assert found[0].line == textwrap.dedent(GUARDED_SRC).splitlines().index(
        '        self._live.clear()'
    ) + 1


def test_guarded_by_waiver_honored():
    waived = GUARDED_SRC.replace(
        "self._live.clear()",
        "self._live.clear()  # aios: waive(guarded-by): fixture rationale",
    )
    assert not _unwaived(_analyze(waived), "guarded-by")


# -- rule 4: dispatch hygiene (jit-warmup) -----------------------------------

def test_jit_warmup_fires_off_warmup_path():
    src = """
        class Eng:
            def serve(self):
                fn = jax.jit(body)
                return fn(x)
    """
    reg = _registry(dispatch_hygiene_modules=(FIX,))
    found = _unwaived(_analyze(src, reg), "jit-warmup")
    assert len(found) == 1
    assert "serve" in found[0].message


def test_jit_warmup_reachable_from_registration_is_clean():
    src = """
        class Eng:
            def warmup(self):
                self.compile_step_fn(1)

            def compile_step_fn(self, n):
                self._store[n] = self._make_jit(n)

            def _make_jit(self, n):
                return jax.jit(body)
    """
    reg = _registry(dispatch_hygiene_modules=(FIX,))
    assert not _unwaived(_analyze(src, reg), "jit-warmup")


def test_jit_warmup_waiver_honored():
    src = """
        class Eng:
            def serve(self):
                fn = jax.jit(body)  # aios: waive(jit-warmup): fixture rationale
    """
    reg = _registry(dispatch_hygiene_modules=(FIX,))
    assert not _unwaived(_analyze(src, reg), "jit-warmup")


def test_jit_warmup_covers_draft_module():
    """ISSUE 11: the draft-model speculation module is serving-path —
    the rule must watch it (today its graphs are jitted from engine.py
    behind compile_draft_spec_fn/compile_draft_ingest_fns, which the
    WARMUP_ROOT_RE compile_* root already matches; a stray jax.jit added
    to spec.py itself must fail tier-1, not reach prod)."""
    from aios_tpu.analysis import registry as live_reg

    assert "aios_tpu.engine.spec" in live_reg.DISPATCH_HYGIENE_MODULES
    assert live_reg.WARMUP_ROOT_RE.match("compile_draft_spec_fn")
    assert live_reg.WARMUP_ROOT_RE.match("compile_draft_ingest_fns")


# -- rule: silent-except (ISSUE 10) ------------------------------------------

def _se_registry():
    r = _registry()
    r.silent_except_prefixes = (FIX,)
    return r


SILENT_SRC = """
    class Pool:
        def cleanup(self):
            try:
                self.batcher.shutdown()
            except Exception:
                pass
"""


def test_silent_except_fires_on_swallowed_broad_handler():
    found = _unwaived(_analyze(SILENT_SRC, _se_registry()), "silent-except")
    assert len(found) == 1
    assert "black hole" in found[0].message


def test_silent_except_waiver_honored():
    waived = SILENT_SRC.replace(
        "except Exception:",
        "except Exception:  # aios: waive(silent-except): fixture rationale",
    )
    assert not _unwaived(_analyze(waived, _se_registry()), "silent-except")


@pytest.mark.parametrize("body", [
    "raise",
    "log.exception('boom')",
    "log.warning('boom %s', exc)",
    "self._abort_all(exc)",
    "live.abort_reason = 'evicted: boom'",
    "self._finish(live, abort_reason='boom')",
    "context.abort(code, 'boom')",
])
def test_silent_except_recording_handlers_are_clean(body):
    src = f"""
        class Pool:
            def cleanup(self):
                try:
                    self.batcher.shutdown()
                except Exception as exc:
                    {body}
    """
    assert not _unwaived(_analyze(src, _se_registry()), "silent-except")


def test_silent_except_bare_and_tuple_handlers_count_as_broad():
    src = """
        class Pool:
            def a(self):
                try:
                    work()
                except:
                    pass

            def b(self):
                try:
                    work()
                except (ValueError, BaseException):
                    pass

            def c(self):
                try:
                    work()
                except ValueError:
                    pass  # narrow: not this rule's business
    """
    found = _unwaived(_analyze(src, _se_registry()), "silent-except")
    assert len(found) == 2


def test_silent_except_scoped_to_registry_prefixes():
    """A module outside the declared prefixes is not checked — the rule
    polices the serving plane, not every utility script."""
    assert not _unwaived(_analyze(SILENT_SRC, _registry()), "silent-except")


# -- rule 5: knob drift + metric catalog -------------------------------------

def test_knob_docs_missing_knob_fires_and_waives():
    src = """
        import os
        FLAG = os.environ.get("AIOS_TPU_FIXTURE_KNOB", "")
    """
    found = _unwaived(_analyze(src, doc="nothing here"), "knob-docs")
    assert len(found) == 1 and "AIOS_TPU_FIXTURE_KNOB" in found[0].message
    waived = src.replace(
        'FLAG = os.environ.get("AIOS_TPU_FIXTURE_KNOB", "")',
        'FLAG = os.environ.get("AIOS_TPU_FIXTURE_KNOB", "")'
        '  # aios: waive(knob-docs): fixture rationale',
    )
    assert not _unwaived(_analyze(waived, doc="nothing"), "knob-docs")


def test_knob_docs_stale_doc_row_fires():
    found = _unwaived(
        _analyze("x = 1", doc="| `AIOS_TPU_GONE_KNOB` | old |"),
        "knob-docs",
    )
    assert len(found) == 1
    assert found[0].path.endswith("CONFIG.md")
    assert "AIOS_TPU_GONE_KNOB" in found[0].message


def test_metric_catalog_fires_outside_instruments():
    src = """
        COUNT = Counter("aios_tpu_fixture_total", "help", ("model",))
    """
    found = _unwaived(_analyze(src), "metric-catalog")
    assert len(found) == 1
    waived = src.replace(
        '("model",))',
        '("model",))  # aios: waive(metric-catalog): fixture rationale',
    )
    assert not _unwaived(_analyze(waived), "metric-catalog")


def test_metric_catalog_ignores_collections_counter():
    src = """
        import collections
        by_cat = collections.Counter(e["category"] for e in events)
    """
    assert not _unwaived(_analyze(src), "metric-catalog")


# -- the real tree, through the CLI entry point ------------------------------

def test_tree_is_clean():
    """Zero unwaived findings on the shipped tree — THE tier-1 gate,
    through the exact entry point ``python -m aios_tpu.analysis`` uses,
    so local runs and CI cannot diverge."""
    assert analysis_cli.main([]) == 0


def test_cli_rule_filter_and_json(capsys):
    import json

    assert analysis_cli.main(["--rule", "lock-order", "--json"]) == 0
    out = capsys.readouterr().out
    assert isinstance(json.loads(out), list)


def test_cli_list_rules(capsys):
    assert analysis_cli.main(["--list-rules"]) == 0
    listed = capsys.readouterr().out.split()
    assert listed == list(RULE_IDS)


def test_registry_locks_all_wired_to_make_lock():
    """Every declared lock is constructed through make_lock(<name>) in
    its declared module (the static registry and the runtime DebugLock
    names must agree, or AIOS_TPU_LOCK_DEBUG verifies a different lock
    set than the analyzer defends)."""
    import importlib

    from aios_tpu.analysis.core import module_info_for, string_call_args

    wired = set()
    for decl in LOCKS:
        mod = importlib.import_module(decl.module)
        mi = module_info_for(mod)
        names = {
            lit for lit, _ in string_call_args(mi.tree, ("make_lock",))
        }
        assert decl.name in names, (
            f"{decl.module} never calls make_lock({decl.name!r})"
        )
        wired.add(decl.name)
    assert wired == {d.name for d in LOCKS}


# -- DebugLock runtime half --------------------------------------------------

def test_debug_lock_detects_ab_ba_inversion():
    """Two threads acquiring two lock roles in opposite orders: the
    second ordering raises LockOrderError carrying both stacks."""
    a = DebugLock("t_inv_a")
    b = DebugLock("t_inv_b")

    def order_ab():
        with a:
            with b:
                pass

    t = threading.Thread(target=order_ab)
    t.start()
    t.join()

    caught = []

    def order_ba():
        try:
            with b:
                with a:  # closes the cycle -> raises
                    pass
        except LockOrderError as e:
            caught.append(e)

    t2 = threading.Thread(target=order_ba)
    t2.start()
    t2.join()
    assert len(caught) == 1
    msg = str(caught[0])
    assert "t_inv_a" in msg and "t_inv_b" in msg
    assert "current acquisition" in msg
    assert "opposite order" in msg
    # the failed acquire left nothing held: b released by the context
    # manager, a never acquired
    assert not a.locked() and not b.locked()


def test_debug_lock_roles_not_instances():
    """Two instances of the SAME role nested do not form an edge (two
    replicas' batcher locks are one role), but opposite-order roles
    across DIFFERENT instances still trip."""
    a1, a2 = DebugLock("t_role_a"), DebugLock("t_role_a")
    with a1:
        with a2:  # same role: no self-edge, no raise
            pass
    b = DebugLock("t_role_b")
    with a1:
        with b:
            pass
    with pytest.raises(LockOrderError):
        with b:
            with a2:  # a-role then b-role was recorded via a1
                pass


def test_debug_lock_watchdog_trips(monkeypatch):
    monkeypatch.setenv("AIOS_TPU_LOCK_WATCHDOG_SECS", "0.05")
    lk = DebugLock("t_watchdog")
    before = len(watchdog_trips())
    with lk:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            trips = watchdog_trips()[before:]
            if any(t["lock"] == "t_watchdog" for t in trips):
                break
            time.sleep(0.02)
    trips = [t for t in watchdog_trips()[before:]
             if t["lock"] == "t_watchdog"]
    assert trips, "watchdog never tripped on a 0.05s threshold"
    assert trips[0]["held_secs"] >= 0.05
    assert trips[0]["stack"]  # the holder's live stack was captured


def test_make_lock_honors_debug_flag(monkeypatch):
    monkeypatch.setenv("AIOS_TPU_LOCK_DEBUG", "1")
    assert isinstance(make_lock("t_flag"), DebugLock)
    monkeypatch.setenv("AIOS_TPU_LOCK_DEBUG", "0")
    lk = make_lock("t_flag")
    assert isinstance(lk, type(threading.Lock()))


def test_debug_lock_is_a_lock():
    """Context manager + acquire/release/locked surface parity."""
    lk = DebugLock("t_surface")
    assert lk.acquire(blocking=False)
    assert lk.locked()
    assert not lk.acquire(blocking=False)
    lk.release()
    assert not lk.locked()
    with lk:
        assert lk.locked()
    assert not lk.locked()
