#!/usr/bin/env bash
# Seeded chaos storm against a live 2-replica pool — the pre-merge
# robustness gate (docs/FAULTS.md, docs/TESTING.md), the fault-tolerance
# sibling of scripts/analyze.sh.
#
# Runs bench.py --chaos: the SAME seeded fault schedule (replica
# scheduler crash + probabilistic dispatch delays) against fresh pools
# under a concurrent greedy wave — FOUR ARMS (a plain pool; a
# draft-speculation pool with a paired DraftModel + speculative
# batchers; a longctx pool with window+sink KV compression armed
# and prompts long enough to prune mid-storm; and a megagraph pool
# serving mega_ticks=8 device-resident decode windows with
# pool.megatick_abort layered on so a seeded device early-exit fires
# mid-window on top of the crash), each run twice. Exit is
# NON-ZERO on any stuck request, any aborted stream (transparent
# failover must complete every greedy request), a nondeterministic
# re-run (token streams, terminal states, and the nth-mode
# injected-fault sequence must be identical — including the compressed
# arm's pruned streams), a draft-arm stream that diverges from the
# plain arm's (speculation may change dispatch counts, never tokens —
# even across a mid-storm crash and the failover-time draft-KV
# rebuild), a mega-arm stream that diverges from the plain arm's
# (K-tick windows and forced early exits may change dispatch counts,
# never tokens), or a mega arm whose seeded abort never fired.
#
# Usage:
#   scripts/chaos.sh                 # default seed (42)
#   scripts/chaos.sh --seed 7        # a different storm
#   CHAOS_SEED=7 scripts/chaos.sh    # same, env-style for CI matrices
#
# Reading a failure: the JSON line on stdout carries stuck/aborted
# counts + the nth fault sequence; the flight recorder's crash_respawn
# snapshot (GET /debug/snapshots on a live deployment, or the
# AIOS_TPU_FLIGHTREC_DUMP_DIR files) holds the per-request timelines.
# docs/RUNBOOK.md "chaos drill" walks the live-pool version.
#
# The gate also fails LOUDLY when the fault schedule never fired
# (faults_armed=false in the JSON): an empty faults.fired() journal —
# e.g. a point name mis-spelled during a refactor — used to let the
# storm pass vacuously, proving nothing.
set -euo pipefail
cd "$(dirname "$0")/.."

seed="${CHAOS_SEED:-42}"
if [[ "${1:-}" == "--seed" && -n "${2:-}" ]]; then
  seed="$2"
fi

exec python bench.py --chaos --chaos-seed "$seed"
