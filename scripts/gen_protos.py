#!/usr/bin/env python
"""Generate Python protobuf message modules for the 7 aiOS proto packages.

The image ships `protoc` (libprotoc 3.21) but not `grpcio-tools`, so we:
  1. run `protoc --python_out` for the message classes, and
  2. rewrite absolute imports to package-relative ones so the generated
     modules live inside `aios_tpu.proto_gen`.

gRPC stubs/servicers are NOT generated; they are built programmatically at
import time by `aios_tpu.rpc` from the method tables in
`aios_tpu.proto_gen.services` (equivalent surface to grpcio-tools output).
"""

from __future__ import annotations

import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PROTO_DIR = REPO / "aios_tpu" / "protos"
OUT_DIR = REPO / "aios_tpu" / "proto_gen"

PROTOS = [
    "common.proto",
    "runtime.proto",
    "orchestrator.proto",
    "agent.proto",
    "tools.proto",
    "api_gateway.proto",
    "memory.proto",
    "fleet.proto",
]


def main() -> int:
    OUT_DIR.mkdir(parents=True, exist_ok=True)
    cmd = [
        "protoc",
        f"--proto_path={PROTO_DIR}",
        f"--python_out={OUT_DIR}",
        *PROTOS,
    ]
    subprocess.run(cmd, check=True, cwd=PROTO_DIR)

    # protoc emits `import common_pb2 as common__pb2`; make it relative.
    for py in OUT_DIR.glob("*_pb2.py"):
        text = py.read_text()
        fixed = re.sub(
            r"^import (\w+_pb2) as", r"from . import \1 as", text, flags=re.M
        )
        py.write_text(fixed)

    init = OUT_DIR / "__init__.py"
    names = [p.replace(".proto", "_pb2") for p in PROTOS]
    init.write_text(
        '"""Generated protobuf modules (see scripts/gen_protos.py)."""\n'
        + "".join(f"from . import {n}\n" for n in names)
        + "\n__all__ = [\n"
        + "".join(f'    "{n}",\n' for n in names)
        + "]\n"
    )
    print(f"generated {len(names)} modules into {OUT_DIR}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
