"""Capability ACL with risk levels.

Reference parity (tools/src/capabilities.rs): ~30 capability strings,
tool-pattern -> required-capability mapping with four risk levels
(Low/Medium/High/Critical, capabilities.rs:10-28), and per-agent grant
tables hardcoded for the autonomy loop (ALL) and each system agent
(capabilities.rs:49-181). Grants can be extended/revoked at runtime via the
orchestrator's capability RPCs.
"""

from __future__ import annotations

import fnmatch
import threading
from typing import Dict, List, Set

RISK_LOW = "low"
RISK_MEDIUM = "medium"
RISK_HIGH = "high"
RISK_CRITICAL = "critical"

# tool-name pattern -> (required capabilities, risk level)
TOOL_REQUIREMENTS: List[tuple[str, List[str], str]] = [
    ("fs.read", ["fs.read"], RISK_LOW),
    ("fs.list", ["fs.read"], RISK_LOW),
    ("fs.stat", ["fs.read"], RISK_LOW),
    ("fs.search", ["fs.read"], RISK_LOW),
    ("fs.disk_usage", ["fs.read"], RISK_LOW),
    ("fs.delete", ["fs.write"], RISK_HIGH),
    ("fs.*", ["fs.write"], RISK_MEDIUM),
    ("process.list", ["process.read"], RISK_LOW),
    ("process.info", ["process.read"], RISK_LOW),
    ("process.kill", ["process.manage"], RISK_HIGH),
    ("process.*", ["process.manage"], RISK_MEDIUM),
    ("service.list", ["service.read"], RISK_LOW),
    ("service.status", ["service.read"], RISK_LOW),
    ("service.*", ["service.manage"], RISK_HIGH),
    ("net.port_scan", ["net.scan"], RISK_MEDIUM),
    ("net.*", ["net.diagnose"], RISK_LOW),
    ("firewall.rules", ["firewall.read"], RISK_LOW),
    ("firewall.*", ["firewall.manage"], RISK_CRITICAL),
    ("pkg.search", ["pkg.read"], RISK_LOW),
    ("pkg.list_installed", ["pkg.read"], RISK_LOW),
    ("pkg.*", ["pkg.manage"], RISK_HIGH),
    ("sec.grant", ["sec.admin"], RISK_CRITICAL),
    ("sec.revoke", ["sec.admin"], RISK_CRITICAL),
    ("sec.*", ["sec.audit"], RISK_MEDIUM),
    ("monitor.*", ["monitor.read"], RISK_LOW),
    ("hw.*", ["hw.read"], RISK_LOW),
    ("web.*", ["web.access"], RISK_MEDIUM),
    ("git.*", ["git.use"], RISK_MEDIUM),
    ("code.*", ["code.generate"], RISK_MEDIUM),
    ("self.inspect", ["self.read"], RISK_LOW),
    ("self.*", ["self.manage"], RISK_CRITICAL),
    ("plugin.list", ["plugin.read"], RISK_LOW),
    ("plugin.*", ["plugin.manage"], RISK_HIGH),
    ("container.list", ["container.read"], RISK_LOW),
    ("container.logs", ["container.read"], RISK_LOW),
    ("container.*", ["container.manage"], RISK_HIGH),
    ("email.*", ["email.send"], RISK_MEDIUM),
]

ALL_CAPABILITIES: Set[str] = {
    cap for _, caps, _ in TOOL_REQUIREMENTS for cap in caps
}

# Per-agent default grants (capabilities.rs:49-181). The autonomy loop runs
# with everything; each Python agent gets its own namespace slice.
DEFAULT_GRANTS: Dict[str, Set[str]] = {
    "autonomy-loop": set(ALL_CAPABILITIES),
    "orchestrator": set(ALL_CAPABILITIES),
    "system_agent": {
        "fs.read", "fs.write", "process.read", "process.manage",
        "service.read", "service.manage", "monitor.read", "hw.read",
    },
    "network_agent": {
        "net.diagnose", "net.scan", "firewall.read", "firewall.manage",
        "monitor.read",
    },
    "security_agent": {
        "sec.audit", "sec.admin", "fs.read", "process.read", "monitor.read",
        "net.scan",
    },
    "package_agent": {"pkg.read", "pkg.manage", "fs.read"},
    "monitoring_agent": {"monitor.read", "fs.read", "process.read", "hw.read"},
    "learning_agent": {"monitor.read", "fs.read"},
    "storage_agent": {"fs.read", "fs.write", "hw.read", "monitor.read"},
    "task_agent": {
        "fs.read", "fs.write", "process.read", "service.read", "monitor.read",
        "web.access", "code.generate",
    },
    "web_agent": {"web.access", "net.diagnose", "fs.read", "fs.write"},
    "creator_agent": {"code.generate", "fs.read", "fs.write", "git.use"},
}


def requirements_for(tool_name: str) -> tuple[List[str], str]:
    """First matching pattern wins (patterns are ordered specific-first)."""
    for pattern, caps, risk in TOOL_REQUIREMENTS:
        if fnmatch.fnmatch(tool_name, pattern):
            return caps, risk
    return [], RISK_LOW


class CapabilityChecker:
    def __init__(self):
        self._grants: Dict[str, Set[str]] = {
            agent: set(caps) for agent, caps in DEFAULT_GRANTS.items()
        }
        self._lock = threading.Lock()

    def grants_for(self, agent_id: str) -> Set[str]:
        with self._lock:
            if agent_id in self._grants:
                return set(self._grants[agent_id])
            # agent ids look like "system_agent-1234"; fall back on the type
            for known, caps in self._grants.items():
                if agent_id.startswith(known):
                    return set(caps)
            return set()

    def check(self, agent_id: str, tool_name: str) -> tuple[bool, str]:
        required, risk = requirements_for(tool_name)
        have = self.grants_for(agent_id)
        missing = [c for c in required if c not in have]
        if missing:
            return False, (
                f"agent {agent_id} lacks capabilities {missing} "
                f"for {tool_name} (risk {risk})"
            )
        return True, ""

    def grant(self, agent_id: str, capabilities: List[str]) -> None:
        with self._lock:
            self._grants.setdefault(agent_id, set()).update(capabilities)

    def revoke(self, agent_id: str, capabilities: List[str], all_: bool = False):
        with self._lock:
            if agent_id not in self._grants:
                return
            if all_:
                self._grants[agent_id] = set()
            else:
                self._grants[agent_id] -= set(capabilities)
