"""Seeded per-edge network faults — the fleet's messy-failure surface.

PR 17's ``fleet.host_kill`` covers the CLEAN loss (a process dies, exit
17, everyone agrees). Production fleets mostly die messily: partitions,
asymmetric reachability, links that accept a connection and then sever
the stream mid-transfer. This module extends the seeded determinism
contract of :mod:`aios_tpu.faults.inject` to *edges* — every fault is
keyed ``(src_host, dst_host)`` with its own hit counter, so the k-th
send on one edge fires the same way across re-runs no matter how other
edges interleave (docs/FAULTS.md "Per-edge network faults"):

    net.partition          both directions refused (send refused AND
                           inbound announces rejected at the server)
    net.partition_oneway   src->dst dropped, the reverse edge clean —
                           the asymmetric case the up/suspect/dead
                           machine in obs/fleet.py has never seen
    net.delay              per-edge latency (``delay_ms``) before send
    net.drop_after         the connection is accepted and the stream
                           severed after ``after_msgs`` messages

Injection happens at exactly two choke points so membership,
federation, KVX, and Handoff all traverse ONE fault surface: the shared
gRPC client interceptor (``rpc.insecure_channel``) and the
``obs/fleet.py`` announce/scrape/stitch HTTP helpers. Each is a
near-zero-cost no-op unless a schedule is armed — the gate is one
module-global None check inside :func:`aios_tpu.faults.point`.

Edges are named by fleet HOST IDS (``AIOS_TPU_FLEET_HOST``), not
addresses: schedules survive ephemeral ports. The addr->host map is fed
by membership gossip (``obs/fleet._observe`` calls :func:`map_addr` for
every descriptor it folds); an address never seen in gossip resolves to
itself, so addr-keyed schedules also work in addressless tests.

:class:`NetFault` subclasses BOTH :class:`ConnectionError` and
``grpc.RpcError`` with an UNAVAILABLE ``code()`` — every existing
``except grpc.RpcError`` recovery path (kvx cause accounting, the
Handoff resume ladder) catches an injected edge fault exactly as it
catches a real dead peer, which is the point.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Dict, Iterator, Tuple

import grpc

from . import inject

log = logging.getLogger("aios.faults.net")

__all__ = [
    "NET_POINTS", "SURFACES", "NetFault", "NetFaultRefused",
    "NetFaultSevered", "self_host", "host_of", "map_addr", "check_send",
    "check_drop_response", "sever_stream", "gate_announce",
]

# The per-edge subset of faults.POINTS this module injects (pinned
# against the catalog by tests/test_fleet_faults.py).
NET_POINTS = (
    "net.partition",
    "net.partition_oneway",
    "net.delay",
    "net.drop_after",
)

# Legal surface= filter values; "" in a schedule means both surfaces.
SURFACES = ("rpc", "http")


class NetFault(ConnectionError, grpc.RpcError):
    """An injected network-edge fault. Doubles as a grpc.RpcError with
    an UNAVAILABLE code so RPC-shaped recovery paths treat it as the
    dead-peer error it is simulating."""

    def __init__(self, point: str, edge: Tuple[str, str], hit: int) -> None:
        super().__init__(
            f"injected {point} on edge {edge[0]}->{edge[1]} (hit {hit})"
        )
        self.point = point
        self.edge = edge
        self.hit = hit

    def code(self) -> grpc.StatusCode:
        return grpc.StatusCode.UNAVAILABLE

    def details(self) -> str:
        return str(self)


class NetFaultRefused(NetFault):
    """net.partition / net.partition_oneway: the send never left."""


class NetFaultSevered(NetFault):
    """net.drop_after: the transfer started and the link cut it."""


# -- edge naming -------------------------------------------------------------

# self host id: env wins (the fleet worker contract), else the same
# hostname:pid fallback process_identity() uses. The fallback is cached
# (stable for the process lifetime); the env read is live so tests that
# flip AIOS_TPU_FLEET_HOST see the change.
_fallback_host = ""

# peer address -> fleet host id, fed by membership gossip. Writes are
# rare (first sight of a member); reads ride the GIL-atomic dict get.
_addr_hosts: Dict[str, str] = {}
_addr_lock = threading.Lock()  # map writes only — never on hot paths


def self_host() -> str:
    """This process's fleet host id — the src of every outbound edge."""
    env = os.environ.get("AIOS_TPU_FLEET_HOST", "")
    if env:
        return env
    global _fallback_host
    if not _fallback_host:
        _fallback_host = f"{socket.gethostname()}:{os.getpid()}"
    return _fallback_host


def map_addr(addr: str, host: str) -> None:
    """Teach the edge namer that ``addr`` (host:port) belongs to fleet
    host ``host`` — called by obs/fleet._observe for every descriptor's
    metrics_addr and kvx_addr."""
    if not addr or not host:
        return
    with _addr_lock:
        _addr_hosts[addr] = host


def host_of(addr: str) -> str:
    """Fleet host id for a peer address (URL or host:port); an address
    gossip has not named yet resolves to itself."""
    a = addr
    if "//" in a:
        a = a.split("//", 1)[1]
    a = a.split("/", 1)[0]
    return _addr_hosts.get(a, a)


def _reset() -> None:
    """Test isolation: drop the addr->host map and host cache."""
    global _fallback_host
    with _addr_lock:
        _addr_hosts.clear()
    _fallback_host = ""


# -- client-side injection gates ---------------------------------------------

def check_send(dst: str, surface: str) -> None:
    """The outbound gate, called before a send on ``surface`` to ``dst``
    (URL or host:port). Raises :class:`NetFaultRefused` on a fired
    partition (either flavor — the send direction is the dropped one),
    sleeps on a fired net.delay. No-op unless a schedule is armed."""
    if not inject.active():
        return
    edge = (self_host(), host_of(dst))
    act = inject.point("net.partition", edge=edge, surface=surface)
    if act is None:
        act = inject.point(
            "net.partition_oneway", edge=edge, surface=surface
        )
    if act is not None:
        raise NetFaultRefused(act.point, edge, act.hit)
    act = inject.point("net.delay", edge=edge, surface=surface)
    if act is not None and act.delay_s > 0:
        time.sleep(act.delay_s)


def check_drop_response(dst: str, surface: str = "http") -> None:
    """The HTTP half of net.drop_after, called AFTER a successful fetch:
    the request reached the server (its side effects happened — that is
    what distinguishes a sever from a refusal) but the response is
    discarded on the floor. Raises :class:`NetFaultSevered` when the
    point fires."""
    if not inject.active():
        return
    edge = (self_host(), host_of(dst))
    act = inject.point("net.drop_after", edge=edge, surface=surface)
    if act is not None:
        raise NetFaultSevered(act.point, edge, act.hit)


class _SeveredStream:
    """A response-stream wrapper that delivers ``after_msgs`` messages
    and then cuts the link — the caller sees a healthy stream die
    mid-transfer, exactly the failure the resume ladder must absorb."""

    def __init__(self, inner: Iterator, act: inject.FaultAction,
                 edge: Tuple[str, str]) -> None:
        self._inner = inner
        self._left = max(0, act.after_msgs)
        self._act = act
        self._edge = edge

    def __iter__(self) -> "_SeveredStream":
        return self

    def __next__(self):
        if self._left <= 0:
            try:
                self._inner.cancel()  # type: ignore[attr-defined]
            except Exception:  # noqa: BLE001 - best-effort upstream cancel
                pass
            raise NetFaultSevered(
                self._act.point, self._edge, self._act.hit
            )
        self._left -= 1
        return next(self._inner)

    def __getattr__(self, name: str):
        # delegate the grpc call surface (code/cancel/trailing metadata)
        return getattr(self._inner, name)


def sever_stream(dst: str, response: Iterator) -> Iterator:
    """The gRPC half of net.drop_after: consulted ONCE per unary-stream
    call; when the point fires the returned iterator yields
    ``after_msgs`` messages then raises :class:`NetFaultSevered`."""
    if not inject.active():
        return response
    edge = (self_host(), host_of(dst))
    act = inject.point("net.drop_after", edge=edge, surface="rpc")
    if act is None:
        return response
    return _SeveredStream(response, act, edge)


# -- server-side announce gate -----------------------------------------------

def gate_announce(peer_host: str) -> Tuple[bool, bool]:
    """The server side of ``/fleet/announce`` under a per-edge schedule
    -> ``(fold, reply)``. A one-process schedule must be able to model
    an asymmetric partition end to end, and the announce REPLY travels
    the self->announcer edge: when ``net.partition_oneway`` fires on it
    the peer's descriptor still folds (their data reached us) but the
    reply body — our descriptor AND the gossip list — is withheld
    (``reply=False`` -> the handler answers 503). A full
    ``net.partition`` additionally refuses the inbound fold
    (``fold=False``): both directions dead."""
    if not inject.active():
        return True, True
    edge = (self_host(), peer_host)
    if inject.point("net.partition", edge=edge, surface="http") is not None:
        return False, False
    if inject.point(
        "net.partition_oneway", edge=edge, surface="http"
    ) is not None:
        return True, False
    return True, True


def active_points() -> Tuple[str, ...]:
    """Which net points the active plan schedules (breaker/drain tests
    and fleetctl debugging); empty when faults are off."""
    plan = inject._PLAN
    if plan is None:
        return ()
    return tuple(n for n in plan.schedule if n in NET_POINTS)
