"""JSON-Schema-guided decoding: structured outputs on top of the logit-mask
machinery (jsonmode.py).

Where jsonmode's generic automaton guarantees *some* JSON object, this
module compiles a schema (a practical subset of JSON Schema) into a byte
automaton that guarantees the model's output matches an exact SHAPE —
known/required object keys (steered byte-by-byte through a property-name
prefix trie), string enums (e.g. the orchestrator's tool-name set), integer
vs number, booleans/null, arrays, nested schemas, and free-form `{}`
subtrees for open fields like tool-call args. This is the TPU engine's
equivalent of "structured outputs" in modern serving stacks; the reference
has nothing comparable (its autonomy loop re-prompts through JSON-repair
rounds when the model's tool_calls don't parse, autonomy.rs:290-328 —
guided decoding makes the first round parse by construction).

Supported schema subset (validated at compile time):
  {"type": "object", "properties": {...}, "required": [...]}
  {"type": "array", "items": <schema>}   (optionally "minItems": 0|1)
  {"type": "string"}  /  {"type": "string", "enum": [...]}
  {"type": "number"} / {"type": "integer"} / {"type": "boolean"}
  {"type": "null"}   /  {} or {"type": "any"} — any JSON value
  {"const": <string>} — sugar for a one-element enum

Unknown object keys are impossible by construction (every key byte is
steered through the trie), required keys gate '}', and the closing mask
(budget exhaustion) drives the shortest completion that still satisfies
the schema. States are small tuples over a frame stack; the shared
vectorized mask cache (SchemaMaskCache) does the per-state vocab walks.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from . import jsonmode
from .jsonmode import _NUM_DONE, JsonMaskCache

_WS = frozenset(b" \t\n\r")
_DIGITS = frozenset(b"0123456789")
_HEX = frozenset(b"0123456789abcdefABCDEF")

# node kinds
OBJ, ARR, STR, ENUM, NUM, INT, BOOL, NULL, ANY, ANYOBJ = range(10)


class Schema:
    """Compiled schema: a flat node table the automaton indexes into."""

    def __init__(self) -> None:
        self.kinds: List[int] = []
        # OBJ: (props {name_bytes: node_id}, required frozenset[name_bytes])
        # ARR: (items_id, min_items)
        # ENUM: tuple of value bytes
        self.data: List[object] = []

    def add(self, kind: int, data=None) -> int:
        self.kinds.append(kind)
        self.data.append(data)
        return len(self.kinds) - 1


def _check_enum_value(v) -> bytes:
    """Enum/const values are matched (and emitted) as raw bytes inside the
    string — values needing JSON escapes could never be produced (or would
    decode differently), so reject them at compile time."""
    if not isinstance(v, str) or not v:
        raise ValueError(f"enum values must be non-empty strings: {v!r}")
    if '"' in v or "\\" in v or any(ord(c) < 0x20 for c in v):
        raise ValueError(
            f"enum value {v!r} contains characters that need JSON string "
            "escapes (unsupported)"
        )
    return v.encode("utf-8")


def compile_schema(schema: dict) -> Tuple[Schema, int]:
    """Compile a schema dict; returns (table, root node id). Raises
    ValueError on anything outside the supported subset (client input —
    the service maps it to INVALID_ARGUMENT)."""
    table = Schema()

    def build(node) -> int:
        if not isinstance(node, dict):
            raise ValueError(f"schema node must be an object: {node!r}")
        if "const" in node:
            return table.add(ENUM, (_check_enum_value(node["const"]),))
        t = node.get("type")
        if t is None or t == "any":
            return table.add(ANY)
        if t == "object":
            props = node.get("properties", {})
            if not isinstance(props, dict) or not all(
                isinstance(k, str) for k in props
            ):
                raise ValueError("properties must be an object")
            required = node.get("required", list(props.keys()))
            if not isinstance(required, (list, tuple)) or not all(
                isinstance(k, str) for k in required
            ):
                raise ValueError("required must be a list of strings")
            unknown = set(required) - set(props)
            if unknown:
                raise ValueError(f"required keys not in properties: {unknown}")
            for k in props:
                _check_enum_value(k)  # same byte-emission constraints
            if not props:
                # open object: any keys/values, but still an OBJECT
                return table.add(ANYOBJ)
            nid = table.add(OBJ, None)  # reserve (cycles not supported)
            compiled = {
                k.encode("utf-8"): build(v) for k, v in props.items()
            }
            table.data[nid] = (
                compiled,
                frozenset(k.encode("utf-8") for k in required),
            )
            return nid
        if t == "array":
            items = node.get("items", {})
            min_items = node.get("minItems", 0)
            if min_items not in (0, 1):
                raise ValueError("minItems supports 0 or 1")
            nid = table.add(ARR, None)
            table.data[nid] = (build(items), int(min_items))
            return nid
        if t == "string":
            enum = node.get("enum")
            if enum is not None:
                if not isinstance(enum, (list, tuple)) or not enum:
                    raise ValueError("enum must be a non-empty list")
                vals = tuple(sorted(_check_enum_value(v) for v in enum))
                return table.add(ENUM, vals)
            return table.add(STR)
        if t == "integer":
            return table.add(INT)
        if t == "number":
            return table.add(NUM)
        if t == "boolean":
            return table.add(BOOL)
        if t == "null":
            return table.add(NULL)
        raise ValueError(f"unsupported schema type: {t!r}")

    try:
        return table, build(schema)
    except ValueError:
        raise
    except Exception as e:  # malformed client input must not escape as
        raise ValueError(f"malformed schema: {e}") from e  # internal errors


# ---------------------------------------------------------------------------
# the automaton
#
# state tuples (stack is a tuple of frames):
#   ("V", stack, nid)          expecting a value of node nid (ws ok)
#   ("E", stack)               value complete; continuation from top frame
#   ("KQ", stack)              object: expecting '"' (key) or maybe '}'
#   ("KQ1", stack)             object after ',': expecting '"' only
#   ("K", stack, prefix)       inside a key string; prefix bytes matched
#   ("C", stack, key)          after key close: expecting ':' (ws ok)
#   ("S", stack) ("X", stack) ("U", stack, n)    free string / escapes
#   ("SE", stack, nid, prefix) inside an enum string
#   ("N", stack, sub, is_int)  number; sub as in jsonmode
#   ("L", stack, lit, pos)     literal true/false/null
#   ("Y", stack, inner)        free-form subtree; inner = jsonmode state
# frames:
#   ("o", nid, seen frozenset[bytes])
#   ("a", nid, emitted 0|1)    emitted saturates at 1 (minItems gate)
# ---------------------------------------------------------------------------

SState = Tuple


class SchemaMachine:
    def __init__(self, table: Schema, root: int, max_depth: int = 16,
                 compact: bool = False) -> None:
        self.t = table
        self.root = root
        self.max_depth = max_depth
        # compact: disallow inter-element whitespace (string/enum/key
        # CONTENT keeps its spaces) so schema-forced positions become
        # singleton states — the property jump-ahead decoding compresses
        # into multi-token runs (see jsonmode.next_state's compact doc)
        self.compact = compact

    def start(self) -> SState:
        return ("V", (), self.root)

    def terminal(self, st: SState) -> bool:
        return st[0] == "E" and st[1] == ()

    # -- transitions --------------------------------------------------------

    def step(self, st: SState, b: int) -> Optional[SState]:
        phase, stack = st[0], st[1]
        t = self.t

        if phase == "E":
            if b in _WS:
                return None if self.compact else st
            if not stack:
                return None
            top = stack[-1]
            if top[0] == "o":
                _, nid, seen = top
                props, required = t.data[nid]
                if b == ord(","):
                    if set(props) - seen:  # some key still addable
                        return ("KQ1", stack)
                    return None
                if b == ord("}") and required <= seen:
                    return ("E", stack[:-1])
                return None
            # array frame
            _, nid, _emitted = top
            items, _min = t.data[nid]
            if b == ord(","):
                return ("V", stack, items)
            if b == ord("]"):
                return ("E", stack[:-1])
            return None

        if phase == "V":
            nid = st[2]
            if b in _WS:
                return None if self.compact else st
            kind = t.kinds[nid]
            if kind == ANY:
                inner = jsonmode.next_state(("V", ""), b, self.max_depth,
                                            self.compact)
                if inner is None:
                    return None
                return self._norm_y(stack, inner, b)
            if kind == ANYOBJ:  # free-form keys/values, but an OBJECT
                if b != ord("{"):
                    return None
                inner = jsonmode.next_state(("V", ""), b, self.max_depth,
                                            self.compact)
                return self._norm_y(stack, inner, b)
            if kind == OBJ:
                if b == ord("{") and len(stack) < self.max_depth:
                    return ("KQ", stack + (("o", nid, frozenset()),))
                return None
            if kind == ARR:
                if b == ord("[") and len(stack) < self.max_depth:
                    items, min_items = t.data[nid]
                    frame = ("a", nid, 0)
                    # empty array closes immediately unless minItems
                    return ("AV", stack + (frame,), items, min_items)
                return None
            if kind == STR:
                return ("S", stack) if b == ord('"') else None
            if kind == ENUM:
                return ("SE", stack, nid, b"") if b == ord('"') else None
            if kind in (NUM, INT):
                is_int = kind == INT
                if b == ord("-"):
                    return ("N", stack, "-", is_int)
                if b == ord("0"):
                    return ("N", stack, "0", is_int)
                if b in _DIGITS:
                    return ("N", stack, "i", is_int)
                return None
            if kind == BOOL:
                if b == ord("t"):
                    return ("L", stack, "true", 1)
                if b == ord("f"):
                    return ("L", stack, "false", 1)
                return None
            if kind == NULL:
                return ("L", stack, "null", 1) if b == ord("n") else None
            return None

        if phase == "AV":  # first array slot: value or (if allowed) ']'
            nid_items, min_items = st[2], st[3]
            if b in _WS:
                return None if self.compact else st
            if b == ord("]") and min_items == 0:
                return ("E", stack[:-1])
            return self.step(("V", stack, nid_items), b)

        if phase in ("KQ", "KQ1"):
            if b in _WS:
                return None if self.compact else st
            top = stack[-1]
            _, nid, seen = top
            props, required = t.data[nid]
            if b == ord('"'):
                return ("K", stack, b"")
            if phase == "KQ" and b == ord("}") and required <= seen:
                return ("E", stack[:-1])
            return None

        if phase == "K":  # key prefix trie over unseen property names
            prefix = st[2]
            top = stack[-1]
            _, nid, seen = top
            props, _required = t.data[nid]
            if b == ord('"'):
                if prefix in props and prefix not in seen:
                    return ("C", stack, prefix)
                return None
            cand = prefix + bytes([b])
            for name in props:
                if name not in seen and name.startswith(cand):
                    return ("K", stack, cand)
            return None

        if phase == "C":
            key = st[2]
            if b in _WS:
                return None if self.compact else st
            if b == ord(":"):
                top = stack[-1]
                _, nid, seen = top
                props, _req = t.data[nid]
                new_top = ("o", nid, seen | {key})
                return ("V", stack[:-1] + (new_top,), props[key])
            return None

        if phase == "S":
            if b == ord('"'):
                return ("E", stack)
            if b == ord("\\"):
                return ("X", stack)
            return st if b >= 0x20 else None

        if phase == "X":
            if b in b'"\\/bfnrt':
                return ("S", stack)
            if b == ord("u"):
                return ("U", stack, 0)
            return None

        if phase == "U":
            n = st[2]
            if b in _HEX:
                return ("S", stack) if n == 3 else ("U", stack, n + 1)
            return None

        if phase == "SE":
            nid, prefix = st[2], st[3]
            vals = self.t.data[nid]
            if b == ord('"'):
                return ("E", stack) if prefix in vals else None
            cand = prefix + bytes([b])
            for v in vals:
                if v.startswith(cand):
                    return ("SE", stack, nid, cand)
            return None

        if phase == "L":
            lit, pos = st[2], st[3]
            if b == ord(lit[pos]):
                if pos + 1 == len(lit):
                    return ("E", stack)
                return ("L", stack, lit, pos + 1)
            return None

        if phase == "N":
            sub, is_int = st[2], st[3]
            if sub == "-":
                if b == ord("0"):
                    return ("N", stack, "0", is_int)
                if b in _DIGITS:
                    return ("N", stack, "i", is_int)
                return None
            if sub in ("0", "i"):
                if sub == "i" and b in _DIGITS:
                    return st
                if not is_int:
                    if b == ord("."):
                        return ("N", stack, ".", is_int)
                    if b in (ord("e"), ord("E")):
                        return ("N", stack, "e", is_int)
            if sub == ".":
                return ("N", stack, "f", is_int) if b in _DIGITS else None
            if sub == "f":
                if b in _DIGITS:
                    return st
                if b in (ord("e"), ord("E")):
                    return ("N", stack, "e", is_int)
            if sub == "e":
                if b in (ord("+"), ord("-")):
                    return ("N", stack, "s", is_int)
                if b in _DIGITS:
                    return ("N", stack, "E", is_int)
                return None
            if sub == "s":
                return ("N", stack, "E", is_int) if b in _DIGITS else None
            if sub == "E" and b in _DIGITS:
                return st
            if sub in _NUM_DONE:  # complete number: delegate terminator
                return self.step(("E", stack), b)
            return None

        if phase == "Y":  # free-form subtree via the generic machine
            inner = st[2]
            nxt = jsonmode.next_state(inner, b, self.max_depth, self.compact)
            if nxt is None:
                # the generic machine can't see the schema continuation: a
                # COMPLETE inner value followed by ',', '}', ']' must pop
                # back to the schema frame
                if jsonmode.is_terminal(inner) or (
                    inner[0] == "N" and inner[2] in _NUM_DONE
                    and inner[1] == ""
                ):
                    return self.step(("E", stack), b)
                return None
            return self._norm_y(stack, nxt, b)

        return None

    def _norm_y(self, stack, inner, b) -> SState:
        """Wrap a generic-machine state; a completed top-level inner value
        collapses back to the schema's E."""
        if jsonmode.is_terminal(inner):
            return ("E", stack)
        return ("Y", stack, inner)

    # -- closing distance --------------------------------------------------
    #
    # Minimal completion cost in BYTES — an upper bound on the TOKENS a
    # closing walk needs (a token carries >= 1 byte), so the budget-aware
    # switch engages early enough on every tokenizer. Mid-key states must
    # count the whole remaining key + quote + colon + a minimal value —
    # the generic per-phase constants of jsonmode underestimate that
    # badly (observed: truncation inside a schema key at budget end).

    def _node_cost(self, nid: int) -> int:
        cached = getattr(self, "_node_costs", None)
        if cached is None:
            cached = self._node_costs = {}
        got = cached.get(nid)
        if got is not None:
            return got
        cached[nid] = 2 + self.max_depth * 8  # cycle guard (unused: no refs)
        t = self.t
        kind = t.kinds[nid]
        if kind in (NUM, INT):
            c = 1  # "0"
        elif kind == BOOL:
            c = 4  # true
        elif kind == NULL:
            c = 4
        elif kind == STR:
            c = 2  # ""
        elif kind == ENUM:
            c = 2 + min(len(v) for v in t.data[nid])
        elif kind in (ANY, ANYOBJ):
            c = 2  # {}
        elif kind == ARR:
            items, min_items = t.data[nid]
            c = 2 + (self._node_cost(items) if min_items else 0)
        else:  # OBJ
            props, required = t.data[nid]
            c = 2
            for k in required:
                # "key":<value> plus a comma between entries
                c += len(k) + 4 + self._node_cost(props[k])
            if required:
                c -= 1  # no trailing comma
        cached[nid] = c
        return c

    def _entry_cost(self, name: bytes, props, prefix_done: int = 0) -> int:
        """Remaining bytes for the TAIL of `name":<minimal value>` given
        ``prefix_done`` name bytes emitted (close quote + colon included,
        OPEN quote not)."""
        return (
            len(name) - prefix_done + 2 + self._node_cost(props[name])
        )

    def _frame_charge(self, name: bytes, props) -> int:
        """Bytes one missing required entry adds: `,"` + the entry tail."""
        return 2 + self._entry_cost(name, props)

    def distance(self, st: SState) -> int:
        """Bytes of the cheapest completion from ``st``. Along a closing
        walk every consumed byte reduces this by >= 1 (signed phase
        extras UNCHARGE the enclosing frame's estimate for the required
        entry currently being typed), so min-distance token selection
        can never dither in place."""
        phase, stack = st[0], st[1]
        t = self.t
        d = 0
        for fr in stack:
            if fr[0] == "o":
                _, nid, seen = fr
                props, required = t.data[nid]
                d += 1  # '}'
                for k in required - seen:
                    d += self._frame_charge(k, props)
            else:
                d += 1  # ']'
        if phase == "E":
            return d
        if phase == "N":
            return d if st[2] in _NUM_DONE else d + 1
        if phase == "S":
            return d + 1  # closing quote
        if phase == "X":
            return d + 2  # escape char + quote
        if phase == "U":
            return d + (4 - st[2]) + 1
        if phase == "SE":
            _, _, nid, prefix = st
            vals = [v for v in t.data[nid] if v.startswith(prefix)]
            return d + min(len(v) - len(prefix) for v in vals) + 1
        if phase == "L":
            return d + len(st[2]) - st[3]
        if phase in ("KQ", "KQ1", "K", "C"):
            top = stack[-1]
            _, nid, seen = top
            props, required = t.data[nid]
            if phase == "C":
                key = st[2]
                extra = 1 + self._node_cost(props[key])  # ':' + value
                if key in required:
                    extra -= self._frame_charge(key, props)
                return d + extra
            if phase == "K":
                prefix = st[2]
                best = None
                for name in props:
                    if name in seen or not name.startswith(prefix):
                        continue
                    cost = self._entry_cost(name, props, len(prefix))
                    if name in required:
                        cost -= self._frame_charge(name, props)
                    best = cost if best is None else min(best, cost)
                return d + (best if best is not None else 1)
            if phase == "KQ1":  # comma emitted: a key must follow
                best = None
                for name in props:
                    if name in seen:
                        continue
                    cost = 1 + self._entry_cost(name, props)  # open quote
                    if name in required:
                        cost -= self._frame_charge(name, props)
                    best = cost if best is None else min(best, cost)
                return d + (best if best is not None else 1)
            # KQ: '}' or the (already charged) required entries; the first
            # entry after '{' needs no comma, so uncharge one byte —
            # without this '{' never reduces the distance and the
            # feasibility gate can dither on whitespace at the budget edge
            return d - (1 if required - seen else 0)
        if phase == "Y":
            # generic distances are exact byte minimums now
            return d + jsonmode.distance_to_terminal(st[2])
        if phase == "AV":
            return d + (self._node_cost(st[2]) if st[3] else 0)
        if phase == "V":
            return d + self._node_cost(st[2])
        return d + 1


class SchemaMaskCache(JsonMaskCache):
    """Mask cache over a compiled schema automaton (one per (model,
    schema); see ContinuousBatcher's registry)."""

    def __init__(
        self,
        token_bytes,
        eos_id,
        schema: dict,
        max_depth: int = 16,
        byte_matrix=None,
        compact: bool = False,
    ) -> None:
        table, root = compile_schema(schema)
        self.machine = SchemaMachine(table, root, max_depth, compact=compact)
        super().__init__(
            token_bytes,
            eos_id,
            require_object=True,
            max_depth=max_depth,
            byte_matrix=byte_matrix,
            compact=compact,
        )
        # the forced opener depends on the root node kind
        root_kind = table.kinds[root]
        opener = {OBJ: b"{", ARR: b"[", ANY: b"{", ANYOBJ: b"{"}.get(
            root_kind
        )
        if opener is None:
            self.start_token_id = None  # scalar roots: no forced opener
        else:
            self.start_token_id = None
            for i, tb in enumerate(token_bytes):
                if tb == opener:
                    self.start_token_id = i
                    break

    def start(self):
        return self.machine.start()

    def _transition(self, state, b):
        return self.machine.step(state, b)

    def _terminal(self, state) -> bool:
        return self.machine.terminal(state)

    def _distance(self, state) -> int:
        return self.machine.distance(state)


def schema_cache_key(schema: dict) -> str:
    """Canonical registry key for a schema dict."""
    return json.dumps(schema, sort_keys=True, separators=(",", ":"))
