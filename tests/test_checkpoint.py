"""Checkpoint/resume: serving-weight checkpoints and train-state resume.

The aux-subsystem layer the reference lacks (SURVEY.md section 5
"Checkpoint/resume": goals persist in SQLite, models don't) — here model
state checkpoints with the same crash-resume semantics.
"""

import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import checkpoint as ckpt
from aios_tpu.engine import model as M
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.tokenizer import (
    ByteTokenizer,
    SentencePieceBPE,
    tokenizer_from_dict,
    tokenizer_to_dict,
)

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


def test_params_roundtrip(tmp_path):
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    ckpt.save_params(str(tmp_path), params)
    assert ckpt.is_checkpoint_dir(str(tmp_path))
    back = ckpt.load_params(str(tmp_path), like=params)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        back,
    )


def test_model_checkpoint_roundtrip_and_manager_load(tmp_path):
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(1), dtype=jnp.float32)
    d = str(tmp_path / "model")
    ckpt.save_model_checkpoint(d, TINY_TEST, params, ByteTokenizer())
    assert ckpt.is_model_checkpoint(d)

    cfg2, params2, tok2 = ckpt.load_model_checkpoint(d)
    assert cfg2 == TINY_TEST
    assert isinstance(tok2, ByteTokenizer)

    # the runtime's LoadModel path recognizes prepared checkpoint dirs
    from aios_tpu.runtime.model_manager import ModelManager

    mgr = ModelManager(num_slots=2, warm_compile=False, quantize=False)
    m = mgr.load_model("from-ckpt", d, context_length=64)
    assert m.state == "ready"
    out = m.engine.generate([1, 2, 3], max_new_tokens=4, temperature=0.0)
    ref_engine_params = jax.tree.map(jnp.asarray, params)
    from aios_tpu.engine.engine import TPUEngine

    ref = TPUEngine(TINY_TEST, ref_engine_params, num_slots=2, max_context=64)
    assert out == ref.generate([1, 2, 3], max_new_tokens=4, temperature=0.0)


def test_spbpe_tokenizer_serde():
    pieces = ["▁", "h", "e", "l", "o", "lo", "llo", "ello", "hello", "▁hello"]
    tok = SentencePieceBPE(
        tokens=["<unk>", "<s>", "</s>", *pieces, "<0x41>"],
        scores=[0.0, 0.0, 0.0, *([-1.0] * len(pieces)), 0.0],
        token_types=[2, 3, 3, *([1] * len(pieces)), 6],
    )
    d = tokenizer_to_dict(tok)
    tok2 = tokenizer_from_dict(d)
    text = "hello"
    assert tok2.encode(text) == tok.encode(text)
    assert tok2.decode(tok.encode(text, add_bos=False)) == "hello"


def test_checkpoint_manager_retention_and_restore(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path), max_to_keep=2)
    tree = {"a": jnp.arange(4, dtype=jnp.float32), "step": jnp.int32(0)}
    for s in (1, 2, 3):
        mgr.save(s, {"a": tree["a"] * s, "step": jnp.int32(s)})
    assert mgr.latest_step() == 3
    back = mgr.restore(like=tree)
    assert int(back["step"]) == 3
    np.testing.assert_allclose(np.asarray(back["a"]), np.arange(4) * 3)
    mgr.close()


def test_train_loop_resume(tmp_path):
    from aios_tpu.engine.train import make_optimizer, train_loop

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            yield {
                "tokens": jnp.asarray(
                    rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32
                ),
                "loss_mask": jnp.ones((2, 16), jnp.float32),
            }

    d = str(tmp_path / "train")
    opt = make_optimizer(warmup_steps=1, total_steps=10)
    losses = []
    state = train_loop(
        cfg, params, batches(3), optimizer=opt, checkpoint_dir=d,
        save_every=2, on_metrics=lambda s, m: losses.append(float(m["loss"])),
    )
    assert int(state["step"]) == 3 and len(losses) == 3

    # resume: a fresh call continues from step 3, not from scratch
    state2 = train_loop(
        cfg, params, batches(2), optimizer=opt, checkpoint_dir=d, save_every=10
    )
    assert int(state2["step"]) == 5


def test_prepare_model_script(tmp_path):
    out = tmp_path / "prepared"
    env_script = Path(__file__).resolve().parent.parent / "scripts" / "prepare_model.py"
    proc = subprocess.run(
        [
            sys.executable,
            str(env_script),
            "synthetic://tiny-test",
            str(out),
            "--dtype",
            "f32",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env={
            "PATH": "/usr/bin:/bin",
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": str(Path(__file__).resolve().parent.parent),
            "HOME": str(tmp_path),
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert ckpt.is_model_checkpoint(str(out))


def test_prepared_quantized_checkpoint_serves_without_requantize(tmp_path):
    """prepare_model --quantize saves {"q","s"} serving leaves; restoring
    through the model manager serves them as-is (no re-quantization, no
    dense transient), and decode matches quantizing at load time."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import checkpoint as ckpt
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.engine.tokenizer import ByteTokenizer

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(31), dtype=jnp.float32)
    qparams = M.quantize_params(params, mode="int8")
    out_dir = tmp_path / "prepared-int8"
    ckpt.save_model_checkpoint(str(out_dir), TINY_TEST, qparams, ByteTokenizer())

    cfg2, params2, tok2 = ckpt.load_model_checkpoint(str(out_dir))
    assert "q" in params2["layers"]["w_qkv"]
    # engine with quantize set must NOT re-quantize already-quantized leaves
    eng = TPUEngine(cfg2, params2, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, quantize="int8")
    ref = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, quantize="int8")
    prompt = [1, 5, 9, 2]
    got = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
    want = ref.generate(prompt, max_new_tokens=8, temperature=0.0)
    assert got == want


def test_fused_prequantized_checkpoint_refused_under_sharding_plan():
    """FUSED prepared checkpoints (the single-chip layout) have no TP
    sharding rule; a sharded engine must refuse them with the re-prepare
    recipe (unfused artifacts load fine — tests below)."""
    import jax
    import jax.numpy as jnp
    import pytest

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(32), dtype=jnp.float32)
    qp = M.quantize_params(params, mode="int8")
    plan = ShardingPlan(build_mesh(tp=2, n_devices=2))
    with pytest.raises(ValueError, match="FUSED"):
        TPUEngine(TINY_TEST, qp, num_slots=2, max_context=64,
                  shardings=plan, quantize="int8")


def test_tp_prepared_checkpoint_loads_under_plan(tmp_path):
    """prepare_model --quantize int8 --tp 2 equivalent: the unfused
    artifact restores straight to the mesh and decodes token-identically
    to quantizing the dense source at load time (VERDICT r4 item 6 — the
    BASELINE config-4 boot path without the per-boot quantization pass)."""
    import jax
    import jax.numpy as jnp

    from aios_tpu.engine import checkpoint as ckpt
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.engine.tokenizer import ByteTokenizer
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(33), dtype=jnp.float32)
    qp = M.quantize_params(params, mode="int8", fuse=False, tp=2)
    out_dir = tmp_path / "prepared-int8-tp2"
    ckpt.save_model_checkpoint(str(out_dir), TINY_TEST, qp, ByteTokenizer(),
                               tp=2)

    cfg2, params2, _ = ckpt.load_model_checkpoint(str(out_dir))
    assert "q" in params2["layers"]["wq"]  # unfused quantized leaves
    import json as _json

    meta = _json.loads((out_dir / "aios_model.json").read_text())
    assert meta["prepared_tp"] == 2

    plan = ShardingPlan(build_mesh(tp=2, n_devices=2))
    eng = TPUEngine(cfg2, params2, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, shardings=plan)
    assert eng.quant_mode == "int8"
    ref = TPUEngine(TINY_TEST, params, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, shardings=plan, quantize="int8")
    prompt = [1, 5, 9, 2]
    got = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
    want = ref.generate(prompt, max_new_tokens=8, temperature=0.0)
    assert got == want


def test_tp_prepared_int4_checkpoint_loads_under_plan(tmp_path):
    """int4 tp-prepared artifact on a kernel-aligned geometry: shard-local
    eligibility baked at prepare time, restored under the matching plan,
    token-identical to load-time int4 quantization; a mismatched plan is
    refused with the re-prepare recipe."""
    import jax
    import jax.numpy as jnp
    import pytest

    from aios_tpu.engine import checkpoint as ckpt
    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine
    from aios_tpu.engine.tokenizer import ByteTokenizer
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    # dims chosen so the int4 kernel rule holds on tp=2 SHARDS for the
    # column projections (N/2 % 128 == 0, group 128 | K) while wk/wv
    # (kv_dim 128 -> shard N 64) fall back to int8 — a realistic mixed tree
    cfg = TINY_TEST.scaled(
        name="tiny-int4-tp", vocab_size=512, hidden_size=256,
        intermediate_size=512, num_heads=4, num_kv_heads=2, head_dim=64,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(34), dtype=jnp.float32)
    qp = M.quantize_params(params, mode="int4", fuse=False, tp=2,
                           target="tpu")
    assert "q4" in qp["layers"]["wq"]
    assert "q" in qp["layers"]["wk"]  # shard N=64 not kernel-alignable
    out_dir = tmp_path / "prepared-int4-tp2"
    ckpt.save_model_checkpoint(str(out_dir), cfg, qp, ByteTokenizer(), tp=2)

    cfg2, params2, _ = ckpt.load_model_checkpoint(str(out_dir))
    # the disk round-trip is bit-exact leaf by leaf (restore IS the
    # quantized tree — no re-quantization happens at load)
    import numpy as np

    flat_a = jax.tree.leaves(qp)
    flat_b = jax.tree.leaves(params2)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    plan = ShardingPlan(build_mesh(tp=2, n_devices=2))
    eng = TPUEngine(cfg2, params2, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, shardings=plan)
    assert eng.quant_mode == "int4"
    # identical decode to serving the same prepared tree without the disk
    # hop. (Load-time quantization of the dense source only matches
    # exactly when both sides use the same int4 eligibility rule — on a
    # TPU backend both run the kernel rule; this CPU test's load-time path
    # is storage-eligible (target="auto"), so the dense-source comparison
    # lives in the int8 test above where no eligibility rule exists.)
    ref = TPUEngine(cfg, qp, num_slots=2, max_context=64,
                    cache_dtype=jnp.float32, shardings=plan)
    prompt = [1, 5, 9, 2]
    got = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
    want = ref.generate(prompt, max_new_tokens=8, temperature=0.0)
    assert got == want

    # a plan the groups weren't baked for must be refused up front
    plan4 = ShardingPlan(build_mesh(tp=4, n_devices=4))
    with pytest.raises(ValueError, match="re-run scripts/prepare_model"):
        TPUEngine(cfg2, params2, num_slots=2, max_context=64,
                  cache_dtype=jnp.float32, shardings=plan4)
