"""Device-time attribution (obs/devprof.py, ISSUE 14).

Covers: the per-graph cost ledger units (register/note/sample,
roofline resolution, the closed GRAPH_KINDS enum), the extended PR 6/7/8
invariant — devprof ON vs OFF leaves token streams (greedy AND sampled),
dispatch counts, and compile counters identical through the pipelined
batcher — per-request/tenant attribution, the bounded one-at-a-time
``/debug/profile`` capture route, and the scripts/benchdiff.py
regression sentinel (exit non-zero on a seeded 20% per-graph
regression; refuse cross-schema diffs).
"""

import importlib.util
import json
import os
import time
import urllib.error
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from aios_tpu.engine import model as M
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.obs import devprof, flightrec
from aios_tpu.obs import instruments as obs
from aios_tpu.obs.http import start_metrics_server

ROOT = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# ledger units
# ---------------------------------------------------------------------------


class _FakeCompiled:
    def __init__(self, flops, byt):
        self._ca = {"flops": flops, "bytes accessed": byt}

    def cost_analysis(self):
        return self._ca


def test_ledger_note_sample_and_costs():
    led = devprof.DevprofLedger("m", device_kind="TPU v5 lite", sample_n=4)
    led.register("step", 8, _FakeCompiled(100.0, 1000.0), 0.5)
    # dispatch 1 is due a sample, then every 4th
    assert led.note("step", 8) is True
    for _ in range(3):
        assert led.note("step", 8) is False
    assert led.note("step", 8) is True
    led.sample("step", 8, 0.002)
    led.sample("step", 8, 0.004)
    snap = led.snapshot()["graphs"]["step"]
    assert snap["dispatches"] == 5
    assert snap["compiles"] == 1
    assert snap["est_flops"] == pytest.approx(500.0)
    assert snap["est_bytes"] == pytest.approx(5000.0)
    assert snap["samples"] == 2
    assert snap["device_seconds_per_dispatch"] == pytest.approx(
        0.003, rel=1e-3
    )
    assert snap["device_seconds"] == pytest.approx(0.015, rel=1e-3)
    # roofline: sampled flops (2 x 100) over sampled seconds over peak
    assert snap["mfu"] == pytest.approx(
        200.0 / 0.006 / 197e12, rel=1e-2
    )
    assert snap["hbm_bw_util"] == pytest.approx(
        2000.0 / 0.006 / 819e9, rel=1e-2
    )
    assert led.mean_s("step") == pytest.approx(0.003, rel=1e-3)
    assert led.mean_s("prefill") is None
    # the last sample is poppable exactly once
    assert led.take_last_sample() == ("step", 0.004)
    assert led.take_last_sample() is None


def test_ledger_rejects_unknown_graph_kind():
    led = devprof.DevprofLedger("m", device_kind="", sample_n=1)
    with pytest.raises(ValueError, match="GRAPH_KINDS"):
        led.register("warp_drive", 1, None, 0.0)


def test_unknown_device_kind_omits_utilization():
    led = devprof.DevprofLedger("m", device_kind="cpu", sample_n=1)
    assert led.peaks is None
    led.register("step", 1, _FakeCompiled(10.0, 10.0), 0.1)
    led.note("step", 1)
    led.sample("step", 1, 0.001)
    snap = led.snapshot()["graphs"]["step"]
    # raw seconds kept, utilization gauges omitted (no invented peaks)
    assert "device_seconds" in snap
    assert "mfu" not in snap and "hbm_bw_util" not in snap
    # known kinds resolve, including lenient prefixes
    assert devprof.resolve_peaks("TPU v4") == (275e12, 1228e9)
    assert devprof.resolve_peaks("TPU v5 litepod") == (197e12, 819e9)
    assert devprof.resolve_peaks("") is None


# ---------------------------------------------------------------------------
# the PR 6/7/8 invariant, extended: devprof is metadata + sampling only
# ---------------------------------------------------------------------------


def _wave(monkeypatch, enabled):
    """One engine+pipelined-batcher lifecycle: sequential greedy AND
    sampled single-request waves (deterministic dispatch counts), with
    devprof armed or not at construction."""
    if enabled:
        monkeypatch.setenv("AIOS_TPU_DEVPROF", "1")
        monkeypatch.setenv("AIOS_TPU_DEVPROF_SAMPLE", "2")
    else:
        monkeypatch.delenv("AIOS_TPU_DEVPROF", raising=False)
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0),
                           dtype=jnp.float32)
    eng = TPUEngine(TINY_TEST, params, num_slots=2, max_context=128,
                    cache_dtype=jnp.float32)
    eng.warmup(step_sizes=(2, 4), prefill_chunk=0)
    compiles_after_warmup = eng.stats()["xla_compiles"]
    b = ContinuousBatcher(eng, chunk_steps=4, admit_chunk_steps=4,
                          pipeline=True)
    try:
        outs = []
        for i in range(2):  # greedy
            outs.append(b.submit(Request(
                prompt_ids=[3 + i, 17, 91], max_tokens=13,
                temperature=0.0,
            )).tokens())
        for i in range(2):  # sampled (same engine seed both arms)
            outs.append(b.submit(Request(
                prompt_ids=[7 + i, 23, 55], max_tokens=11,
                temperature=0.7, top_p=0.9,
            )).tokens())
        return {
            "outs": outs,
            "decode_steps": eng.stats()["decode_steps"],
            "compile_delta":
                eng.stats()["xla_compiles"] - compiles_after_warmup,
            "snapshot": eng.devprof_snapshot(),
        }
    finally:
        b.shutdown()
        eng.close()


def test_devprof_on_off_streams_and_compiles_identical(monkeypatch):
    tenant_before = obs.DEVPROF_TENANT_SECONDS.labels(
        tenant="anonymous"
    ).value
    on = _wave(monkeypatch, True)
    off = _wave(monkeypatch, False)
    assert on["compile_delta"] == 0, (
        "devprof ON compiled post-warmup — registration must be "
        "metadata-only"
    )
    assert off["compile_delta"] == 0
    assert on["decode_steps"] == off["decode_steps"]
    assert on["outs"] == off["outs"]
    # the ON arm actually measured: step+prefill dispatches counted,
    # samples landed, and the static cost estimates are populated
    graphs = on["snapshot"]["graphs"]
    assert off["snapshot"] is None
    assert graphs["step"]["dispatches"] > 0
    assert graphs["prefill"]["dispatches"] == 4
    assert graphs["step"]["samples"] > 0
    assert graphs["step"]["est_flops"] > 0
    # per-request attribution reached the timelines and the tenant
    # counter was billed at retirement
    tls = [
        t for t in flightrec.RECORDER.recent(model=TINY_TEST.name,
                                             limit=256)
        if t.tokens_out in (13, 11) and t.device_us > 0
    ]
    assert len(tls) >= 4
    ev_dev = [
        e for t in tls for e in t.to_dict()["events"]
        if "dev_us" in e and e["dev_us"] > 0
    ]
    assert ev_dev, "no dispatch event carried a sampled dev_us join"
    assert obs.DEVPROF_TENANT_SECONDS.labels(
        tenant="anonymous"
    ).value > tenant_before


@pytest.mark.slow
def test_devprof_live_grpc_streams_and_compiles_identical():
    """The acceptance-criteria path: with devprof enabled on the LIVE
    gRPC surface, response streams and engine compile counters are
    byte-identical to disabled, and the ON run's ledger + tenant
    billing actually populated."""
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    def run(enabled):
        mp = pytest.MonkeyPatch()
        mp.setenv("AIOS_TPU_PAGED_KV", "auto")
        if enabled:
            mp.setenv("AIOS_TPU_DEVPROF", "1")
            mp.setenv("AIOS_TPU_DEVPROF_SAMPLE", "2")
        else:
            mp.delenv("AIOS_TPU_DEVPROF", raising=False)
        manager = ModelManager(num_slots=2, warm_compile=False)
        manager.load_model("devprof-live", "synthetic://tiny-test",
                           context_length=256)
        server, service, port = serve(
            address="127.0.0.1:0", manager=manager, block=False,
            metrics_port=0,
        )
        channel = rpc.insecure_channel(f"127.0.0.1:{port}")
        stub = services.AIRuntimeStub(channel)
        try:
            texts = []
            for i in range(3):
                resp = stub.Infer(runtime_pb2.InferRequest(
                    prompt=f"devprof live check {i}", max_tokens=8,
                    temperature=0.0, requesting_agent="devprof-agent",
                    task_id=f"devprof-live-{int(enabled)}-{i}",
                ))
                texts.append(resp.text)
            eng = manager.models["devprof-live"].pool.replicas[0].engine
            return {
                "texts": texts,
                "compiles": eng.stats()["xla_compiles"],
                "decode_steps": eng.stats()["decode_steps"],
                "snapshot": eng.devprof_snapshot(),
            }
        finally:
            channel.close()
            server.stop(grace=None)
            if service.metrics_server is not None:
                service.metrics_server.shutdown()
            manager.unload_model("devprof-live")
            mp.undo()

    billed_before = obs.DEVPROF_TENANT_SECONDS.labels(
        tenant="devprof-agent"
    ).value
    on = run(True)
    off = run(False)
    assert on["texts"] == off["texts"]
    assert on["compiles"] == off["compiles"]
    assert on["decode_steps"] == off["decode_steps"]
    assert off["snapshot"] is None
    assert on["snapshot"]["graphs"]["step"]["dispatches"] > 0
    assert obs.DEVPROF_TENANT_SECONDS.labels(
        tenant="devprof-agent"
    ).value > billed_before


# ---------------------------------------------------------------------------
# /debug/profile: bounded, one-at-a-time, disabled without a dump dir
# ---------------------------------------------------------------------------


def _drain_capture(deadline_s: float = 120.0) -> None:
    deadline = time.monotonic() + deadline_s
    while devprof.capture_status()["busy"]:
        assert time.monotonic() < deadline, "capture never finished"
        time.sleep(0.05)


def test_profile_capture_route(tmp_path, monkeypatch):
    """Route semantics (403 disabled / 200 start / 409 busy / status
    clears) with the profiler itself mocked — the real jax.profiler
    capture rides the slow tier below (its first use imports the
    TF-profiler machinery, ~seconds)."""
    import contextlib

    import jax as jax_mod

    started = []

    @contextlib.contextmanager
    def fake_trace(path):
        os.makedirs(path, exist_ok=True)
        started.append(path)
        yield

    monkeypatch.setattr(jax_mod.profiler, "trace", fake_trace)
    server, port = start_metrics_server(port=0)
    url = f"http://127.0.0.1:{port}/debug/profile"
    try:
        monkeypatch.delenv("AIOS_TPU_DEVPROF_DUMP_DIR", raising=False)
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{url}?secs=0.2", timeout=5)
        assert err.value.code == 403

        monkeypatch.setenv("AIOS_TPU_DEVPROF_DUMP_DIR", str(tmp_path))
        body = json.loads(urllib.request.urlopen(
            f"{url}?secs=2.0", timeout=5
        ).read().decode())
        assert body["profiling"] and body["path"].startswith(str(tmp_path))
        assert body["secs"] == pytest.approx(2.0)
        # one at a time: a second request during the window is a 409
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{url}?secs=0.2", timeout=5)
        assert err.value.code == 409
        _drain_capture()
        assert started and os.path.isdir(body["path"])
        # /debug/devprof serves the ledgers + capture state
        dbg = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/devprof", timeout=5
        ).read().decode())
        assert dbg["capture"]["busy"] is False
    finally:
        server.shutdown()


def test_capture_secs_hard_cap(tmp_path, monkeypatch):
    import contextlib

    import jax as jax_mod

    @contextlib.contextmanager
    def fake_trace(path):
        os.makedirs(path, exist_ok=True)
        yield

    monkeypatch.setattr(jax_mod.profiler, "trace", fake_trace)
    monkeypatch.setenv("AIOS_TPU_DEVPROF_DUMP_DIR", str(tmp_path))
    monkeypatch.setattr(devprof, "CAPTURE_MAX_SECS", 0.2)
    _drain_capture()
    info = devprof.start_capture(9999.0)
    assert info["secs"] == pytest.approx(0.2)
    _drain_capture()


@pytest.mark.slow
def test_profile_capture_real_jax_profiler(tmp_path, monkeypatch):
    """One REAL jax.profiler capture end to end: the trace directory
    lands under the dump dir with actual profiler output."""
    monkeypatch.setenv("AIOS_TPU_DEVPROF_DUMP_DIR", str(tmp_path))
    _drain_capture()
    info = devprof.start_capture(0.3)
    _drain_capture()
    assert os.path.isdir(info["path"])
    assert os.listdir(info["path"]), "profiler wrote nothing"


# ---------------------------------------------------------------------------
# scripts/benchdiff.py: the per-graph regression sentinel
# ---------------------------------------------------------------------------


def _benchdiff():
    spec = importlib.util.spec_from_file_location(
        "benchdiff", ROOT / "scripts" / "benchdiff.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ledger_line(step_s=0.002, step_disp=18, schema=1):
    return {
        "schema_version": schema,
        "metric": "devprof per-graph device-time ledger",
        "devprof": {
            "model": "m", "device_kind": "cpu", "sample_every": 8,
            "graphs": {
                "step": {
                    "dispatches": step_disp, "samples": 3,
                    "device_seconds_per_dispatch": step_s,
                    "device_seconds": step_s * step_disp,
                },
                "prefill": {
                    "dispatches": 6, "samples": 1,
                    "device_seconds_per_dispatch": 0.03,
                    "device_seconds": 0.18,
                },
            },
        },
    }


def _write(tmp_path, name, line):
    p = tmp_path / name
    p.write_text(json.dumps(line) + "\n")
    return str(p)


def test_benchdiff_clean_and_seeded_regression(tmp_path, capsys):
    bd = _benchdiff()
    base = _write(tmp_path, "base.json", _ledger_line())
    same = _write(tmp_path, "same.json", _ledger_line())
    assert bd.main([base, same]) == 0
    # a seeded 20% per-graph device-time regression exits non-zero at
    # the default threshold (the ISSUE 14 acceptance criterion)
    slow = _write(tmp_path, "slow.json", _ledger_line(step_s=0.0024))
    assert bd.main([base, slow]) == 1
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["verdict"] == "regression"
    assert verdict["regressions"][0]["graph"] == "step"
    # dispatch-count inflation on the fixed workload is a regression too
    more = _write(tmp_path, "more.json", _ledger_line(step_disp=24))
    assert bd.main([base, more]) == 1


def test_benchdiff_refuses_cross_schema(tmp_path, capsys):
    bd = _benchdiff()
    base = _write(tmp_path, "base.json", _ledger_line(schema=0))
    new = _write(tmp_path, "new.json", _ledger_line(schema=1))
    assert bd.main([base, new]) == 2
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["verdict"] == "schema_mismatch"
    # and unusable inputs (no ledger line) are a 2 as well, not a pass
    empty = _write(tmp_path, "empty.json", {"metric": "x"})
    assert bd.main([base, empty]) == 2


def test_bench_emit_stamps_schema_version(capsys):
    spec = importlib.util.spec_from_file_location(
        "bench_emit_probe", ROOT / "bench.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.emit({"metric": "probe", "value": 1.0})
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["schema_version"] == mod.BENCH_SCHEMA_VERSION
    assert "platform" in line and "device_kind" in line
