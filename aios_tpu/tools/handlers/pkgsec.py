"""pkg.* / sec.* — package management and security tools.

Reference: tools/src/{pkg,sec}/ (15 handlers). apt paths degrade cleanly
when the host has no apt or no network; security scans are implemented with
stdlib/psutil so they run anywhere.
"""

from __future__ import annotations

import hashlib
import os
import stat as stat_mod
import subprocess
from pathlib import Path

import psutil

from . import ToolError, ToolSpec, run_cmd

# ---------------------------------------------------------------------------
# pkg.* — apt wrappers
# ---------------------------------------------------------------------------


def pkg_install(args: dict) -> dict:
    name = args.get("name")
    if not name:
        raise ToolError("missing package name")
    out = run_cmd(["apt-get", "install", "-y", str(name)], timeout=300)
    return {"installed": name, "log": out["stdout"][-2000:]}


def pkg_remove(args: dict) -> dict:
    name = args.get("name")
    if not name:
        raise ToolError("missing package name")
    out = run_cmd(["apt-get", "remove", "-y", str(name)], timeout=300)
    return {"removed": name, "log": out["stdout"][-2000:]}


def pkg_search(args: dict) -> dict:
    query = args.get("query") or args.get("name")
    if not query:
        raise ToolError("missing query")
    out = run_cmd(["apt-cache", "search", str(query)], timeout=60)
    return {"results": out["stdout"].splitlines()[:50]}


def pkg_update(args: dict) -> dict:
    out = run_cmd(["apt-get", "update"], timeout=300)
    return {"log": out["stdout"][-2000:]}


def pkg_list_installed(args: dict) -> dict:
    out = run_cmd(["dpkg-query", "-W", "-f", "${Package}\t${Version}\n"],
                  timeout=60)
    pkgs = []
    for line in out["stdout"].splitlines()[: int(args.get("limit", 500))]:
        if "\t" in line:
            name, version = line.split("\t", 1)
            pkgs.append({"name": name, "version": version})
    return {"packages": pkgs, "count": len(pkgs)}


# ---------------------------------------------------------------------------
# sec.*
# ---------------------------------------------------------------------------


def sec_check_perms(args: dict) -> dict:
    path = Path(args.get("path", "/etc"))
    findings = []
    for f in list(path.rglob("*"))[:2000]:
        try:
            st = f.stat()
        except OSError:
            continue
        if st.st_mode & stat_mod.S_IWOTH and not f.is_symlink():
            findings.append({"path": str(f), "issue": "world-writable",
                             "mode": oct(st.st_mode)})
    return {"path": str(path), "findings": findings[:100],
            "count": len(findings)}


def sec_scan(args: dict) -> dict:
    """Open listening sockets + suspicious process names."""
    listeners = []
    try:
        for c in psutil.net_connections(kind="inet"):
            if c.status == psutil.CONN_LISTEN:
                listeners.append(
                    {"addr": f"{c.laddr.ip}:{c.laddr.port}", "pid": c.pid}
                )
    except (psutil.AccessDenied, PermissionError):
        pass
    return {"listening": listeners[:100]}


def sec_scan_rootkits(args: dict) -> dict:
    """Heuristic checks the reference delegates to chkrootkit-style scans:
    PATH hijack candidates, setuid binaries in odd places, /tmp executables."""
    findings = []
    for d in ("/tmp", "/var/tmp", "/dev/shm"):
        p = Path(d)
        if not p.is_dir():
            continue
        for f in list(p.iterdir())[:500]:
            try:
                st = f.stat()
            except OSError:
                continue
            if f.is_file() and st.st_mode & 0o111:
                findings.append({"path": str(f), "issue": "executable in tmp"})
            if st.st_mode & stat_mod.S_ISUID:
                findings.append({"path": str(f), "issue": "setuid in tmp"})
    return {"findings": findings[:100], "clean": not findings}


def sec_file_integrity(args: dict) -> dict:
    """SHA-256 manifest of a directory (store + compare runs)."""
    path = Path(args.get("path", "/etc"))
    manifest = {}
    for f in sorted(path.rglob("*"))[:1000]:
        if f.is_file():
            try:
                manifest[str(f)] = hashlib.sha256(f.read_bytes()).hexdigest()
            except OSError:
                continue
    baseline = args.get("baseline") or {}
    changed = [p for p, h in manifest.items() if baseline.get(p) not in (None, h)]
    return {"path": str(path), "files": len(manifest),
            "manifest": manifest if not baseline else {},
            "changed": changed}


def sec_cert_generate(args: dict) -> dict:
    """Self-signed cert via openssl (the reference uses rcgen, tls.rs:52-80)."""
    cn = args.get("common_name", "aios.local")
    out_dir = Path(args.get("out_dir", "/tmp/aios/certs"))
    out_dir.mkdir(parents=True, exist_ok=True)
    key = out_dir / f"{cn}.key"
    crt = out_dir / f"{cn}.crt"
    run_cmd(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(crt), "-days",
            str(args.get("days", 365)), "-subj", f"/CN={cn}",
        ],
        timeout=60,
    )
    return {"common_name": cn, "key": str(key), "cert": str(crt)}


def sec_cert_rotate(args: dict) -> dict:
    result = sec_cert_generate(args)
    result["rotated"] = True
    return result


def _make_grant_revoke(action: str):
    # capability mutation is wired to the live CapabilityChecker in
    # executor.build_registry (these placeholders are replaced there)
    def handler(args: dict) -> dict:
        raise ToolError(f"sec.{action} must be routed through the executor")

    return handler


def sec_audit_query_placeholder(args: dict) -> dict:
    raise ToolError("sec.audit_query must be routed through the executor")


TOOLS = {
    "pkg.install": ToolSpec(pkg_install, "Install an apt package",
                            requires_confirmation=True, timeout_ms=300_000),
    "pkg.remove": ToolSpec(pkg_remove, "Remove an apt package",
                           requires_confirmation=True, timeout_ms=300_000),
    "pkg.search": ToolSpec(pkg_search, "Search apt cache", idempotent=True),
    "pkg.update": ToolSpec(pkg_update, "Refresh apt indexes",
                           timeout_ms=300_000),
    "pkg.list_installed": ToolSpec(pkg_list_installed,
                                   "List installed packages", idempotent=True),
    "sec.check_perms": ToolSpec(sec_check_perms,
                                "Scan for world-writable files",
                                idempotent=True),
    "sec.audit_query": ToolSpec(sec_audit_query_placeholder,
                                "Query the audit ledger", idempotent=True),
    "sec.grant": ToolSpec(_make_grant_revoke("grant"),
                          "Grant capabilities to an agent"),
    "sec.revoke": ToolSpec(_make_grant_revoke("revoke"),
                           "Revoke capabilities from an agent"),
    "sec.audit": ToolSpec(_make_grant_revoke("audit"),
                          "Verify the audit hash chain", idempotent=True),
    "sec.scan": ToolSpec(sec_scan, "Listening sockets scan", idempotent=True),
    "sec.cert_generate": ToolSpec(sec_cert_generate,
                                  "Generate a self-signed TLS cert"),
    "sec.cert_rotate": ToolSpec(sec_cert_rotate, "Rotate a TLS cert"),
    "sec.file_integrity": ToolSpec(sec_file_integrity,
                                   "SHA-256 manifest / integrity diff",
                                   idempotent=True),
    "sec.scan_rootkits": ToolSpec(sec_scan_rootkits,
                                  "Heuristic rootkit indicators",
                                  idempotent=True),
}
