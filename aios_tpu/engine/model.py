"""Functional JAX implementation of the Llama-family decoder.

One code path serves TinyLlama-1.1B, Mistral-7B (GQA + sliding window),
DeepSeek-R1-Distill-8B and Qwen3-14B (QK-norm) — the four local tiers of the
reference intelligence hierarchy (SURVEY.md section 2.3). The design is
TPU-first:

  * layer parameters are stacked on a leading axis and the block stack runs
    under `jax.lax.scan` — one traced layer, fast compiles, XLA-friendly;
  * all matmuls are bf16 einsums (MXU), normalization/softmax accumulate in
    fp32;
  * masks are computed from positions with static shapes — no dynamic shapes
    anywhere, so prefill/decode jit cleanly onto the MXU;
  * three entry points: `forward_full` (training/parity), `prefill`
    (returns per-layer K/V for cache insertion), `decode_step` (batched
    single-token step over a slot cache — the continuous-batching hot loop).

Params pytree layout (E=hidden, Q=heads*head_dim, K=kv_heads*head_dim,
F=intermediate, L=layers, V=vocab, D=head_dim):

  embed      [V, E]
  layers/attn_norm [L, E]   layers/ffn_norm [L, E]
  layers/wq  [L, E, Q]      layers/wk [L, E, K]   layers/wv [L, E, K]
  layers/wo  [L, Q, E]
  layers/w_gate [L, E, F]   layers/w_up [L, E, F] layers/w_down [L, F, E]
  layers/q_norm [L, D]      layers/k_norm [L, D]      (only if cfg.qk_norm)
  final_norm [E]
  lm_head    [E, V]                                   (absent if tied)
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

Params = Dict[str, jnp.ndarray]


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm with fp32 accumulation, output in x.dtype."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * weight


def rope_tables(
    positions: jnp.ndarray, head_dim: int, theta: float
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for the given absolute positions.

    Returns arrays of shape positions.shape + (head_dim,) using the
    half-rotation (HF transformers) convention: the frequency vector is
    duplicated across the two halves of the head dimension.
    """
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    angles = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate q or k. x: [B, T, H, D]; cos/sin: [B, T, D]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    return (x.astype(jnp.float32) * cos + rotated.astype(jnp.float32) * sin).astype(
        x.dtype
    )


def gqa_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, S, KH, D]
    v: jnp.ndarray,  # [B, S, KH, D]
    mask: jnp.ndarray,  # bool [B, T, S] or [T, S]
) -> jnp.ndarray:
    """Grouped-query attention, fp32 softmax. Returns [B, T, H, D]."""
    B, T, H, D = q.shape
    KH = k.shape[2]
    G = H // KH
    q = q.reshape(B, T, KH, G, D)
    scores = jnp.einsum("btkgd,bskd->bkgts", q, k).astype(jnp.float32)
    scores = scores / np.sqrt(D)
    if mask.ndim == 2:
        mask = mask[None]
    scores = jnp.where(mask[:, None, None, :, :], scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(B, T, H, D)


def causal_mask(T: int, window: Optional[int]) -> jnp.ndarray:
    """[T, T] causal (optionally sliding-window) mask."""
    rows = jnp.arange(T)[:, None]
    cols = jnp.arange(T)[None, :]
    m = cols <= rows
    if window is not None:
        m = m & (cols > rows - window)
    return m


# ---------------------------------------------------------------------------
# One transformer block (shared by all entry points)
# ---------------------------------------------------------------------------


def _project_qkv(x, lp, cfg: ModelConfig, cos, sin):
    B, T, E = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"]).reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = (h @ lp["wk"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = (h @ lp["wv"]).reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    return q, k, v


def _mlp(x, lp, cfg: ModelConfig):
    h = rms_norm(x, lp["ffn_norm"], cfg.rms_norm_eps)
    gate = jax.nn.silu((h @ lp["w_gate"]).astype(jnp.float32)).astype(h.dtype)
    return (gate * (h @ lp["w_up"])) @ lp["w_down"]


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def forward_full(
    params: Params, cfg: ModelConfig, tokens: jnp.ndarray, attn_fn=None
) -> jnp.ndarray:
    """Full-sequence causal forward; logits [B, T, V] in fp32.

    Used for training, numeric-parity testing and as the prefill core.
    ``attn_fn`` swaps the attention implementation (e.g. ring attention for
    sequence-parallel training); it defaults to in-core GQA attention.
    """
    logits, _, _ = _forward_with_kv(params, cfg, tokens, attn_fn)
    return logits


def prefill(
    params: Params, cfg: ModelConfig, tokens: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Causal forward returning (logits [B,T,V], k [L,B,T,KH,D], v [...]).

    The engine copies the returned K/V into the request's cache slot.
    """
    return _forward_with_kv(params, cfg, tokens)


def _forward_with_kv(params, cfg: ModelConfig, tokens, attn_fn=None):
    B, T = tokens.shape
    x = params["embed"][tokens]
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    cos, sin = rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    mask = causal_mask(T, cfg.sliding_window)
    attention = attn_fn or gqa_attention

    def block(x, lp):
        q, k, v = _project_qkv(x, lp, cfg, cos, sin)
        attn = attention(q, k, v, mask)
        x = x + attn.reshape(B, T, -1) @ lp["wo"]
        x = x + _mlp(x, lp, cfg)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(block, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = (x @ head).astype(jnp.float32)
    return logits, ks, vs


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] int32 — one new token per slot
    lengths: jnp.ndarray,  # [B] int32 — tokens already in each slot's cache
    k_cache: jnp.ndarray,  # [L, B, C, KH, D]
    v_cache: jnp.ndarray,  # [L, B, C, KH, D]
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One batched decode step over the slot cache.

    Writes the new K/V at row ``lengths[b]`` of each slot, attends over all
    valid rows (with sliding window if configured), and returns
    (logits [B, V] fp32, k_cache', v_cache'). Intended to be jitted with the
    caches donated so XLA updates them in place.
    """
    B = tokens.shape[0]
    C = k_cache.shape[2]
    x = params["embed"][tokens][:, None, :]  # [B, 1, E]
    cos, sin = rope_tables(lengths[:, None], cfg.head_dim, cfg.rope_theta)

    batch_idx = jnp.arange(B)
    cols = jnp.arange(C)[None, :]
    # column j is visible if it holds a written token (j <= lengths, since we
    # write the new token before attending) and inside the sliding window
    mask = cols <= lengths[:, None]
    if cfg.sliding_window is not None:
        mask = mask & (cols > (lengths[:, None] - cfg.sliding_window))
    mask = mask[:, None, :]  # [B, 1, C]

    def block(x, layer):
        lp, k_l, v_l = layer
        q, k_new, v_new = _project_qkv(x, lp, cfg, cos, sin)
        k_l = k_l.at[batch_idx, lengths].set(k_new[:, 0])
        v_l = v_l.at[batch_idx, lengths].set(v_new[:, 0])
        attn = gqa_attention(q, k_l, v_l, mask)
        x = x + attn.reshape(B, 1, -1) @ lp["wo"]
        x = x + _mlp(x, lp, cfg)
        return x, (k_l, v_l)

    x, (k_cache, v_cache) = jax.lax.scan(
        block, x, (params["layers"], k_cache, v_cache)
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = (x[:, 0] @ head).astype(jnp.float32)
    return logits, k_cache, v_cache


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16
) -> Params:
    """Random params (scaled-normal init) — for tests, benches and training."""
    keys = iter(jax.random.split(key, 16))

    def normal(shape, scale=0.02):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(
            dtype
        )

    L, E, F, D = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    layers = {
        "attn_norm": jnp.ones((L, E), dtype),
        "ffn_norm": jnp.ones((L, E), dtype),
        "wq": normal((L, E, cfg.q_dim)),
        "wk": normal((L, E, cfg.kv_dim)),
        "wv": normal((L, E, cfg.kv_dim)),
        "wo": normal((L, cfg.q_dim, E)),
        "w_gate": normal((L, E, F)),
        "w_up": normal((L, E, F)),
        "w_down": normal((L, F, E)),
    }
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, D), dtype)
        layers["k_norm"] = jnp.ones((L, D), dtype)
    params: Params = {
        "embed": normal((cfg.vocab_size, E)),
        "layers": layers,
        "final_norm": jnp.ones((E,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = normal((E, cfg.vocab_size))
    return params


def init_kv_cache(
    cfg: ModelConfig, num_slots: int, max_len: int, dtype=jnp.bfloat16
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    shape = (cfg.num_layers, num_slots, max_len, cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
