#!/usr/bin/env python3
"""Fleet status CLI — the operator surface over ``/fleet/members``
(docs/RUNBOOK.md §9 "a host is sick").

Usage:
    scripts/fleetctl.py status      [--target HOST:PORT] [--json]
    scripts/fleetctl.py top         [--target HOST:PORT] [--json]
    scripts/fleetctl.py history METRIC [--target HOST:PORT] [--host H]
                                    [--window S] [--json]
    scripts/fleetctl.py drain-check [--target HOST:PORT] --host HOSTID
    scripts/fleetctl.py drain       [--target HOST:PORT] --host HOSTID
                                    [--timeout S] [--json]

Target is any ONE member's metrics endpoint (``--target``, else
``AIOS_TPU_FLEET_TARGET``, default 127.0.0.1:9100) — membership is
symmetric, so any member renders the whole fleet.

  * ``status``      — the membership table: host, role, state, heartbeat
                      age, rank, version, pid, metrics endpoint; plus
                      the recent transition journal. Exit 0 when every
                      member is "up", 1 when any is suspect/dead (the
                      scriptable health probe), 2 when the target is
                      unreachable.
  * ``top``         — per-host load: pool occupancy / waiting / degrade
                      rung, devprof MFU and device-seconds, SLO worst
                      burn, and the megagraph early-exit savings
                      (dispatches x K - ticks) — sorted worst-burn-first
                      so the sick host is the top row; the worst few
                      tenants by TTFT burn fleet-wide render below the
                      table. Exit codes as ``status``.
  * ``history``     — a sparkline table of METRIC's recent points per
                      host (off ``/debug/tsdb/fleet``; requires
                      ``AIOS_TPU_TSDB`` armed on the members), sorted
                      worst-host-first (highest last value). ``--host``
                      narrows to one host, ``--window`` bounds the range
                      in seconds. Exit 0 with data, 1 when no host
                      returned points (metric unknown / ring unarmed),
                      2 when the target is unreachable.
  * ``drain-check`` — is ``--host`` safe to take down? Exit 0 when every
                      one of its pools reports zero waiting and zero
                      batch occupancy (idle), 1 when it still holds
                      work, 2 when the host is unknown or the target is
                      unreachable.
  * ``drain``       — ACTUALLY drain ``--host``: POST its
                      ``/fleet/drain`` (resolved from the membership
                      table), then poll the table until the host
                      announces the terminal ``leaving`` phase. Exit 0
                      drained, 1 still holding at ``--timeout``, 2 when
                      the host is unknown/unreachable.

Human-readable tables go to stderr; ONE machine-readable JSON verdict
line goes to stdout (the benchdiff.py convention), so scripts can parse
the verdict while operators read the table. ``--json`` (status/top)
replaces the terse verdict with the FULL row set on stdout — the same
fields the table renders, one JSON document — for dashboards and
fleet-aware tooling that want data, not a verdict. Exit codes are
identical either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request
from typing import List, Optional


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def default_target() -> str:
    return os.environ.get("AIOS_TPU_FLEET_TARGET", "127.0.0.1:9100")


def fetch_members(target: str, timeout: float = 5.0) -> dict:
    url = f"http://{target}/fleet/members"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def _table(rows: List[List[str]], header: List[str]) -> None:
    widths = [
        max(len(str(r[i])) for r in [header] + rows)
        for i in range(len(header))
    ]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    log(fmt.format(*header))
    for r in rows:
        log(fmt.format(*(str(c) for c in r)))


def _pool_load(member: dict) -> tuple:
    """(waiting, occupancy, degrade) summed/maxed across the member's
    pools — the load triple top and drain-check read."""
    waiting, occupancy, degrade = 0, 0.0, 0
    for name, stats in (member.get("pools") or {}).items():
        if name == "_error" or not isinstance(stats, dict):
            continue
        waiting += int(stats.get("waiting", 0) or 0)
        occupancy = max(occupancy,
                        float(stats.get("batch_occupancy", 0.0) or 0.0))
        degrade = max(degrade, int(stats.get("degrade_level", 0) or 0))
    return waiting, occupancy, degrade


def _mega_savings(member: dict) -> Optional[int]:
    """Megagraph early-exit savings summed across the member's pools:
    dispatches x K - ticks (the decode ticks the early exit never ran).
    None when no pool runs the megagraph."""
    savings = None
    for name, stats in (member.get("pools") or {}).items():
        if name == "_error" or not isinstance(stats, dict):
            continue
        k = int(stats.get("mega_k", 0) or 0)
        dispatches = int(stats.get("mega_dispatches", 0) or 0)
        if not k or not dispatches:
            continue
        ticks = int(stats.get("mega_ticks", 0) or 0)
        savings = (savings or 0) + dispatches * k - ticks
    return savings


def _worst_tenants(members: List[dict], limit: int = 5) -> List[dict]:
    """Fleet-wide union of each heartbeat's worst-tenant slice, ranked
    by TTFT burn (the noisy-neighbor answer ``top`` renders)."""
    rows = []
    for m in members:
        for key, burn in ((m.get("slo") or {}).get("tenants") or {}).items():
            model, _, tenant = key.partition("/")
            rows.append({"host": m["host"], "model": model,
                         "tenant": tenant, "burn": float(burn)})
    rows.sort(key=lambda r: -r["burn"])
    return rows[:limit]


def _mfu_secs(member: dict) -> tuple:
    mfu: Optional[float] = None
    secs = 0.0
    for entry in (member.get("capacity") or {}).values():
        if not isinstance(entry, dict):
            continue
        secs += float(entry.get("device_seconds", 0.0) or 0.0)
        if entry.get("mfu") is not None:
            mfu = max(mfu or 0.0, float(entry["mfu"]))
    return mfu, secs


def cmd_status(data: dict, as_json: bool = False) -> int:
    members = data.get("members", [])
    not_up = [m for m in members if m["state"] != "up"]
    if as_json:
        print(json.dumps({
            "cmd": "status", "size": len(members),
            "up": len(members) - len(not_up), "pass": not not_up,
            "members": [
                {k: m.get(k) for k in (
                    "host", "role", "state", "age_secs", "rank",
                    "version", "pid", "metrics_addr", "kvx_addr", "self",
                )}
                for m in members
            ],
            "journal": data.get("journal", [])[-32:],
        }, sort_keys=True))
        return 0 if not not_up else 1
    rows = [
        [m["host"], m["role"], m["state"], f"{m.get('age_secs', 0):.1f}s",
         m.get("rank") or "-", m.get("version") or "-",
         m.get("pid") or "-", m.get("metrics_addr") or "-",
         "*" if m.get("self") else ""]
        for m in members
    ]
    _table(rows, ["HOST", "ROLE", "STATE", "AGE", "RANK", "VERSION",
                  "PID", "METRICS", "SELF"])
    journal = data.get("journal", [])
    if journal:
        log("")
        log("recent transitions:")
        for e in journal[-8:]:
            log(f"  {e['host']}/{e['role']}: "
                f"{e.get('from') or 'new'} -> {e['to']}")
    print(json.dumps({
        "cmd": "status", "size": len(members),
        "up": len(members) - len(not_up),
        "not_up": [{"host": m["host"], "role": m["role"],
                    "state": m["state"]} for m in not_up],
        "pass": not not_up,
    }, sort_keys=True))
    return 0 if not not_up else 1


def cmd_top(data: dict, as_json: bool = False) -> int:
    members = data.get("members", [])

    def burn(m: dict) -> float:
        b = (m.get("slo") or {}).get("worst_burn")
        return float(b) if b is not None else -1.0

    ordered = sorted(members, key=burn, reverse=True)
    not_up = [m for m in members if m["state"] != "up"]
    tenants = _worst_tenants(members)
    if as_json:
        out = []
        for m in ordered:
            waiting, occupancy, degrade = _pool_load(m)
            mfu, secs = _mfu_secs(m)
            b = (m.get("slo") or {}).get("worst_burn")
            out.append({
                "host": m["host"], "role": m["role"], "state": m["state"],
                "worst_burn": b, "occupancy": occupancy,
                "waiting": waiting, "degrade_level": degrade,
                "mfu": mfu, "device_seconds": secs,
                "mega_savings": _mega_savings(m),
            })
        print(json.dumps({
            "cmd": "top", "pass": not not_up, "members": out,
            "tenants": tenants,
        }, sort_keys=True))
        return 0 if not not_up else 1
    rows = []
    for m in ordered:
        waiting, occupancy, degrade = _pool_load(m)
        mfu, secs = _mfu_secs(m)
        b = (m.get("slo") or {}).get("worst_burn")
        save = _mega_savings(m)
        rows.append([
            m["host"], m["state"],
            f"{b:.2f}" if b is not None else "-",
            f"{occupancy:.2f}", waiting, degrade,
            f"{mfu:.3f}" if mfu is not None else "-",
            f"{secs:.2f}",
            save if save is not None else "-",
        ])
    _table(rows, ["HOST", "STATE", "BURN", "OCCUP", "WAIT", "DEGRADE",
                  "MFU", "DEV_SECS", "MEGA_SAVE"])
    if tenants:
        log("")
        log("worst tenants by TTFT burn:")
        for t in tenants:
            log(f"  {t['model']}/{t['tenant']} on {t['host']}: "
                f"burn={t['burn']:.2f}")
    print(json.dumps({
        "cmd": "top",
        "worst": ({"host": ordered[0]["host"], "burn": burn(ordered[0])}
                  if ordered and burn(ordered[0]) >= 0 else None),
        "worst_tenant": tenants[0] if tenants else None,
        "pass": not not_up,
    }, sort_keys=True))
    return 0 if not not_up else 1


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[float], width: int = 32) -> str:
    """Min-max scaled block sparkline, downsampled to ``width`` by
    bucket-averaging (the whole window must fit one table cell)."""
    if not values:
        return ""
    if len(values) > width:
        step = len(values) / width
        values = [
            sum(chunk) / len(chunk)
            for chunk in (
                values[int(i * step):max(int((i + 1) * step),
                                         int(i * step) + 1)]
                for i in range(width)
            )
        ]
    lo, hi = min(values), max(values)
    span = hi - lo
    return "".join(
        _SPARK_BLOCKS[
            int((v - lo) / span * (len(_SPARK_BLOCKS) - 1)) if span else 0
        ]
        for v in values
    )


def cmd_history(target: str, metric: str, host: str, window: float,
                timeout: float, as_json: bool = False) -> int:
    """Sparkline table of ``metric``'s recent points per host, off the
    target's ``/debug/tsdb/fleet`` federation — worst host (highest last
    value) first, one row per series."""
    url = (f"http://{target}/debug/tsdb/fleet?name={metric}"
           f"&verb=raw&window={max(window, 1.0):g}")
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            data = json.loads(r.read().decode("utf-8"))
    except Exception as exc:  # noqa: BLE001 - unreachable target is the
        # operator's first answer, render it as such
        log(f"history: cannot reach {target}: {exc!r}")
        print(json.dumps({"cmd": "history", "metric": metric,
                          "error": repr(exc)[:200]}, sort_keys=True))
        return 2
    rows = []
    for h, answer in sorted((data.get("hosts") or {}).items()):
        if host and h != host:
            continue
        if not isinstance(answer, dict):
            continue
        for s in answer.get("series") or []:
            values = [pv for _, pv in s.get("points") or []]
            if not values:
                continue
            labels = ",".join(
                f"{k}={v}" for k, v in sorted(s["labels"].items())
            )
            rows.append({
                "host": h, "labels": labels, "points": len(values),
                "last": values[-1], "max": max(values), "values": values,
            })
    # worst host first: the row whose series last sampled highest tops
    # the table (the status/top sick-host-on-top convention)
    rows.sort(key=lambda r: -r["last"])
    if as_json:
        print(json.dumps({
            "cmd": "history", "metric": metric, "window_secs": window,
            "pass": bool(rows),
            "series": [{k: r[k] for k in ("host", "labels", "points",
                                          "last", "max", "values")}
                       for r in rows],
        }, sort_keys=True))
        return 0 if rows else 1
    if rows:
        _table(
            [[r["host"], r["labels"] or "-", r["points"],
              f"{r['last']:g}", f"{r['max']:g}", _sparkline(r["values"])]
             for r in rows],
            ["HOST", "LABELS", "PTS", "LAST", "MAX", "HISTORY"],
        )
    else:
        log(f"history: no points for {metric!r} on any reachable host "
            "(unknown metric, empty window, or AIOS_TPU_TSDB unarmed)")
    print(json.dumps({
        "cmd": "history", "metric": metric, "window_secs": window,
        "hosts": len({r["host"] for r in rows}), "series": len(rows),
        "pass": bool(rows),
    }, sort_keys=True))
    return 0 if rows else 1


def cmd_drain_check(data: dict, host: str) -> int:
    targets = [m for m in data.get("members", []) if m["host"] == host]
    if not targets:
        log(f"drain-check: host {host!r} not in the membership table")
        print(json.dumps({"cmd": "drain-check", "host": host,
                          "error": "unknown host"}, sort_keys=True))
        return 2
    holding = []
    for m in targets:
        waiting, occupancy, _ = _pool_load(m)
        if waiting > 0 or occupancy > 0:
            holding.append({"role": m["role"], "waiting": waiting,
                            "occupancy": occupancy})
    verdict = {"cmd": "drain-check", "host": host,
               "holding": holding, "pass": not holding}
    if holding:
        log(f"drain-check: {host} still holds work: {holding}")
    else:
        log(f"drain-check: {host} is idle — safe to drain")
    print(json.dumps(verdict, sort_keys=True))
    return 0 if not holding else 1


def cmd_drain(target: str, host: str, timeout: float,
              as_json: bool = False) -> int:
    """Drive one host's graceful drain end to end: resolve its metrics
    endpoint off the membership table, POST /fleet/drain, then poll any
    member's table until the host's descriptor announces "leaving" (the
    descriptor outlives the process — membership keeps the last fold)."""
    import time

    try:
        data = fetch_members(target)
    except Exception as exc:  # noqa: BLE001 - see main()'s fetch
        log(f"drain: cannot reach {target}: {exc!r}")
        print(json.dumps({"cmd": "drain", "host": host,
                          "error": repr(exc)[:200]}, sort_keys=True))
        return 2
    rows = [m for m in data.get("members", []) if m["host"] == host]
    addrs = [m.get("metrics_addr") for m in rows if m.get("metrics_addr")]
    if not addrs:
        log(f"drain: host {host!r} not in the membership table (or it "
            "never announced a metrics endpoint)")
        print(json.dumps({"cmd": "drain", "host": host,
                          "error": "unknown host"}, sort_keys=True))
        return 2
    url = f"http://{addrs[0]}/fleet/drain?timeout={max(timeout, 0.1):g}"
    try:
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=5.0) as r:
            started = json.loads(r.read().decode("utf-8"))
    except Exception as exc:  # noqa: BLE001 - a dead drain endpoint is
        # the verdict, not a traceback
        log(f"drain: POST {url} failed: {exc!r}")
        print(json.dumps({"cmd": "drain", "host": host,
                          "error": repr(exc)[:200]}, sort_keys=True))
        return 2
    log(f"drain: {host} acknowledged (phase={started.get('phase')}); "
        "polling for leaving ...")
    deadline = time.monotonic() + max(timeout, 0.1)
    phase = str(started.get("phase") or "")
    while time.monotonic() < deadline and phase != "leaving":
        time.sleep(0.2)
        try:
            data = fetch_members(target, timeout=2.0)
        except Exception:  # noqa: BLE001 - the polled member may be the
            # draining one; keep polling until the deadline decides
            continue
        for m in data.get("members", []):
            if m["host"] == host and m.get("phase"):
                phase = str(m["phase"])
    drained = phase == "leaving"
    verdict = {"cmd": "drain", "host": host, "phase": phase,
               "pass": drained}
    if as_json:
        verdict["members"] = [
            {k: m.get(k) for k in ("host", "role", "state", "phase",
                                   "quarantined")}
            for m in data.get("members", [])
        ]
    log(f"drain: {host} -> {phase or 'unknown'} "
        f"({'drained' if drained else 'still holding at timeout'})")
    print(json.dumps(verdict, sort_keys=True))
    return 0 if drained else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleetctl", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("cmd", choices=["status", "top", "history",
                                    "drain-check", "drain"])
    ap.add_argument("metric", nargs="?", default="",
                    help="history: the metric name to render")
    ap.add_argument("--target", default=default_target(),
                    help="any member's metrics endpoint (host:port)")
    ap.add_argument("--host", default="",
                    help="host id to drain-check / drain / narrow "
                         "history to")
    ap.add_argument("--window", type=float, default=300.0,
                    help="history: trailing range in seconds")
    ap.add_argument("--timeout", type=float, default=5.0,
                    help="fetch timeout; for drain, also the bound on "
                         "waiting for the leaving phase")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="status/top: full row set as one JSON document "
                         "on stdout instead of the table + verdict")
    args = ap.parse_args(argv)
    if args.cmd == "history":
        if not args.metric:
            ap.error("history requires a metric name")
        return cmd_history(args.target, args.metric, args.host,
                           args.window, args.timeout,
                           as_json=args.as_json)
    if args.cmd == "drain":
        if not args.host:
            ap.error("drain requires --host")
        return cmd_drain(args.target, args.host, args.timeout,
                         as_json=args.as_json)
    try:
        data = fetch_members(args.target, timeout=args.timeout)
    except Exception as exc:  # noqa: BLE001 - unreachable target is the
        # operator's first answer, render it as such
        log(f"fleetctl: cannot reach {args.target}: {exc!r}")
        print(json.dumps({"cmd": args.cmd, "target": args.target,
                          "error": repr(exc)[:200]}, sort_keys=True))
        return 2
    if args.cmd == "status":
        return cmd_status(data, as_json=args.as_json)
    if args.cmd == "top":
        return cmd_top(data, as_json=args.as_json)
    if not args.host:
        ap.error("drain-check requires --host")
    return cmd_drain_check(data, args.host)


if __name__ == "__main__":
    sys.exit(main())
