"""Restricted subprocess execution for plugin scripts.

Reference parity (tools/src/sandbox.rs:12-140): cleared environment with a
minimal PATH/HOME, resource limits (memory 256 MB, CPU 30 s, 64 fds,
16 processes), an allowlist of writable paths, and an optional network flag
(we cannot truly firewall per-process without namespaces, so `network=False`
removes proxy vars and sets a marker env; plugin code runs with least
privilege either way).
"""

from __future__ import annotations

import os
import resource
import subprocess
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class ResourceLimits:
    memory_bytes: int = 256 * 1024 * 1024
    cpu_seconds: int = 30
    max_fds: int = 64
    max_procs: int = 16
    wall_timeout: float = 60.0


@dataclass
class Sandbox:
    limits: ResourceLimits = field(default_factory=ResourceLimits)
    writable_paths: List[str] = field(default_factory=lambda: ["/tmp"])
    allow_network: bool = False

    def _preexec(self):
        limits = self.limits

        def apply():
            resource.setrlimit(
                resource.RLIMIT_AS, (limits.memory_bytes, limits.memory_bytes)
            )
            resource.setrlimit(
                resource.RLIMIT_CPU, (limits.cpu_seconds, limits.cpu_seconds)
            )
            resource.setrlimit(resource.RLIMIT_NOFILE, (limits.max_fds, limits.max_fds))
            try:
                resource.setrlimit(
                    resource.RLIMIT_NPROC, (limits.max_procs, limits.max_procs)
                )
            except (ValueError, OSError):
                pass  # may be below current usage in containers
            os.setsid()

        return apply

    def _env(self) -> Dict[str, str]:
        env = {
            "PATH": "/usr/local/bin:/usr/bin:/bin",
            "HOME": "/tmp",
            "LANG": "C.UTF-8",
            "AIOS_SANDBOX": "1",
            "AIOS_WRITABLE": ":".join(self.writable_paths),
        }
        if not self.allow_network:
            env["AIOS_NO_NETWORK"] = "1"
        return env

    def run(
        self,
        argv: List[str],
        stdin_data: Optional[bytes] = None,
        cwd: str = "/tmp",
    ) -> subprocess.CompletedProcess:
        """Run argv under the sandbox; raises TimeoutExpired on wall timeout."""
        return subprocess.run(
            argv,
            input=stdin_data,
            capture_output=True,
            cwd=cwd,
            env=self._env(),
            preexec_fn=self._preexec(),
            timeout=self.limits.wall_timeout,
        )
