"""aios.runtime.AIRuntime gRPC service over the TPU engine.

Reference parity (runtime/src/grpc_service.rs):
  * resolution order for Infer: explicit model name -> intelligence-level
    ladder -> any ready model -> UNAVAILABLE (grpc_service.rs:187-233);
  * reactive level is rejected with INVALID_ARGUMENT ("heuristics, no model",
    grpc_service.rs:208-211); strategic with no big model ready returns
    FAILED_PRECONDITION "route via api-gateway" (grpc_service.rs:213-216);
  * defaults: max_tokens 512, temperature 0.7 (inference.rs:103-112).

Improvement over the reference: StreamInfer is genuinely token-by-token (the
reference buffers the whole SSE body before chunking, inference.rs:257-353 —
a quirk SURVEY.md says to fix consciously). Chunks carry incremental
detokenized text; the final chunk has done=true and empty text.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Iterator, Optional

import grpc

from .. import rpc
from ..fleet import disagg as fleet_disagg
from ..fleet import drain as fleet_drain
from ..fleet import gprefix as fleet_gprefix
from ..obs import fleet, flightrec, instruments as obs, slo, tracing
from ..obs.http import maybe_start_metrics_server
from ..proto_gen import common_pb2, runtime_pb2
from ..services import KVTRANSFER, RUNTIME, AIRuntimeServicer, service_address
from ..engine.batching import Request
from ..engine.tokenizer import render_chat
from ..serving import AdmissionError, tenant_of
from .model_manager import (
    STATE_READY,
    ManagedModel,
    ModelManager,
)

log = logging.getLogger("aios.runtime")

DEFAULT_MAX_TOKENS = 512
DEFAULT_TEMPERATURE = 0.7
DEFAULT_TOP_P = 0.95


def json_mode_forced() -> bool:
    """AIOS_TPU_JSON_MODE=force: every non-streaming Infer is grammar-
    constrained to one JSON object (the reference's response_format
    behavior, inference.rs:114-122). Single accepted-value set shared by
    the per-request check and the model manager's warmup gate."""
    return os.environ.get("AIOS_TPU_JSON_MODE", "").lower() in (
        "force", "1", "on",
    )


class RuntimeService(AIRuntimeServicer):
    def __init__(self, manager: Optional[ModelManager] = None):
        self.manager = manager or ModelManager()
        self.started_at = time.time()
        # weakref: the process-global gauge must not pin a discarded
        # manager (and its loaded engines' HBM/caches) for process life
        import weakref

        ref = weakref.ref(self.manager)
        obs.RUNTIME_MODELS_READY.set_function(
            lambda: (lambda m: float(len(m.ready_models())) if m is not None
                     else 0.0)(ref())
        )

    # -- lifecycle RPCs -----------------------------------------------------

    def LoadModel(self, request, context):
        try:
            m = self.manager.load_model(
                request.model_name,
                request.model_path,
                context_length=request.context_length,
            )
        except Exception as exc:  # noqa: BLE001
            context.set_code(grpc.StatusCode.INTERNAL)
            context.set_details(f"load failed: {exc}")
            return runtime_pb2.ModelStatus(
                model_name=request.model_name, status="error"
            )
        return self._status_of(m)

    def UnloadModel(self, request, context):
        ok = self.manager.unload_model(request.model_name)
        return common_pb2.Status(
            success=ok,
            message="unloaded" if ok else f"model {request.model_name} not loaded",
        )

    def ListModels(self, request, context):
        return runtime_pb2.ModelList(
            models=[self._status_of(m) for m in self.manager.models.values()]
        )

    def HealthCheck(self, request, context):
        # list(): Load/Unload RPCs mutate the dict on other gRPC threads
        models = list(self.manager.models.values())
        details = {m.name: m.state for m in models}
        details["backend"] = "jax-tpu"
        # per-model serving counters (spec acceptance, KV page usage,
        # prefix-cache hits, evictions) — additive observability the
        # reference's llama-server health probe has no equivalent for
        for m in models:
            # snapshot: a concurrent UnloadModel nulls these fields on the
            # same ManagedModel object mid-iteration
            pool, engine, batcher = m.pool, m.engine, m.batcher
            if pool is not None and engine is not None:
                # pool.stats() is the pool-level engine.stats(): counters
                # summed across replicas + routing/shed/occupancy detail
                stats = pool.stats()
            elif engine is not None and batcher is not None:
                stats = engine.stats()
                stats["pool_evictions"] = batcher.pool_evictions
                stats["completed"] = batcher.completed
                stats["cancelled"] = batcher.cancellations
                stats["waiting"] = batcher.queue_depth()
                stats["num_slots"] = engine.num_slots
            else:
                continue
            details[f"{m.name}.serving"] = ",".join(
                f"{k}={v}" for k, v in sorted(stats.items())
            )
        # SLO view (obs/slo.py): per-objective windowed attainment, with
        # breached objectives flagged — the gRPC twin of the /healthz
        # degradation signal
        for name in slo.ENGINE.models():
            ev = slo.ENGINE.evaluate(name)
            details[f"{name}.slo"] = ",".join(
                f"{o}={v['attainment']:.4f}"
                + ("!breach" if v["breached"] else "")
                for o, v in sorted(ev.items())
            )
        ready = len(self.manager.ready_models())
        return common_pb2.HealthStatus(
            healthy=True,
            service="runtime",
            message=f"{ready} model(s) ready",
            uptime_seconds=int(time.time() - self.started_at),
            details=details,
        )

    # -- inference RPCs -----------------------------------------------------

    def Infer(self, request, context):
        t0 = time.time()
        m = self._resolve_model(request, context)
        if m is None:
            return runtime_pb2.InferResponse()
        handle, n_prompt = self._submit(m, request, context=context)
        # decode span: child of the interceptor's RPC server span (same
        # handler thread), the leaf of the goal->task->agent->RPC->decode
        # hierarchy
        with tracing.start_span(
            "runtime.decode", model=m.name, rpc="Infer"
        ) as span:
            token_ids = [t for t in handle if t != m.tokenizer.eos_id]
            span.set_attribute("tokens", len(token_ids))
        obs.RUNTIME_INFER_LATENCY.labels(model=m.name, rpc="Infer").observe(
            time.time() - t0
        )
        if handle.aborted:
            # mid-request abort (model unload, scheduler failure): the
            # collected tokens are a truncation — error out, don't present
            # them as a completion. RETRYABLE causes (a crashed replica
            # whose failover budget was exhausted) additionally carry a
            # retry-after-ms hint, the admission-shed convention, so
            # compliant clients back off and resubmit instead of treating
            # the crash as permanent.
            retry_ms = getattr(handle, "retry_after_ms", 0)
            if retry_ms:
                context.set_trailing_metadata(
                    (("retry-after-ms", str(retry_ms)),)
                )
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"request aborted: {handle.abort_reason}",
            )
        text = m.tokenizer.decode(token_ids)
        latency_ms = int((time.time() - t0) * 1000)
        return runtime_pb2.InferResponse(
            text=text,
            tokens_used=n_prompt + len(token_ids),
            latency_ms=latency_ms,
            model_used=m.name,
        )

    def StreamInfer(self, request, context) -> Iterator[runtime_pb2.InferChunk]:
        t0 = time.time()
        m = self._resolve_model(request, context)
        if m is None:
            return
        handle, _ = self._submit(
            m, request, streaming=True, context=context
        )
        chunk_counter = obs.RUNTIME_STREAM_CHUNKS.labels(model=m.name)
        emitted = ""
        ids = []
        try:
            with tracing.start_span(
                "runtime.decode", model=m.name, rpc="StreamInfer"
            ) as span:
                for tok in handle:
                    if tok == m.tokenizer.eos_id:
                        break
                    ids.append(tok)
                    # incremental detokenization: emit the stable text delta
                    text = m.tokenizer.decode(ids)
                    if text.startswith(emitted):
                        delta = text[len(emitted) :]
                    else:  # rare resegmentation: resend from scratch marker
                        delta = text
                    if delta:
                        emitted = text
                        chunk_counter.inc()
                        yield runtime_pb2.InferChunk(text=delta, done=False)
                span.set_attribute("tokens", len(ids))
            obs.RUNTIME_INFER_LATENCY.labels(
                model=m.name, rpc="StreamInfer"
            ).observe(time.time() - t0)
            if handle.aborted:
                # an error status instead of a done-chunk: the client
                # must not mistake a mid-stream abort for a short
                # completion. RETRYABLE causes (crashed replica, failover
                # budget spent) surface UNAVAILABLE + retry-after-ms so
                # the client resubmits — the re-prefill is a prefix-cache
                # hit; deliberate aborts (unload) stay ABORTED.
                retry_ms = getattr(handle, "retry_after_ms", 0)
                if retry_ms:
                    context.set_trailing_metadata(
                        (("retry-after-ms", str(retry_ms)),)
                    )
                    context.set_code(grpc.StatusCode.UNAVAILABLE)
                else:
                    context.set_code(grpc.StatusCode.ABORTED)
                context.set_details(
                    f"stream aborted: {handle.abort_reason}"
                )
                return
            yield runtime_pb2.InferChunk(text="", done=True)
        finally:
            # a cancelled/disconnected client closes this generator at its
            # yield point (GeneratorExit) — abort the engine request NOW
            # rather than waiting for the termination callback, so the slot
            # and KV pages free within one scheduler tick (llama-server
            # parity: decode stops when the HTTP client goes away). No-op
            # on normal completion.
            handle.cancel()

    # -- helpers ------------------------------------------------------------

    def _submit(self, m: ManagedModel, request, streaming: bool = False,
                context=None):
        m.touch()
        prompt_text = render_chat(
            m.config.name, request.prompt, request.system_prompt
        )
        prompt_ids = m.tokenizer.encode(prompt_text)
        stop = (m.tokenizer.eos_id,) if m.tokenizer.eos_id is not None else ()
        # TPU extension field: grammar-guided structured output (the schema
        # subset of engine/jsonschema.py); malformed input is the caller's
        # error, surfaced as INVALID_ARGUMENT
        schema = None
        raw_schema = getattr(request, "json_schema", "")
        if raw_schema:
            import json as _json

            try:
                schema = _json.loads(raw_schema)
                if not isinstance(schema, dict):
                    raise ValueError("schema must be a JSON object")
            except ValueError as e:
                if context is not None:
                    context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        f"invalid json_schema: {e}",
                    )
                raise
        # The reference forces response_format=json_object on every
        # NON-streaming local inference (inference.rs:114-122, enforced by
        # llama-server's grammar engine). The TPU equivalent is logit-mask
        # grammar decoding (engine/jsonmode.py). Conscious default: OFF —
        # the blanket force would garble plain-text think() flows that the
        # reference only gets away with because its prompts all demand
        # JSON; AIOS_TPU_JSON_MODE=force restores exact reference behavior.
        json_mode = (
            schema is None and not streaming and json_mode_forced()
        )
        req = Request(
            prompt_ids=prompt_ids,
            max_tokens=request.max_tokens or DEFAULT_MAX_TOKENS,
            temperature=(
                request.temperature
                if request.temperature > 0
                else DEFAULT_TEMPERATURE
            ),
            top_p=DEFAULT_TOP_P,
            stop_ids=stop,
            request_id=request.task_id or "",
            json_mode=json_mode,
            json_schema=schema,
            # admission priority from the request's intelligence level:
            # priority ranks LATENCY SENSITIVITY as much as intelligence —
            # under slot contention, strategic reasoning admits ahead of
            # bulk operational traffic, and a reactive request (a quick
            # latency-sensitive probe that explicitly named a model — the
            # ladder rejects model-less reactive calls) ranks with
            # operational rather than at the bottom with unclassified
            # traffic (FIFO within a level; no wire change — the level
            # field already rides InferRequest)
            priority={
                "strategic": 3, "tactical": 2, "operational": 1,
                "reactive": 1,
            }.get(request.intelligence_level.lower(), 0),
        )
        # serving front door: per-tenant quota (tenant = agent id / task
        # prefix, per the pool's AIOS_TPU_TENANT_BY policy), bounded
        # queues, deadline feasibility — the propagated gRPC deadline is
        # the request's budget
        tenant = tenant_of(
            request, m.pool.cfg.tenant_by if m.pool is not None else "agent"
        )
        # flight recorder: the timeline opens HERE — the first point that
        # knows model, tenant, AND the RPC's trace identity (the server
        # interceptor's span is current on this handler thread), so shed
        # decisions, route choice, and scheduler events all land on one
        # record correlated with the span tree by trace id
        span = tracing.current_span()
        req.rec = flightrec.RECORDER.begin(
            m.name, req.request_id, tenant,
            trace_id=span.trace_id if span is not None else "",
            prompt_tokens=len(prompt_ids), priority=req.priority,
        )
        deadline_s = None
        if context is not None:
            tr = context.time_remaining()
            if tr is not None and tr < 3600 * 24 * 365:
                deadline_s = tr
        try:
            try:
                # fleet data plane rung (fleet/disagg.py): exactly
                # m.submit when the plane is disarmed; on a prefill-role
                # host the returned handle hands the stream to a decode
                # host after the first token
                handle = fleet_disagg.route_submit(
                    m, req, tenant=tenant, deadline_s=deadline_s
                )
            except AdmissionError as e:
                # load shed: RESOURCE_EXHAUSTED + a retry-after-ms
                # trailing-metadata hint instead of an unbounded queue;
                # PERMANENT conditions (cost can never fit the bucket)
                # are INVALID_ARGUMENT so clients don't retry forever
                if context is not None:
                    if not e.retriable:
                        context.abort(
                            grpc.StatusCode.INVALID_ARGUMENT,
                            f"request not admittable ({e.cause}): {e}",
                        )
                    context.set_trailing_metadata(
                        (("retry-after-ms", str(e.retry_after_ms)),)
                    )
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"request shed ({e.cause}): {e}",
                    )
                raise
            except RuntimeError as e:
                # submit raced UnloadModel's shutdown: the batcher refuses
                # (rather than stranding the consumer forever)
                if context is not None:
                    context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        f"model {m.name} is unloading: {e}",
                    )
                raise
            if context is not None:
                # llama-server parity (model_manager.rs spawns a server that
                # aborts decode when its HTTP client goes away): a gRPC
                # disconnect/cancel frees the request's slot and KV pages
                # instead of decoding to max_tokens for nobody. Fires on
                # normal termination too — cancel() is a no-op then.
                # add_callback returns False (never firing) when the RPC
                # already terminated — cancel straight away then, or the
                # submitted request would decode for a client that is gone.
                if not context.add_callback(handle.cancel):
                    handle.cancel()
            return handle, len(prompt_ids)
        except ValueError as e:
            # unsupported schema constructs / scalar roots fail fast
            if context is not None and schema is not None:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"unsupported json_schema: {e}",
                )
            raise

    def _resolve_model(self, request, context) -> Optional[ManagedModel]:
        """explicit name -> level ladder -> any ready -> gRPC error."""
        if request.model:
            m = self.manager.find_by_partial_name(request.model)
            if m is not None:
                return m
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(f"model {request.model} not loaded")
            return None

        level = request.intelligence_level.lower()
        if level == "reactive":
            context.set_code(grpc.StatusCode.INVALID_ARGUMENT)
            context.set_details(
                "reactive tasks use heuristics, not model inference"
            )
            return None
        if level:
            m = self.manager.select_for_level(level)
            if m is not None:
                return m
            if level == "strategic":
                context.set_code(grpc.StatusCode.FAILED_PRECONDITION)
                context.set_details(
                    "no strategic-tier model loaded; route via api-gateway"
                )
                return None

        ready = self.manager.ready_models()
        if ready:
            return ready[0]
        context.set_code(grpc.StatusCode.UNAVAILABLE)
        context.set_details("no models loaded")
        return None

    def _status_of(self, m: ManagedModel) -> runtime_pb2.ModelStatus:
        return runtime_pb2.ModelStatus(
            model_name=m.name,
            status=m.state,
            port=0,  # no HTTP sidecar on TPU
            loaded_at=m.loaded_at,
            last_used=m.last_used,
            request_count=m.request_count,
        )


def serve(
    address: Optional[str] = None,
    manager: Optional[ModelManager] = None,
    block: bool = True,
    metrics_port: Optional[int] = None,
):
    """Start the runtime gRPC server (reference binds [::]:50055,
    runtime/src/main.rs:140). ``metrics_port`` (or
    AIOS_RUNTIME_METRICS_PORT) also starts the /metrics + /healthz
    endpoint; its server and bound port ride on the service object."""
    address = address or service_address("runtime")
    server = rpc.create_server()
    service = RuntimeService(manager)
    rpc.add_to_server(RUNTIME, service, server)
    # the fleet transfer plane (aios.fleet.KvTransfer) rides the SAME
    # server — registered unconditionally (answering Fetch/Push/Handoff
    # on a solo host is harmless) so arming the fleet later needs no
    # restart
    rpc.add_to_server(
        KVTRANSFER, fleet_disagg.DisaggService(service.manager), server
    )
    port = server.add_insecure_port(address)
    server.start()
    # pool stats ride every fleet heartbeat (obs/fleet.py): peers rank
    # hosts by live occupancy/degrade level without scraping each model.
    # Registered before the metrics server so the registry's very first
    # announce already carries them.
    fleet.add_stats_provider(lambda: {
        m.name: m.pool.heartbeat_stats()
        for m in service.manager.ready_models()
        if m.pool is not None
    })
    # fleet data plane: publish this process's transfer endpoint + prefix
    # digest on the heartbeat, and arm the disagg routing rung
    host = address.rsplit(":", 1)[0].strip("[]")
    reach = "127.0.0.1" if host in ("", "0.0.0.0", "::", "localhost") else host
    fleet.set_transfer_addr(f"{reach}:{port}")
    fleet.add_digest_provider(fleet_gprefix.provider(service.manager))
    # the routing rung arms only on a configured fleet (or an explicit
    # role): a solo host keeps the exact pre-fleet submit path
    if fleet.FleetConfig().active() or os.environ.get("AIOS_TPU_FLEET_ROLE"):
        fleet_disagg.arm(service.manager)
        # the graceful-drain coordinator (POST /fleet/drain) arms with
        # the data plane: a solo host has no fleet to drain toward
        fleet_drain.arm(service.manager)
    service.metrics_server, service.metrics_port = maybe_start_metrics_server(
        "runtime",
        metrics_port,
        # the SLO view rides the probe: any breached objective flips
        # status to "degraded", which obs/http.py maps to HTTP 503 — so
        # load balancers eject the replica instead of reading prose
        health_fn=lambda: slo.annotate_health({
            "status": "ok",
            "service": "runtime",
            "models_ready": len(service.manager.ready_models()),
        }),
    )
    log.info("AIRuntime listening on %s", address)
    if block:
        server.wait_for_termination()
    return server, service, port


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    # multi-host deployments set AIOS_TPU_COORDINATOR (+NUM_PROCESSES,
    # +PROCESS_ID) so every host's runtime joins one process group and the
    # engines see the global mesh; single-host is a no-op
    from ..parallel import multihost

    multihost.initialize_from_env()
    manager = ModelManager()
    manager.autoload()
    serve(manager=manager)
