"""Storm driver: replay a trace against a live runtime gRPC endpoint.

One worker thread per in-flight call (storms are CPU-sized — tens to a
few hundred calls; the point is contention realism, not driver
throughput). Each worker issues the call at its scheduled time through
the REAL service surface — ``StreamInfer`` for streaming tenants (TTFT
measured at the first delta), ``Infer`` otherwise — propagating the
scenario's per-call gRPC deadline so the admission layer's feasibility
gate sees exactly what production clients send.

Outcomes record what the PLANE did, classified off the gRPC status the
service contract promises: ``RESOURCE_EXHAUSTED`` + ``retry-after-ms``
is a retriable shed (cause parsed from the detail string the service
formats), ``INVALID_ARGUMENT`` with a shed cause is a permanent
rejection (a cost no bucket refill can cover), anything else non-OK is
an error the verdict fails on.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import grpc

from .. import rpc, services
from ..proto_gen import runtime_pb2
from .trace import Call

_SHED_RE = re.compile(r"request (?:shed|not admittable) \((\w+)\)")


@dataclass
class Outcome:
    call: Call
    status: str = "ok"  # ok | shed | rejected | error
    shed_cause: str = ""
    code: str = ""
    retry_after_ms: int = 0
    text: str = ""
    ttft_ms: float = 0.0  # streaming calls only (first delta)
    wall_ms: float = 0.0
    chunks: int = 0
    detail: str = ""
    extras: dict = field(default_factory=dict)


class StormDriver:
    def __init__(self, address: str, model: str,
                 metrics_port: Optional[int] = None,
                 time_scale: float = 1.0) -> None:
        self.address = address
        self.model = model
        self.metrics_port = metrics_port
        self.time_scale = time_scale
        self._channel = rpc.insecure_channel(address)
        self._stub = services.AIRuntimeStub(self._channel)

    def close(self) -> None:
        self._channel.close()

    # -- one call ------------------------------------------------------------

    def _request(self, c: Call) -> runtime_pb2.InferRequest:
        # proto temperature 0 means UNSET to the service (it substitutes
        # the 0.7 default, inference.rs parity) — greedy rides just
        # under sampling.GREEDY_EPS so the engine takes argmax
        temp = c.temperature if c.temperature > 0 else 5e-5
        return runtime_pb2.InferRequest(
            model=self.model,
            prompt=c.prompt,
            max_tokens=c.max_tokens,
            temperature=temp,
            intelligence_level=c.level,
            requesting_agent=c.tenant,  # tenant identity (AIOS_TPU_TENANT_BY)
            task_id=c.task_id,
        )

    def _classify(self, out: Outcome, err: grpc.RpcError) -> None:
        code = err.code()
        out.code = code.name if code is not None else "UNKNOWN"
        out.detail = (err.details() or "")[:200]
        m = _SHED_RE.search(out.detail)
        for k, v in (err.trailing_metadata() or ()):  # retry hint, if any
            if k == "retry-after-ms":
                try:
                    out.retry_after_ms = int(v)
                except ValueError:
                    pass
        if m and code == grpc.StatusCode.RESOURCE_EXHAUSTED:
            out.status, out.shed_cause = "shed", m.group(1)
        elif m and code == grpc.StatusCode.INVALID_ARGUMENT:
            out.status, out.shed_cause = "rejected", m.group(1)
        else:
            out.status = "error"

    def _fire(self, c: Call, out: Outcome) -> None:
        req = self._request(c)
        timeout = c.deadline_ms / 1000.0 if c.deadline_ms else None
        t0 = time.monotonic()
        try:
            if c.streaming:
                text = []
                for chunk in self._stub.StreamInfer(req, timeout=timeout):
                    if chunk.text and not text:
                        out.ttft_ms = (time.monotonic() - t0) * 1000.0
                    if chunk.text:
                        text.append(chunk.text)
                        out.chunks += 1
                out.text = "".join(text)
            else:
                resp = self._stub.Infer(req, timeout=timeout)
                out.text = resp.text
                out.extras["tokens_used"] = resp.tokens_used
        except grpc.RpcError as err:
            self._classify(out, err)
        out.wall_ms = (time.monotonic() - t0) * 1000.0

    # -- warmup prologue -----------------------------------------------------

    def warmup(self, n: int = 3, max_tokens: int = 8) -> None:
        """Sequential throwaway greedy requests before the clock starts:
        the first dispatches of a cold pool compile for seconds, and the
        batcher's first observed tokens/sec window is compile-polluted —
        a deadline-carrying call judged against that rate sheds on a
        COLD run and admits on a warm one, which is exactly the
        cold-vs-warm asymmetry the determinism contract forbids (the
        bench.py gateway-disconnect deflake lesson, now at storm scale).
        Warmup requests never enter the verdict (their task ids are not
        in the trace)."""
        for i in range(n):
            try:
                self._stub.Infer(runtime_pb2.InferRequest(
                    model=self.model,
                    prompt=f"[storm warmup {i}] prime the decode graphs",
                    max_tokens=max_tokens,
                    temperature=5e-5,
                    task_id=f"storm-warmup-{i}",
                ), timeout=120)
            except grpc.RpcError as err:  # warmup must not kill the storm
                code = err.code()
                raise RuntimeError(
                    f"storm warmup request {i} failed "
                    f"({code.name if code else '?'}): {err.details()}"
                ) from err

    # -- the storm -----------------------------------------------------------

    def run(self, calls: List[Call],
            join_timeout: float = 180.0) -> List[Outcome]:
        """Replay the trace on the wall clock (``time_scale`` stretches
        it: 2.0 = half speed). Returns outcomes in trace order; a worker
        still blocked after the join budget marks its outcome
        ``error/stuck`` (the zero-leak contract the verdict enforces)."""
        outcomes = [Outcome(call=c) for c in calls]
        threads = []
        t0 = time.monotonic()
        for c, out in zip(calls, outcomes):
            delay = c.t * self.time_scale - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(
                target=self._fire, args=(c, out), daemon=True,
                name=f"storm-{c.task_id}",
            )
            th.start()
            threads.append(th)
        deadline = time.monotonic() + join_timeout
        for th, out in zip(threads, outcomes):
            th.join(timeout=max(deadline - time.monotonic(), 0.1))
            if th.is_alive():
                out.status, out.detail = "error", "stuck"
        return outcomes

    # -- live SLO surface ----------------------------------------------------

    def slo_surface(self) -> dict:
        """Read the live ``/debug/slo`` view off the service's metrics
        port — the storm records the PLANE's own windowed attainment
        next to the driver-side measurements."""
        if not self.metrics_port:
            return {}
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{self.metrics_port}/debug/slo",
                timeout=5,
            ) as r:
                return json.loads(r.read().decode())
        except Exception as exc:  # noqa: BLE001 - surface absence is data
            return {"error": repr(exc)[:120]}


def target_of(tenant: str, n_targets: int) -> int:
    """Deterministic tenant -> target routing for multi-endpoint storms:
    a pure function of the tenant NAME, so (a) two seeded runs route
    identically (the per-target fingerprint is comparable with ``==``)
    and (b) cache-coupled families (shared preambles, fork children —
    always same-tenant) land on one target, keeping the radix-cache
    determinism argument intact across the fan-out."""
    if n_targets <= 1:
        return 0
    return zlib.crc32(tenant.encode("utf-8")) % n_targets


class FleetStormDriver:
    """The multi-target storm driver: one :class:`StormDriver` per
    endpoint, the trace spread over them by :func:`target_of`. The
    verdict side (loadgen/report.py) aggregates one fingerprint per
    target off the ``target`` extra stamped on every outcome."""

    def __init__(self, addresses: Sequence[str], model: str,
                 metrics_ports: Optional[Sequence[Optional[int]]] = None,
                 time_scale: float = 1.0) -> None:
        if not addresses:
            raise ValueError("FleetStormDriver needs at least one address")
        ports: Sequence[Optional[int]] = (
            metrics_ports if metrics_ports is not None
            else [None] * len(addresses)
        )
        if len(ports) != len(addresses):
            raise ValueError("metrics_ports must match addresses")
        self.drivers = [
            StormDriver(addr, model, metrics_port=p, time_scale=time_scale)
            for addr, p in zip(addresses, ports)
        ]
        self.time_scale = time_scale

    def close(self) -> None:
        for d in self.drivers:
            d.close()

    def warmup(self, n: int = 3, max_tokens: int = 8) -> None:
        for d in self.drivers:
            d.warmup(n=n, max_tokens=max_tokens)

    def run(self, calls: List[Call],
            join_timeout: float = 180.0) -> List[Outcome]:
        """Same wall-clock replay contract as StormDriver.run, each call
        fired at its tenant's target; outcomes carry
        ``extras["target"]``."""
        outcomes = [Outcome(call=c) for c in calls]
        n = len(self.drivers)
        threads = []
        t0 = time.monotonic()
        for c, out in zip(calls, outcomes):
            delay = c.t * self.time_scale - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            target = target_of(c.tenant, n)
            out.extras["target"] = target
            th = threading.Thread(
                target=self.drivers[target]._fire, args=(c, out),
                daemon=True, name=f"storm-{c.task_id}",
            )
            th.start()
            threads.append(th)
        deadline = time.monotonic() + join_timeout
        for th, out in zip(threads, outcomes):
            th.join(timeout=max(deadline - time.monotonic(), 0.1))
            if th.is_alive():
                out.status, out.detail = "error", "stuck"
        return outcomes

    def slo_surface(self) -> Dict[str, dict]:
        """Per-target /debug/slo readback, keyed by target index."""
        return {
            str(i): d.slo_surface() for i, d in enumerate(self.drivers)
        }
