"""Model lifecycle + intelligence-level routing for the TPU runtime.

Reference parity (runtime/src/model_manager.rs):
  * name -> managed model registry with states loading/ready/error/unloading
    (model_manager.rs:24-29) — here a model is an in-process TPUEngine +
    ContinuousBatcher + tokenizer, not a llama-server child, so "loading"
    covers dequantize + device_put + warm-compile and "ready" means the
    decode graph is compiled (the /health polling of the reference,
    model_manager.rs:222-263, collapses into warmup()).
  * startup auto-scan of AIOS_MODEL_DIR for *.gguf with context length
    chosen by file size (runtime/src/main.rs:65-132).
  * select_model_for_level routing ladders with partial case-insensitive
    name matching (model_manager.rs:462-518): reactive -> None;
    operational -> tinyllama > deepseek > mistral; tactical -> deepseek >
    qwen3 > mistral > tinyllama; strategic -> qwen3 > deepseek > mistral.

TPU-specific: `synthetic://<preset>` model paths build a random-weight model
of that architecture (benchmarks and tests run without weight files).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from ..analysis.locks import make_lock
from ..engine import gguf as gguf_mod
from ..engine import model as model_mod
from ..engine import weights as weights_mod
from ..engine.batching import ContinuousBatcher
from ..engine.config import (
    PRESETS,
    ModelConfig,
    from_gguf_metadata,
    TINY_MOE,
    TINY_TEST,
)
from ..engine.engine import TPUEngine
from ..engine.tokenizer import (
    BaseTokenizer,
    ByteTokenizer,
    HFTokenizer,
    gguf_tokenizer,
)
from ..serving import ReplicaPool, ServingConfig

log = logging.getLogger("aios.runtime.models")

STATE_LOADING = "loading"
STATE_READY = "ready"
STATE_ERROR = "error"
STATE_UNLOADING = "unloading"

# Routing ladders per intelligence level (model_manager.rs:462-505).
LEVEL_LADDERS: Dict[str, List[str]] = {
    "reactive": [],
    "operational": ["tinyllama", "deepseek", "mistral"],
    "tactical": ["deepseek", "qwen3", "mistral", "tinyllama"],
    "strategic": ["qwen3", "deepseek", "mistral"],
}


@dataclass
class ManagedModel:
    name: str
    config: ModelConfig
    # replica 0's engine/batcher, kept for single-replica callers and
    # HealthCheck snapshots; the POOL is the serving entry point
    engine: TPUEngine
    batcher: ContinuousBatcher
    tokenizer: BaseTokenizer
    state: str = STATE_LOADING
    loaded_at: int = 0
    last_used: int = 0
    request_count: int = 0
    error: str = ""
    # estimated per-chip HBM this model pins (weights + KV); co-resident
    # loads subtract it from the auto-degradation budget
    hbm_chip_bytes: float = 0.0
    # the replica pool fronting this model (aios_tpu/serving/): admission
    # -> cache-aware routing -> one replica's batcher. None only for
    # error-state placeholders.
    pool: Optional[ReplicaPool] = None
    # load identity, so a LoadModel for the same name with a different
    # source/geometry hot-swaps instead of returning the stale pool
    model_path: str = ""
    context_length: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)

    def touch(self) -> None:
        self.last_used = int(time.time())
        self.request_count += 1

    def submit(self, req, tenant: str = "anonymous", deadline_s=None):
        """Serving entry point: through the pool (admission + routing)
        when present, straight to the batcher otherwise. Raises
        serving.AdmissionError on shed."""
        pool = self.pool
        if pool is not None:
            return pool.submit(req, tenant=tenant, deadline_s=deadline_s)
        return self.batcher.submit(req)


def _context_for_file_size(n_bytes: int) -> int:
    """Context length by GGUF file size, as the reference's auto-loader
    chooses ctx/threads (runtime/src/main.rs:86-98)."""
    gb = n_bytes / 1e9
    if gb > 8:
        return 8192
    if gb > 2:
        return 4096
    return 2048


def _plan_from_env():
    """Build a sharding plan from AIOS_TPU_MESH ("dp=2,sp=2,tp=2"; missing
    axes default to 1) — how a multi-chip deployment's boot config selects
    its mesh (the [models] mesh knob -> serving_env()). Returns None when
    unset, malformed, or when the visible devices can't fill the mesh (a
    bad tuning knob must not take down boot — the lenient pattern of the
    sibling env parsers)."""
    spec = os.environ.get("AIOS_TPU_MESH", "").strip().lower()
    if not spec:
        return None
    axes = {"dp": 1, "sp": 1, "ep": 1, "tp": 1}
    try:
        for part in spec.split(","):
            k, _, v = part.strip().partition("=")
            if k not in axes:
                raise ValueError(f"unknown mesh axis {k!r}")
            axes[k] = int(v)
            if axes[k] < 1:
                raise ValueError(f"axis {k} must be >= 1")
    except ValueError as exc:
        log.warning("AIOS_TPU_MESH=%r ignored (%s); serving single-chip",
                    spec, exc)
        return None
    n = axes["dp"] * axes["sp"] * axes["ep"] * axes["tp"]
    if n == 1:
        return None
    from ..parallel.sharding import ShardingPlan, build_mesh

    if len(jax.devices()) < n:
        log.warning(
            "AIOS_TPU_MESH=%r needs %d devices, found %d; serving "
            "single-chip", spec, n, len(jax.devices()),
        )
        return None
    return ShardingPlan(build_mesh(
        n, dp=axes["dp"], sp=axes["sp"], ep=axes["ep"], tp=axes["tp"]
    ))


def _chip_hbm_bytes() -> float:
    """Per-device HBM capacity: AIOS_TPU_HBM_GB override, else the
    backend's reported limit, else the v5e default (16 GB)."""
    env = os.environ.get("AIOS_TPU_HBM_GB", "")
    if env:
        try:
            return float(env) * 1e9
        except ValueError:
            log.warning("AIOS_TPU_HBM_GB=%r ignored (not a number)", env)
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return float(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 - stats are best-effort off-TPU
        pass
    return 16e9


class ModelManager:
    """Registry of co-resident TPU models sharing the chip's HBM."""

    def __init__(
        self,
        num_slots: int = 8,
        sharding_plan=None,
        warm_compile: bool = True,
        quantize: Union[bool, str, None] = None,  # None=auto, bool, "int8"/"int4"
    ) -> None:
        self.models: Dict[str, ManagedModel] = {}
        self.num_slots = num_slots
        if sharding_plan is None:
            sharding_plan = _plan_from_env()
        self.plan = sharding_plan
        self.warm_compile = warm_compile
        # int8 serving weights: the default on single-chip TPU (the reference
        # serves Q4 GGUF through llama.cpp, so int8 is *more* precise than
        # its default); AIOS_TPU_QUANTIZE=0 forces bf16 serving. CPU-fallback
        # backends keep dense weights — without the TPU int8 dot they would
        # re-dequantize every matmul.
        # explicit = the operator chose a mode (param or env); auto-derived
        # defaults must not argue with a prepared checkpoint's stored mode.
        # Derived as "did not fall through to the auto branch" so the
        # recognized-value list exists in exactly one place (the chain).
        self.quantize_explicit = quantize is not None
        if quantize is None:
            self.quantize_explicit = True
            env = os.environ.get("AIOS_TPU_QUANTIZE", "").lower()
            if env in ("0", "false", "off"):
                quantize = False
            elif env in ("1", "true", "int8"):
                quantize = "int8"
            elif env == "int4":
                # group-wise packed-nibble int4 (ops/int4_matmul.py): half
                # the int8 weight bytes, Q4-class precision like the
                # reference's GGUF serving format
                quantize = "int4"
            else:
                self.quantize_explicit = False  # fell through to auto
                if env:
                    log.warning(
                        "unrecognized AIOS_TPU_QUANTIZE=%r (expected 0/1/"
                        "int8/int4); using the auto default", env,
                    )
                try:
                    import jax

                    on_tpu = jax.default_backend() == "tpu"
                except Exception:  # noqa: BLE001
                    on_tpu = False
                # default: int8 on single-chip TPU; sharded serving keeps
                # the conservative bf16 default until measured on a real
                # mesh — but an EXPLICIT AIOS_TPU_QUANTIZE=1 is honored
                # either way (the engine shards the unfused int8 layout)
                quantize = "int8" if (sharding_plan is None and on_tpu) else False
        elif quantize is True:
            quantize = "int8"
        self.quantize = quantize or False
        # AIOS_TPU_KV_CACHE=int8 halves KV-cache footprint/traffic (the
        # long-context + co-residency lever); default bf16. Composes with a
        # sharding plan: cache + scales shard by the plan's cache rules and
        # the dequantizing attention partitions under GSPMD.
        kv_env = os.environ.get("AIOS_TPU_KV_CACHE", "").lower()
        self.cache_dtype = jnp.bfloat16
        if kv_env == "int8":
            self.cache_dtype = jnp.int8
        elif kv_env and kv_env not in ("bf16", "bfloat16"):
            log.warning(
                "unrecognized AIOS_TPU_KV_CACHE=%r (expected 'int8'); "
                "using bf16",
                kv_env,
            )
        # AIOS_TPU_PAGED_KV serves every model over a paged KV cache
        # (engine/paged.py): slots x context becomes a logical limit, HBM
        # is spent per page in use, and prompt-prefix pages are SHARED
        # across requests (paged.PrefixIndex) — the lever that takes the
        # 8 agents' common preambles off the prefill path entirely.
        #   <rows>  — fixed physical pool of that many rows
        #   auto    — size per model at load: (num_slots + 1) x context
        #             rows, i.e. the dense cache's HBM plus one slot's
        #             worth of slack so prefix pages can outlive their
        #             originating request without starving admissions.
        #             The production boot config defaults to auto
        #             (boot/config.py [models] paged_kv_rows).
        #   0/off   — dense slot cache.
        # Composes with tp and dp plans (dp partitions the pool per
        # replica); sp-sharded contexts use AIOS_TPU_SEQ_SHARD_KV instead.
        self.paged_pool_rows: Optional[Union[int, str]] = None
        paged_env = os.environ.get("AIOS_TPU_PAGED_KV", "").lower()
        if paged_env in ("auto",):
            self.paged_pool_rows = "auto"
        elif paged_env not in ("", "0", "off", "false"):
            try:
                rows = int(paged_env)
            except ValueError:
                rows = 0
            if rows > 0:
                self.paged_pool_rows = rows
            else:
                log.warning(
                    "AIOS_TPU_PAGED_KV=%r ignored (expected a positive "
                    "row count, 'auto', or 0/off)", paged_env,
                )
        # AIOS_TPU_PREFIX_HOST_BYTES gives the prefix cache a host-RAM
        # spill tier (engine/paged.py HostPageStore): evicted prefix
        # pages' KV copies device->host inside this byte budget and
        # restores with a device_put + scatter on a later hash-chain hit
        # — a memcpy instead of a prefill recompute. Unset defers to
        # ModelConfig.prefix_host_bytes (0 = off); 0 forces it off.
        self.prefix_host_bytes: Optional[int] = None
        host_env = os.environ.get("AIOS_TPU_PREFIX_HOST_BYTES", "")
        if host_env:
            try:
                v = int(float(host_env))
                if v < 0:
                    raise ValueError("must be >= 0")
                self.prefix_host_bytes = v
            except ValueError:
                log.warning(
                    "AIOS_TPU_PREFIX_HOST_BYTES=%r ignored (expected a "
                    "non-negative byte count)", host_env,
                )
        # AIOS_TPU_HOST_RESTORE_MIN_PAGES floors the restore path: a
        # host-tier chain shorter than this many pages prefills normally
        # (device_put of a short prefix can lose to recompute). Default 1.
        self.host_restore_min_pages: Optional[int] = None
        floor_env = os.environ.get("AIOS_TPU_HOST_RESTORE_MIN_PAGES", "")
        if floor_env:
            try:
                v = int(float(floor_env))
                if v < 1:
                    raise ValueError("must be >= 1")
                self.host_restore_min_pages = v
            except ValueError:
                log.warning(
                    "AIOS_TPU_HOST_RESTORE_MIN_PAGES=%r ignored (expected "
                    "an integer >= 1)", floor_env,
                )
        # sp > 1 in the mesh no longer disables paging wholesale: the pool
        # replicates over sp, and the per-model HBM-budget check at load
        # time degrades only the models that actually need their context
        # sharded (seq_sharded_cache) — see the auto-degrade branch in
        # _build_engine's config resolution below.
        # AIOS_TPU_SPECULATIVE=1 turns on n-gram speculative decode
        # dispatches (engine/spec.py): greedy agent requests — tool-call
        # JSON, quoted context — emit several tokens per verify round with
        # identical output. Off by default until measured per deployment.
        self.speculative = os.environ.get(
            "AIOS_TPU_SPECULATIVE", ""
        ).lower() in ("1", "true", "on")
        # AIOS_TPU_SEQ_SHARD_KV=1 shards every model's KV context axis over
        # the mesh's sp axis (long-context serving: one slot's cache spans
        # chips); needs a sharding plan with sp > 1
        self.seq_shard_kv = sharding_plan is not None and os.environ.get(
            "AIOS_TPU_SEQ_SHARD_KV", ""
        ).lower() in ("1", "true", "on")
        self._lock = make_lock("model_manager")

    @staticmethod
    def _kv_row_bytes(cfg, cache_dtype) -> float:
        """Bytes one KV row (both k and v, all layers) occupies."""
        item = 1 if cache_dtype == jnp.int8 else 2
        return 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * item

    def _kv_bytes_per_chip(self, cfg, ctx, cache_dtype, kw) -> float:
        """Estimated per-chip HBM the KV cache will pin under the current
        plan: slots shard over dp and kv heads over tp; the paged pool's
        rows split across dp replicas. sp does NOT divide the estimate
        unless the cache is seq-sharded — which is exactly what the
        auto-degrade check decides."""
        row = self._kv_row_bytes(cfg, cache_dtype)
        dp = tp = 1
        if self.plan is not None:
            dp, tp = self.plan.dp, self.plan.tp
        rows = kw.get("paged_pool_rows") or self.num_slots * ctx
        return row * rows / (dp * tp)

    # -- loading ------------------------------------------------------------

    def _replica_plans(self, n: int) -> List:
        """One sharding plan per replica. With enough devices each replica
        gets its OWN submesh slice (n disjoint dp x sp x ep x tp meshes);
        otherwise every replica shares the manager's plan/devices — the
        CPU-test and oversubscribed layout."""
        if n <= 1 or self.plan is None:
            return [self.plan] * n
        plan = self.plan
        size = plan.dp * plan.sp * plan.ep * plan.tp
        devs = jax.devices()
        if len(devs) < n * size:
            log.info(
                "%d replicas share one %d-device mesh (%d devices visible)",
                n, size, len(devs),
            )
            return [plan] * n
        from ..parallel.sharding import ShardingPlan, build_mesh

        return [
            ShardingPlan(build_mesh(
                devices=devs[i * size:(i + 1) * size],
                dp=plan.dp, sp=plan.sp, ep=plan.ep, tp=plan.tp,
            ))
            for i in range(n)
        ]

    def load_model(
        self,
        name: str,
        path: str = "",
        context_length: int = 0,
    ) -> ManagedModel:
        with self._lock:
            existing = self.models.get(name)
        if existing is not None and existing.state == STATE_READY:
            want_replicas = ServingConfig.from_env(
                existing.config.replicas
            ).replicas
            have_replicas = (
                len(existing.pool.replicas) if existing.pool is not None else 1
            )
            if (
                existing.model_path == path
                and existing.context_length == (context_length or 0)
                and have_replicas == want_replicas
            ):
                return existing
            # different source/geometry/replica count: fall through and
            # HOT-SWAP — the new pool is built first, swapped into the
            # registry, and the old one drains in the background so
            # in-flight streams finish on the engines they started on
            log.info(
                "%s: reload with changed config; hot-swapping the pool",
                name,
            )

        t0 = time.time()
        try:
            cfg, params, tokenizer = self._load_weights(name, path, context_length)
            serving_cfg = ServingConfig.from_env(
                cfg.replicas,
                draft_model_default=getattr(cfg, "draft_model", ""),
            )
            n_replicas = max(1, serving_cfg.replicas)
            plans = self._replica_plans(n_replicas)
            # replicas on DISJOINT submeshes cost 1x per chip (each chip
            # hosts one replica); replicas sharing a device set multiply
            # the per-chip footprint — both the budget check below and the
            # recorded hbm_chip_bytes must use the same factor
            repl_factor = n_replicas
            if n_replicas > 1 and self.plan is not None \
                    and plans[0] is not self.plan:
                repl_factor = 1
            cache_dtype = self.cache_dtype
            ctx = context_length or cfg.max_context
            # Draft-model speculation (ModelConfig.draft_model /
            # AIOS_TPU_DRAFT_MODEL / boot [models] draft_model): load the
            # paired small model ONCE — its int4 params are shared
            # read-only by every replica engine, each of which keeps its
            # own slot-aligned draft KV state. Built BEFORE the HBM
            # budget math below so the draft's weights + dense KV cache
            # count against the per-chip budget like any co-resident
            # footprint. A paired draft implies speculative serving for
            # this model even when the global AIOS_TPU_SPECULATIVE knob
            # is off (the draft exists for nothing else); the proposer
            # ladder still carries the n-gram fallback.
            draft = None
            spec_on = self.speculative
            draft_bytes = 0.0
            if serving_cfg.draft_model:
                draft = self._build_draft(
                    serving_cfg.draft_model, cfg, ctx, tokenizer
                )
                if draft is not None:
                    spec_on = True
                    # weights are device-shared across replica engines
                    # (one DraftModel object); the dense draft KV is
                    # allocated PER ENGINE, and a draft only survives
                    # with plan=None, where replicas share the device
                    # set — so the KV term pays repl_factor times
                    draft_bytes = (
                        draft.weight_bytes()
                        + self._kv_row_bytes(draft.cfg, jnp.bfloat16)
                        * self.num_slots * ctx * repl_factor
                    )
            kw = {}
            pool_rows = self.paged_pool_rows
            if pool_rows == "auto":
                # dense-cache HBM + one slot of slack (prefix retention)
                pool_rows = (self.num_slots + 1) * ctx
            if pool_rows is not None:
                # page size must divide the context; 128 aligns with the
                # kernel block and every power-of-two bucket >= 128. An
                # indivisible context degrades to the dense cache (like
                # every other invalid paged config) instead of failing load.
                # AIOS_TPU_PREFIX_CACHE=0 disables prompt-prefix page
                # sharing (on by default with the paged cache)
                prefix = os.environ.get(
                    "AIOS_TPU_PREFIX_CACHE", "1"
                ).lower() not in ("0", "false", "off")
                # host spill tier: env wins over the model config (the
                # convention everywhere); both resolve HERE so the
                # HealthCheck host-tier occupancy keys and the engine
                # agree on whether the tier exists
                host_bytes = self.prefix_host_bytes
                if host_bytes is None:
                    host_bytes = cfg.prefix_host_bytes
                tier_kw = dict(
                    prefix_host_bytes=host_bytes,
                    host_restore_min_pages=self.host_restore_min_pages,
                )
                if ctx % 128 == 0:
                    kw = dict(
                        paged_pool_rows=pool_rows, page_size=128,
                        prefix_cache=prefix, **tier_kw,
                    )
                elif ctx % 16 == 0 and cache_dtype != jnp.int8:
                    # the int8 paged kernel needs 128-aligned pages
                    # (_paged_call guard) — resolve that conflict HERE,
                    # at the same altitude as the sibling config
                    # conflicts, not as a load-time kernel ValueError
                    kw = dict(
                        paged_pool_rows=pool_rows, page_size=16,
                        prefix_cache=prefix, **tier_kw,
                    )
                else:
                    log.warning(
                        "AIOS_TPU_PAGED_KV ignored for %s: context %d "
                        "needs a multiple of %d; serving dense", name, ctx,
                        128 if cache_dtype == jnp.int8 else 16,
                    )
            if self.seq_shard_kv:
                if self.plan is not None and self.plan.sp > 1 \
                        and ctx % self.plan.sp == 0:
                    if kw:
                        # the operator explicitly asked for the sp-sharded
                        # cache; it and the paged pool are exclusive, so
                        # the explicit force wins over the paging default
                        log.info(
                            "%s: AIOS_TPU_SEQ_SHARD_KV drops the paged "
                            "pool (exclusive with the sp-sharded cache)",
                            name,
                        )
                    kw = dict(seq_sharded_cache=True)
                else:
                    log.warning(
                        "AIOS_TPU_SEQ_SHARD_KV ignored for %s: needs "
                        "sp > 1 dividing context %d", name, ctx,
                    )
            # Per-chip HBM footprint estimate (recorded on the managed
            # model so later co-resident loads can budget against it).
            # Prepared trees are already in serving precision; dense trees
            # shrink when the engine quantizes them later.
            from ..engine.engine import _is_prequantized

            factor = 1.0 if _is_prequantized(params) else {
                "int8": 0.5, "int4": 0.25,
            }.get(self.quantize, 1.0)
            tp = self.plan.tp if self.plan is not None else 1
            weight_chip = model_mod.serving_weight_bytes(params) * factor / tp
            kv_chip = self._kv_bytes_per_chip(cfg, ctx, cache_dtype, kw)
            hbm_estimate = weight_chip + kv_chip
            if not kw.get("seq_sharded_cache"):
                # Long-context auto-degradation (the graceful path a boot
                # config with sp > 1 selects without any extra knob): when
                # this model's KV cache cannot fit the per-chip HBM budget
                # even paged, shard the context axis over sp instead —
                # giving up paging/prefix sharing (pages hold contiguous
                # rows and cannot split across sp shards) but keeping the
                # model servable. Estimates carry a 15% headroom;
                # co-resident models' footprints count against the budget.
                # Without a usable sp axis the shortfall is still WARNED so
                # the first symptom isn't a serve-time OOM.
                # co-resident models count against the budget — INCLUDING
                # a still-READY same-name entry: during a hot-swap the old
                # pool keeps serving (and pinning HBM) while the new one
                # builds, so the transient is 2x, not a replacement
                resident = sum(
                    mm.hbm_chip_bytes for mm in self.models.values()
                    if mm.name != name or mm.state == STATE_READY
                )
                budget = (
                    _chip_hbm_bytes() * 0.85
                    - weight_chip * repl_factor - resident - draft_bytes
                )
                sp = self.plan.sp if self.plan is not None else 1
                if kv_chip * repl_factor > max(budget, 0.0):
                    # the seq-sharded config is a DENSE num_slots x ctx
                    # cache sharded over dp x tp x sp — recompute its
                    # estimate rather than dividing the PAGED estimate by
                    # sp (the paged pool may hold more rows than the dense
                    # cache, which overstated the degraded footprint and
                    # could degrade onto a layout that saves nothing)
                    dp = self.plan.dp if self.plan is not None else 1
                    tp = self.plan.tp if self.plan is not None else 1
                    seq_kv = (
                        self._kv_row_bytes(cfg, cache_dtype)
                        * self.num_slots * ctx / (dp * tp * sp)
                    )
                    if sp > 1 and ctx % sp == 0 and seq_kv < kv_chip:
                        log.warning(
                            "%s: KV cache needs ~%.1f GB/chip (budget "
                            "~%.1f GB after weights + co-resident "
                            "models); sharding the context axis over "
                            "sp=%d (~%.1f GB/chip%s) and dropping the "
                            "paged pool",
                            name, kv_chip * repl_factor / 1e9,
                            max(budget, 0.0) / 1e9,
                            sp, seq_kv * repl_factor / 1e9,
                            "" if seq_kv * repl_factor <= max(budget, 0.0)
                            else ", STILL over budget — HBM may overflow",
                        )
                        kw = dict(seq_sharded_cache=True)
                        hbm_estimate = weight_chip + seq_kv
                    else:
                        if sp <= 1:
                            why = "no sp axis in the mesh"
                        elif ctx % sp:
                            why = f"context {ctx} does not divide by sp={sp}"
                        else:
                            why = (
                                f"the seq-sharded cache (~{seq_kv / 1e9:.1f}"
                                " GB/chip) would not shrink the footprint"
                            )
                        log.warning(
                            "%s: KV cache needs ~%.1f GB/chip (budget "
                            "~%.1f GB) and the seq-sharded degradation "
                            "is unavailable (%s) — loading anyway and "
                            "HBM may overflow",
                            name, kv_chip * repl_factor / 1e9,
                            max(budget, 0.0) / 1e9, why,
                        )
            quantize = self.quantize
            if not self.quantize_explicit:
                if quantize and _is_prequantized(params):
                    # auto-derived default meets a prepared checkpoint:
                    # serve the stored mode without a mismatch warning
                    quantize = None
            elif not quantize:
                from ..engine.engine import _prequantized_mode

                if _is_prequantized(params):
                    # the engine cannot distinguish explicit bf16 from
                    # the auto default; surface the ignored override HERE,
                    # where explicitness is known
                    log.warning(
                        "explicit bf16 request (quantize=False or "
                        "AIOS_TPU_QUANTIZE=0) for %s ignored: checkpoint "
                        "stores prepared %s serving weights (re-run "
                        "prepare_model without --quantize for bf16 "
                        "serving)", name, _prequantized_mode(params),
                    )
            engines = []
            try:
                for i in range(n_replicas):
                    engine = TPUEngine(
                        cfg,
                        params,
                        num_slots=self.num_slots,
                        max_context=ctx,
                        shardings=plans[i],
                        quantize=quantize,
                        cache_dtype=cache_dtype,
                        # the per-step history scatter serves only the
                        # speculative proposers — skip it (and its
                        # serial scan dependency) when speculative
                        # serving is off
                        track_history=spec_on,
                        draft=draft,
                        **kw,
                    )
                    if self.warm_compile:
                        # json-mode deployments dispatch the grammar-masked
                        # step; compile it behind the readiness gate too
                        # (AOT, no dispatch). Speculative round graphs are
                        # covered when the pool's batchers attach below —
                        # ContinuousBatcher AOT-compiles its ACTUAL chunk
                        # sizes, still before STATE_READY
                        from .service import json_mode_forced

                        engine.warmup(masked_step=json_mode_forced())
                    engines.append(engine)
            except BaseException:
                # a failed replica build must not strand its siblings'
                # HBM until a gc pass
                for e in engines:
                    try:
                        e.close()
                    except Exception:  # noqa: BLE001
                        pass
                raise
            del params
            # long-context tier (docs/ENGINE_PERF.md): surface what the
            # engines armed — the knobs resolve env-over-config inside
            # the engine, so the load log is where an operator sees the
            # effective policy
            if getattr(engines[0], "kv_compress_armed", False):
                log.info(
                    "%s: window+sink KV compression armed (threshold %d "
                    "rows; %d sink + %d window pages/slot)", name,
                    engines[0].kv_compress_after,
                    engines[0].kv_sink_pages, engines[0].kv_window_pages,
                )
            if getattr(engines[0], "seq_prefill_min", 0):
                log.info(
                    "%s: sequence-sharded prefill armed (prompts >= %d "
                    "rows spread over sp=%d)", name,
                    engines[0].seq_prefill_min,
                    self.plan.sp if self.plan is not None else 1,
                )

            def batcher_factory(eng, _tok=tokenizer, _spec=spec_on):
                # the pool's spawn AND crash-respawn path — a replica
                # whose scheduler died gets an identical fresh batcher
                # (the proposer ladder re-resolves from eng.draft, so a
                # respawned replica keeps its draft rung)
                return ContinuousBatcher(
                    eng, speculative=_spec, tokenizer=_tok
                )

            try:
                pool = ReplicaPool(
                    name, engines, batcher_factory, serving_cfg
                )
            except BaseException:
                # the pool shuts its partial batchers down itself; the
                # engines are still ours to free
                for e in engines:
                    try:
                        e.close()
                    except Exception:  # noqa: BLE001
                        pass
                raise
            managed = ManagedModel(
                name=name,
                config=cfg,
                engine=engines[0],
                batcher=pool.replicas[0].batcher,
                tokenizer=tokenizer,
                state=STATE_READY,
                loaded_at=int(time.time()),
                # every replica pins its own weights + KV; co-resident
                # replicas (shared device set) multiply the per-chip
                # footprint, disjoint submeshes pay 1x per chip.
                # draft_bytes already carries its own replica factor
                # (shared weights x1, per-engine KV x repl_factor)
                hbm_chip_bytes=hbm_estimate * repl_factor + draft_bytes,
                pool=pool,
                model_path=path,
                context_length=context_length or 0,
            )
            # keep the replica-0 snapshot fresh across crash-respawns
            # (the pool swaps Replica.batcher; the ManagedModel field
            # would otherwise point at the dead scheduler)
            def _sync_batcher(idx, b, _m=managed):
                if idx == 0:
                    _m.batcher = b

            pool.on_respawn = _sync_batcher
            # SLO autoscaling closed loop (AIOS_TPU_AUTOSCALE, docs/
            # RUNBOOK.md §8): a per-pool controller scales replicas off
            # the windowed burn rate and walks the degrade ladder at the
            # ceiling. The engine factory clones replica 0's geometry
            # over its LIVE (already device-resident, possibly
            # prequantized) param tree, so a scale-up shares weight
            # buffers instead of re-reading the checkpoint; scale-up
            # replicas ride the manager's shared mesh (disjoint-submesh
            # growth would need devices the plan already claimed).
            from ..serving.autoscale import (
                AutoscaleController, enabled as autoscale_enabled,
            )

            if autoscale_enabled():
                def engine_factory(
                    _cfg=cfg, _ctx=ctx, _cache=cache_dtype, _kw=kw,
                    _spec=spec_on, _draft=draft, _pool=pool,
                    _warm=self.warm_compile, _plan=self.plan,
                    _slots=self.num_slots,
                ):
                    from .service import json_mode_forced

                    e0 = _pool.replicas[0].engine
                    eng = TPUEngine(
                        _cfg, e0.params, num_slots=_slots,
                        max_context=_ctx, shardings=_plan,
                        quantize=None, cache_dtype=_cache,
                        track_history=_spec, draft=_draft, **_kw,
                    )
                    if _warm:
                        eng.warmup(masked_step=json_mode_forced())
                    return eng

                AutoscaleController(
                    pool, engine_factory=engine_factory, start=True,
                )
                log.info(
                    "%s: SLO autoscaler attached (ceiling %d replicas)",
                    name, pool.autoscaler.cfg.max_replicas,
                )
            with self._lock:
                old = self.models.get(name)
                self.models[name] = managed
            if old is not None and old is not managed \
                    and old.state == STATE_READY:
                self._retire_async(old)
            log.info(
                "model %s ready in %.1fs (ctx=%d, %d slots, %d replica%s)",
                name,
                time.time() - t0,
                engines[0].max_context,
                engines[0].num_slots,
                n_replicas,
                "" if n_replicas == 1 else "s",
            )
            return managed
        except Exception as exc:
            # a FAILED hot-swap must not clobber the still-serving model:
            # keep the READY entry (its pool keeps serving; the caller
            # still sees the load error) and only register the error
            # placeholder when there was nothing working to preserve
            with self._lock:
                cur = self.models.get(name)
                if cur is None or cur.state != STATE_READY:
                    self.models[name] = ManagedModel(
                        name=name,
                        config=TINY_TEST,
                        engine=None,  # type: ignore[arg-type]
                        batcher=None,  # type: ignore[arg-type]
                        tokenizer=ByteTokenizer(),
                        state=STATE_ERROR,
                        error=str(exc),
                    )
            if cur is not None and cur.state == STATE_READY:
                log.error(
                    "model %s reload failed (%s); the previous pool keeps "
                    "serving", name, exc,
                )
            else:
                log.error("model %s failed to load: %s", name, exc)
            raise

    def _build_draft(self, source: str, cfg: ModelConfig, ctx: int,
                     tokenizer: BaseTokenizer):
        """Resolve the paired draft model (a preset name like
        "tinyllama" or a weights path) into an int4 spec.DraftModel, or
        None when this deployment cannot carry one. Lenient like every
        other serving knob: a bad pairing logs and falls back to n-gram
        speculation instead of taking down the model load."""
        from ..engine import spec as spec_mod

        if self.plan is not None:
            log.warning(
                "%s: draft-model speculation is single-device only "
                "(no shard_map twins for the draft graphs); serving "
                "with n-gram speculation under AIOS_TPU_MESH", cfg.name,
            )
            return None
        try:
            p = Path(source)
            if source.endswith(".gguf") or "/" in source or p.exists():
                dcfg, dparams, dtok = self._load_weights(
                    p.stem.lower() or "draft", source, 0
                )
            else:
                dcfg, dparams, dtok = self._load_weights(source, "", 0)
        except Exception as exc:  # noqa: BLE001 - lenient knob pattern
            log.warning(
                "%s: draft model %r failed to load (%s); serving with "
                "n-gram speculation", cfg.name, source, exc,
            )
            return None
        if dcfg.vocab_size != cfg.vocab_size:
            log.warning(
                "%s: draft model %s vocab (%d) does not match the "
                "serving vocab (%d) — they must share one tokenizer; "
                "serving with n-gram speculation",
                cfg.name, dcfg.name, dcfg.vocab_size, cfg.vocab_size,
            )
            return None
        # matching vocab SIZES do not imply the same tokenizer (32000 is
        # every Llama-family size): a mismatched pairing would propose
        # garbage ids with ~0 acceptance, and with the default
        # spec_min_accept=0 the ladder would never fall back — a silent
        # permanent throughput regression. Probe-encode through both.
        try:
            probe = 'The quick brown fox ran 42 {"tool": "call"}'
            if dtok.encode(probe) != tokenizer.encode(probe):
                log.warning(
                    "%s: draft model %s tokenizes differently (same "
                    "vocab size, different tokenizer) — draft proposals "
                    "would be garbage ids; serving with n-gram "
                    "speculation", cfg.name, dcfg.name,
                )
                return None
        except Exception as exc:  # noqa: BLE001 - lenient knob pattern
            log.warning(
                "%s: draft tokenizer probe failed (%s); pairing on "
                "vocab size alone", cfg.name, exc,
            )
        draft = spec_mod.DraftModel(dcfg, dparams, quantize="int4")
        log.info(
            "%s: paired draft model %s (%.0f MB serving weights, "
            "ctx %d)", cfg.name, dcfg.name,
            draft.weight_bytes() / 1e6, ctx,
        )
        return draft

    def _load_weights(self, name: str, path: str, context_length: int):
        """Resolve (config, params, tokenizer) from a model source."""
        if path.startswith("synthetic://") or not path:
            preset_name = path.removeprefix("synthetic://") or name
            cfg = self._resolve_preset(preset_name)
            params = model_mod.init_params(
                cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16
            )
            return cfg, params, ByteTokenizer()

        p = Path(path)
        if p.is_file() and p.suffix == ".gguf":
            dtype = jnp.bfloat16
            params, cfg = weights_mod.params_from_gguf(str(p))
            params = weights_mod.map_params(params, lambda a: a.astype(dtype))
            f = gguf_mod.GGUFFile(p)
            tokenizer: BaseTokenizer
            if "tokenizer.ggml.tokens" in f.metadata:
                tokenizer = gguf_tokenizer(f.metadata)
            else:
                tokenizer = ByteTokenizer()
            if context_length:
                cfg = cfg.scaled(max_context=context_length)
            return cfg, params, tokenizer

        if p.is_dir():
            from ..engine import checkpoint as ckpt_mod

            if ckpt_mod.is_model_checkpoint(str(p)):
                # prepared aios-tpu checkpoint: params restore straight to
                # device, no GGUF parse/dequant on the serving path
                # host-stage only when a quantize pass may follow; plain
                # bf16 serving restores straight to the accelerator
                cfg, params, tokenizer = ckpt_mod.load_model_checkpoint(
                    str(p), host_stage=bool(self.quantize)
                )
                if context_length:
                    cfg = cfg.scaled(max_context=context_length)
                return cfg, params, tokenizer

            # HF checkpoint directory
            import json

            import safetensors.numpy

            with open(p / "config.json") as fh:
                hf_cfg = json.load(fh)
            from ..engine.config import from_hf_config

            cfg = from_hf_config(hf_cfg, name=name)
            sd = {}
            for st_file in sorted(p.glob("*.safetensors")):
                sd.update(safetensors.numpy.load_file(st_file))
            params = weights_mod.params_from_hf_state_dict(sd, cfg)
            params = weights_mod.map_params(params, lambda a: a.astype(jnp.bfloat16))
            return cfg, params, HFTokenizer(str(p))

        raise FileNotFoundError(f"model path not found: {path}")

    @staticmethod
    def _resolve_preset(name: str) -> ModelConfig:
        low = name.lower()
        if low in ("tiny-test", "tiny"):
            return TINY_TEST
        if low == "tiny-moe":
            return TINY_MOE
        if low in PRESETS:  # exact name wins before any fuzzy match
            return PRESETS[low]
        for key, cfg in PRESETS.items():
            if low in key or key in low or key.split("-")[0] in low:
                return cfg
        raise KeyError(f"no preset matches {name!r}")

    def autoload(self, model_dir: Optional[str] = None) -> List[str]:
        """Scan AIOS_MODEL_DIR for *.gguf and load each (main.rs:65-132)."""
        model_dir = model_dir or os.environ.get(
            "AIOS_MODEL_DIR", "/var/lib/aios/models"
        )
        loaded = []
        d = Path(model_dir)
        if not d.is_dir():
            return loaded
        for f in sorted(d.glob("*.gguf")):
            name = f.stem.lower()
            ctx = _context_for_file_size(f.stat().st_size)
            try:
                self.load_model(name, str(f), context_length=ctx)
                loaded.append(name)
            except Exception:
                continue
        return loaded

    # -- unloading ----------------------------------------------------------

    def unload_model(self, name: str) -> bool:
        with self._lock:
            managed = self.models.pop(name, None)
        if managed is None:
            return False
        managed.state = STATE_UNLOADING
        # the pool shuts every replica down (batcher + engine.close() —
        # close frees HBM deterministically; the jitted-step closures form
        # a ref cycle with the engine, so plain deref would leave the
        # weights resident until a gc pass)
        if managed.pool is not None:
            managed.pool.shutdown()
        else:
            if managed.batcher is not None:
                managed.batcher.shutdown()
            if managed.engine is not None:
                managed.engine.close()
        managed.engine = None  # type: ignore[assignment]
        managed.batcher = None  # type: ignore[assignment]
        return True

    def _retire_async(self, old: ManagedModel) -> None:
        """Hot-swap retirement: the replacement pool is already in the
        registry serving new requests; the OLD pool drains its in-flight
        streams in the background, then frees its HBM. The swapped-out
        ManagedModel keeps its pool reference until the drain thread is
        done with it, but its engine/batcher snapshots null immediately
        (HealthCheck must not read a closing engine)."""
        old.state = STATE_UNLOADING
        pool, batcher, engine = old.pool, old.batcher, old.engine
        old.engine = None  # type: ignore[assignment]
        old.batcher = None  # type: ignore[assignment]

        def _drain():
            if pool is not None:
                pool.shutdown(drain_timeout=30.0)
            else:
                if batcher is not None:
                    batcher.shutdown()
                if engine is not None:
                    engine.close()

        threading.Thread(
            target=_drain, name=f"retire-{old.name}", daemon=True
        ).start()

    # -- resolution ---------------------------------------------------------

    def get(self, name: str) -> Optional[ManagedModel]:
        return self.models.get(name)

    def ready_models(self) -> List[ManagedModel]:
        return [m for m in self.models.values() if m.state == STATE_READY]

    def find_by_partial_name(self, name: str) -> Optional[ManagedModel]:
        """Case-insensitive substring match (model_manager.rs:506-518)."""
        low = name.lower()
        exact = self.models.get(name)
        if exact is not None and exact.state == STATE_READY:
            return exact
        for m in self.ready_models():
            if low in m.name.lower() or m.name.lower() in low:
                return m
        return None

    def select_for_level(self, level: str) -> Optional[ManagedModel]:
        """Routing ladder; None for reactive or when nothing matches."""
        ladder = LEVEL_LADDERS.get(level.lower())
        if not ladder:
            return None
        for candidate in ladder:
            m = self.find_by_partial_name(candidate)
            if m is not None:
                return m
        return None
