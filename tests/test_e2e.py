"""End-to-end: the full service plane wired over real localhost gRPC.

Replaces the reference's QEMU boot test (tests/e2e/test_boot.sh) with a
host-process e2e per SURVEY.md section 4: memory + tools + runtime (tiny
synthetic TPU model) + gateway (local provider -> runtime) + orchestrator
with a live autonomy loop, plus a real agent thread — then goals flow
through goal_engine -> task_planner -> (heuristic | agent | AI) -> tools.
"""

import json
import time

import pytest

from aios_tpu import rpc, services
from aios_tpu.proto_gen import (
    api_gateway_pb2,
    common_pb2,
    memory_pb2,
    orchestrator_pb2,
    runtime_pb2,
)

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """Boot every service on random ports, cross-wired via env overrides."""
    import os

    tmp = tmp_path_factory.mktemp("e2e")
    servers = []

    # --- memory ----------------------------------------------------------
    from aios_tpu.memory.service import serve as serve_memory

    mem_server, mem_service, mem_port = serve_memory(
        address="127.0.0.1:0", block=False
    )
    servers.append(mem_server)

    # --- tools ------------------------------------------------------------
    from aios_tpu.tools.executor import ToolExecutor
    from aios_tpu.tools.service import serve as serve_tools

    tools_server, tools_service, tools_port = serve_tools(
        address="127.0.0.1:0",
        executor=ToolExecutor(
            audit_path=str(tmp / "audit.db"),
            backup_dir=str(tmp / "backups"),
            plugin_dir=str(tmp / "plugins"),
        ),
        block=False,
    )
    servers.append(tools_server)

    # --- runtime (tiny synthetic model on the CPU "TPU") -------------------
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve as serve_runtime

    manager = ModelManager(num_slots=2, warm_compile=False)
    manager.load_model("tinyllama-e2e", "synthetic://tiny-test")
    rt_server, rt_service, rt_port = serve_runtime(
        address="127.0.0.1:0", manager=manager, block=False
    )
    servers.append(rt_server)

    # --- gateway (no cloud keys -> local provider = runtime) ---------------
    for var in ("CLAUDE_API_KEY", "OPENAI_API_KEY", "QWEN3_API_KEY"):
        os.environ.pop(var, None)
    from aios_tpu.gateway.router import RequestRouter
    from aios_tpu.gateway.service import serve as serve_gateway

    gw_server, gw_service, gw_port = serve_gateway(
        address="127.0.0.1:0",
        router=RequestRouter(runtime_address=f"127.0.0.1:{rt_port}"),
        block=False,
    )
    servers.append(gw_server)

    # --- orchestrator ------------------------------------------------------
    env_overrides = {
        "AIOS_MEMORY_ADDR": f"127.0.0.1:{mem_port}",
        "AIOS_TOOLS_ADDR": f"127.0.0.1:{tools_port}",
        "AIOS_RUNTIME_ADDR": f"127.0.0.1:{rt_port}",
        "AIOS_GATEWAY_ADDR": f"127.0.0.1:{gw_port}",
    }
    old_env = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)

    from aios_tpu.orchestrator.autonomy import AutonomyConfig
    from aios_tpu.orchestrator.clients import ServiceClients
    from aios_tpu.orchestrator.main import build_orchestrator
    from aios_tpu.orchestrator.service import serve as serve_orch

    clients = ServiceClients(
        runtime_addr=f"127.0.0.1:{rt_port}",
        tools_addr=f"127.0.0.1:{tools_port}",
        memory_addr=f"127.0.0.1:{mem_port}",
        gateway_addr=f"127.0.0.1:{gw_port}",
    )
    (service, autonomy, scheduler, proactive, health, bus,
     _serving) = build_orchestrator(
        data_dir=str(tmp / "orch"),
        clients=clients,
        autonomy_config=AutonomyConfig(tick_interval=0.05),
    )
    autonomy.start()
    orch_server, orch_service, orch_port = serve_orch(
        address="127.0.0.1:0", service=service, block=False
    )
    servers.append(orch_server)
    os.environ["AIOS_ORCHESTRATOR_ADDR"] = f"127.0.0.1:{orch_port}"

    # --- management console (the aiosctl surface) --------------------------
    from aios_tpu.orchestrator.management import ManagementConsole

    console = ManagementConsole(service, port=0, serving_stats=_serving)
    console.start()

    channel = rpc.insecure_channel(f"127.0.0.1:{orch_port}")
    stub = services.OrchestratorStub(channel)

    yield {
        "orch": stub,
        "orch_service": service,
        "console_port": console.bound_port,
        "ports": {
            "orchestrator": orch_port,
            "tools": tools_port,
            "memory": mem_port,
            "gateway": gw_port,
            "runtime": rt_port,
        },
        "memory": services.MemoryServiceStub(
            rpc.insecure_channel(f"127.0.0.1:{mem_port}")
        ),
        "gateway": services.ApiGatewayStub(
            rpc.insecure_channel(f"127.0.0.1:{gw_port}")
        ),
        "runtime": services.AIRuntimeStub(
            rpc.insecure_channel(f"127.0.0.1:{rt_port}")
        ),
    }

    autonomy.stop()
    console.stop()
    channel.close()
    for server in servers:
        server.stop(grace=None)
    for k, v in old_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _wait_goal(stub, goal_id, want_states=("completed",), timeout=30):
    deadline = time.time() + timeout
    status = None
    while time.time() < deadline:
        status = stub.GetGoalStatus(common_pb2.GoalId(id=goal_id))
        if status.goal.status in want_states:
            return status
        time.sleep(0.2)
    return status


def test_heuristic_goal_end_to_end(stack):
    """goal -> planner -> autonomy heuristic -> real tools gRPC -> completed."""
    gid = stack["orch"].SubmitGoal(
        orchestrator_pb2.SubmitGoalRequest(description="check cpu usage")
    )
    status = _wait_goal(stack["orch"], gid.id)
    assert status.goal.status == "completed", status.goal.status
    task = status.tasks[0]
    output = json.loads(task.output_json)
    # the tool result came through the real tool registry
    assert output["tool_results"][0]["tool"] == "monitor.cpu"
    assert output["tool_results"][0]["success"]
    assert status.progress_percent == 100.0


def test_agent_routed_goal_end_to_end(stack):
    """A live SystemAgent thread polls, executes via tools, reports back."""
    from aios_tpu.agents.catalog import SystemAgent

    agent = SystemAgent(name="system_agent-e2e")
    agent.run(block=False)
    try:
        gid = stack["orch"].SubmitGoal(
            orchestrator_pb2.SubmitGoalRequest(
                description="check memory usage and report status"
            )
        )
        status = _wait_goal(stack["orch"], gid.id, timeout=40)
        assert status.goal.status == "completed", (
            f"{status.goal.status}: {[t.error for t in status.tasks]}"
        )
        task = status.tasks[0]
        assert task.assigned_agent == "system_agent-e2e"
    finally:
        agent.shutdown()


def test_runtime_infer_through_gateway(stack):
    """gateway local-provider fallback reaches the TPU runtime engine."""
    resp = stack["gateway"].Infer(
        api_gateway_pb2.ApiInferRequest(prompt="hello", max_tokens=8)
    )
    assert resp.model_used.startswith("local/")
    assert resp.tokens_used > 0


def test_memory_accumulates_tool_calls(stack):
    """Tool executions from e2e goals landed in working memory via agents."""
    stack["memory"].UpdateMetric(
        memory_pb2.MetricUpdate(key="e2e.alive", value=1.0)
    )
    got = stack["memory"].GetMetric(memory_pb2.MetricRequest(key="e2e.alive"))
    assert got.value == 1.0


def test_runtime_lists_e2e_model(stack):
    models = stack["runtime"].ListModels(common_pb2.Empty())
    names = [m.model_name for m in models.models]
    assert "tinyllama-e2e" in names


def test_aiosctl_smoke_against_live_stack(stack):
    """The operator CLI's probe/parse logic against the real stack (VERDICT
    r4 weak #6): `status` must see every service up (via the AIOS_*_ADDR
    env overrides the CLI shares with the service clients), and `serving`
    must return the runtime's per-model counters through the console."""
    import os
    import subprocess

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        AIOS_CONSOLE=f"http://127.0.0.1:{stack['console_port']}",
        **{
            f"AIOS_{name.upper()}_ADDR": f"127.0.0.1:{port}"
            for name, port in stack["ports"].items()
        },
    )
    ctl = os.path.join(repo_root, "scripts", "aiosctl.sh")

    status = subprocess.run(
        ["bash", ctl, "status"], env=env, capture_output=True, text=True,
        timeout=30,
    )
    assert status.returncode == 0, status.stdout + status.stderr
    lines = status.stdout.strip().splitlines()
    assert len(lines) == 6
    for line in lines:
        assert line.endswith(" up"), line

    serving = subprocess.run(
        ["bash", ctl, "serving"], env=env, capture_output=True, text=True,
        timeout=30,
    )
    assert serving.returncode == 0, serving.stdout + serving.stderr
    payload = json.loads(serving.stdout)
    # the e2e model's counters came runtime -> HealthCheck -> console -> CLI
    assert "tinyllama-e2e" in payload["models"]
    assert payload["models"]["tinyllama-e2e"]["num_slots"] == 2.0

    health = subprocess.run(
        ["bash", ctl, "health"], env=env, capture_output=True, text=True,
        timeout=30,
    )
    assert health.returncode == 0, health.stdout + health.stderr
    first_line = health.stdout.strip().splitlines()[0]
    assert json.loads(first_line)["healthy"] is True

    # submit + cancel round-trip through the CLI (the operator's kill
    # switch for a runaway goal rides the console cancel route)
    submitted = subprocess.run(
        ["bash", ctl, "submit", "aiosctl cancel-me goal"], env=env,
        capture_output=True, text=True, timeout=30,
    )
    assert submitted.returncode == 0, submitted.stdout + submitted.stderr
    goal_id = json.loads(submitted.stdout)["goal_id"]
    cancelled = subprocess.run(
        ["bash", ctl, "cancel", goal_id], env=env, capture_output=True,
        text=True, timeout=30,
    )
    # the heuristic path may complete tiny goals before the cancel lands;
    # either way the CLI round-trips and the goal ends terminal
    if cancelled.returncode == 0:
        assert json.loads(cancelled.stdout)["cancelled"] is True
    status2 = stack["orch"].GetGoalStatus(common_pb2.GoalId(id=goal_id))
    assert status2.goal.status in ("cancelled", "completed", "failed")
