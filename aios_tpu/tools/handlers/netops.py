"""net.* / firewall.* / web.* / email.* — network tools.

Reference: tools/src/{net,firewall(+firewall_apply.rs nftables),web,email}/
(14 handlers). Zero-egress hosts degrade with clear errors on the paths that
need the internet; local operations (interfaces, port scan on localhost,
webhooks to localhost services) work everywhere.
"""

from __future__ import annotations

import json
import smtplib
import socket
import subprocess
import time
from email.message import EmailMessage

import psutil

from . import ToolError, ToolSpec, run_cmd

# ---------------------------------------------------------------------------
# net.*
# ---------------------------------------------------------------------------


def net_interfaces(args: dict) -> dict:
    out = []
    stats = psutil.net_if_stats()
    for name, addrs in psutil.net_if_addrs().items():
        st = stats.get(name)
        out.append(
            {
                "name": name,
                "up": bool(st.isup) if st else False,
                "mtu": st.mtu if st else 0,
                "addresses": [
                    {"family": str(a.family.name), "address": a.address}
                    for a in addrs
                ],
            }
        )
    return {"interfaces": out}


def net_ping(args: dict) -> dict:
    host = args.get("host", "8.8.8.8")
    count = min(int(args.get("count", 3)), 10)
    try:
        out = run_cmd(["ping", "-c", str(count), "-W", "2", host], timeout=30)
        return {"host": host, "output": out["stdout"].splitlines()[-2:],
                "reachable": True}
    except ToolError:
        # fall back to a TCP connect probe (ping may be missing/unprivileged)
        t0 = time.time()
        try:
            with socket.create_connection((host, 53), timeout=3):
                pass
            return {"host": host, "reachable": True,
                    "rtt_ms": round((time.time() - t0) * 1000, 1),
                    "method": "tcp-connect"}
        except OSError:
            return {"host": host, "reachable": False, "method": "tcp-connect"}


def net_dns(args: dict) -> dict:
    host = args.get("host") or args.get("hostname")
    if not host:
        raise ToolError("missing host")
    try:
        infos = socket.getaddrinfo(host, None)
    except socket.gaierror as exc:
        raise ToolError(f"DNS resolution failed for {host}: {exc}") from exc
    addrs = sorted({i[4][0] for i in infos})
    return {"host": host, "addresses": addrs}


def net_http_get(args: dict) -> dict:
    url = args.get("url")
    if not url:
        raise ToolError("missing url")
    import urllib.request

    req = urllib.request.Request(url, headers={"User-Agent": "aios-tpu/0.1"})
    try:
        with urllib.request.urlopen(req, timeout=float(args.get("timeout", 15))) as resp:
            body = resp.read(256 * 1024)
            return {
                "url": url,
                "status": resp.status,
                "headers": dict(list(resp.headers.items())[:20]),
                "body": body.decode("utf-8", "replace"),
            }
    except OSError as exc:
        raise ToolError(f"GET {url} failed: {exc}") from exc


def net_port_scan(args: dict) -> dict:
    host = args.get("host", "127.0.0.1")
    ports = args.get("ports") or [22, 80, 443, 9090, 50051, 50052, 50053, 50054, 50055]
    open_ports = []
    for port in list(ports)[:1024]:
        try:
            with socket.create_connection((host, int(port)), timeout=0.5):
                open_ports.append(int(port))
        except OSError:
            continue
    return {"host": host, "open_ports": open_ports, "scanned": len(ports)}


# ---------------------------------------------------------------------------
# firewall.* — nftables wrappers (reference: firewall_apply.rs)
# ---------------------------------------------------------------------------


def firewall_rules(args: dict) -> dict:
    out = run_cmd(["nft", "list", "ruleset"], timeout=15)
    return {"ruleset": out["stdout"].splitlines()[:500]}


def firewall_add_rule(args: dict) -> dict:
    rule = args.get("rule")
    if not rule:
        raise ToolError("missing rule (nft syntax, e.g. 'add rule inet aios input tcp dport 22 accept')")
    run_cmd(["nft", *str(rule).split()], timeout=15)
    return {"added": rule}


def firewall_delete_rule(args: dict) -> dict:
    handle = args.get("handle")
    chain = args.get("chain", "input")
    table = args.get("table", "aios")
    if handle is None:
        raise ToolError("missing rule handle")
    run_cmd(
        ["nft", "delete", "rule", "inet", table, chain, "handle", str(handle)],
        timeout=15,
    )
    return {"deleted_handle": handle}


# ---------------------------------------------------------------------------
# web.*
# ---------------------------------------------------------------------------


def web_http_request(args: dict) -> dict:
    import urllib.request

    url = args.get("url")
    if not url:
        raise ToolError("missing url")
    method = args.get("method", "GET").upper()
    body = args.get("body", "")
    headers = {"User-Agent": "aios-tpu/0.1", **(args.get("headers") or {})}
    req = urllib.request.Request(
        url, data=body.encode() if body else None, headers=headers, method=method
    )
    try:
        with urllib.request.urlopen(req, timeout=float(args.get("timeout", 20))) as resp:
            return {
                "status": resp.status,
                "body": resp.read(256 * 1024).decode("utf-8", "replace"),
            }
    except OSError as exc:
        raise ToolError(f"{method} {url} failed: {exc}") from exc


def web_scrape(args: dict) -> dict:
    got = web_http_request({**args, "method": "GET"})
    import re

    text = re.sub(r"<script.*?</script>|<style.*?</style>", " ", got["body"],
                  flags=re.S | re.I)
    text = re.sub(r"<[^>]+>", " ", text)
    text = re.sub(r"\s+", " ", text).strip()
    links = re.findall(r'href=["\'](https?://[^"\']+)', got["body"])[:50]
    return {"url": args.get("url"), "text": text[:20_000], "links": links}


def web_webhook(args: dict) -> dict:
    payload = json.dumps(args.get("payload") or {})
    return web_http_request(
        {
            "url": args.get("url"),
            "method": "POST",
            "body": payload,
            "headers": {"Content-Type": "application/json"},
            "timeout": args.get("timeout", 15),
        }
    )


def web_download(args: dict) -> dict:
    import urllib.request

    url, dest = args.get("url"), args.get("dest")
    if not url or not dest:
        raise ToolError("missing url or dest")
    try:
        urllib.request.urlretrieve(url, dest)  # noqa: S310
    except OSError as exc:
        raise ToolError(f"download {url} failed: {exc}") from exc
    import os

    return {"url": url, "dest": dest, "bytes": os.path.getsize(dest)}


def web_api_call(args: dict) -> dict:
    out = web_http_request(args)
    try:
        out["json"] = json.loads(out["body"])
    except ValueError:
        pass
    return out


# ---------------------------------------------------------------------------
# email.send
# ---------------------------------------------------------------------------


def email_send(args: dict) -> dict:
    to = args.get("to")
    subject = args.get("subject", "")
    body = args.get("body", "")
    if not to:
        raise ToolError("missing 'to'")
    host = args.get("smtp_host", "127.0.0.1")
    port = int(args.get("smtp_port", 25))
    msg = EmailMessage()
    msg["From"] = args.get("from", "aios@localhost")
    msg["To"] = to
    msg["Subject"] = subject
    msg.set_content(body)
    try:
        with smtplib.SMTP(host, port, timeout=10) as smtp:
            smtp.send_message(msg)
    except OSError as exc:
        raise ToolError(f"SMTP {host}:{port} failed: {exc}") from exc
    return {"to": to, "subject": subject, "relay": f"{host}:{port}"}


TOOLS = {
    "net.interfaces": ToolSpec(net_interfaces, "List network interfaces",
                               idempotent=True),
    "net.ping": ToolSpec(net_ping, "Ping / TCP-probe a host", idempotent=True),
    "net.dns": ToolSpec(net_dns, "Resolve a hostname", idempotent=True),
    "net.http_get": ToolSpec(net_http_get, "HTTP GET a url", idempotent=True),
    "net.port_scan": ToolSpec(net_port_scan, "TCP connect scan",
                              idempotent=True),
    "firewall.rules": ToolSpec(firewall_rules, "List nftables ruleset",
                               idempotent=True),
    "firewall.add_rule": ToolSpec(firewall_add_rule, "Add an nft rule",
                                  requires_confirmation=True),
    "firewall.delete_rule": ToolSpec(firewall_delete_rule,
                                     "Delete an nft rule by handle",
                                     requires_confirmation=True),
    "web.http_request": ToolSpec(web_http_request, "Arbitrary HTTP request"),
    "web.scrape": ToolSpec(web_scrape, "Fetch a page and extract text/links",
                           idempotent=True),
    "web.webhook": ToolSpec(web_webhook, "POST a JSON payload to a webhook"),
    "web.download": ToolSpec(web_download, "Download a url to a file"),
    "web.api_call": ToolSpec(web_api_call, "HTTP call with JSON parsing"),
    "email.send": ToolSpec(email_send, "Send an email via SMTP relay"),
}
