"""The rule engine: five static rules over the shared AST substrate.

Rule ids (the ``--rule`` filter and waiver pragmas use these):

  * ``lock-dispatch`` / ``lock-readback`` / ``lock-rpc`` — lock
    discipline: no device dispatch, no D2H readback, no blocking
    RPC/wait inside a declared lock's body (call-graph-aware one level
    deep; each lock declares which classes it forbids — the engine lock
    shelters dispatch by design, so it forbids only readback + RPC);
  * ``lock-order`` — the static acquired-while-holding graph over the
    declared locks must be acyclic;
  * ``guarded-by`` — fields annotated ``#: guarded_by <lock-attr>`` may
    only be mutated under that lock (or in ``__init__``);
  * ``jit-warmup`` — every ``jax.jit`` call site in the serving-path
    modules must be reachable from an AOT-warmup registration
    (``warmup`` / ``_compile_aot`` / ``compile_*``), keeping the PR 6
    "compile counters flat after warmup" invariant statically;
  * ``silent-except`` — broad ``except Exception``/``BaseException``/
    bare handlers in ``serving/`` + ``engine/`` must record the failure
    (re-raise, log, or land an abort/terminal cause) — fault paths must
    never be observability black holes;
  * ``knob-docs`` — every ``AIOS_TPU_*`` string in the tree appears in
    ``docs/CONFIG.md`` (and vice versa: stale doc rows are findings);
  * ``metric-catalog`` — ``aios_tpu_*`` instruments are constructed only
    in ``obs/instruments.py`` (the reviewed catalog), never at point of
    use;
  * ``waiver-reason`` — a waiver pragma without justification text (or
    with an unknown rule id) is itself a finding, never a waiver.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import registry as reg
from .core import (
    Finding,
    FuncInfo,
    ModuleInfo,
    callee_chain,
    iter_calls,
    load_package,
    string_constants,
)

__all__ = ["RULE_IDS", "Analyzer", "run_analysis"]

RULE_IDS = (
    "lock-dispatch",
    "lock-readback",
    "lock-rpc",
    "lock-order",
    "guarded-by",
    "jit-warmup",
    "silent-except",
    "knob-docs",
    "metric-catalog",
    "waiver-reason",
)

GUARDED_BY_RE = re.compile(r"#:\s*guarded_by\s+(\w+)")
_SELF_ASSIGN_RE = re.compile(r"self\.(\w+)")

# container mutators rule guarded-by treats as writes
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "add", "update",
    "setdefault", "move_to_end", "sort", "rotate",
})


class Analyzer:
    """Runs the rule set over a list of ModuleInfos.

    ``config_doc`` is the text of docs/CONFIG.md (injectable for the
    fixture tests); when None and ``repo_root`` is set, it is read from
    disk. A custom ``registry`` lets tests seed violations with a
    two-line fixture registry instead of the production one."""

    def __init__(
        self,
        modules: Sequence[ModuleInfo],
        registry: reg.Registry = reg.DEFAULT,
        repo_root: Optional[Path] = None,
        config_doc: Optional[str] = None,
    ) -> None:
        self.modules = list(modules)
        self.by_name: Dict[str, ModuleInfo] = {m.name: m for m in modules}
        self.reg = registry
        self.repo_root = repo_root
        self._config_doc = config_doc
        self.findings: List[Finding] = []
        self._seen: Set[Tuple] = set()

    # -- public -------------------------------------------------------------

    def run(self, rules: Optional[Sequence[str]] = None) -> List[Finding]:
        want = set(rules) if rules else set(RULE_IDS)
        self.findings = []
        self._seen = set()
        self._edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._edge_visited: Set[Tuple[str, str, str]] = set()
        if want & {"lock-dispatch", "lock-readback", "lock-rpc",
                   "lock-order"}:
            self._run_lock_scopes()
        if "lock-order" in want:
            self._check_lock_cycles()
        if "guarded-by" in want:
            self._check_guarded_by()
        if "jit-warmup" in want:
            self._check_dispatch_hygiene()
        if "silent-except" in want:
            self._check_silent_except()
        if "knob-docs" in want:
            self._check_knob_drift()
        if "metric-catalog" in want:
            self._check_metric_catalog()
        if "waiver-reason" in want:
            self._check_waivers()
        self.findings = [f for f in self.findings if f.rule in want]
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return self.findings

    # -- lock resolution ----------------------------------------------------

    def _decl_for_class_attr(
        self, mi: ModuleInfo, class_name: Optional[str], attr: str
    ) -> Optional[reg.LockDecl]:
        if class_name is None:
            return None
        ancestry = mi.ancestry(class_name)
        for d in self.reg.locks:
            if d.module == mi.name and d.attr == attr and (
                d.class_name in ancestry
            ):
                return d
        return None

    def _lock_for_with_item(
        self, mi: ModuleInfo, func: Optional[FuncInfo], expr: ast.AST
    ) -> Optional[reg.LockDecl]:
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            owner, attr = expr.value.id, expr.attr
            if owner == "self" and func is not None:
                return self._decl_for_class_attr(mi, func.class_name, attr)
            # `<global-or-param>.attr` — only registry globals resolve
            tgt = self.reg.global_types.get(owner)
            if tgt is not None:
                tmod = self.by_name.get(tgt[0])
                if tmod is not None:
                    return self._decl_for_class_attr(tmod, tgt[1], attr)
        if isinstance(expr, ast.Name) and func is not None:
            name = self.reg.local_locks.get(
                (mi.name, func.qualname, expr.id)
            )
            if name is not None:
                return self.reg.lock_named(name)
        return None

    def _resolve_callee(
        self, mi: ModuleInfo, func: Optional[FuncInfo], call: ast.Call
    ) -> Optional[Tuple[ModuleInfo, FuncInfo]]:
        """One-level static call resolution: bare module functions,
        ``self.method`` (through in-module bases), ``ClassName.method``,
        ``self.<typed-field>.method`` via the registry's FIELD_TYPES, and
        registered dynamic hooks (``self.<hook>(...)``)."""
        f = call.func
        if isinstance(f, ast.Name):
            fi = mi.functions.get(f.id)
            return (mi, fi) if fi else None
        if not isinstance(f, ast.Attribute):
            return None
        base = f.value
        if isinstance(base, ast.Name):
            if base.id == "self" and func is not None:
                hook = self.reg.hook_targets.get((mi.name, f.attr))
                if hook is not None:
                    hmod = self.by_name.get(hook[0])
                    if hmod is not None:
                        hfn = hmod.functions.get(hook[1])
                        if hfn is not None:
                            return (hmod, hfn)
                if func.class_name:
                    for cls in mi.ancestry(func.class_name):
                        fi = mi.functions.get(f"{cls}.{f.attr}")
                        if fi:
                            return (mi, fi)
                return None
            if base.id in mi.classes:  # ClassName.static_method(...)
                fi = mi.functions.get(f"{base.id}.{f.attr}")
                return (mi, fi) if fi else None
            tgt = self.reg.global_types.get(base.id)
            if tgt is not None:
                return self._method_on(tgt, f.attr)
        if isinstance(base, ast.Attribute):
            if (
                isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and func is not None
                and func.class_name
            ):
                for cls in mi.ancestry(func.class_name):
                    tgt = self.reg.field_types.get(
                        (mi.name, cls, base.attr)
                    )
                    if tgt is not None:
                        return self._method_on(tgt, f.attr)
            # dotted singletons (`flightrec.RECORDER.event(...)`)
            tgt = self.reg.global_types.get(base.attr)
            if tgt is not None:
                return self._method_on(tgt, f.attr)
        return None

    def _method_on(
        self, tgt: Tuple[str, str], method: str
    ) -> Optional[Tuple[ModuleInfo, FuncInfo]]:
        tmod = self.by_name.get(tgt[0])
        if tmod is None:
            return None
        for cls in [tgt[1]] + tmod.subclasses_of(tgt[1]):
            fi = tmod.functions.get(f"{cls}.{method}")
            if fi:
                return (tmod, fi)
        return None

    # -- hazard classification ----------------------------------------------

    @staticmethod
    def _hazard_class(call: ast.Call) -> Optional[Tuple[str, str]]:
        """(hazard, description) for a call, else None."""
        chain = callee_chain(call)
        if not chain:
            return None
        term = chain[-1]
        dotted = ".".join(chain)
        if tuple(chain[-2:]) in reg.READBACK_CHAINS or (
            term in reg.READBACK_TERMINALS
        ):
            return ("readback", dotted)
        if term in reg.DISPATCH_TERMINALS or reg.DISPATCH_FN_HANDLE_RE.match(
            term
        ):
            return ("dispatch", dotted)
        if term in reg.RPC_TERMINALS or any(
            reg.RPC_CHAIN_MARKER in seg.lower() for seg in chain[:-1]
        ):
            return ("rpc", dotted)
        return None

    # -- rule 1 + rule 2 edge collection -------------------------------------

    def _run_lock_scopes(self) -> None:
        for mi in self.modules:
            for node in ast.walk(mi.tree):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                func = mi.enclosing_function(node)
                for item in node.items:
                    decl = self._lock_for_with_item(
                        mi, func, item.context_expr
                    )
                    if decl is None:
                        continue
                    held = self._context_locks(mi, func) if func else ()
                    for outer in held:
                        self._edge(outer, decl.name, mi, node.lineno)
                    self._scan_scope(
                        mi, func, decl, node.body, node.lineno
                    )
            # caller-held contexts: whole function bodies under a lock
            for (mod, qual), held in self.reg.context_fns.items():
                if mod != mi.name:
                    continue
                fi = mi.functions.get(qual)
                if fi is None:
                    continue
                for name in held:
                    decl = self.reg.lock_named(name)
                    if decl is not None:
                        self._scan_scope(
                            mi, fi, decl, fi.node.body, fi.node.lineno,
                            context=True,
                        )

    def _context_locks(
        self, mi: ModuleInfo, func: Optional[FuncInfo]
    ) -> Tuple[str, ...]:
        if func is None:
            return ()
        return self.reg.context_fns.get((mi.name, func.qualname), ())

    def _scan_scope(
        self,
        mi: ModuleInfo,
        func: Optional[FuncInfo],
        decl: reg.LockDecl,
        body: Sequence[ast.stmt],
        scope_line: int,
        context: bool = False,
    ) -> None:
        """Scan a lock body (or caller-held context function body): direct
        hazards, nested acquisitions (lock-order edges), and ONE level of
        resolvable calls."""
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.With, ast.AsyncWith)):
                    for item in sub.items:
                        inner = self._lock_for_with_item(
                            mi, func, item.context_expr
                        )
                        if inner is not None and inner.name != decl.name:
                            self._edge(
                                decl.name, inner.name, mi, sub.lineno
                            )
                if not isinstance(sub, ast.Call):
                    continue
                hz = self._hazard_class(sub)
                if hz is not None and hz[0] in decl.forbids:
                    self._hazard_finding(
                        mi, decl, sub.lineno, hz, scope_line
                    )
                resolved = self._resolve_callee(mi, func, sub)
                if resolved is not None:
                    cmod, cfn = resolved
                    if not (cmod is mi and func is not None
                            and cfn.qualname == func.qualname):
                        self._scan_callee(
                            mi, decl, sub.lineno, scope_line, cmod, cfn,
                            depth=1,
                        )

    # hazards are reported one call level deep (the ISSUE contract); the
    # acquired-while-holding EDGES keep resolving a few levels further,
    # because cross-object acquisitions (engine lock -> prefix-index
    # lock) sit behind thin accessor methods.
    _EDGE_DEPTH = 4

    def _scan_callee(
        self,
        call_mi: ModuleInfo,
        decl: reg.LockDecl,
        call_line: int,
        scope_line: int,
        cmod: ModuleInfo,
        cfn: FuncInfo,
        depth: int,
    ) -> None:
        """Hazards one level deep, lock-order edges up to _EDGE_DEPTH."""
        # keyed on depth==1 so an edges-only visit at depth>1 never
        # swallows a later hazard-reporting visit at depth 1
        vkey = (decl.name, cmod.name, cfn.qualname, depth == 1)
        if vkey in self._edge_visited:
            return
        self._edge_visited.add(vkey)
        for sub in ast.walk(cfn.node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    inner = self._lock_for_with_item(cmod, cfn,
                                                     item.context_expr)
                    if inner is not None and inner.name != decl.name:
                        self._edge(decl.name, inner.name, cmod, sub.lineno)
            if not isinstance(sub, ast.Call):
                continue
            if depth < self._EDGE_DEPTH:
                resolved = self._resolve_callee(cmod, cfn, sub)
                if resolved is not None:
                    self._scan_callee(
                        call_mi, decl, call_line, scope_line,
                        resolved[0], resolved[1], depth + 1,
                    )
            if depth > 1:
                continue  # hazard attribution stays one level deep
            hz = self._hazard_class(sub)
            if hz is not None and hz[0] in decl.forbids:
                # waivable at the inner hazard line, the call site, or
                # the governing with statement
                key = ("lock-" + hz[0], cmod.path, sub.lineno, decl.name)
                if key in self._seen:
                    continue
                self._seen.add(key)
                reason = (
                    cmod.waiver_for("lock-" + hz[0], sub.lineno)
                    or call_mi.waiver_for(
                        "lock-" + hz[0], call_line, scope_line
                    )
                )
                self.findings.append(Finding(
                    "lock-" + hz[0], cmod.path, sub.lineno,
                    f"{hz[1]}(...) runs under lock '{decl.name}' via "
                    f"{cfn.qualname} (called at {call_mi.path}:"
                    f"{call_line}) — {_HAZARD_WHY[hz[0]]}",
                    waived=reason is not None,
                    waive_reason=reason or "",
                ))

    def _hazard_finding(
        self,
        mi: ModuleInfo,
        decl: reg.LockDecl,
        line: int,
        hz: Tuple[str, str],
        scope_line: int,
    ) -> None:
        rule = "lock-" + hz[0]
        key = (rule, mi.path, line, decl.name)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(mi.finding(
            rule, line,
            f"{hz[1]}(...) inside `with` body of lock '{decl.name}' — "
            f"{_HAZARD_WHY[hz[0]]}",
            scope_line,
        ))

    # -- rule 2: cycles ------------------------------------------------------

    def _edge(self, a: str, b: str, mi: ModuleInfo, line: int) -> None:
        if a == b:
            return
        self._edges.setdefault((a, b), (mi.path, line))

    def _check_lock_cycles(self) -> None:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self._edges:
            graph.setdefault(a, set()).add(b)
        seen_cycles: Set[Tuple[str, ...]] = set()
        for start in sorted(graph):
            path: List[str] = []
            self._dfs_cycles(start, graph, path, set(), seen_cycles)
        for cyc in sorted(seen_cycles):
            closed = list(cyc) + [cyc[0]]
            evidence = []
            for a, b in zip(closed, closed[1:]):
                p, ln = self._edges[(a, b)]
                evidence.append(f"{a}->{b} at {p}:{ln}")
            p0, l0 = self._edges[(closed[0], closed[1])]
            mi = next(
                (m for m in self.modules if m.path == p0), None
            )
            msg = (
                "lock-order cycle: " + " -> ".join(closed)
                + " (" + "; ".join(evidence) + ")"
            )
            waiver_lines = [
                self._edges[(a, b)][1]
                for a, b in zip(closed, closed[1:])
                if self._edges[(a, b)][0] == p0
            ]
            if mi is not None:
                self.findings.append(
                    mi.finding("lock-order", l0, msg, *waiver_lines)
                )
            else:
                self.findings.append(Finding("lock-order", p0, l0, msg))

    def _dfs_cycles(self, node, graph, path, on_path, out) -> None:
        if node in on_path:
            i = path.index(node)
            cyc = tuple(path[i:])
            # canonicalize rotation so each cycle reports once
            k = cyc.index(min(cyc))
            out.add(cyc[k:] + cyc[:k])
            return
        path.append(node)
        on_path.add(node)
        for nxt in sorted(graph.get(node, ())):
            self._dfs_cycles(nxt, graph, path, on_path, out)
        path.pop()
        on_path.discard(node)

    # -- rule 3: guarded-by --------------------------------------------------

    def _check_guarded_by(self) -> None:
        for mi in self.modules:
            guarded = self._guarded_fields(mi)
            if not guarded:
                continue
            for node in ast.walk(mi.tree):
                hit = self._mutation_of(node, guarded)
                if hit is None:
                    continue
                field_name, decl = hit
                func = mi.enclosing_function(node)
                if func is not None and func.node.name in (
                    "__init__", "__del__"
                ):
                    continue
                if self._under_lock(mi, func, node, decl):
                    continue
                self.findings.append(mi.finding(
                    "guarded-by", node.lineno,
                    f"write to '{field_name}' (guarded_by {decl.attr} — "
                    f"lock '{decl.name}') outside its lock",
                ))

    def _guarded_fields(
        self, mi: ModuleInfo
    ) -> Dict[str, reg.LockDecl]:
        """field name -> guard decl, from `#: guarded_by <attr>` trailing
        comments on `self.<field> = ...` lines."""
        out: Dict[str, reg.LockDecl] = {}
        for node in ast.walk(mi.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            line = (
                mi.lines[node.lineno - 1]
                if node.lineno - 1 < len(mi.lines) else ""
            )
            m = GUARDED_BY_RE.search(line)
            if not m and node.lineno >= 2:
                # standalone `#: guarded_by <attr>` on the line above
                above = mi.lines[node.lineno - 2]
                if above.lstrip().startswith("#"):
                    m = GUARDED_BY_RE.search(above)
            if not m:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name
                ) and t.value.id == "self":
                    cls = mi.enclosing_class(node)
                    decl = self._decl_for_class_attr(mi, cls, m.group(1))
                    if decl is not None:
                        out[t.attr] = decl
        return out

    @staticmethod
    def _mutation_of(
        node: ast.AST, guarded: Dict[str, reg.LockDecl]
    ) -> Optional[Tuple[str, reg.LockDecl]]:
        def attr_hit(expr) -> Optional[str]:
            if isinstance(expr, ast.Attribute) and expr.attr in guarded:
                return expr.attr
            return None

        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                f = attr_hit(t)
                if f:
                    return (f, guarded[f])
                if isinstance(t, ast.Subscript):
                    f = attr_hit(t.value)
                    if f:
                        return (f, guarded[f])
        if isinstance(node, ast.Delete):
            for t in node.targets:
                f = attr_hit(t)
                if f is None and isinstance(t, ast.Subscript):
                    f = attr_hit(t.value)
                if f:
                    return (f, guarded[f])
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr in MUTATORS:
            f = attr_hit(node.func.value)
            if f:
                return (f, guarded[f])
        return None

    def _under_lock(
        self,
        mi: ModuleInfo,
        func: Optional[FuncInfo],
        node: ast.AST,
        decl: reg.LockDecl,
    ) -> bool:
        if decl.name in self._context_locks(mi, func):
            return True
        cur = getattr(node, "_aios_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    got = self._lock_for_with_item(
                        mi, func, item.context_expr
                    )
                    if got is not None and got.name == decl.name:
                        return True
            cur = getattr(cur, "_aios_parent", None)
        return False

    # -- rule 4: dispatch hygiene -------------------------------------------

    def _check_dispatch_hygiene(self) -> None:
        mods = [
            self.by_name[m]
            for m in self.reg.dispatch_hygiene_modules
            if m in self.by_name
        ]
        if not mods:
            return
        # forward call graph from warmup roots, name-resolved
        reachable: Set[Tuple[str, str]] = set()
        frontier: List[Tuple[ModuleInfo, FuncInfo]] = []
        for mi in mods:
            for fi in mi.functions.values():
                if reg.WARMUP_ROOT_RE.match(fi.node.name):
                    frontier.append((mi, fi))
                    reachable.add((mi.name, fi.qualname))
        while frontier:
            mi, fi = frontier.pop()
            for call in iter_calls(fi.node):
                resolved = self._resolve_callee(mi, fi, call)
                if resolved is None:
                    continue
                cmod, cfn = resolved
                key = (cmod.name, cfn.qualname)
                if key not in reachable:
                    reachable.add(key)
                    frontier.append((cmod, cfn))
        for mi in mods:
            for call in iter_calls(mi.tree):
                chain = callee_chain(call)
                if chain not in (["jax", "jit"], ["jit"]):
                    continue
                fn = mi.enclosing_function(call)
                if fn is not None and (mi.name, fn.qualname) in reachable:
                    continue
                where = fn.qualname if fn else "<module>"
                self.findings.append(mi.finding(
                    "jit-warmup", call.lineno,
                    f"jax.jit in {where} is not reachable from an "
                    f"AOT-warmup registration (warmup/_compile_aot/"
                    f"compile_*) — it will compile on the serving hot "
                    f"path",
                ))

    # -- rule: silent-except (fault paths must not be black holes) -----------

    @staticmethod
    def _is_broad_handler(node: ast.ExceptHandler) -> bool:
        def broad(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in reg.BROAD_EXCEPTION_NAMES
            if isinstance(expr, ast.Attribute):
                return expr.attr in reg.BROAD_EXCEPTION_NAMES
            return False

        t = node.type
        if t is None:  # bare `except:`
            return True
        if isinstance(t, ast.Tuple):
            return any(broad(e) for e in t.elts)
        return broad(t)

    def _handler_records(self, node: ast.ExceptHandler) -> bool:
        """Whether the handler body records the failure: re-raises, logs
        it, lands an abort/terminal cause, or forwards to the abort
        plumbing (registry SILENT_EXCEPT_RECORDERS)."""
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Raise):
                    return True
                if isinstance(sub, ast.Call):
                    chain = callee_chain(sub)
                    if chain and chain[-1] in self.reg.silent_except_recorders:
                        return True
                if isinstance(sub, ast.keyword) and sub.arg == "abort_reason":
                    return True
                if isinstance(sub, (ast.Assign, ast.AugAssign)):
                    targets = (
                        sub.targets if isinstance(sub, ast.Assign)
                        else [sub.target]
                    )
                    if any(
                        isinstance(t, ast.Attribute)
                        and t.attr == "abort_reason"
                        for t in targets
                    ):
                        return True
        return False

    def _check_silent_except(self) -> None:
        for mi in self.modules:
            if not mi.name.startswith(
                tuple(self.reg.silent_except_prefixes)
            ):
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not self._is_broad_handler(node):
                    continue
                if self._handler_records(node):
                    continue
                self.findings.append(mi.finding(
                    "silent-except", node.lineno,
                    "broad except handler swallows the failure without "
                    "recording it (no raise / log / abort cause) — fault "
                    "paths must not be observability black holes; record "
                    "the failure or waive with a reason",
                ))

    # -- rule 5: knob/docs drift + metric catalog ----------------------------

    def _config_doc_text(self) -> Optional[str]:
        if self._config_doc is not None:
            return self._config_doc
        if self.repo_root is None:
            return None
        p = self.repo_root / reg.CONFIG_DOC
        return p.read_text() if p.exists() else None

    def _check_knob_drift(self) -> None:
        doc = self._config_doc_text()
        if doc is None:
            return
        doc_names = set(reg.KNOB_RE.findall(doc))
        code_names: Dict[str, Tuple[ModuleInfo, int]] = {}
        for mi in self.modules:
            for name, line in string_constants(mi.tree, reg.KNOB_RE):
                code_names.setdefault(name, (mi, line))
                if name not in doc_names:
                    self.findings.append(mi.finding(
                        "knob-docs", line,
                        f"env knob {name} is read here but missing from "
                        f"{reg.CONFIG_DOC}",
                    ))
        for stale in sorted(doc_names - set(code_names)):
            line = next(
                (i for i, t in enumerate(doc.splitlines(), 1) if stale in t),
                1,
            )
            self.findings.append(Finding(
                "knob-docs", reg.CONFIG_DOC, line,
                f"{reg.CONFIG_DOC} documents {stale} but nothing in the "
                f"tree reads it (stale row — delete or re-wire it)",
            ))

    def _check_metric_catalog(self) -> None:
        for mi in self.modules:
            if mi.name in reg.METRIC_CATALOG_MODULES:
                continue
            for call in iter_calls(mi.tree):
                chain = callee_chain(call)
                if not chain or chain[-1] not in reg.METRIC_CTORS:
                    continue
                if not call.args:
                    continue
                arg = call.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ) and arg.value.startswith(reg.METRIC_PREFIX):
                    self.findings.append(mi.finding(
                        "metric-catalog", call.lineno,
                        f"instrument {arg.value!r} constructed outside "
                        f"obs/instruments.py — add it to the catalog so "
                        f"the obs lint reviews it",
                    ))

    # -- meta: waiver hygiene ------------------------------------------------

    def _check_waivers(self) -> None:
        from .core import WAIVE_RE

        for mi in self.modules:
            for line, text in enumerate(mi.lines, start=1):
                m = WAIVE_RE.search(text)
                if not m:
                    continue
                for rule, reason in [
                    (m.group(1), (m.group(2) or "").strip())
                ]:
                    if rule not in RULE_IDS and rule != "all":
                        self.findings.append(Finding(
                            "waiver-reason", mi.path, line,
                            f"waiver names unknown rule {rule!r} "
                            f"(known: {', '.join(RULE_IDS)})",
                        ))
                    elif not reason:
                        self.findings.append(Finding(
                            "waiver-reason", mi.path, line,
                            f"waiver for {rule!r} carries no "
                            f"justification — the reason is mandatory "
                            f"(# aios: waive({rule}): <why>)",
                        ))


_HAZARD_WHY = {
    "dispatch": "a graph call/compile stalls every thread sharing the "
                "lock (router probes, scrape callbacks, the scheduler)",
    "readback": "a device->host sync holds the lock for the whole "
                "transfer (the PR 4/6 bug class)",
    "rpc": "a blocking wait under a lock invites deadlock and "
           "convoying",
}


def run_analysis(
    rules: Optional[Sequence[str]] = None,
    registry: reg.Registry = reg.DEFAULT,
    repo_root: Optional[Path] = None,
) -> List[Finding]:
    """Analyze the installed ``aios_tpu`` tree (the CLI and the tier-1
    test share this entry point)."""
    pkg_root = Path(__file__).resolve().parents[1]
    root = repo_root or pkg_root.parent
    modules = load_package(pkg_root, root)
    return Analyzer(modules, registry, repo_root=root).run(rules)
