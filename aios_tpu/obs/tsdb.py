"""Black-box metrics time series: a bounded in-process ring over the
metric registry.

Every observability layer below this one is *current-value only*:
``/metrics`` exposes the instant, a flight-recorder snapshot freezes
request timelines — but nothing in the process can answer "what did the
burn rate do over the last five minutes" after the fact. This module is
that memory: a background sampler walks **every registered instrument**
on a fixed cadence and appends one point per live series —

  * **counters** (and histogram buckets / counts / sums) as **deltas**
    since the previous pass, with Prometheus-style counter-reset
    handling (a respawned process's lower total becomes the delta,
    never a negative spike);
  * **gauges** raw (fn-backed gauges evaluate at sample time; a NaN —
    the scrape-must-never-crash sentinel — is skipped, not stored);

into a two-level store with a fixed memory budget: a **raw ring**
(default 1 s step x 5 min) cascading into a **downsampled wheel**
(default 10 s buckets x 1 h) that keeps sum/count/min/max per bucket, so
windowed queries stay exact after the raw points have rotated out. A
cardinality cap bounds the series map; series past the cap are counted
on ``aios_tpu_tsdb_dropped_series_total`` — never silently truncated.

Armed by ``AIOS_TPU_TSDB`` (the faults/devprof pattern): the module
global :data:`TSDB` stays ``None`` when off, every integration point is
one attribute-is-None check, and the sampler only *reads* the registry —
token streams, dispatch counts, and compile counters are pinned
identical ON vs OFF (tests/test_tsdb.py).

Queried at ``GET /debug/tsdb`` with a small closed-verb expression form
(:data:`QUERY_VERBS` — select by name + label matchers, then ``raw`` /
``rate`` / ``avg`` / ``min`` / ``max`` / ``pNN`` over a window), and
federated fleet-wide at ``/debug/tsdb/fleet`` with the host label
injected (the PR 16 exposition-merge discipline). Incident bundles
(obs/incidents.py) freeze :meth:`Tsdb.window_snapshot` ranges around
their trigger.

Locking: ``_lock`` (registry role "tsdb") guards the series map and the
per-series deques only. Registry reads (which take metric locks) and
metric emission happen OUTSIDE it; queries copy points under it and
aggregate after release.
"""

from __future__ import annotations

import logging
import math
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.locks import make_lock
from .metrics import (
    _OVERFLOW_KEY,
    Gauge,
    Histogram,
    HistogramChild,
    MetricsRegistry,
    REGISTRY,
)

log = logging.getLogger("aios.tsdb")

# THE closed query-verb enum (pinned by test_obs_lint, AST-iterated at
# metric registration): ``raw`` returns the windowed points themselves,
# ``rate`` is summed deltas / window (delta-kind series only), the
# aggregates fold gauge points (raw or wheel), and the ``pNN`` verbs
# compute a Prometheus-style histogram quantile from summed bucket
# deltas over the window. A new verb is a reviewed enum change, never a
# stray string in a query parser.
QUERY_VERBS = ("raw", "rate", "avg", "min", "max", "p50", "p90", "p95",
               "p99")

_PNN_RE = re.compile(r"^p(\d{2})$")

# How a series' samples are produced — "delta" covers counters,
# histogram buckets, and histogram count/sum (monotonic sources sampled
# as per-pass deltas); "gauge" is sampled raw.
SERIES_KINDS = ("delta", "gauge")

# Hard ceiling on points one ``raw`` query or window snapshot returns
# per series (the raw ring itself is the real bound; this guards a
# misconfigured huge ring from ballooning one HTTP response).
_MAX_POINTS = 4096

# Bound on the distinct-dropped-keys set backing the dropped_series
# counter: past it, drops still count but new keys stop being tracked
# individually (the set itself must not become the leak it guards).
_MAX_DROPPED_KEYS = 65536


def _env_float(name: str, default: float, lo: float, hi: float) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return min(max(v, lo), hi)


class TsdbConfig:
    """Knobs (docs/CONFIG.md "Black-box time series" rows). Read live
    from the environment at construction — tests and deploy scripts
    reconfigure per process."""

    def __init__(self) -> None:
        self.enabled = os.environ.get(
            "AIOS_TPU_TSDB", ""
        ).lower() in ("1", "true", "on")
        self.step_secs = _env_float("AIOS_TPU_TSDB_STEP_SECS", 1.0,
                                    0.05, 60.0)
        self.raw_secs = _env_float("AIOS_TPU_TSDB_RAW_SECS", 300.0,
                                   1.0, 3600.0)
        self.wheel_step_secs = _env_float(
            "AIOS_TPU_TSDB_WHEEL_STEP_SECS", 10.0, 1.0, 600.0
        )
        self.wheel_secs = _env_float("AIOS_TPU_TSDB_WHEEL_SECS", 3600.0,
                                     10.0, 86400.0)
        self.max_series = int(_env_float(
            "AIOS_TPU_TSDB_MAX_SERIES", 4096, 16, 1 << 20
        ))

    @property
    def raw_slots(self) -> int:
        return max(int(self.raw_secs / self.step_secs), 1)

    @property
    def wheel_slots(self) -> int:
        return max(int(self.wheel_secs / self.wheel_step_secs), 1)


class _Series:
    """One sampled time series: identity + previous raw value (for
    deltas) + the raw ring + the downsampled wheel. All mutable state is
    guarded by the owning :class:`Tsdb`'s ``_lock``."""

    __slots__ = ("name", "labels", "kind", "prev", "raw", "wheel",
                 "bucket_t", "b_sum", "b_count", "b_min", "b_max")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 kind: str, raw_slots: int, wheel_slots: int) -> None:
        self.name = name
        self.labels = labels
        self.kind = kind
        self.prev: Optional[float] = None
        self.raw: deque = deque(maxlen=raw_slots)  # (t, v)
        self.wheel: deque = deque(maxlen=wheel_slots)
        self.bucket_t: Optional[float] = None  # open wheel bucket start
        self.b_sum = 0.0
        self.b_count = 0
        self.b_min = math.inf
        self.b_max = -math.inf

    def append(self, t: float, v: float, wheel_step: float) -> None:
        self.raw.append((t, v))
        bt = math.floor(t / wheel_step) * wheel_step
        if self.bucket_t is not None and bt != self.bucket_t:
            self.wheel.append((self.bucket_t, self.b_sum, self.b_count,
                               self.b_min, self.b_max))
            self.bucket_t = None
        if self.bucket_t is None:
            self.bucket_t = bt
            self.b_sum, self.b_count = 0.0, 0
            self.b_min, self.b_max = math.inf, -math.inf
        self.b_sum += v
        self.b_count += 1
        self.b_min = min(self.b_min, v)
        self.b_max = max(self.b_max, v)

    def points(self, start: float, end: float) -> List[Tuple[float, float]]:
        """Raw points in [start, end], falling back to wheel buckets
        (rendered as (bucket_start, avg)) for the part of the window the
        raw ring no longer covers."""
        raw = [(t, v) for t, v in self.raw if start <= t <= end]
        raw_t0 = raw[0][0] if raw else end
        out = [
            (bt, (s / c if self.kind == "gauge" else s))
            for bt, s, c, _, _ in self.wheel
            if start <= bt <= end and bt < raw_t0 and c
        ]
        out.extend(raw)
        return out[-_MAX_POINTS:]


def _series_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> tuple:
    return (name, labels)


class Tsdb:
    """The sampler + store + query engine. ``clock`` is injectable (and
    the sampler thread optional) for deterministic ring/wheel tests."""

    def __init__(self, cfg: Optional[TsdbConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.cfg = cfg or TsdbConfig()
        self.registry = registry if registry is not None else REGISTRY
        self.clock = clock
        self._lock = make_lock("tsdb")
        self._series: Dict[tuple, _Series] = {}  #: guarded_by _lock
        self._dropped: set = set()  #: guarded_by _lock
        self._dropped_total = 0  #: guarded_by _lock
        self._passes = 0  #: guarded_by _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._register_metrics()

    def _register_metrics(self) -> None:
        """Pre-register every query-verb child by iterating the closed
        QUERY_VERBS enum (the autoscale/SLO registration pattern, pinned
        by test_obs_lint) and wire the live-state gauges."""
        from . import instruments

        for verb in QUERY_VERBS:
            instruments.TSDB_QUERIES.labels(verb=verb)
        instruments.TSDB_SERIES.set_function(self.series_count)
        instruments.TSDB_DROPPED.set_function(
            lambda: float(self._dropped_total)
        )

    # -- sampling -------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()  # restartable: bench/test arms cycle stop/start
        self._thread = threading.Thread(
            target=self._loop, name="tsdb-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - the sampler must outlive
                # any single bad pass; the log carries the evidence
                log.exception("tsdb sample pass failed")
            self._stop.wait(self.cfg.step_secs)

    def sample_once(self, now: Optional[float] = None) -> int:
        """One sampler pass: read every registered instrument (metric
        locks only — the registry is the sole contact surface with the
        serving plane), then fold the batch into the ring under the tsdb
        lock. Returns the number of points appended."""
        t = self.clock() if now is None else now
        batch: List[Tuple[str, Tuple[Tuple[str, str], ...], str, float]] = []
        for metric in self.registry.collect():
            try:
                self._read_metric(metric, batch)
            except Exception:  # noqa: BLE001 - one sick instrument must
                # not stop the pass; the rest of the registry still lands
                log.debug("tsdb read of %s failed", metric.name,
                          exc_info=True)
        appended = self._ingest(t, batch)
        from . import instruments

        instruments.TSDB_SAMPLES.inc()
        return appended

    def _read_metric(self, metric, batch: list) -> None:
        """Flatten one metric into (name, labels, kind, raw_value) rows.
        Histograms expand into per-bucket rows (``le`` label, cumulative
        counts — deltas computed downstream) plus _count/_sum rows."""
        is_hist = isinstance(metric, Histogram)
        kind = "gauge" if isinstance(metric, Gauge) else "delta"
        for key, child in metric._iter_children():
            if key == _OVERFLOW_KEY:
                labels: Tuple[Tuple[str, str], ...] = (("overflow", "true"),)
            else:
                labels = tuple(zip(metric.labelnames, key))
            if is_hist and isinstance(child, HistogramChild):
                with child._lock:
                    counts = list(child.counts)
                    h_sum, h_count = child._sum, child._count
                cum = 0
                for b, c in zip(list(metric.buckets) + [math.inf], counts):
                    cum += c
                    le = "+Inf" if b == math.inf else repr(float(b))
                    batch.append((
                        f"{metric.name}_bucket",
                        labels + (("le", le),), "delta", float(cum),
                    ))
                batch.append((f"{metric.name}_count", labels, "delta",
                              float(h_count)))
                batch.append((f"{metric.name}_sum", labels, "delta",
                              float(h_sum)))
            else:
                v = child.value
                if v != v:  # NaN: a failing fn-backed gauge — skip
                    continue
                batch.append((metric.name, labels, kind, float(v)))

    def _ingest(self, t: float, batch: list) -> int:
        cfg = self.cfg
        appended = 0
        dropped = 0
        with self._lock:
            for name, labels, kind, value in batch:
                key = _series_key(name, labels)
                s = self._series.get(key)
                if s is None:
                    if len(self._series) >= cfg.max_series:
                        # the explicit-drop contract: count, never
                        # silently truncate (one count per NEW series)
                        if key not in self._dropped:
                            if len(self._dropped) < _MAX_DROPPED_KEYS:
                                self._dropped.add(key)
                            self._dropped_total += 1
                            dropped += 1
                        continue
                    s = self._series[key] = _Series(
                        name, labels, kind, cfg.raw_slots, cfg.wheel_slots
                    )
                if kind == "delta":
                    prev = s.prev
                    s.prev = value
                    if prev is None:
                        continue  # rate needs two observations
                    # counter-reset (respawn): the new total IS the
                    # delta since the reset — never a negative spike
                    delta = value - prev if value >= prev else value
                    s.append(t, delta, cfg.wheel_step_secs)
                else:
                    s.append(t, value, cfg.wheel_step_secs)
                appended += 1
            self._passes += 1
        if dropped:
            log.warning("tsdb cardinality cap (%d): %d new series dropped",
                        cfg.max_series, dropped)
        return appended

    # -- introspection --------------------------------------------------------

    def series_count(self) -> float:
        with self._lock:
            return float(len(self._series))

    def dropped_series(self) -> int:
        with self._lock:
            return self._dropped_total

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "dropped_series": self._dropped_total,
                "passes": self._passes,
                "step_secs": self.cfg.step_secs,
                "raw_secs": self.cfg.raw_secs,
                "wheel_step_secs": self.cfg.wheel_step_secs,
                "wheel_secs": self.cfg.wheel_secs,
                "max_series": self.cfg.max_series,
            }

    # -- queries --------------------------------------------------------------

    def _select(self, name: str,
                matchers: Optional[Dict[str, str]]) -> List[_Series]:
        """Series whose name matches exactly and whose labels are a
        superset of the matchers. Caller must NOT hold ``_lock``."""
        want = matchers or {}
        with self._lock:
            out = []
            for s in self._series.values():
                if s.name != name:
                    continue
                have = dict(s.labels)
                if all(have.get(k) == v for k, v in want.items()):
                    out.append(s)
            return out

    def query(self, name: str, matchers: Optional[Dict[str, str]] = None,
              verb: str = "raw", window: Optional[float] = None,
              now: Optional[float] = None) -> dict:
        """The closed-verb expression form behind ``GET /debug/tsdb``:
        select series by name + label matchers, then apply one verb over
        the trailing ``window`` seconds. Unknown verbs raise ValueError
        (the HTTP layer renders a 400 listing QUERY_VERBS)."""
        if verb not in QUERY_VERBS:
            raise ValueError(
                f"unknown verb {verb!r}; one of {', '.join(QUERY_VERBS)}"
            )
        t = self.clock() if now is None else now
        w = float(window) if window else self.cfg.raw_secs
        start = t - w
        from . import instruments

        instruments.TSDB_QUERIES.labels(verb=verb).inc()
        m = _PNN_RE.match(verb)
        if m:
            series = self._quantile_series(
                name, matchers, int(m.group(1)) / 100.0, start, t
            )
        else:
            series = []
            for s in self._select(name, matchers):
                with self._lock:
                    pts = s.points(start, t)
                    kind = s.kind
                    labels = dict(s.labels)
                entry: dict = {"name": name, "labels": labels, "kind": kind}
                if verb == "raw":
                    entry["points"] = [[round(pt, 3), pv] for pt, pv in pts]
                elif verb == "rate":
                    entry["value"] = (
                        sum(pv for _, pv in pts) / w if kind == "delta"
                        else None
                    )
                elif not pts:
                    entry["value"] = None
                elif verb == "avg":
                    entry["value"] = sum(pv for _, pv in pts) / len(pts)
                elif verb == "min":
                    entry["value"] = min(pv for _, pv in pts)
                else:  # max
                    entry["value"] = max(pv for _, pv in pts)
                series.append(entry)
        series.sort(key=lambda e: sorted(e["labels"].items()))
        return {"name": name, "verb": verb, "window_secs": w,
                "now": round(t, 3), "series": series}

    def _quantile_series(self, name: str,
                         matchers: Optional[Dict[str, str]], q: float,
                         start: float, end: float) -> List[dict]:
        """pNN over a histogram family: group the ``<name>_bucket``
        delta series by labels-minus-le, sum each bucket's deltas over
        the window, and interpolate the quantile inside its bucket (the
        Prometheus histogram_quantile shape)."""
        groups: Dict[tuple, List[Tuple[float, float]]] = {}
        for s in self._select(f"{name}_bucket", matchers):
            with self._lock:
                total = sum(pv for _, pv in s.points(start, end))
                labels = dict(s.labels)
            le = labels.pop("le", "")
            bound = math.inf if le == "+Inf" else float(le)
            groups.setdefault(tuple(sorted(labels.items())), []).append(
                (bound, total)
            )
        out = []
        for labelkey, buckets in groups.items():
            buckets.sort()
            # de-cumulate: sampled values are cumulative counts, so the
            # summed deltas are cumulative too
            total = buckets[-1][1] if buckets else 0.0
            value: Optional[float] = None
            if total > 0:
                rank = q * total
                prev_bound, prev_cum = 0.0, 0.0
                for bound, cum in buckets:
                    if cum >= rank:
                        if bound == math.inf:
                            value = prev_bound
                        else:
                            span = cum - prev_cum
                            frac = ((rank - prev_cum) / span) if span else 0.0
                            value = prev_bound + (bound - prev_bound) * frac
                        break
                    prev_bound, prev_cum = bound, cum
            out.append({"name": name, "labels": dict(labelkey),
                        "kind": "delta", "value": value,
                        "samples": total})
        return out

    def window_snapshot(self, start: float, end: float,
                        max_series: int = 512) -> dict:
        """Every series' raw/wheel points inside [start, end] — the
        incident-bundle freeze. Bounded: at most ``max_series`` series
        land in the snapshot (name-sorted, so truncation is stable), the
        rest are counted in ``truncated`` — no silent loss."""
        with self._lock:
            all_series = sorted(
                self._series.values(),
                key=lambda s: (s.name, s.labels),
            )
        out = []
        truncated = 0
        for s in all_series:
            with self._lock:
                pts = s.points(start, end)
                labels = dict(s.labels)
                kind = s.kind
            if not pts:
                continue
            if len(out) >= max_series:
                truncated += 1
                continue
            out.append({
                "name": s.name, "labels": labels, "kind": kind,
                "points": [[round(pt, 3), pv] for pt, pv in pts],
            })
        return {"start": round(start, 3), "end": round(end, 3),
                "series": out, "truncated": truncated}

    def clear(self) -> None:
        """Test isolation."""
        with self._lock:
            self._series.clear()
            self._dropped.clear()
            self._dropped_total = 0
            self._passes = 0


# -- process-wide instance ----------------------------------------------------

# The one ring obs/http.py, incidents, and autoscale annotations read;
# None until maybe_start() arms it — every integration point is a single
# attribute-is-None check (the faults/devprof pattern), so an unarmed
# process pays nothing.
TSDB: Optional[Tsdb] = None


def enabled() -> bool:
    return TSDB is not None


def maybe_start() -> Optional[Tsdb]:
    """Arm the ring for this process when ``AIOS_TPU_TSDB`` asks for it
    (called by maybe_start_metrics_server — every real serving process
    passes through there). Idempotent."""
    global TSDB
    cfg = TsdbConfig()
    if TSDB is not None or not cfg.enabled:
        return TSDB
    TSDB = Tsdb(cfg)
    TSDB.start()
    log.info(
        "tsdb armed: step=%.2fs raw=%.0fs wheel=%.0fs/%.0fs max_series=%d",
        cfg.step_secs, cfg.raw_secs, cfg.wheel_step_secs, cfg.wheel_secs,
        cfg.max_series,
    )
    return TSDB


def install(t: Optional[Tsdb]) -> Optional[Tsdb]:
    """Swap the process-wide ring (tests); returns the previous."""
    global TSDB
    prev, TSDB = TSDB, t
    return prev


def handle_query(query: Dict[str, List[str]]) -> Tuple[dict, int]:
    """Map a parsed ``/debug/tsdb`` query string onto the expression
    form — ``?name=<metric>`` selects, repeated ``match=key:value``
    filters, ``verb=`` one of :data:`QUERY_VERBS` (default ``raw``),
    ``window=<secs>`` bounds. No ``name`` returns the ring's stats.
    Returns (payload, http_status); shared by obs/http.py and the
    fleet federation (each peer answers the SAME query locally)."""
    t = TSDB
    if t is None:
        return {"error": "tsdb not armed (set AIOS_TPU_TSDB=1)"}, 404

    def q(key: str, default: str = "") -> str:
        return query.get(key, [default])[0]

    name = q("name")
    if not name:
        return {"stats": t.stats()}, 200
    matchers: Dict[str, str] = {}
    for m in query.get("match", []):
        k, sep, v = m.partition(":")
        if not sep or not k:
            return {"error": f"bad matcher {m!r}; want key:value"}, 400
        matchers[k] = v
    raw_window = q("window")
    try:
        window = float(raw_window) if raw_window else None
    except ValueError:
        return {"error": f"bad window {raw_window!r}"}, 400
    try:
        return t.query(name, matchers or None, verb=q("verb", "raw"),
                       window=window), 200
    except ValueError as exc:  # unknown verb -> 400 listing QUERY_VERBS
        return {"error": str(exc)}, 400


def trend(name: str, matchers: Optional[Dict[str, str]] = None,
          window: float = 60.0) -> Optional[dict]:
    """Compact first/last/avg over the trailing window for ONE series —
    the autoscale-decision annotation ("the burn trend it acted on").
    None when the ring is off or the series has no points."""
    t = TSDB
    if t is None:
        return None
    now = t.clock()
    best: Optional[dict] = None
    for s in t._select(name, matchers):
        with t._lock:
            pts = s.points(now - window, now)
        if not pts:
            continue
        vals = [pv for _, pv in pts]
        cand = {
            "first": round(vals[0], 6), "last": round(vals[-1], 6),
            "avg": round(sum(vals) / len(vals), 6),
            "points": len(vals), "window_secs": window,
        }
        if best is None or cand["last"] > best["last"]:
            best = cand  # worst (highest) series wins the annotation
    return best
