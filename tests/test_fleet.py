"""Fleet telemetry plane units (aios_tpu/obs/fleet.py, ISSUE 16).

Fast CPU tier: config/env parsing, the membership state machine on an
injected clock, exposition relabel/merge, trace stitching, SLO rollups,
the HTTP surface over a real ephemeral-port server, the multihost env
contract, and the multi-target storm routing/verdict helpers. The slow
tier runs scripts/fleet_smoke.py — two REAL runtime processes
federating, stitching one trace, and one dying deterministically."""

import json
import os
import subprocess
import sys
import urllib.request

import pytest

from aios_tpu.obs import fleet
from aios_tpu.obs.fleet import (
    FleetConfig,
    FleetRegistry,
    MEMBER_STATES,
    merge_expositions,
    relabel_exposition,
    stitch_chrome_traces,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- config / identity ------------------------------------------------------


def test_fleet_config_defaults_inactive(monkeypatch):
    for var in ("AIOS_TPU_FLEET", "AIOS_TPU_FLEET_PEERS"):
        monkeypatch.delenv(var, raising=False)
    cfg = FleetConfig()
    assert not cfg.active()
    assert cfg.interval_secs == 2.0
    assert cfg.suspect_secs == 6.0
    assert cfg.dead_secs == 15.0
    assert cfg.seed_peers() == ()


def test_fleet_config_env_parsing(monkeypatch):
    monkeypatch.setenv("AIOS_TPU_FLEET", "1")
    monkeypatch.setenv("AIOS_TPU_FLEET_PEERS", "10.0.0.1:9100, 10.0.0.2:9100")
    monkeypatch.setenv("AIOS_TPU_FLEET_INTERVAL_SECS", "0.5")
    monkeypatch.setenv("AIOS_TPU_FLEET_SUSPECT_SECS", "2")
    monkeypatch.setenv("AIOS_TPU_FLEET_DEAD_SECS", "4")
    cfg = FleetConfig()
    assert cfg.active()
    assert cfg.peers == ("10.0.0.1:9100", "10.0.0.2:9100")
    assert cfg.seed_peers() == cfg.peers
    assert (cfg.interval_secs, cfg.suspect_secs, cfg.dead_secs) == (
        0.5, 2.0, 4.0)


def test_fleet_peers_alone_activate(monkeypatch):
    monkeypatch.delenv("AIOS_TPU_FLEET", raising=False)
    monkeypatch.setenv("AIOS_TPU_FLEET_PEERS", "10.0.0.9:9100")
    assert FleetConfig().active()


def test_fleet_seed_peers_fall_back_to_coordinator(monkeypatch):
    """With no explicit peer list, the multihost coordinator host on
    AIOS_TPU_FLEET_SEED_PORT seeds membership — one seed is enough,
    gossip converges the rest."""
    monkeypatch.delenv("AIOS_TPU_FLEET_PEERS", raising=False)
    monkeypatch.setenv("AIOS_TPU_COORDINATOR", "10.1.2.3:8476")
    monkeypatch.setenv("AIOS_TPU_NUM_PROCESSES", "2")
    monkeypatch.setenv("AIOS_TPU_PROCESS_ID", "1")
    monkeypatch.setenv("AIOS_TPU_FLEET_SEED_PORT", "9200")
    assert FleetConfig().seed_peers() == ("10.1.2.3:9200",)


def test_process_identity_env_overrides(monkeypatch):
    monkeypatch.setenv("AIOS_TPU_FLEET_HOST", "hostX")
    monkeypatch.setenv("AIOS_TPU_FLEET_ROLE", "orchestrator")
    monkeypatch.setenv("AIOS_TPU_COORDINATOR", "10.1.2.3:8476")
    monkeypatch.setenv("AIOS_TPU_NUM_PROCESSES", "4")
    monkeypatch.setenv("AIOS_TPU_PROCESS_ID", "3")
    ident = fleet.process_identity("runtime")
    assert ident["host"] == "hostX"
    assert ident["role"] == "orchestrator"  # env wins over the service name
    assert ident["rank"] == "3"
    import aios_tpu

    assert ident["version"] == aios_tpu.__version__


def test_process_identity_defaults_are_unique_per_process(monkeypatch):
    for var in ("AIOS_TPU_FLEET_HOST", "AIOS_TPU_FLEET_ROLE",
                "AIOS_TPU_COORDINATOR", "AIOS_TPU_MULTIHOST"):
        monkeypatch.delenv(var, raising=False)
    ident = fleet.process_identity("runtime")
    assert ident["host"].endswith(f":{os.getpid()}")
    assert ident["role"] == "runtime"
    assert ident["rank"] == "0"


def test_stamp_process_info_sets_identity_gauge(monkeypatch):
    from aios_tpu.obs import instruments

    monkeypatch.setenv("AIOS_TPU_FLEET_HOST", "stamp-test")
    ident = fleet.stamp_process_info("runtime")
    assert instruments.PROCESS_INFO.labels(**ident).value == 1.0


# -- the membership state machine (injected clock) --------------------------


def _registry(now, **cfg_overrides):
    cfg = FleetConfig()
    cfg.suspect_secs = cfg_overrides.get("suspect_secs", 5.0)
    cfg.dead_secs = cfg_overrides.get("dead_secs", 10.0)
    cfg.peers = ()
    return FleetRegistry(
        {"host": "hostA", "role": "runtime", "rank": "0", "version": "t"},
        "127.0.0.1:9100", cfg=cfg, clock=lambda: now[0],
    )


def _desc(host, addr="127.0.0.1:9101", **extra):
    return {"host": host, "role": "runtime", "rank": "1", "version": "t",
            "metrics_addr": addr, **extra}


def test_member_lifecycle_up_suspect_dead_and_recovery():
    now = [100.0]
    reg = _registry(now)
    reg.receive(_desc("hostB"))
    states = {m["host"]: m["state"] for m in reg.members()}
    assert states == {"hostA": "up", "hostB": "up"}

    # inside the suspect window nothing moves
    assert reg.tick(now=104.0) == []
    # past it: exactly one up -> suspect edge
    assert reg.tick(now=106.0) == [("hostB", "runtime", "up", "suspect")]
    # a detector tick never un-suspects (recovery needs fresh evidence)
    assert reg.tick(now=106.5) == []
    # past the dead window: suspect -> dead
    assert reg.tick(now=111.0) == [("hostB", "runtime", "suspect", "dead")]
    assert reg.tick(now=200.0) == []  # dead is terminal for the detector

    # a fresh announce resurrects: dead -> up (restarts are the common case)
    now[0] = 200.0
    reg.receive(_desc("hostB"))
    states = {m["host"]: m["state"] for m in reg.members()}
    assert states["hostB"] == "up"

    edges = [(e["host"], e["from"], e["to"]) for e in reg.journal()]
    assert edges == [
        ("hostA", "", "up"),
        ("hostB", "", "up"),
        ("hostB", "up", "suspect"),
        ("hostB", "suspect", "dead"),
        ("hostB", "dead", "up"),
    ]


def test_detector_never_ages_self():
    now = [0.0]
    reg = _registry(now)
    assert reg.tick(now=1e6) == []
    assert reg.members()[0]["state"] == "up"


def test_journal_is_bounded():
    now = [0.0]
    reg = _registry(now)
    for i in range(300):
        now[0] = i * 100.0
        reg.receive(_desc("hostB"))  # dead -> up
        reg.tick(now=now[0] + 50.0)  # up -> suspect -> (next round) dead
        reg.tick(now=now[0] + 99.0)
    assert len(reg.journal()) <= fleet._MAX_JOURNAL


def test_receive_returns_self_and_gossips_peers():
    now = [0.0]
    reg = _registry(now)
    reply = reg.receive(_desc("hostB", addr="127.0.0.1:9101"))
    assert reply["member"]["host"] == "hostA"
    assert reply["member"]["metrics_addr"] == "127.0.0.1:9100"
    assert "pools" in reply["member"] and "slo" in reply["member"]
    # hostB's endpoint is now gossiped to the NEXT announcer
    reply2 = reg.receive(_desc("hostC", addr="127.0.0.1:9102"))
    assert "127.0.0.1:9101" in reply2["peers"]


def test_health_summary_rolls_up_burn_and_attainment(monkeypatch):
    # self's descriptor reads the LIVE slo tracker; earlier suite tests may
    # have left burn there, so pin it empty to keep the rollup hermetic
    monkeypatch.setattr(fleet, "_self_slo", lambda: {})
    now = [0.0]
    reg = _registry(now)
    reg.receive(_desc("hostB", slo={
        "worst_burn": 3.5,
        "attainment": {"m": {"ttft": 0.91, "tpot": 0.99}},
    }))
    reg.receive(_desc("hostC", addr="127.0.0.1:9102", slo={
        "worst_burn": 0.2,
        "attainment": {"m": {"ttft": 0.99, "tpot": 0.97}},
    }))
    s = reg.health_summary()
    assert s["size"] == 3 and s["up"] == 3
    assert s["worst_burn"] == {"host": "hostB", "burn": 3.5}
    # fleet attainment = the MINIMUM any member reports per objective
    assert s["attainment"] == {"ttft": 0.91, "tpot": 0.97}


def test_scrape_targets_exclude_self_and_dead():
    now = [0.0]
    reg = _registry(now)
    reg.receive(_desc("hostB", addr="127.0.0.1:9101"))
    reg.receive(_desc("hostC", addr="127.0.0.1:9102"))
    assert [t[0] for t in reg._scrape_targets()] == ["hostB", "hostC"]
    reg.tick(now=11.0)  # both dead
    assert reg._scrape_targets() == []


# -- exposition relabel / merge ---------------------------------------------

EXPO_A = """\
# HELP aios_tpu_rpc_requests_total RPCs
# TYPE aios_tpu_rpc_requests_total counter
aios_tpu_rpc_requests_total{service="runtime"} 4
# HELP aios_tpu_queue_wait_seconds waits
# TYPE aios_tpu_queue_wait_seconds histogram
aios_tpu_queue_wait_seconds_bucket{le="1"} 2
aios_tpu_queue_wait_seconds_bucket{le="+Inf"} 3
aios_tpu_queue_wait_seconds_sum 1.5
aios_tpu_queue_wait_seconds_count 3
up 1
"""

EXPO_B = """\
# HELP aios_tpu_rpc_requests_total RPCs from B
# TYPE aios_tpu_rpc_requests_total counter
aios_tpu_rpc_requests_total{service="runtime"} 9
aios_tpu_already{host="elsewhere",x="1"} 2
"""


def test_relabel_injects_host_and_keeps_histogram_family_together():
    fams = relabel_exposition(EXPO_A, "h1")
    by_name = {f[0]: f for f in fams}
    assert by_name["aios_tpu_rpc_requests_total"][3] == [
        'aios_tpu_rpc_requests_total{host="h1",service="runtime"} 4'
    ]
    # _bucket/_sum/_count ride under the histogram family header
    hist = by_name["aios_tpu_queue_wait_seconds"]
    assert hist[2] == "histogram"
    assert len(hist[3]) == 4
    assert hist[3][2] == 'aios_tpu_queue_wait_seconds_sum{host="h1"} 1.5'
    # an unlabeled sample gains the label set outright
    assert by_name["up"][3] == ['up{host="h1"} 1']


def test_relabel_passes_through_preexisting_host_label():
    fams = relabel_exposition(EXPO_B, "h2")
    samples = [s for f in fams for s in f[3]]
    assert 'aios_tpu_already{host="elsewhere",x="1"} 2' in samples


def test_merge_expositions_families_contiguous_first_help_wins():
    text = merge_expositions([("h1", EXPO_A), ("h2", EXPO_B)])
    lines = text.splitlines()
    # exactly one header pair for the shared family, first HELP text wins
    assert lines.count("# HELP aios_tpu_rpc_requests_total RPCs") == 1
    assert "# HELP aios_tpu_rpc_requests_total RPCs from B" not in text
    # both hosts' samples sit directly under that one header
    i = lines.index("# TYPE aios_tpu_rpc_requests_total counter")
    assert lines[i + 1:i + 3] == [
        'aios_tpu_rpc_requests_total{host="h1",service="runtime"} 4',
        'aios_tpu_rpc_requests_total{host="h2",service="runtime"} 9',
    ]


# -- trace stitching ---------------------------------------------------------


def _timeline(model, request_id, trace_id):
    return {
        "model": model, "request_id": request_id, "tenant": "t",
        "state": "completed", "submitted_at": 100.0, "duration_ms": 5.0,
        "queue_wait_ms": 1.0, "trace_id": trace_id,
        "events": [{"t_ms": 0.0, "kind": "admission"}],
    }


def test_stitch_chrome_traces_one_lane_group_per_host():
    merged = stitch_chrome_traces({
        "hostA": [_timeline("m", "r1", "T")],
        "hostB": [_timeline("m", "r2", "T")],
    })
    names = {
        ev["args"]["name"]
        for ev in merged["traceEvents"]
        if ev.get("name") == "process_name"
    }
    assert names == {"host:hostA model:m", "host:hostB model:m"}
    # hosts occupy disjoint pid blocks (hostA < stride <= hostB)
    pids = {
        ev["args"]["name"]: ev["pid"]
        for ev in merged["traceEvents"]
        if ev.get("name") == "process_name"
    }
    assert pids["host:hostA model:m"] < fleet._PID_STRIDE
    assert pids["host:hostB model:m"] >= fleet._PID_STRIDE


# -- the HTTP surface over a real ephemeral-port server ----------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.status, r.read().decode()


def test_fleet_http_surface(monkeypatch):
    from aios_tpu.obs.http import start_metrics_server

    monkeypatch.setenv("AIOS_TPU_FLEET_HOST", "httpA")
    now = [0.0]
    server, port = start_metrics_server(port=0)
    reg = _registry(now)
    prev = fleet.install(reg)
    try:
        # /healthz names the ACTUAL bound port (ephemeral discoverability)
        status, body = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["metrics_port"] == port

        # announce folds the peer in and answers with us + gossip
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/fleet/announce",
            data=json.dumps(_desc("httpB")).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            reply = json.loads(r.read().decode())
        assert reply["member"]["host"] == "hostA"

        status, body = _get(port, "/fleet/members")
        data = json.loads(body)
        hosts = {m["host"] for m in data["members"]}
        assert {"hostA", "httpB"} <= hosts
        assert data["summary"]["up"] >= 2

        # federation: own registry renders with our host label injected
        status, body = _get(port, "/metrics/fleet")
        assert status == 200
        assert 'host="hostA"' in body

        # malformed announce -> 400, not a crashed endpoint
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/fleet/announce", data=b"[1,2]",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400
    finally:
        fleet.install(prev)
        server.shutdown()


def test_fleet_routes_404_when_unarmed():
    from aios_tpu.obs.http import start_metrics_server

    prev = fleet.install(None)
    server, port = start_metrics_server(port=0)
    try:
        for path in ("/metrics/fleet", "/fleet/members",
                     "/debug/trace/fleet?trace=x"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(port, path)
            assert ei.value.code == 404, path
    finally:
        fleet.install(prev)
        server.shutdown()


def test_slo_annotate_health_folds_fleet_summary():
    from aios_tpu.obs import slo

    now = [0.0]
    reg = _registry(now)
    prev = fleet.install(reg)
    try:
        payload = slo.annotate_health({"status": "ok"})
        assert payload["fleet"]["size"] == 1
        assert payload["fleet"]["up"] == 1
    finally:
        fleet.install(prev)


def test_stats_providers_feed_heartbeat_and_survive_errors():
    def good():
        return {"m": {"waiting": 2}}

    def bad():
        raise RuntimeError("sick pool")

    fleet.clear_stats_providers()
    try:
        fleet.add_stats_provider(good)
        fleet.add_stats_provider(bad)
        pools = fleet._self_pools()
        assert pools["m"] == {"waiting": 2}
        assert "provider" in pools["_error"]
    finally:
        fleet.clear_stats_providers()


def test_self_descriptor_refreshes_member_row():
    """Regression pin (ISSUE 17 satellite): the heartbeat descriptor
    must sample pool stats AT ANNOUNCE TIME and refresh self's stored
    member row. Before the fix, self's row held the boot-time snapshot
    forever — a degrade-ladder controller mid-walk was invisible to
    /fleet/members and fleetctl."""
    level = {"v": 0}
    fleet.clear_stats_providers()
    try:
        fleet.add_stats_provider(lambda: {"m": {"degrade_level": level["v"]}})
        now = [100.0]
        reg = _registry(now)
        row = next(m for m in reg.members() if m["self"])
        assert row["pools"]["m"]["degrade_level"] == 0
        level["v"] = 2  # the ladder walks between heartbeats
        reg.self_descriptor()
        row = next(m for m in reg.members() if m["self"])
        assert row["pools"]["m"]["degrade_level"] == 2
    finally:
        fleet.clear_stats_providers()


def test_gprefix_and_kvx_addr_piggyback_on_heartbeat():
    """The fleet data plane rides the EXISTING heartbeat: digest
    providers and the transfer endpoint land in the descriptor and in
    the membership rows peers score against."""
    digest = {"m": {"page": 32, "tails": {"ab12cd34ef567890": 3}}}
    fleet.clear_digest_providers()
    try:
        fleet.add_digest_provider(lambda: digest)
        fleet.set_transfer_addr("1.2.3.4:9400")
        now = [100.0]
        reg = _registry(now)
        desc = reg.self_descriptor()
        assert desc["gprefix"] == digest
        assert desc["kvx_addr"] == "1.2.3.4:9400"
        row = next(m for m in reg.members() if m["self"])
        assert row["gprefix"] == digest
        assert row["kvx_addr"] == "1.2.3.4:9400"
    finally:
        fleet.clear_digest_providers()
        fleet.set_transfer_addr("")


def test_digest_provider_errors_survive():
    def bad():
        raise RuntimeError("sick engine")

    fleet.clear_digest_providers()
    try:
        fleet.add_digest_provider(bad)
        digest = fleet._self_gprefix()
        assert "provider" in digest["_error"]
    finally:
        fleet.clear_digest_providers()


# -- the multihost env contract ---------------------------------------------


def test_env_contract_unset_is_single_host():
    from aios_tpu.parallel import multihost

    assert multihost.env_contract({}) is None


def test_env_contract_explicit_coordinator():
    from aios_tpu.parallel import multihost

    c = multihost.env_contract({
        "AIOS_TPU_COORDINATOR": "10.0.0.1:8476",
        "AIOS_TPU_NUM_PROCESSES": "4",
        "AIOS_TPU_PROCESS_ID": "2",
    })
    assert c.coordinator == "10.0.0.1:8476"
    assert c.num_processes == 4 and c.process_id == 2
    assert not c.auto


@pytest.mark.parametrize("missing", [
    {"AIOS_TPU_COORDINATOR": "10.0.0.1:8476"},
    {"AIOS_TPU_COORDINATOR": "10.0.0.1:8476",
     "AIOS_TPU_NUM_PROCESSES": "4"},
    {"AIOS_TPU_COORDINATOR": "10.0.0.1:8476",
     "AIOS_TPU_PROCESS_ID": "0"},
    {"AIOS_TPU_COORDINATOR": "10.0.0.1:8476",
     "AIOS_TPU_NUM_PROCESSES": "4", "AIOS_TPU_PROCESS_ID": ""},
])
def test_env_contract_incomplete_explicit_path_raises(missing):
    from aios_tpu.parallel import multihost

    with pytest.raises(ValueError, match="AIOS_TPU_COORDINATOR requires"):
        multihost.env_contract(missing)


@pytest.mark.parametrize("val", ["auto", "1", "AUTO"])
def test_env_contract_auto(val):
    from aios_tpu.parallel import multihost

    c = multihost.env_contract({"AIOS_TPU_MULTIHOST": val})
    assert c.auto and c.coordinator == ""


def test_env_contract_auto_with_coordinator_needs_no_companions():
    """AIOS_TPU_MULTIHOST=auto beside a coordinator is the pod
    self-describe path: the companion vars are optional there."""
    from aios_tpu.parallel import multihost

    c = multihost.env_contract({
        "AIOS_TPU_MULTIHOST": "auto",
        "AIOS_TPU_COORDINATOR": "10.0.0.1:8476",
    })
    assert c.auto and c.coordinator == "10.0.0.1:8476"


# -- multi-target storm routing / verdict -----------------------------------


def test_target_of_deterministic_and_tenant_affine():
    from aios_tpu.loadgen import target_of

    assert target_of("anyone", 1) == 0
    assert target_of("anyone", 0) == 0
    ts = [target_of(f"tenant-{i}", 3) for i in range(64)]
    assert ts == [target_of(f"tenant-{i}", 3) for i in range(64)]  # stable
    assert set(ts) == {0, 1, 2}  # spreads across targets
    # same tenant, same target, always (cache-coupled families stay put)
    assert len({target_of("chat", 3) for _ in range(10)}) == 1


def test_per_target_verdict_aggregation():
    from aios_tpu.loadgen.driver import Outcome
    from aios_tpu.loadgen.report import _per_target
    from aios_tpu.loadgen.trace import Call

    def call(tenant, deadline_ms=0):
        return Call(t=0.0, tenant=tenant, klass="interactive",
                    task_id=f"t-{tenant}", prompt="p", max_tokens=1,
                    temperature=0.0, streaming=False,
                    deadline_ms=deadline_ms, level="")

    outcomes = [
        Outcome(call=call("a"), status="ok", extras={"target": 0}),
        Outcome(call=call("b"), status="shed", extras={"target": 1}),
        Outcome(call=call("c", deadline_ms=50), status="shed",
                extras={"target": 1}),
    ]
    per = _per_target(outcomes)
    assert per["0"] == {"submitted": 1, "completed": 1, "shed": 0,
                       "rejected": 0}
    # the deadline tenant's submission pins; its outcome does not
    assert per["1"] == {"submitted": 2, "completed": 0, "shed": 1,
                       "rejected": 0}


def test_per_target_empty_for_single_endpoint_storms():
    from aios_tpu.loadgen.driver import Outcome
    from aios_tpu.loadgen.report import _per_target
    from aios_tpu.loadgen.trace import Call

    c = Call(t=0.0, tenant="a", klass="interactive", task_id="t",
             prompt="p", max_tokens=1, temperature=0.0, streaming=False,
             deadline_ms=0, level="")
    assert _per_target([Outcome(call=c, status="ok")]) == {}


def test_scenario_endpoints_field_parses():
    from aios_tpu.loadgen.scenario import _build

    sc = _build({
        "scenario": {"name": "multi", "seed": 1, "duration_secs": 1.0,
                     "endpoints": ["127.0.0.1:1", "127.0.0.1:2"]},
        "tenants": [{"name": "chat"}],
    }, "inline")
    assert sc.endpoints == ("127.0.0.1:1", "127.0.0.1:2")


# -- fleetctl --json (ISSUE 17 satellite) -----------------------------------


def _fleetctl():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fleetctl", os.path.join(REPO, "scripts", "fleetctl.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleetctl_json_status_and_top(capsys):
    """``--json`` replaces the terse verdict with the full row set —
    same fields the table renders (plus kvx_addr), same exit codes."""
    mod = _fleetctl()
    data = {
        "members": [
            {"host": "hostA", "role": "runtime", "state": "up",
             "age_secs": 0.1, "rank": "0", "version": "t", "pid": 1,
             "metrics_addr": "a:1", "kvx_addr": "a:2", "self": True,
             "pools": {"m": {"waiting": 1, "batch_occupancy": 0.5,
                             "degrade_level": 2}},
             "slo": {"worst_burn": 1.5}},
            {"host": "hostB", "role": "decode", "state": "suspect",
             "age_secs": 7.0, "rank": "1", "version": "t", "pid": 2,
             "metrics_addr": "b:1", "kvx_addr": "b:2", "self": False,
             "pools": {}, "slo": {}},
        ],
        "journal": [{"host": "hostB", "role": "decode", "from": "up",
                     "to": "suspect", "at": 0.0}],
    }
    rc = mod.cmd_status(data, as_json=True)
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and out["pass"] is False
    assert out["size"] == 2 and out["up"] == 1
    assert out["members"][0]["kvx_addr"] == "a:2"
    assert out["journal"][0]["to"] == "suspect"
    rc = mod.cmd_top(data, as_json=True)
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and out["pass"] is False
    # worst burn sorts first, load triple flattened per row
    assert out["members"][0]["host"] == "hostA"
    assert out["members"][0]["worst_burn"] == 1.5
    assert out["members"][0]["degrade_level"] == 2
    assert out["members"][0]["waiting"] == 1
    # the terse verdict path is unchanged
    rc = mod.cmd_status(data)
    out = json.loads(capsys.readouterr().out.strip())
    assert rc == 1 and out["not_up"] == [
        {"host": "hostB", "role": "decode", "state": "suspect"}
    ]


# -- the two-process e2e (slow tier) ----------------------------------------


@pytest.mark.slow
def test_fleet_smoke_two_real_processes():
    """scripts/fleet_smoke.py end to end: two runtime processes on
    ephemeral ports federate /metrics/fleet, stitch one traced request
    into per-host Chrome lanes, fleetctl exits 0, and the killed
    worker's up -> suspect -> dead journal is identical across two
    runs."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "fleet_smoke.py")],
        cwd=REPO, capture_output=True, text=True, timeout=580,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["pass"] and verdict["identical"] and verdict["lifecycle"]
