"""Span-based tracing with W3C traceparent context propagation.

Spans form the goal -> task -> agent -> RPC -> decode hierarchy
(docs/OBSERVABILITY.md): a span opened inside another span on the same
thread becomes its child (contextvars), and the current span's identity
crosses process/service boundaries as a ``traceparent`` gRPC metadata
entry (``00-<trace_id>-<span_id>-01``) injected by the client
interceptor and re-parented by the server interceptor.

Finished spans land in a bounded in-process ring (``recent_spans``) —
enough for tests, debugging, and the management console to reconstruct
recent request trees without an external collector; an exporter callback
can be attached for anything heavier.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)

_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "aios_obs_current_span", default=None
)

_MAX_FINISHED = 2048
_finished: "deque[Span]" = deque(maxlen=_MAX_FINISHED)
_finished_lock = threading.Lock()
_exporter: Optional[Callable[["Span"], None]] = None


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str = ""
    start: float = field(default_factory=time.time)
    end: float = 0.0
    status: str = "ok"  # ok | error
    attributes: Dict[str, object] = field(default_factory=dict)

    def set_attribute(self, key: str, value: object) -> None:
        self.attributes[key] = value

    @property
    def duration_s(self) -> float:
        return max(0.0, (self.end or time.time()) - self.start)

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def current_span() -> Optional[Span]:
    return _current.get()


def current_traceparent() -> Optional[str]:
    span = _current.get()
    return span.traceparent if span is not None else None


def parse_traceparent(value: str) -> Optional[Tuple[str, str]]:
    """``traceparent`` header -> (trace_id, parent_span_id), or None."""
    m = _TRACEPARENT_RE.match(value.strip().lower()) if value else None
    return (m.group(1), m.group(2)) if m else None


def set_exporter(fn: Optional[Callable[[Span], None]]) -> None:
    """Attach a finished-span callback (None clears). The ring keeps
    filling either way. The default exporter (installed by the obs
    package) feeds the flight recorder so finished RPC spans fold into
    request timelines; deployments may replace it."""
    global _exporter
    _exporter = fn


def get_exporter() -> Optional[Callable[[Span], None]]:
    return _exporter


def recent_spans(name: str = "", limit: int = 100) -> List[Span]:
    """Most-recent finished spans, newest last; ``name`` is a substring
    filter."""
    with _finished_lock:
        spans = list(_finished)
    if name:
        spans = [s for s in spans if name in s.name]
    return spans[-limit:]


def clear_spans() -> None:
    """Drop the finished-span ring (test isolation)."""
    with _finished_lock:
        _finished.clear()


def _finish(span: Span, token, parent: Optional[Span]) -> None:
    span.end = time.time()
    try:
        _current.reset(token)
    except ValueError:
        # a generator finalized from a DIFFERENT context (a cancelled
        # stream handler torn down by the gRPC machinery) can't reset the
        # token. Restore the parent explicitly in this context; the
        # original thread may still hold the finished span — that's why
        # continue_span() never trusts ambient context for its fresh-root
        # fallback (server entry points on reused pool threads).
        _current.set(parent)
    with _finished_lock:
        _finished.append(span)
    exporter = _exporter
    if exporter is not None:
        try:
            exporter(span)
        except Exception:  # noqa: BLE001 - exporters must not break serving
            pass


@contextlib.contextmanager
def _run_span(span: Span, parent: Optional[Span]) -> Iterator[Span]:
    token = _current.set(span)
    try:
        yield span
    except BaseException as exc:
        span.status = "error"
        span.attributes.setdefault("error", repr(exc)[:200])
        raise
    finally:
        _finish(span, token, parent)


def start_span(name: str, **attributes: object):
    """Open a span as a child of the current one (same thread), or as a
    new trace root when there is none. Context manager."""
    parent = _current.get()
    span = Span(
        name=name,
        trace_id=parent.trace_id if parent else _new_trace_id(),
        span_id=_new_span_id(),
        parent_id=parent.span_id if parent else "",
        attributes=dict(attributes),
    )
    return _run_span(span, parent)


def continue_span(
    traceparent: Optional[str], name: str, **attributes: object
):
    """Open a span continuing a remote trace (server side of an RPC).
    A missing/malformed traceparent starts a FRESH ROOT — deliberately
    ignoring ambient context: server entry points run on reused pool
    threads, and a stale span left by a cross-context generator teardown
    (see _finish) must not adopt unrelated requests into a dead trace."""
    parsed = parse_traceparent(traceparent or "")
    if parsed is None:
        trace_id, parent_id = _new_trace_id(), ""
    else:
        trace_id, parent_id = parsed
    span = Span(
        name=name,
        trace_id=trace_id,
        span_id=_new_span_id(),
        parent_id=parent_id,
        attributes=dict(attributes),
    )
    return _run_span(span, None)
