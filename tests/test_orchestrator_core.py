"""Orchestrator core: goal engine, planner, router, autonomy ladder.

Model-based tests in the style of the reference's tests/integration/
test_orchestrator.rs — lifecycle/cascade/dependency semantics exercised
in-process with injected fake AI backends and tool executors.
"""

import json
import time

import pytest

from aios_tpu.orchestrator.agent_router import AgentRouter, TrackedAgent
from aios_tpu.orchestrator.autonomy import (
    AutonomyConfig,
    AutonomyLoop,
    heuristic_tool_calls,
    parse_tool_calls,
)
from aios_tpu.orchestrator.goal_engine import GoalEngine, Task
from aios_tpu.orchestrator.task_planner import (
    TaskPlanner,
    classify_complexity,
    extract_json_array,
    infer_required_tools,
    strip_think_tags,
)


# ---------------------------------------------------------------------------
# Goal engine
# ---------------------------------------------------------------------------


def test_goal_lifecycle_and_persistence(tmp_db_path):
    e = GoalEngine(tmp_db_path)
    g = e.submit_goal("check disk space", priority=7)
    assert g.status == "pending"
    t1 = Task(id="t1", goal_id=g.id, description="step 1")
    t2 = Task(id="t2", goal_id=g.id, description="step 2", depends_on=["t1"])
    e.add_tasks(g.id, [t1, t2])
    assert e.goals[g.id].status == "in_progress"

    # dependency gating
    unblocked = e.unblocked_pending_tasks()
    assert [t.id for t in unblocked] == ["t1"]
    e.complete_task("t1")
    assert [t.id for t in e.unblocked_pending_tasks()] == ["t2"]
    e.complete_task("t2")
    assert e.check_goal_completion(g.id) == "completed"
    assert e.progress(g.id) == 100.0

    # reload from SQLite
    e2 = GoalEngine(tmp_db_path)
    assert e2.goals[g.id].status == "completed"
    assert len(e2.tasks_for_goal(g.id)) == 2


def test_crash_recovery_resets_in_progress(tmp_db_path):
    e = GoalEngine(tmp_db_path)
    g = e.submit_goal("long running")
    t = Task(id="t1", goal_id=g.id, description="work")
    e.add_tasks(g.id, [t])
    e.set_task_status("t1", "in_progress", agent="agent-x")

    e2 = GoalEngine(tmp_db_path)
    n = e2.recover()
    assert n == 1
    assert e2.tasks["t1"].status == "pending"
    assert e2.tasks["t1"].assigned_agent == ""


def test_goal_cancellation_cascades():
    e = GoalEngine()
    g = e.submit_goal("cancel me")
    e.add_tasks(g.id, [Task(id="t1", goal_id=g.id, description="a"),
                       Task(id="t2", goal_id=g.id, description="b")])
    assert e.cancel_goal(g.id)
    assert all(t.status == "cancelled" for t in e.tasks_for_goal(g.id))
    assert not e.cancel_goal(g.id)  # already terminal


def test_failed_task_fails_goal():
    e = GoalEngine()
    g = e.submit_goal("will fail")
    e.add_tasks(g.id, [Task(id="t1", goal_id=g.id, description="a")])
    e.set_task_status("t1", "failed", error="boom")
    assert e.check_goal_completion(g.id) == "failed"


def test_conversation_thread():
    e = GoalEngine()
    g = e.submit_goal("chat goal")
    e.add_message(g.id, "user", "please do the thing")
    e.add_message(g.id, "assistant", "which thing?")
    msgs = e.messages_for_goal(g.id)
    assert [m.role for m in msgs] == ["user", "assistant"]
    assert e.count_messages(g.id, role="assistant") == 1


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


def test_classify_complexity_ladder():
    assert classify_complexity("ping 8.8.8.8") == "reactive"
    assert classify_complexity("restart the web service") == "operational"
    assert classify_complexity("investigate high memory usage") == "tactical"
    assert classify_complexity("design a backup system") == "strategic"


def test_infer_required_tools():
    assert "service" in infer_required_tools("restart nginx")
    assert "net" in infer_required_tools("check network connectivity")
    assert "sec" in infer_required_tools("run a security audit")
    assert infer_required_tools("compose a sonnet") == []


def test_think_tag_stripping_and_json_extraction():
    raw = '<think>hmm let me think</think>```json\n[{"description": "a"}]\n```'
    assert strip_think_tags(raw).startswith("```json")
    arr = extract_json_array(raw)
    assert arr == [{"description": "a"}]
    assert extract_json_array("no json here") is None
    assert extract_json_array('text before [1, 2, 3] after') == [1, 2, 3]


def test_operational_goal_single_task():
    p = TaskPlanner()
    e = GoalEngine()
    g = e.submit_goal("restart the nginx service")
    tasks = p.decompose_goal(g)
    assert len(tasks) == 1
    assert tasks[0].required_tools == ["service"]


def test_tactical_ai_decomposition_with_chaining():
    def fake_ai(prompt):
        return json.dumps([
            {"description": "scan ports", "required_tools": ["net"]},
            {"description": "check perms", "required_tools": ["sec"]},
            {"description": "summarize", "required_tools": ["monitor"]},
        ])

    p = TaskPlanner(gateway_infer=fake_ai)
    e = GoalEngine()
    g = e.submit_goal("audit the system security")
    tasks = p.decompose_goal(g)
    assert len(tasks) == 3
    assert tasks[0].depends_on == []
    assert tasks[1].depends_on == [tasks[0].id]
    assert tasks[2].depends_on == [tasks[1].id]
    assert all(t.intelligence_level == "tactical" for t in tasks)


def test_ai_decompose_falls_back_on_garbage_then_keywords():
    p = TaskPlanner(gateway_infer=lambda prompt: "I cannot help with that")
    e = GoalEngine()
    g = e.submit_goal("audit security posture")
    tasks = p.decompose_goal(g)
    assert len(tasks) >= 3  # keyword security fallback kicks in


def test_gateway_error_falls_to_runtime():
    def broken(prompt):
        raise RuntimeError("gateway down")

    def runtime(prompt):
        return '[{"description": "only step", "required_tools": ["fs"]}]'

    p = TaskPlanner(gateway_infer=broken, runtime_infer=runtime)
    e = GoalEngine()
    g = e.submit_goal("investigate the disk errors")
    tasks = p.decompose_goal(g)
    assert len(tasks) == 1
    assert tasks[0].required_tools == ["fs"]


# ---------------------------------------------------------------------------
# Agent router
# ---------------------------------------------------------------------------


def _agent(aid, namespaces, completed=0):
    return TrackedAgent(agent_id=aid, agent_type=aid.split("-")[0],
                        tool_namespaces=namespaces,
                        tasks_completed=completed)


def test_routing_prefers_idle_then_experienced():
    r = AgentRouter()
    r.register(_agent("sys-1", ["fs", "service"], completed=2))
    r.register(_agent("sys-2", ["fs", "service"], completed=9))
    busy = _agent("sys-3", ["fs", "service"], completed=50)
    busy.status = "busy"
    busy.current_task_id = "other"
    r.register(busy)

    t = Task(id="t", goal_id="g", description="x", required_tools=["service"])
    chosen = r.route_task(t)
    assert chosen == "sys-2"  # idle with most experience


def test_empty_required_tools_unroutable():
    r = AgentRouter()
    r.register(_agent("sys-1", ["fs"]))
    t = Task(id="t", goal_id="g", description="think about stuff")
    assert r.route_task(t) is None


def test_dead_agent_detection_and_requeue():
    r = AgentRouter()
    a = _agent("sys-1", ["fs"])
    r.register(a)
    t = Task(id="t", goal_id="g", description="x", required_tools=["fs"])
    assert r.route_task(t) == "sys-1"
    a.last_heartbeat -= 20  # simulate heartbeat timeout (15 s)
    assert [d.agent_id for d in r.dead_agents()] == ["sys-1"]
    requeued = r.requeue_from("sys-1")
    assert [t.id for t in requeued] == ["t"]


def test_polling_queue():
    r = AgentRouter()
    r.register(_agent("sys-1", ["fs"]))
    t = Task(id="t", goal_id="g", description="x", required_tools=["fs"])
    r.route_task(t)
    got = r.next_task_for("sys-1")
    assert got.id == "t"
    assert r.next_task_for("sys-1") is None


# ---------------------------------------------------------------------------
# Tool-call parsing
# ---------------------------------------------------------------------------


def test_parse_tool_calls_formats():
    calls, done, thought = parse_tool_calls(
        '{"thought": "checking", "tool_calls": [{"tool": "monitor.cpu", "args": {}}], "done": false}'
    )
    assert calls == [{"tool": "monitor.cpu", "args": {}}]
    assert not done and thought == "checking"

    calls, done, _ = parse_tool_calls('{"done": true, "thought": "all good", "tool_calls": []}')
    assert done and not calls

    calls, _, _ = parse_tool_calls(
        'Sure! ```json\n{"tool_calls": [{"name": "fs.read", "input": {"path": "/x"}}]}\n```'
    )
    assert calls == [{"tool": "fs.read", "args": {"path": "/x"}}]

    calls, _, _ = parse_tool_calls('I will call monitor.cpu({}) now')
    assert calls == [{"tool": "monitor.cpu", "args": {}}]


def test_heuristic_tool_mapping():
    t = Task(id="t", goal_id="g", description="check cpu usage")
    assert heuristic_tool_calls(t) == [{"tool": "monitor.cpu", "args": {}}]
    t2 = Task(id="t", goal_id="g", description="ping 1.1.1.1")
    assert heuristic_tool_calls(t2) == [{"tool": "net.ping",
                                         "args": {"host": "1.1.1.1"}}]
    t3 = Task(id="t", goal_id="g", description="write a haiku")
    assert heuristic_tool_calls(t3) is None
    t4 = Task(id="t", goal_id="g", description="custom",
              input={"tool_calls": [{"tool": "fs.list", "args": {"path": "/"}}]})
    assert heuristic_tool_calls(t4) == [{"tool": "fs.list",
                                         "args": {"path": "/"}}]


# ---------------------------------------------------------------------------
# Autonomy loop (injected fakes, no sockets)
# ---------------------------------------------------------------------------


class FakeTools:
    def __init__(self, fail_on=()):
        self.calls = []
        self.fail_on = set(fail_on)

    def __call__(self, tool, agent_id, args):
        self.calls.append((tool, agent_id, args))
        if tool in self.fail_on:
            return {"success": False, "output": {}, "error": f"{tool} broke"}
        return {"success": True, "output": {"tool": tool, "ok": True},
                "error": ""}


def _loop(engine, planner=None, tools=None, gateway=None, runtime=None):
    return AutonomyLoop(
        engine=engine,
        planner=planner or TaskPlanner(),
        router=AgentRouter(),
        execute_tool=tools or FakeTools(),
        gateway_infer=gateway,
        runtime_infer=runtime,
        config=AutonomyConfig(tick_interval=0.01),
    )


def _drain(loop, timeout=10.0):
    """Tick until no pending/in-flight work or timeout."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        loop.tick()
        pending = loop.engine.unblocked_pending_tasks(limit=100)
        with loop._lock:
            busy = bool(loop._in_flight)
        if not pending and not busy:
            return
        time.sleep(0.02)


def test_heuristic_path_completes_goal():
    e = GoalEngine()
    tools = FakeTools()
    loop = _loop(e, tools=tools)
    g = e.submit_goal("check cpu usage")
    _drain(loop)
    assert e.goals[g.id].status == "completed"
    assert tools.calls[0][0] == "monitor.cpu"


def test_reasoning_token_budget_per_level():
    """Every AI call carries the per-level reasoning token budget
    (autonomy.rs:596-607: 2048/2048/8192/16384), which the production
    closures forward as InferRequest.max_tokens (orchestrator/main.py)."""
    from aios_tpu.orchestrator.autonomy import TOKEN_BUDGETS

    e = GoalEngine()
    captured = []

    def gateway(prompt, level, max_tokens):
        captured.append((level, max_tokens))
        return '{"thought": "ok", "tool_calls": [], "done": true}'

    loop = _loop(e, gateway=gateway)
    levels = ("reactive", "operational", "tactical", "strategic")
    for level in levels:
        assert loop._ai_infer("prompt", level) is not None
    assert captured == [(lvl, TOKEN_BUDGETS[lvl]) for lvl in levels]

    # two-arg backends (legacy fakes) are still accepted, budget elided
    loop2 = _loop(e, gateway=lambda p, lvl: "plain")
    assert loop2._ai_infer("prompt", "tactical") == "plain"


def test_ai_reasoning_loop_multi_round():
    e = GoalEngine()
    tools = FakeTools()
    replies = iter([
        '{"thought": "inspect", "tool_calls": [{"tool": "monitor.logs", "args": {}}], "done": false}',
        '{"thought": "fixed the problem", "tool_calls": [], "done": true}',
    ])

    loop = _loop(e, tools=tools, gateway=lambda p, lvl: next(replies))
    g = e.submit_goal("investigate strange log entries")  # tactical, 3 rounds
    _drain(loop)
    assert e.goals[g.id].status == "completed"
    task = e.tasks_for_goal(g.id)[0]
    assert task.output["answer"] == "fixed the problem"
    assert tools.calls[0][0] == "monitor.logs"


def test_tool_failure_fails_task_and_goal():
    e = GoalEngine()
    tools = FakeTools(fail_on={"monitor.cpu"})
    loop = _loop(e, tools=tools)
    g = e.submit_goal("check cpu usage")
    _drain(loop)
    assert e.goals[g.id].status == "failed"
    assert "broke" in e.tasks_for_goal(g.id)[0].error


def test_json_self_correction_round():
    e = GoalEngine()
    tools = FakeTools()
    replies = iter([
        "sorry, here is prose with no JSON at all",
        '{"thought": "ok", "tool_calls": [{"tool": "fs.list", "args": {"path": "/tmp"}}], "done": true}',
    ])
    prompts = []

    def gateway(p, lvl):
        prompts.append(p)
        return next(replies)

    loop = _loop(e, tools=tools, gateway=gateway)
    g = e.submit_goal("tidy up temp folder somehow")  # operational -> 1 round
    _drain(loop)
    assert e.goals[g.id].status == "completed"
    assert "not valid JSON" in prompts[1]


def test_zero_tool_calls_awaits_input_then_fails():
    e = GoalEngine()
    loop = _loop(
        e,
        gateway=lambda p, lvl: '{"thought": "what exactly should I delete?", "tool_calls": [], "done": true}',
    )
    g = e.submit_goal("handle the thing appropriately")
    for _ in range(12):
        loop.tick()
        time.sleep(0.05)
    # after MAX_AI_MESSAGES assistant questions, the task fails
    _drain(loop)
    assert e.goals[g.id].status == "failed"
    assert e.count_messages(g.id, role="assistant") >= 3


def test_no_ai_backend_fails_ai_task():
    e = GoalEngine()
    loop = _loop(e)  # neither gateway nor runtime
    g = e.submit_goal("compose a summary of recent activity")
    _drain(loop)
    assert e.goals[g.id].status == "failed"
    assert "no AI backend" in e.tasks_for_goal(g.id)[0].error


def test_agent_routing_preferred_over_ai():
    e = GoalEngine()
    loop = _loop(e)
    agent = TrackedAgent(agent_id="system_agent-1", agent_type="system",
                         tool_namespaces=["service", "monitor"])
    loop.router.register(agent)
    g = e.submit_goal("restart the nginx service")
    loop.tick()
    task = e.tasks_for_goal(g.id)[0]
    assert task.status == "assigned"
    assert task.assigned_agent == "system_agent-1"
    # the agent polls it
    polled = loop.router.next_task_for("system_agent-1")
    assert polled.id == task.id


def test_terminal_task_states_are_final():
    """A late success/failure report must not resurrect a cancelled task
    (the cancel record would be silently overwritten)."""
    e = GoalEngine()
    g = e.submit_goal("cancel me mid-flight")
    e.add_tasks(g.id, [Task(id="t1", goal_id=g.id, description="a")])
    e.set_task_status("t1", "in_progress")
    assert e.cancel_goal(g.id)
    e.complete_task("t1", output={"late": "report"})
    assert e.tasks["t1"].status == "cancelled"
    e.set_task_status("t1", "failed", error="late failure")
    assert e.tasks["t1"].status == "cancelled"
    # same terminal state re-set stays a no-op-safe path
    e.set_task_status("t1", "cancelled")
    assert e.tasks["t1"].status == "cancelled"


def test_reasoning_loop_stops_on_cancelled_goal():
    """CancelGoal mid-reasoning: the loop must not run further AI rounds
    or tool calls for a dead goal (checked between rounds)."""
    e = GoalEngine()
    tools = FakeTools()
    ai_calls = []

    def gateway(prompt, level, json_schema=""):
        ai_calls.append(level)
        # cancel the goal the moment the FIRST reply lands; reply carries
        # a tool call so an unchecked loop would keep going for up to
        # 5 strategic rounds
        e.cancel_goal(goal_holder["id"])
        return json.dumps({
            "thought": "working",
            "tool_calls": [{"tool": "monitor.cpu", "args": {}}],
            "done": False,
        })

    loop = _loop(e, tools=tools, gateway=gateway)
    goal_holder = {}
    g = e.submit_goal(
        "design and implement a comprehensive multi-phase migration plan "
        "for the storage architecture"  # strategic-complexity wording
    )
    goal_holder["id"] = g.id
    _drain(loop)
    assert len(ai_calls) == 1, f"loop kept reasoning: {ai_calls}"
    # the cancelled task was not resurrected by a late record
    for t in e.tasks_for_goal(g.id):
        assert t.status == "cancelled"


def test_cancel_during_decomposition_not_resurrected():
    """CancelGoal landing while the planner's slow AI decomposition runs:
    the late add_tasks must not flip the cancelled goal back to
    in_progress, and its tasks must arrive cancelled, not as dispatchable
    strays."""
    e = GoalEngine()
    g = e.submit_goal("cancel mid-planning")
    e.set_goal_status(g.id, "planning")
    assert e.cancel_goal(g.id)
    e.add_tasks(g.id, [Task(id="late1", goal_id=g.id, description="a"),
                       Task(id="late2", goal_id=g.id, description="b")])
    assert e.goals[g.id].status == "cancelled"
    assert all(t.status == "cancelled" for t in e.tasks_for_goal(g.id))
    assert e.unblocked_pending_tasks(limit=10) == []


def test_duplicate_terminal_report_keeps_first_payload():
    """An agent retry after a dropped response re-reports a completed
    task: the duplicate must not overwrite the first report's output."""
    e = GoalEngine()
    g = e.submit_goal("report twice")
    e.add_tasks(g.id, [Task(id="t1", goal_id=g.id, description="a")])
    e.complete_task("t1", output={"first": True})
    e.complete_task("t1", output={"second": True})
    assert e.tasks["t1"].output == {"first": True}


def test_cancel_aborts_in_flight_inference():
    """notify_goal_cancelled (the CancelGoal hook) must abort the AI call
    that is IN FLIGHT right now — via the cancel_event threaded to the
    backend — and the loop must return without recording a failure."""
    import threading

    from aios_tpu.orchestrator.autonomy import InferenceCancelled

    e = GoalEngine()
    started = threading.Event()
    released = threading.Event()
    state = {"cancelled_seen": False, "calls": 0}

    def gateway(prompt, level, max_tokens, json_schema="", cancel_event=None):
        state["calls"] += 1
        started.set()
        # block like a slow AI call until the cancel (or give up)
        if cancel_event.wait(timeout=20):
            state["cancelled_seen"] = True
            released.set()
            raise InferenceCancelled()
        released.set()
        return json.dumps({"thought": "x", "tool_calls": [], "done": True})

    loop = _loop(e, gateway=gateway)
    g = e.submit_goal(
        "design a comprehensive multi-phase migration strategy for storage"
    )
    # drive ticks until the AI call is in flight
    deadline = time.time() + 10
    while not started.is_set() and time.time() < deadline:
        loop.tick()
        time.sleep(0.01)
    assert started.is_set()
    e.cancel_goal(g.id)
    loop.notify_goal_cancelled(g.id)
    assert released.wait(timeout=10), "backend never saw the cancel"
    _drain(loop)
    assert state["cancelled_seen"] and state["calls"] == 1
    for t in e.tasks_for_goal(g.id):
        assert t.status == "cancelled", t.status  # no failure recorded
