"""Tokenizers: GGUF-embedded SentencePiece-BPE, HF wrapper, byte fallback.

llama-server tokenizes with the vocab embedded in the GGUF file; to replace
it with zero extra assets we implement the same SentencePiece-style BPE
(greedy best-score pair merging with byte fallback) directly over the GGUF
metadata arrays (tokenizer.ggml.tokens/scores/token_type). When a HF model
directory is available we defer to transformers instead. Chat templating for
the reference's prompt/system_prompt pair (runtime.proto InferRequest)
follows each family's native format.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

# token_type values in GGUF (llama.cpp llama_token_type)
TOKEN_TYPE_NORMAL = 1
TOKEN_TYPE_UNKNOWN = 2
TOKEN_TYPE_CONTROL = 3
TOKEN_TYPE_USER_DEFINED = 4
TOKEN_TYPE_BYTE = 6

SPIECE_SPACE = "▁"  # ▁


class BaseTokenizer:
    bos_id: Optional[int] = None
    eos_id: Optional[int] = None

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int]) -> str:
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError


@dataclass
class SentencePieceBPE(BaseTokenizer):
    """SentencePiece-style BPE over a GGUF vocab (llama/mistral models)."""

    tokens: List[str]
    scores: List[float]
    token_types: List[int]
    bos_id: Optional[int] = 1
    eos_id: Optional[int] = 2
    add_prefix_space: bool = True
    _index: Dict[str, int] = field(default_factory=dict, repr=False)
    _byte_ids: Dict[int, int] = field(default_factory=dict, repr=False)

    def __post_init__(self):
        self._index = {t: i for i, t in enumerate(self.tokens)}
        for i, (tok, typ) in enumerate(zip(self.tokens, self.token_types)):
            if typ == TOKEN_TYPE_BYTE and tok.startswith("<0x") and tok.endswith(">"):
                self._byte_ids[int(tok[3:-1], 16)] = i

    @classmethod
    def from_gguf_metadata(cls, md: dict) -> "SentencePieceBPE":
        tokens = md["tokenizer.ggml.tokens"]
        n = len(tokens)
        return cls(
            tokens=tokens,
            scores=list(md.get("tokenizer.ggml.scores", [0.0] * n)),
            token_types=list(md.get("tokenizer.ggml.token_type", [1] * n)),
            bos_id=int(md.get("tokenizer.ggml.bos_token_id", 1)),
            eos_id=int(md.get("tokenizer.ggml.eos_token_id", 2)),
        )

    @property
    def vocab_size(self) -> int:
        return len(self.tokens)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        if self.add_prefix_space and not text.startswith(" "):
            text = " " + text
        text = text.replace(" ", SPIECE_SPACE)

        # initial symbols: one per character; unknowns byte-fall-back at the end
        symbols = list(text)

        def piece_score(s: str) -> Optional[float]:
            i = self._index.get(s)
            if i is None:
                return None
            return self.scores[i] if i < len(self.scores) else 0.0

        # greedy best-score merge (SentencePiece BPE semantics)
        while len(symbols) > 1:
            best_idx, best_score = -1, None
            for i in range(len(symbols) - 1):
                merged = symbols[i] + symbols[i + 1]
                sc = piece_score(merged)
                if sc is not None and (best_score is None or sc > best_score):
                    best_idx, best_score = i, sc
            if best_idx < 0:
                break
            symbols[best_idx : best_idx + 2] = [symbols[best_idx] + symbols[best_idx + 1]]

        ids: List[int] = []
        if add_bos and self.bos_id is not None:
            ids.append(self.bos_id)
        for sym in symbols:
            idx = self._index.get(sym)
            if idx is not None:
                ids.append(idx)
                continue
            for b in sym.encode("utf-8"):  # byte fallback
                bid = self._byte_ids.get(b)
                if bid is not None:
                    ids.append(bid)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out: List[str] = []
        byte_run: List[int] = []

        def flush_bytes():
            if byte_run:
                out.append(bytes(byte_run).decode("utf-8", errors="replace"))
                byte_run.clear()

        for i in ids:
            if not 0 <= i < len(self.tokens):
                continue
            typ = self.token_types[i] if i < len(self.token_types) else 1
            if typ == TOKEN_TYPE_BYTE:
                tok = self.tokens[i]
                byte_run.append(int(tok[3:-1], 16))
                continue
            flush_bytes()
            if typ == TOKEN_TYPE_CONTROL:
                continue
            out.append(self.tokens[i])
        flush_bytes()
        return "".join(out).replace(SPIECE_SPACE, " ").lstrip(" ")


def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's byte<->printable-unicode table (every byte gets a visible
    char so BPE merges operate on strings)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


# pretokenizer split patterns by GGUF `tokenizer.ggml.pre` family; the
# regex module supports the \p{} classes these need
_PRE_PATTERNS = {
    "gpt2": r"""'(?:[sdmt]|ll|ve|re)| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""",
    "qwen2": r"""(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+""",
    "llama3": r"""(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+""",
}

# the `pre` strings convert_hf_to_gguf actually writes -> pattern family
# (nearest approximation where llama.cpp has a bespoke regex)
_PRE_ALIASES = {
    "llama-bpe": "llama3",  # Llama-3 vocabs (incl. DeepSeek-R1-Distill)
    "llama3": "llama3",
    "qwen2": "qwen2",
    "deepseek-r1-qwen": "qwen2",  # qwen2-derived split (digits singly)
    "deepseek-llm": "gpt2",
    "gpt-2": "gpt2",
}


@dataclass
class ByteLevelBPE(BaseTokenizer):
    """GPT-2-style byte-level BPE over a GGUF vocab — the tokenizer family
    of the Qwen3 / Qwen3-MoE / DeepSeek-R1-Distill (Llama-3 vocab) tiers
    (GGUF ``tokenizer.ggml.model == "gpt2"``; rank-ordered merges in
    ``tokenizer.ggml.merges``). Special (control/user-defined) tokens are
    split out of the text before the merge loop, so chat-template markers
    like <|im_start|> encode to their single ids."""

    tokens: List[str]
    merges: List[str]  # "left right" pairs, rank = list position
    token_types: List[int]
    bos_id: Optional[int] = None
    eos_id: Optional[int] = None
    pre: str = "gpt2"
    # llama.cpp defaults add_bos FALSE for BPE vocabs (true only when the
    # GGUF says so); real Qwen GGUFs declare bos_token_id=<endoftext> WITH
    # add_bos_token=false, so bos_id being set must not imply prepending
    add_bos: bool = False
    _index: Dict[str, int] = field(default_factory=dict, repr=False)
    _ranks: Dict[tuple, int] = field(default_factory=dict, repr=False)
    _b2u: Dict[int, str] = field(default_factory=dict, repr=False)
    _u2b: Dict[str, int] = field(default_factory=dict, repr=False)
    _cache: Dict[str, List[str]] = field(default_factory=dict, repr=False)

    def __post_init__(self):
        import regex

        self._index = {t: i for i, t in enumerate(self.tokens)}
        self._ranks = {
            tuple(m.split(" ", 1)): r for r, m in enumerate(self.merges)
        }
        self._b2u = _bytes_to_unicode()
        self._u2b = {c: b for b, c in self._b2u.items()}
        self._pat = regex.compile(
            _PRE_PATTERNS[_PRE_ALIASES.get(self.pre, "gpt2")]
        )
        specials = [
            t
            for t, typ in zip(self.tokens, self.token_types)
            if typ in (TOKEN_TYPE_CONTROL, TOKEN_TYPE_USER_DEFINED)
        ]
        self._special_pat = None
        if specials:
            self._special_pat = regex.compile(
                "("
                + "|".join(
                    regex.escape(t)
                    for t in sorted(specials, key=len, reverse=True)
                )
                + ")"
            )

    @classmethod
    def from_gguf_metadata(cls, md: dict) -> "ByteLevelBPE":
        tokens = md["tokenizer.ggml.tokens"]
        n = len(tokens)
        bos = md.get("tokenizer.ggml.bos_token_id")
        eos = md.get("tokenizer.ggml.eos_token_id")
        return cls(
            tokens=tokens,
            merges=list(md.get("tokenizer.ggml.merges", [])),
            token_types=list(md.get("tokenizer.ggml.token_type", [1] * n)),
            bos_id=int(bos) if bos is not None else None,
            eos_id=int(eos) if eos is not None else None,
            pre=md.get("tokenizer.ggml.pre", "gpt2"),
            add_bos=bool(md.get("tokenizer.ggml.add_bos_token", False)),
        )

    @property
    def vocab_size(self) -> int:
        return len(self.tokens)

    def _bpe(self, word: str) -> List[str]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        syms = list(word)
        while len(syms) > 1:
            best, best_rank = None, None
            for i in range(len(syms) - 1):
                r = self._ranks.get((syms[i], syms[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = i, r
            if best is None:
                break
            syms[best : best + 2] = [syms[best] + syms[best + 1]]
        if len(self._cache) < 65536:
            self._cache[word] = syms
        return syms

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids: List[int] = []
        # bos is prepended only when the GGUF's add_bos_token flag says so
        # (self.add_bos) — a declared bos_token_id alone must not trigger
        # it (Qwen GGUFs set bos_token_id=<endoftext>, add_bos_token=false)
        if add_bos and self.add_bos and self.bos_id is not None:
            ids.append(self.bos_id)
        chunks = (
            self._special_pat.split(text) if self._special_pat else [text]
        )
        for chunk in chunks:
            if not chunk:
                continue
            sid = self._index.get(chunk)
            if sid is not None and self._special_pat and (
                self.token_types[sid]
                in (TOKEN_TYPE_CONTROL, TOKEN_TYPE_USER_DEFINED)
            ):
                ids.append(sid)
                continue
            for m in self._pat.finditer(chunk):
                word = "".join(
                    self._b2u[b] for b in m.group().encode("utf-8")
                )
                for piece in self._bpe(word):
                    idx = self._index.get(piece)
                    if idx is not None:
                        ids.append(idx)
                    else:  # single-char fallback (vocab covers all bytes)
                        ids.extend(
                            self._index[c] for c in piece if c in self._index
                        )
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        chars: List[str] = []
        for i in ids:
            if not 0 <= i < len(self.tokens):
                continue
            typ = self.token_types[i] if i < len(self.token_types) else 1
            if typ == TOKEN_TYPE_CONTROL:
                continue
            chars.append(self.tokens[i])
        data = bytes(
            b
            for ch in "".join(chars)
            for b in (
                [self._u2b[ch]]
                if ch in self._u2b
                else ch.encode("utf-8")  # user-defined tokens pass through
            )
        )
        return data.decode("utf-8", errors="replace")


def gguf_tokenizer(md: dict) -> BaseTokenizer:
    """Build the right tokenizer for a GGUF file's embedded vocab:
    ``tokenizer.ggml.model`` "gpt2" (byte-level BPE — Qwen/Llama-3/DeepSeek
    families) vs "llama" (SentencePiece BPE — Llama/Mistral families)."""
    model = md.get("tokenizer.ggml.model", "llama")
    if model == "gpt2":
        return ByteLevelBPE.from_gguf_metadata(md)
    return SentencePieceBPE.from_gguf_metadata(md)


class HFTokenizer(BaseTokenizer):
    """transformers-backed tokenizer for HF model directories."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


class ByteTokenizer(BaseTokenizer):
    """256-symbol byte tokenizer — synthetic models, benches, smoke tests."""

    bos_id = 256
    eos_id = 257

    @property
    def vocab_size(self) -> int:
        return 258

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


# ---------------------------------------------------------------------------
# Serialization (model checkpoints carry their tokenizer, like GGUF does)
# ---------------------------------------------------------------------------


def tokenizer_to_dict(tok: BaseTokenizer) -> dict:
    if isinstance(tok, SentencePieceBPE):
        return {
            "type": "spbpe",
            "tokens": tok.tokens,
            "scores": tok.scores,
            "token_types": tok.token_types,
            "bos_id": tok.bos_id,
            "eos_id": tok.eos_id,
            "add_prefix_space": tok.add_prefix_space,
        }
    if isinstance(tok, ByteLevelBPE):
        return {
            "type": "blbpe",
            "tokens": tok.tokens,
            "merges": tok.merges,
            "token_types": tok.token_types,
            "bos_id": tok.bos_id,
            "eos_id": tok.eos_id,
            "pre": tok.pre,
            "add_bos": tok.add_bos,
        }
    if isinstance(tok, HFTokenizer):
        return {"type": "hf", "path": tok._tok.name_or_path}
    return {"type": "byte"}


def tokenizer_from_dict(d: dict) -> BaseTokenizer:
    t = d.get("type", "byte")
    if t == "spbpe":
        return SentencePieceBPE(
            tokens=list(d["tokens"]),
            scores=list(d["scores"]),
            token_types=list(d["token_types"]),
            bos_id=d.get("bos_id"),
            eos_id=d.get("eos_id"),
            add_prefix_space=d.get("add_prefix_space", True),
        )
    if t == "blbpe":
        return ByteLevelBPE(
            tokens=list(d["tokens"]),
            merges=list(d["merges"]),
            token_types=list(d["token_types"]),
            bos_id=d.get("bos_id"),
            eos_id=d.get("eos_id"),
            pre=d.get("pre", "gpt2"),
            add_bos=bool(d.get("add_bos", False)),
        )
    if t == "hf":
        return HFTokenizer(d["path"])
    return ByteTokenizer()


# ---------------------------------------------------------------------------
# Chat templating (llama-server applied the GGUF chat template; we do the
# same per model family for the prompt/system_prompt pair)
# ---------------------------------------------------------------------------


def render_chat(
    family: str, prompt: str, system_prompt: str = ""
) -> str:
    """Render a single-turn chat for the given model family."""
    fam = family.lower()
    if "tinyllama" in fam or "zephyr" in fam:
        parts = []
        if system_prompt:
            parts.append(f"<|system|>\n{system_prompt}</s>\n")
        parts.append(f"<|user|>\n{prompt}</s>\n<|assistant|>\n")
        return "".join(parts)
    if "mistral" in fam:
        sys = f"{system_prompt}\n\n" if system_prompt else ""
        return f"[INST] {sys}{prompt} [/INST]"
    if "qwen" in fam or "deepseek" in fam or "chatml" in fam:
        parts = []
        if system_prompt:
            parts.append(f"<|im_start|>system\n{system_prompt}<|im_end|>\n")
        parts.append(f"<|im_start|>user\n{prompt}<|im_end|>\n<|im_start|>assistant\n")
        return "".join(parts)
    sys = f"System: {system_prompt}\n\n" if system_prompt else ""
    return f"{sys}User: {prompt}\n\nAssistant:"
