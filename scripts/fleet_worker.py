#!/usr/bin/env python3
"""One fleet-smoke member process: a real runtime service on ephemeral
ports with the fleet telemetry plane armed.

Spawned by scripts/fleet_smoke.py (and the slow tier of
tests/test_fleet.py) with the fleet env already set — AIOS_TPU_FLEET,
AIOS_TPU_FLEET_HOST, AIOS_TPU_FLEET_PEERS, the interval/suspect/dead
windows. Loads one synthetic model, binds gRPC and metrics on port 0,
prints ONE ready line

    FLEET_WORKER_READY {"grpc_port": N, "metrics_port": M}

then blocks until stdin closes (the parent's shutdown signal — cleaner
than SIGTERM racing the heartbeat thread) or it is killed (the failure-
detection half of the smoke kills a worker mid-flight on purpose).
"""

import json
import os
import sys

# CPU-only child: never let the TPU-tunnel site hook register its PJRT
# plugin, and keep XLA on the host platform (multihost_worker.py idiom)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

MODEL = "fleet-smoke"


def main() -> int:
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    manager = ModelManager(num_slots=2, warm_compile=False)
    manager.load_model(MODEL, "synthetic://tiny-test", context_length=256)
    server, service, port = serve(
        address="127.0.0.1:0", manager=manager, block=False,
        metrics_port=0,
    )
    print("FLEET_WORKER_READY " + json.dumps({
        "grpc_port": port, "metrics_port": service.metrics_port,
    }), flush=True)
    sys.stdin.read()  # parent closes stdin to shut us down
    server.stop(grace=None)
    if service.metrics_server is not None:
        service.metrics_server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
