"""Int8-weight matmul: weights stream from HBM as int8, dequantize in VMEM.

Batched decode is HBM-bandwidth-bound on the weight matrices (measured on
v5e: a full bf16 weight sweep of TinyLlama-1.1B costs ~4.3 ms — ~70% of the
whole decode step). Storing weights as int8 with per-output-channel scales
halves the streamed bytes; the kernel converts each int8 tile to bf16 in
VMEM immediately before the MXU dot, so the bf16 copy never exists in HBM.
XLA cannot be trusted to do this: an ``x @ w_int8.astype(bf16)`` graph may
materialize the converted weight.

Quantization is symmetric per-output-channel (scale = absmax/127 over the
contraction axis), the same scheme GGUF Q8_0 uses per-block
(SURVEY.md section 7 "GGUF Q4_K_M dequantization" — here quantization is a
serving-time memory format, not a storage format).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def quantize_int8(w: jnp.ndarray, axis: int = -2) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization along ``axis`` (the contraction dim).

    Returns (w_q int8, scale f32) with scale shaped like w but size 1 on
    ``axis`` — for a [K, N] weight that is [1, N] (per-output-channel).
    """
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    w_q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return w_q, scale.astype(jnp.float32)


def dequantize(w_q: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    return (w_q.astype(jnp.float32) * scale).astype(dtype)


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr):
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    w = w_ref[:].astype(x_ref.dtype)  # int8 tile -> bf16 in VMEM
    acc_scr[:] += jax.lax.dot_general(
        x_ref[:],
        w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] * s_ref[:]).astype(o_ref.dtype)


def _pick_block(dim: int, candidates=(512, 256, 128)) -> int:
    for c in candidates:
        if dim % c == 0:
            return c
    return 0


# Rows of x processed per grid step. Bounds the VMEM footprint for large-M
# callers (prefill/training): x block bm*bk*2B + scratch bm*bn*4B stay well
# under a v5e core's ~16 MB VMEM regardless of sequence length.
M_BLOCK = 256


@functools.partial(jax.jit, static_argnames=("interpret",))
def _qmm_2d(x, w_q, scale, interpret=False):
    M, K = x.shape
    N = w_q.shape[1]
    bm = M if M <= M_BLOCK else M_BLOCK  # callers pad M to a multiple
    bk, bn = _pick_block(K), _pick_block(N)
    grid = (M // bm, N // bn, K // bk)
    return pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, i, j: (m, j)),
            pl.BlockSpec((bk, bn), lambda m, i, j: (j, i)),
            pl.BlockSpec((1, bn), lambda m, i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, i, j: (m, i)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, w_q, scale)


def supports_pallas_qmm(K: int, N: int) -> bool:
    """Kernel needs 128-multiple-aligned blocks on both matmul dims."""
    return _pick_block(K) > 0 and _pick_block(N) > 0


def quantized_matmul(
    x: jnp.ndarray,  # [..., K] activations (bf16/f32)
    w_q: jnp.ndarray,  # [K, N] int8
    scale: jnp.ndarray,  # [1, N] f32
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """x @ dequant(w_q) without ever materializing the dequantized weight."""
    K, N = w_q.shape
    lead = x.shape[:-1]
    M = 1
    for d in lead:
        M *= d
    x2 = x.reshape(M, K)
    # sublane alignment for small decode batches; multiple of M_BLOCK for
    # large prefill/training M so the kernel's M grid divides evenly
    pad = (-M) % (8 if M <= M_BLOCK else M_BLOCK)
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = _qmm_2d(x2, w_q, scale, interpret=interpret)
    if pad:
        out = out[:M]
    return out.reshape(*lead, N)


def quantized_matmul_reference(x, w_q, scale):
    """Dequantize-then-matmul ground truth (CPU fallback)."""
    w = dequantize(w_q, scale, dtype=jnp.float32)
    return (x.astype(jnp.float32) @ w).astype(x.dtype)
