"""Boot layer: config, hardware detection, topo-sorted service supervision.

Reference: initd/ (PID-1 aios-init, SURVEY.md section 2 row 1). On a TPU-VM
deployment this runs as an ordinary supervisor process rather than PID 1 —
the QEMU/ISO path of the reference is replaced by TPU-VM host provisioning
(scripts/deploy-tpu-vm.sh).
"""
