"""Proactive goal generation.

Reference parity (agent-core/src/proactive.rs): a 60 s loop that auto-creates
remediation goals on CPU > 90%, memory > 85%, disk > 90%, failed agents,
>= 6 consecutive service-health failures, TLS certs expiring within 30 days,
and backups staler than 24 h (proactive.rs:74-200), deduplicating against
already-active goals.
"""

from __future__ import annotations

import subprocess
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, List, Optional

import psutil


@dataclass
class ProactiveConfig:
    interval: float = 60.0
    cpu_threshold: float = 90.0
    memory_threshold: float = 85.0
    disk_threshold: float = 90.0
    health_failure_threshold: int = 6
    cert_warning_days: int = 30
    backup_max_age_hours: float = 24.0
    cert_dir: str = "/tmp/aios/certs"
    backup_dir: str = "/tmp/aios/backups"


class ProactiveGenerator:
    def __init__(
        self,
        submit_goal: Callable[[str, int], object],
        active_goal_descriptions: Callable[[], List[str]],
        health_failures: Optional[Callable[[], dict]] = None,
        failed_agents: Optional[Callable[[], List[str]]] = None,
        config: Optional[ProactiveConfig] = None,
    ):
        self.submit_goal = submit_goal
        self.active_goal_descriptions = active_goal_descriptions
        self.health_failures = health_failures
        self.failed_agents = failed_agents
        self.config = config or ProactiveConfig()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _maybe_submit(self, description: str, priority: int) -> bool:
        """Dedupe against active goals (proactive.rs dedupe)."""
        key = description.lower()[:40]
        for active in self.active_goal_descriptions():
            if key in active.lower():
                return False
        self.submit_goal(description, priority)
        return True

    def check_once(self) -> List[str]:
        """One pass; returns descriptions of goals created."""
        cfg = self.config
        created: List[str] = []

        cpu = psutil.cpu_percent(interval=None)
        if cpu > cfg.cpu_threshold:
            if self._maybe_submit(
                f"Investigate and reduce high CPU usage ({cpu:.0f}%)", 8
            ):
                created.append("cpu")

        mem = psutil.virtual_memory().percent
        if mem > cfg.memory_threshold:
            if self._maybe_submit(
                f"Investigate and reduce high memory usage ({mem:.0f}%)", 8
            ):
                created.append("memory")

        disk = psutil.disk_usage("/").percent
        if disk > cfg.disk_threshold:
            if self._maybe_submit(
                f"Free disk space on / (at {disk:.0f}%)", 9
            ):
                created.append("disk")

        if self.failed_agents is not None:
            for agent in self.failed_agents():
                if self._maybe_submit(
                    f"Recover failed agent {agent}", 7
                ):
                    created.append(f"agent:{agent}")

        if self.health_failures is not None:
            for service, failures in self.health_failures().items():
                if failures >= cfg.health_failure_threshold:
                    if self._maybe_submit(
                        f"Remediate unhealthy service {service}"
                        f" ({failures} consecutive failures)", 9
                    ):
                        created.append(f"service:{service}")

        created.extend(self._check_certs())
        created.extend(self._check_backups())
        return created

    def _check_certs(self) -> List[str]:
        created = []
        cert_dir = Path(self.config.cert_dir)
        if not cert_dir.is_dir():
            return created
        for cert in cert_dir.glob("*.crt"):
            days = cert_expiry_days(str(cert))
            if days is not None and days < self.config.cert_warning_days:
                if self._maybe_submit(
                    f"Rotate TLS certificate {cert.name}"
                    f" (expires in {days} days)", 6
                ):
                    created.append(f"cert:{cert.name}")
        return created

    def _check_backups(self) -> List[str]:
        backup_dir = Path(self.config.backup_dir)
        if not backup_dir.is_dir():
            return []
        newest = 0.0
        for f in backup_dir.iterdir():
            try:
                newest = max(newest, f.stat().st_mtime)
            except OSError:
                continue
        if newest == 0.0:
            return []
        age_hours = (time.time() - newest) / 3600
        if age_hours > self.config.backup_max_age_hours:
            if self._maybe_submit(
                f"Run system backup (last backup {age_hours:.0f}h ago)", 5
            ):
                return ["backup"]
        return []

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.config.interval):
                try:
                    self.check_once()
                except Exception:  # noqa: BLE001
                    pass

        self._thread = threading.Thread(target=loop, name="proactive",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


def cert_expiry_days(cert_path: str) -> Optional[int]:
    """Days until a PEM cert expires (openssl-based; rcgen in the reference)."""
    try:
        out = subprocess.run(
            ["openssl", "x509", "-enddate", "-noout", "-in", cert_path],
            capture_output=True, text=True, timeout=10,
        )
        if out.returncode != 0:
            return None
        # notAfter=Jan  1 00:00:00 2027 GMT
        raw = out.stdout.strip().split("=", 1)[1]
        expiry = time.mktime(time.strptime(raw, "%b %d %H:%M:%S %Y %Z"))
        return int((expiry - time.time()) / 86400)
    except (OSError, ValueError, IndexError):
        return None
