"""aios.tools.ToolRegistry gRPC service.

Reference parity: tools/src/main.rs — ListTools/GetTool/Execute/Rollback/
Register/Deregister over the executor pipeline (binds 0.0.0.0:50052,
main.rs:330).
"""

from __future__ import annotations

import json
import logging
from typing import Optional

import grpc

from .. import rpc
from ..proto_gen import tools_pb2 as pb
from ..services import TOOLS, ToolRegistryServicer, service_address
from .executor import ToolExecutor

log = logging.getLogger("aios.tools")


class ToolRegistryService(ToolRegistryServicer):
    def __init__(self, executor: Optional[ToolExecutor] = None):
        self.executor = executor or ToolExecutor()

    def ListTools(self, request, context):
        defs = self.executor.list_definitions(namespace=request.namespace)
        return pb.ListToolsResponse(tools=[self._to_proto(d) for d in defs])

    def GetTool(self, request, context):
        d = self.executor.definition(request.name)
        if d is None:
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(f"tool {request.name} not registered")
            return pb.ToolDefinition()
        return self._to_proto(d)

    def Execute(self, request, context):
        result = self.executor.execute(
            agent_id=request.agent_id,
            tool_name=request.tool_name,
            input_json=request.input_json,
            task_id=request.task_id,
            reason=request.reason,
        )
        return pb.ExecuteResponse(
            success=result.success,
            output_json=json.dumps(result.output).encode(),
            error=result.error,
            execution_id=result.execution_id,
            duration_ms=result.duration_ms,
            backup_id=result.backup_id,
        )

    def Rollback(self, request, context):
        ok, msg = self.executor.rollback(request.execution_id, request.reason)
        return pb.RollbackResponse(success=ok, error="" if ok else msg)

    def Register(self, request, context):
        if not request.tool.name:
            return pb.RegisterToolResponse(accepted=False, error="missing name")
        self.executor.register_external(
            {
                "name": request.tool.name,
                "namespace": request.tool.namespace,
                "version": request.tool.version or "0.0.1",
                "description": request.tool.description,
                "required_capabilities": list(request.tool.required_capabilities),
                "risk_level": request.tool.risk_level or "medium",
                "requires_confirmation": request.tool.requires_confirmation,
                "idempotent": request.tool.idempotent,
                "reversible": request.tool.reversible,
                "timeout_ms": request.tool.timeout_ms or 30_000,
                "rollback_tool": request.tool.rollback_tool,
            },
            request.handler_address,
        )
        return pb.RegisterToolResponse(accepted=True)

    def Deregister(self, request, context):
        ok = self.executor.deregister(request.tool_name)
        return pb.Status(
            success=ok,
            message="deregistered" if ok else f"{request.tool_name} not found",
        )

    @staticmethod
    def _to_proto(d: dict) -> pb.ToolDefinition:
        return pb.ToolDefinition(
            name=d["name"],
            namespace=d["namespace"],
            version=d.get("version", "1.0.0"),
            description=d.get("description", ""),
            required_capabilities=d.get("required_capabilities", []),
            risk_level=d.get("risk_level", "low"),
            requires_confirmation=d.get("requires_confirmation", False),
            idempotent=d.get("idempotent", False),
            reversible=d.get("reversible", False),
            timeout_ms=d.get("timeout_ms", 30_000),
            rollback_tool=d.get("rollback_tool", ""),
        )


def serve(
    address: Optional[str] = None,
    executor: Optional[ToolExecutor] = None,
    block: bool = True,
    metrics_port: Optional[int] = None,
):
    from ..obs.http import maybe_start_metrics_server

    address = address or service_address("tools")
    server = rpc.create_server()
    service = ToolRegistryService(executor)
    rpc.add_to_server(TOOLS, service, server)
    port = server.add_insecure_port(address)
    server.start()
    service.metrics_server, service.metrics_port = maybe_start_metrics_server(
        "tools",
        metrics_port,
        health_fn=lambda: {
            "service": "tools",
            "tools": len(service.executor.registry),
        },
    )
    log.info("ToolRegistry listening on %s (%d tools)",
             address, len(service.executor.registry))
    if block:
        server.wait_for_termination()
    return server, service, port


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    import os

    serve(
        executor=ToolExecutor(
            audit_path=os.environ.get("AIOS_AUDIT_DB", "/tmp/aios/audit.db")
        )
    )
