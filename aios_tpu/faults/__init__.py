"""Deterministic fault injection for the serving plane (docs/FAULTS.md).

Hot paths call ``faults.point("<name>")`` — a no-op unless a seeded
schedule is armed via ``AIOS_TPU_FAULTS`` / boot ``[faults]`` /
:func:`activate`. See :mod:`aios_tpu.faults.inject` for the catalog,
trigger grammar, and determinism contract.
"""

from .inject import (
    MODES,
    POINTS,
    FaultAction,
    InjectedFault,
    activate,
    active,
    deactivate,
    fired,
    install_from_env,
    point,
)

__all__ = [
    "MODES",
    "POINTS",
    "FaultAction",
    "InjectedFault",
    "activate",
    "active",
    "deactivate",
    "fired",
    "install_from_env",
    "point",
]
