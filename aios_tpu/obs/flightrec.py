"""Serving-plane flight recorder: one structured timeline per request.

RTP-LLM (PAPERS.md) treats request-level timelines as the operability
backbone of a serving engine: when a request is slow, was shed, or came
back truncated, the operator needs WHAT HAPPENED TO *THIS* REQUEST, not
another aggregate. The recorder answers that question for every request
through the serving plane:

    admission decision (quota/deadline/shed cause + retry-after)
      -> route choice (replica, reason, overlap rows incl. the
         host-discounted ones)
      -> queue wait -> prefill chunks (cached / restored rows)
      -> per-dispatch decode ticks (step count, batch occupancy,
         pipeline host gap) / jump-ahead runs / spec rounds
      -> retirement (or abort / shed, with a CLOSED-ENUM cause)

Everything is host-side bookkeeping: events are appended per DISPATCH or
per DECISION (never per token), records live in a bounded per-model ring
buffer, and the whole thing can be disabled (``AIOS_TPU_FLIGHTREC=0``)
without changing a single dispatch — the engine's compile counters and
dispatch counts are identical recorder ON vs OFF (the PR 6/7 invariant,
extended to observability).

Timelines correlate with the span tree through the request's trace id:
``install_span_export`` wires the previously-dormant
``tracing.set_exporter`` hook so finished RPC spans fold into the
matching timeline as ``span`` events.

Export surfaces (obs/http.py): ``/debug/requests`` (recent timelines as
JSON), ``/debug/trace`` (Chrome trace-event / Perfetto JSON),
``/debug/spans`` (the finished-span ring), and anomaly auto-snapshots —
a shed spike, crash-respawn, SLO breach, or abort freezes the last N
timelines so the evidence survives the ring (docs/RUNBOOK.md section 4).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.locks import make_lock

log = logging.getLogger("aios.obs")

# -- closed enums (linted by tests/test_obs_lint.py) ------------------------
# Every label-shaped string the recorder (and the aios_tpu_slo_* family
# built on it) emits comes from one of these tuples — free-form strings
# ride in non-enumerated detail fields only, so neither the recorder
# output nor any metric built on it can grow unbounded label sets.

# Timeline event kinds. "admit"/"shed" are the admission decision,
# "route" the replica choice, "queue" the wait for a slot, "prefill" one
# prefill dispatch (chunked admissions record one per chunk), "decode" a
# plain/masked decode dispatch, "jump" a grammar jump-ahead run, "spec" a
# speculative round batch, "restore"/"spill" the host KV tier moving
# pages, "retire"/"abort"/"cancel" the terminal event, "span" a folded-in
# finished tracing span, "respawn" a replica crash-respawn (model lane),
# "failover" an in-flight re-route to a surviving replica after a crash
# (serving/failover.py), "fault" an injected fault firing (model lane,
# aios_tpu/faults/), "kv_compress" a slot crossing the window+sink
# compression threshold and "seq_prefill" a sequence-sharded whole-mesh
# prefill admission (model lane, docs/ENGINE_PERF.md "Long-context
# tier").
EVENT_KINDS = (
    "admit", "shed", "route", "queue", "prefill", "decode", "jump",
    "spec", "restore", "spill", "retire", "abort", "cancel", "span",
    "respawn", "failover", "fault", "kv_compress", "seq_prefill",
    # "autoscale": an SLO-burn controller action (scale up/down, degrade
    # ladder rung, restore) on the model lane (serving/autoscale.py)
    "autoscale",
    # "fleet_member": a membership state-machine edge (new/up/suspect/
    # dead) on the "fleet" pseudo-model lane (obs/fleet.py) — the same
    # evidence as the transition journal, time-aligned with request
    # timelines
    "fleet_member",
    # "handoff": a disaggregated prefill->decode transfer of an
    # in-flight stream to a peer host (aios_tpu/fleet/disagg.py) — on
    # the request timeline when it rides one, else the model lane
    "handoff",
    # "quarantine": a per-peer circuit-breaker state edge (closed/open/
    # half_open) on the "fleet" pseudo-model lane
    # (aios_tpu/fleet/breaker.py) — the gray-host evidence trail
    "quarantine",
    # "drain": a graceful-drain phase edge (serving -> draining ->
    # leaving) on the "fleet" pseudo-model lane (aios_tpu/fleet/drain.py)
    "drain",
    # "incident": an incident bundle frozen on the model lane — the tsdb
    # window + snapshot + fault journal + devprof + lock-watchdog state
    # around an anomaly trigger (aios_tpu/obs/incidents.py)
    "incident",
)

# Shed causes — THE closed enum; serving/admission.py raises with these
# and serving/pool.py counts by them (both import this tuple).
# "degraded" is the autoscaler's ladder rung 3: best-effort (priority <
# the protected floor) requests shed while the pool digs out of an SLO
# burn — the reactive/operational tiers keep admitting.
#
# "draining_host" is the fleet drain protocol (aios_tpu/fleet/drain.py):
# the whole HOST is leaving, so unlike the per-pool "draining" cause the
# retry hint points clients at the surviving fleet, not this process.
SHED_CAUSES = ("quota", "deadline", "queue_full", "draining", "degraded",
               "draining_host")

# Abort causes: the batcher's human-readable ``abort_reason`` strings
# normalize onto this enum (the free-form text rides in the timeline's
# ``abort_detail``, never in a label).
ABORT_CAUSES = (
    "evicted", "prompt_too_large", "scheduler_failed", "model_unloading",
    "other",
)

# Abort causes a CLIENT retry (or the pool's transparent failover) can
# plausibly fix: the replica state that killed the request is transient.
# The runtime service returns UNAVAILABLE + retry-after-ms trailing
# metadata for these — the same convention as admission sheds — and
# serving/failover.py retries them in-flight before the client ever
# sees the abort ("evicted" only re-routes on a multi-replica pool; the
# same starved replica would just evict another victim). Deliberate
# aborts (model_unloading is an operator action, prompt_too_large a
# client error) stay non-retryable: a backoff hint there would put
# compliant clients in a futile retry loop.
RETRYABLE_ABORT_CAUSES = ("scheduler_failed", "evicted")

# Terminal timeline states.
STATES = ("live", "retired", "cancelled", "aborted", "shed")

# Anomaly snapshot causes.
SNAPSHOT_CAUSES = ("shed_spike", "crash_respawn", "slo_breach", "abort",
                   "manual")


def abort_cause(reason: str) -> str:
    """Normalize a free-form batcher ``abort_reason`` onto ABORT_CAUSES."""
    if reason.startswith("evicted"):
        return "evicted"
    if reason.startswith("prompt exceeds"):
        return "prompt_too_large"
    if reason.startswith("scheduler failed"):
        return "scheduler_failed"
    if reason.startswith("model unloading"):
        return "model_unloading"
    return "other"


# -- bounds -----------------------------------------------------------------

# Events per timeline: a decode event lands once per DISPATCH (~chunk_steps
# tokens), so 512 events cover a ~8k-token generation with default chunks;
# past the cap events drop and are counted (the record stays bounded no
# matter how long the stream runs).
MAX_EVENTS = 512

# Snapshot policy: how many frozen snapshots to keep, and the per-model
# per-cause cooldown (an abort storm must not thrash the snapshot store —
# the FIRST freeze holds the interesting state).
MAX_SNAPSHOTS = 8
SNAPSHOT_COOLDOWN_SECS = 30.0

# Shed-spike trigger: this many sheds inside the window freezes a snapshot.
SHED_SPIKE_N = 20
SHED_SPIKE_WINDOW_SECS = 10.0

# trace_id -> timeline index bound (client-driven cardinality).
_MAX_TRACE_INDEX = 4096


class Timeline:
    """One request's flight record. Mutated only by the threads that own
    the request at the time (gRPC handler -> pool -> scheduler thread, a
    strictly sequenced handoff); readers (debug routes) take copies."""

    __slots__ = (
        "model", "request_id", "tenant", "trace_id", "priority",
        "prompt_tokens", "t0_wall", "t0", "events", "dropped_events",
        "state", "replica", "route_reason", "shed_cause", "abort_cause",
        "abort_detail", "retry_after_ms", "queue_wait_ms", "ttft_ms",
        "tpot_ms", "tokens_out", "device_us", "finished_at",
        "__weakref__",
    )

    def __init__(self, model: str, request_id: str, tenant: str,
                 trace_id: str, prompt_tokens: int, priority: int) -> None:
        self.model = model
        self.request_id = request_id
        self.tenant = tenant
        self.trace_id = trace_id
        self.priority = priority
        self.prompt_tokens = prompt_tokens
        self.t0_wall = time.time()
        self.t0 = time.monotonic()
        self.events: List[Tuple[float, str, dict]] = []
        self.dropped_events = 0
        self.state = "live"
        self.replica = -1
        self.route_reason = ""
        self.shed_cause = ""
        self.abort_cause = ""  # one of ABORT_CAUSES when aborted
        self.abort_detail = ""
        self.retry_after_ms = 0
        self.queue_wait_ms = 0.0
        self.ttft_ms = 0.0
        self.tpot_ms = 0.0
        self.tokens_out = 0
        # estimated device-microseconds attributed to this request
        # (obs/devprof.py: per-dispatch ledger means split by batch
        # occupancy + measured prefill time); 0 unless devprof is armed
        self.device_us = 0.0
        self.finished_at = 0.0  # monotonic, 0 while live

    def event(self, kind: str, **fields) -> Optional[dict]:
        """Append one event (bounded; drops count rather than grow).
        Returns the stored fields dict so the owning scheduler thread
        can join late-arriving per-dispatch data (the pipelined decode
        worker's sampled device-µs lands at consume time) — readers only
        see FINISHED timelines (the rings), so an owner-side join on a
        live one never races a /debug copy."""
        if len(self.events) >= MAX_EVENTS:
            self.dropped_events += 1
            return None
        self.events.append((time.monotonic() - self.t0, kind, fields))
        return fields

    @property
    def duration_ms(self) -> float:
        end = self.finished_at or time.monotonic()
        return (end - self.t0) * 1000.0

    def to_dict(self, events: bool = True) -> dict:
        out = {
            "model": self.model,
            "request_id": self.request_id,
            "tenant": self.tenant,
            "trace_id": self.trace_id,
            "priority": self.priority,
            "prompt_tokens": self.prompt_tokens,
            "submitted_at": self.t0_wall,
            "state": self.state,
            "replica": self.replica,
            "route_reason": self.route_reason,
            "shed_cause": self.shed_cause,
            "abort_cause": self.abort_cause,
            "abort_detail": self.abort_detail,
            "retry_after_ms": self.retry_after_ms,
            "queue_wait_ms": round(self.queue_wait_ms, 3),
            "ttft_ms": round(self.ttft_ms, 3),
            "tpot_ms": round(self.tpot_ms, 3),
            "tokens_out": self.tokens_out,
            "device_us": round(self.device_us, 1),
            "duration_ms": round(self.duration_ms, 3),
            "dropped_events": self.dropped_events,
        }
        if events:
            out["events"] = [
                {"t_ms": round(t * 1000.0, 3), "kind": k, **f}
                for t, k, f in list(self.events)
            ]
        return out


class FlightRecorder:
    """Bounded per-model rings of finished timelines + anomaly snapshots.

    One process-wide instance (``RECORDER``); tests build private ones.
    ``begin`` is the only entry point that allocates; every other hot-path
    touch is an O(1) append on the timeline itself.
    """

    def __init__(self, ring: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        if ring is None:
            try:
                ring = int(os.environ.get("AIOS_TPU_FLIGHTREC_RING", "256"))
            except ValueError:
                ring = 256
        if enabled is None:
            enabled = os.environ.get(
                "AIOS_TPU_FLIGHTREC", ""
            ).lower() not in ("0", "off", "false", "no")
        self.ring_size = max(ring, 1)
        self.enabled = enabled and ring != 0
        self._lock = make_lock("recorder")
        self._rings: Dict[str, deque] = {}  #: guarded_by _lock
        self._model_events: Dict[str, deque] = {}  #: guarded_by _lock
        # trace_id -> recent timelines sharing it: an agent task's RPCs
        # all propagate ONE traceparent, so a single-slot map would make
        # every begin() steal the previous request's span correlation
        self._by_trace: "OrderedDict[str, deque]" = OrderedDict()
        self._snapshots: deque = deque(maxlen=MAX_SNAPSHOTS)
        self._snapshot_at: Dict[Tuple[str, str], float] = {}
        self._shed_marks: Dict[str, deque] = {}
        self._snap_ids = 0
        # finish listeners (the SLO engine registers itself): called with
        # the finished Timeline OUTSIDE the recorder lock; must not raise.
        self._listeners: List[Callable[[Timeline], None]] = []

    # -- lifecycle ----------------------------------------------------------

    def begin(self, model: str, request_id: str = "",
              tenant: str = "anonymous", trace_id: str = "",
              prompt_tokens: int = 0,
              priority: int = 0) -> Optional[Timeline]:
        """Open a timeline (None when the recorder is disabled — every
        call site guards on that)."""
        if not self.enabled:
            return None
        tl = Timeline(model, request_id, tenant, trace_id, prompt_tokens,
                      priority)
        if trace_id:
            with self._lock:
                peers = self._by_trace.get(trace_id)
                if peers is None:
                    peers = self._by_trace[trace_id] = deque(maxlen=8)
                else:
                    self._by_trace.move_to_end(trace_id)
                peers.append(tl)
                while len(self._by_trace) > _MAX_TRACE_INDEX:
                    self._by_trace.popitem(last=False)
        return tl

    def add_listener(self, fn: Callable[[Timeline], None]) -> None:
        self._listeners.append(fn)

    def _ring(self, model: str) -> deque:
        ring = self._rings.get(model)
        if ring is None:
            ring = self._rings.setdefault(
                model, deque(maxlen=self.ring_size)
            )
        return ring

    def finish(self, tl: Optional[Timeline], state: str = "retired",
               abort_reason: str = "", shed_cause: str = "",
               retry_after_ms: int = 0) -> None:
        """Close a timeline into its model's ring — the ONE owner of the
        close sequence (terminal event, ring append, listener fan-out)
        for every state. ``state`` is one of STATES; an aborted finish
        normalizes ``abort_reason`` onto the closed ABORT_CAUSES enum
        (the raw string rides in abort_detail) and freezes an anomaly
        snapshot."""
        if tl is None or tl.finished_at:
            return
        tl.finished_at = time.monotonic()
        tl.state = state
        if state == "aborted":
            tl.abort_cause = abort_cause(abort_reason)
            tl.abort_detail = abort_reason[:200]
            tl.event("abort", cause=tl.abort_cause)
        elif state == "retired":
            tl.event("retire", tokens=tl.tokens_out)
        elif state == "cancelled":
            tl.event("cancel")
        elif state == "shed":
            tl.shed_cause = (
                shed_cause if shed_cause in SHED_CAUSES else "draining"
            )
            tl.retry_after_ms = int(retry_after_ms)
            tl.event("shed", cause=tl.shed_cause,
                     retry_after_ms=tl.retry_after_ms)
        with self._lock:
            self._ring(tl.model).append(tl)
        for fn in self._listeners:
            try:
                fn(tl)
            except Exception:  # noqa: BLE001 - obs must not break serving
                log.exception("flight-recorder finish listener failed")
        if state == "aborted":
            # async: finish() runs on the batcher scheduler thread
            self.snapshot(tl.model, "abort", sync=False)

    def finish_shed(self, tl: Optional[Timeline], cause: str,
                    retry_after_ms: int, model: str = "") -> None:
        """Close a timeline as shed (+ spike detection, which fires even
        when the recorder is disabled so the snapshot trigger still
        guards the plane)."""
        model = model or (tl.model if tl is not None else "")
        self.finish(tl, "shed", shed_cause=cause,
                    retry_after_ms=retry_after_ms)
        if model:
            self._note_shed(model)

    def _note_shed(self, model: str) -> None:
        now = time.monotonic()
        with self._lock:
            marks = self._shed_marks.setdefault(
                model, deque(maxlen=SHED_SPIKE_N)
            )
            marks.append(now)
            spike = (
                len(marks) == SHED_SPIKE_N
                and now - marks[0] <= SHED_SPIKE_WINDOW_SECS
            )
        if spike:
            self.snapshot(model, "shed_spike", sync=False)  # gRPC path

    # -- model-lane events (engine/pool happenings not owned by one
    # request: host-tier spills, restores, replica respawns) ---------------

    def model_event(self, model: str, kind: str, **fields) -> None:
        if not self.enabled:
            return
        entry = (time.monotonic(), time.time(), kind, fields)
        with self._lock:
            # append INSIDE the lock: model_events()/snapshot() iterate
            # this deque under it, and a concurrent append would raise
            # "deque mutated during iteration" into the engine hot path
            self._model_events.setdefault(
                model, deque(maxlen=MAX_EVENTS)
            ).append(entry)

    # -- span folding (the dormant tracing.set_exporter hook) --------------

    def export_span(self, span) -> None:
        """tracing exporter callback: fold a finished span into the
        timeline sharing its trace id (live or recently finished — RPC
        server spans close AFTER the request retires). An agent task's
        RPCs share ONE propagated traceparent, so among the trace's
        recent timelines the span lands on the newest one whose lifetime
        overlaps it (1 s grace for clock jitter), not blindly on the
        latest begin()."""
        if not self.enabled:
            return
        with self._lock:
            peers = self._by_trace.get(span.trace_id)
            candidates = list(peers) if peers else ()
        if not candidates:
            return
        start = getattr(span, "start", 0.0)
        end = getattr(span, "end", 0.0) or time.time()
        tl = candidates[-1]
        for cand in reversed(candidates):  # newest first
            cand_end = cand.t0_wall + cand.duration_ms / 1000.0
            if cand.t0_wall - 1.0 <= end and start <= cand_end + 1.0:
                tl = cand
                break
        tl.event(
            "span", name=span.name, dur_ms=round(span.duration_s * 1e3, 3),
            status=span.status, span_id=span.span_id,
        )

    # -- reads --------------------------------------------------------------

    def recent(self, model: str = "", limit: int = 64) -> List[Timeline]:
        """Most-recent finished timelines, oldest first."""
        with self._lock:
            if model:
                tls = list(self._rings.get(model, ()))
            else:
                tls = [t for ring in self._rings.values() for t in ring]
        tls.sort(key=lambda t: t.t0)
        return tls[-limit:]

    def model_events(self, model: str = "") -> List[tuple]:
        """Model-lane events as (wall_ts, model, kind, fields) tuples."""
        with self._lock:
            lanes = (
                {model: self._model_events.get(model, ())}
                if model else dict(self._model_events)
            )
            return [
                (wall, m, kind, fields)
                for m, lane in lanes.items()
                for _, wall, kind, fields in lane
            ]

    # -- anomaly snapshots ---------------------------------------------------

    def snapshot(self, model: str, cause: str,
                 sync: bool = True) -> Optional[dict]:
        """Freeze the model's last N timelines (+ model-lane events) so a
        transient anomaly survives ring churn. Cooldown-limited per
        (model, cause); returns the snapshot dict, or None when skipped
        — or when ``sync=False``, which builds the snapshot on a
        background daemon thread (the auto-trigger paths run on the
        scheduler / gRPC threads, and the O(ring x events) to_dict()
        pass must not stall decode scheduling exactly while the plane is
        degraded). The cooldown stamp and snapshot id are still claimed
        synchronously, so a burst of triggers freezes exactly one."""
        if cause not in SNAPSHOT_CAUSES:
            cause = "manual"
        now = time.monotonic()
        with self._lock:
            last = self._snapshot_at.get((model, cause), 0.0)
            if now - last < SNAPSHOT_COOLDOWN_SECS:
                return None
            self._snapshot_at[(model, cause)] = now
            self._snap_ids += 1
            snap_id = self._snap_ids
            # copy references only — the dict-building pass runs OUTSIDE
            # the lock, or every finish()/model_event() on the serving
            # path would stall behind the serialization
            tls = list(self._rings.get(model, ()))
            lane = list(self._model_events.get(model, ()))
        if not sync:
            threading.Thread(
                target=self._build_snapshot,
                args=(snap_id, model, cause, tls, lane),
                name="flightrec-snapshot", daemon=True,
            ).start()
            return None
        return self._build_snapshot(snap_id, model, cause, tls, lane)

    def _build_snapshot(self, snap_id: int, model: str, cause: str,
                        tls: list, lane: list) -> dict:
        snap = {
            "id": snap_id,
            "model": model,
            "cause": cause,
            "at": time.time(),
            "timelines": [t.to_dict() for t in tls],
            "model_events": [
                {"t_wall": w, "kind": k, **f} for _, w, k, f in lane
            ],
        }
        with self._lock:
            self._snapshots.append(snap)
        # Every fired snapshot is also an incident trigger: the bundle
        # freezes the tsdb window + fault journal + devprof state around
        # the same anomaly. Hooked here — after the append — so the
        # incident's flightrec section always finds the snapshot it
        # belongs to. Late import: flightrec loads before incidents in
        # the obs package; notify() is a no-op when the store is
        # unarmed, and runs its own per-(model, cause) cooldown.
        from . import incidents as _incidents
        _incidents.notify(model, cause)
        dump_dir = os.environ.get("AIOS_TPU_FLIGHTREC_DUMP_DIR", "")
        if dump_dir:
            try:
                os.makedirs(dump_dir, exist_ok=True)
                path = os.path.join(
                    dump_dir, f"flightrec-{model}-{cause}-{snap['id']}.json"
                )
                with open(path, "w") as f:
                    json.dump(snap, f)
                log.warning("flight recorder snapshot (%s/%s) -> %s",
                            model, cause, path)
            except OSError as exc:
                log.warning("flight recorder dump failed: %s", exc)
        else:
            log.warning(
                "flight recorder snapshot frozen (%s/%s, %d timelines); "
                "GET /debug/snapshots to read it", model, cause,
                len(snap["timelines"]),
            )
        return snap

    def snapshots(self) -> List[dict]:
        with self._lock:
            return list(self._snapshots)

    def clear(self) -> None:
        """Test isolation."""
        with self._lock:
            self._rings.clear()
            self._model_events.clear()
            self._by_trace.clear()
            self._snapshots.clear()
            self._snapshot_at.clear()
            self._shed_marks.clear()


# -- Chrome trace-event export ----------------------------------------------

# Event kinds rendered as zero-duration instants unless they carry dur_ms.
_PHASE_NAMES = {
    "prefill": "prefill", "decode": "decode", "jump": "jump-ahead",
    "spec": "speculative", "span": "span",
}


def _tl_view(tl) -> tuple:
    """Uniform view over a live :class:`Timeline` or a frozen snapshot's
    ``to_dict()`` dict, so one renderer serves both (the snapshot path
    must not drift from the live one): (model, request_id, tenant,
    state, t0_wall, duration_ms, queue_wait_ms, events, summary_args)
    with events as (t_rel_s, kind, fields) tuples."""
    if isinstance(tl, dict):
        events = [
            (e.get("t_ms", 0.0) / 1e3, e.get("kind", ""),
             {k: v for k, v in e.items() if k not in ("t_ms", "kind")})
            for e in tl.get("events", ())
        ]
        return (
            tl.get("model", ""), tl.get("request_id", ""),
            tl.get("tenant", ""), tl.get("state", ""),
            tl.get("submitted_at", 0.0), tl.get("duration_ms", 0.0),
            tl.get("queue_wait_ms", 0.0), events,
            {k: v for k, v in tl.items() if k != "events"},
        )
    return (
        tl.model, tl.request_id, tl.tenant, tl.state, tl.t0_wall,
        tl.duration_ms, tl.queue_wait_ms, list(tl.events),
        tl.to_dict(events=False),
    )


def chrome_trace(timelines: list, model_events: List[tuple] = ()) -> dict:
    """Render timelines (live :class:`Timeline` objects or a snapshot's
    frozen dicts) as Chrome trace-event JSON (chrome://tracing /
    Perfetto "JSON Object Format"): one pid per model, one tid per
    request, X (complete) events for the request envelope + queue wait +
    dur-carrying dispatches, i (instant) events for decisions. ts/dur
    are microseconds of wall time. ``model_events`` are the recorder's
    (wall_ts, model, kind, fields) lane tuples, rendered on tid 0."""
    events: List[dict] = []
    pids: Dict[str, int] = {}

    def pid_of(model: str) -> int:
        pid = pids.get(model)
        if pid is None:
            pid = pids[model] = len(pids) + 1
            events.append({
                "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": f"model:{model}"},
            })
            events.append({
                "ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
                "args": {"name": "engine lane"},
            })
        return pid

    for tid, tl in enumerate(timelines, start=1):
        (model, request_id, tenant, state, t0_wall, duration_ms,
         queue_wait_ms, tl_events, summary) = _tl_view(tl)
        pid = pid_of(model)
        base_us = t0_wall * 1e6
        events.append({
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": f"{request_id or 'req'} ({tenant})"},
        })
        events.append({
            "ph": "X", "pid": pid, "tid": tid,
            "name": f"request[{state}]",
            "cat": "request", "ts": base_us,
            "dur": max(duration_ms * 1e3, 1.0),
            "args": summary,
        })
        if queue_wait_ms:
            events.append({
                "ph": "X", "pid": pid, "tid": tid, "name": "queue",
                "cat": "queue", "ts": base_us,
                "dur": max(queue_wait_ms * 1e3, 1.0),
                "args": {"wait_ms": round(queue_wait_ms, 3)},
            })
        for t_rel, kind, fields in tl_events:
            ts = base_us + t_rel * 1e6
            dur_ms = fields.get("dur_ms")
            if dur_ms is not None:
                events.append({
                    "ph": "X", "pid": pid, "tid": tid,
                    "name": _PHASE_NAMES.get(kind, kind), "cat": kind,
                    "ts": ts - float(dur_ms) * 1e3,
                    "dur": max(float(dur_ms) * 1e3, 1.0),
                    "args": dict(fields),
                })
            else:
                events.append({
                    "ph": "i", "pid": pid, "tid": tid, "name": kind,
                    "cat": kind, "ts": ts, "s": "t",
                    "args": dict(fields),
                })
    for wall, model, kind, fields in model_events:
        events.append({
            "ph": "i", "pid": pid_of(model), "tid": 0, "name": kind,
            "cat": kind, "ts": wall * 1e6, "s": "p", "args": dict(fields),
        })
    events.sort(key=lambda e: e.get("ts", 0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def snapshot_trace(snap: dict) -> dict:
    """A frozen anomaly snapshot in Chrome trace shape — same renderer
    as the live path (snapshots store to_dict() timelines and the
    engine-lane events; both survive the freeze)."""
    lane = [
        (e.get("t_wall", 0.0), snap.get("model", ""), e.get("kind", ""),
         {k: v for k, v in e.items() if k not in ("t_wall", "kind")})
        for e in snap.get("model_events", ())
    ]
    return chrome_trace(snap.get("timelines", ()), lane)


# -- process-wide instance + tracing hookup ---------------------------------

RECORDER = FlightRecorder()


def install_span_export() -> None:
    """Wire the dormant ``tracing.set_exporter`` hook to the recorder —
    only when nothing else claimed it (a deployment's own exporter
    wins)."""
    from . import tracing

    if tracing.get_exporter() is None:
        tracing.set_exporter(RECORDER.export_span)
