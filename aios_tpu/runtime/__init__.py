"""aios.runtime.AIRuntime — the TPU inference service.

Same gRPC surface as the reference's runtime crate (runtime/src/), backed by
in-process JAX engines instead of llama-server child processes.
"""
