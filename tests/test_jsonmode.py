"""Grammar-constrained JSON decoding (engine/jsonmode.py).

The reference forces response_format=json_object on every non-streaming
local inference and leans on llama-server's GBNF engine to make the output
parse (runtime/src/inference.rs:114-122); the TPU engine realizes the same
guarantee with a byte-level JSON automaton and per-step logit masks.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import jsonmode
from aios_tpu.engine import model as M
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.tokenizer import ByteTokenizer

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


# ---------------------------------------------------------------------------
# automaton
# ---------------------------------------------------------------------------

ACCEPT = [
    b'{"a": 1}',
    b'{ }',
    b'{"a": [1, 2.5e3, -0.25, true, false, null, "x"]}',
    b'{"nested": {"deep": {"x": "y"}}, "b": []}',
    b'{"esc": "a\\n\\t\\u00e9\\\\"}',
    b'  {"ws": 1}  ',
    b'{"unicode": "h\xc3\xa9llo"}',
]

REJECT = [
    b"{",  # unterminated
    b'{"a" 1}',  # missing colon
    b"[1]",  # top level must be an object (json_object mode)
    b'{"a":01}',  # leading zero
    b'{"a":1,}',  # trailing comma
    b'{"a":1}}',  # extra closer
    b"{'a':1}",  # single quotes
    b'{"a":+1}',  # plus sign
    b'{"a":.5}',  # bare fraction
    b'{"a":1 "b":2}',  # missing comma
    b'{"a"}',  # key without value
    b'{"a":tru}',  # bad literal
]


@pytest.mark.parametrize("sample", ACCEPT)
def test_pda_accepts(sample):
    end = jsonmode.run_bytes(jsonmode.start_state(), sample)
    assert end is not None and jsonmode.is_terminal(end), sample


@pytest.mark.parametrize("sample", REJECT)
def test_pda_rejects(sample):
    end = jsonmode.run_bytes(jsonmode.start_state(), sample)
    assert end is None or not jsonmode.is_terminal(end), sample


def test_pda_depth_cap():
    deep = b'{"a":' * 20
    assert jsonmode.run_bytes(jsonmode.start_state(), deep, max_depth=8) is None
    ok = b'{"a":' * 6
    assert jsonmode.run_bytes(jsonmode.start_state(), ok, max_depth=8) is not None


def test_pda_fuzz_against_json_loads():
    """Any byte string the PDA accepts as terminal must json.loads to a
    dict; sampled by random walks over the closing mask."""
    tok = ByteTokenizer()
    table = jsonmode.token_bytes_table(tok, tok.vocab_size)
    cache = jsonmode.JsonMaskCache(table, tok.eos_id)
    rng = np.random.default_rng(0)
    for _ in range(30):
        state = cache.start()
        out = []
        for step in range(60):
            row = (
                cache.mask_row(state)
                if step < 30
                else cache.closing_row(state)
            )
            allowed = np.flatnonzero(row == 0.0)
            allowed = allowed[allowed != tok.eos_id]
            if len(allowed) == 0:
                break
            tid = int(rng.choice(allowed))
            out.append(tid)
            state = jsonmode.run_bytes(state, table[tid])
            assert state is not None
            if jsonmode.is_terminal(state):
                break
        assert jsonmode.is_terminal(state)
        parsed = json.loads(bytes(out).decode("utf-8", "replace"))
        assert isinstance(parsed, dict)


def test_mask_row_matches_single_byte_transitions():
    tok = ByteTokenizer()
    table = jsonmode.token_bytes_table(tok, tok.vocab_size)
    cache = jsonmode.JsonMaskCache(table, tok.eos_id)
    state = jsonmode.run_bytes(cache.start(), b'{"k": ')
    row = cache.mask_row(state)
    for b in range(256):
        ok = jsonmode.next_state(state, b) is not None
        assert (row[b] == 0.0) == ok, b
    # EOS masked: value still open
    assert row[tok.eos_id] == jsonmode.NEG_INF
    done = jsonmode.run_bytes(cache.start(), b'{"k": 1}')
    assert cache.mask_row(done)[tok.eos_id] == 0.0


def test_closing_row_walks_to_terminal():
    tok = ByteTokenizer()
    table = jsonmode.token_bytes_table(tok, tok.vocab_size)
    cache = jsonmode.JsonMaskCache(table, tok.eos_id)
    state = jsonmode.run_bytes(cache.start(), b'{"a": {"b": [1, {"c": "xy')
    steps = 0
    while not jsonmode.is_terminal(state):
        row = cache.closing_row(state)
        allowed = np.flatnonzero(row == 0.0)
        allowed = allowed[allowed != tok.eos_id]
        assert len(allowed) > 0
        state = jsonmode.run_bytes(state, table[int(allowed[0])])
        assert state is not None
        steps += 1
        assert steps < 32, "closing must converge"
    # at terminal, closing mask admits ONLY eos
    row = cache.closing_row(state)
    assert row[tok.eos_id] == 0.0
    assert (row == 0.0).sum() == 1


def test_token_bytes_tables():
    from aios_tpu.engine.tokenizer import ByteLevelBPE, SentencePieceBPE

    sp = SentencePieceBPE(
        tokens=["<unk>", "<s>", "</s>", "▁hi", "<0x7B>", "x"],
        scores=[0.0] * 6,
        token_types=[2, 3, 3, 1, 6, 1],
    )
    t = jsonmode.token_bytes_table(sp, 6)
    assert t[1] is None and t[2] is None  # control
    assert t[3] == b" hi"  # spiece space convention
    assert t[4] == b"{"  # byte token
    bl = ByteLevelBPE(
        tokens=["{", "Ġa", "<|im_end|>"],
        merges=[],
        token_types=[1, 1, 3],
    )
    t2 = jsonmode.token_bytes_table(bl, 3)
    assert t2[0] == b"{" and t2[1] == b" a" and t2[2] is None


def test_hf_token_bytes_keep_space_markers():
    """The HF path must map via token STRINGS: per-id decode strips the
    SentencePiece leading-space marker ('▁7' -> '7'), which would let the
    automaton accept a digit continuation where the emitted text actually
    inserts a space mid-number."""

    class FakeSPFast:  # mimics transformers' API surface we rely on
        all_special_tokens = ["<s>"]

        def convert_ids_to_tokens(self, ids):
            vocab = ["<s>", "▁7", "7", "▁", "<0x7B>"]
            return [vocab[i] for i in ids]

    class FakeHF:
        _tok = FakeSPFast()
        eos_id = 0

        def decode(self, ids):
            raise AssertionError("must not fall back to per-id decode")

    t = jsonmode.token_bytes_table(FakeHF(), 5)
    assert t[0] is None  # special
    assert t[1] == b" 7"  # marker preserved
    assert t[2] == b"7"
    assert t[3] == b" "
    assert t[4] == b"{"  # byte token

    class FakeBLFast:
        all_special_tokens = []

        def convert_ids_to_tokens(self, ids):
            vocab = ["Ġ7", "7", "Ċ"]
            return [vocab[i] for i in ids]

    class FakeHF2:
        _tok = FakeBLFast()
        eos_id = None

        def decode(self, ids):
            raise AssertionError("must not fall back to per-id decode")

    t2 = jsonmode.token_bytes_table(FakeHF2(), 3)
    assert t2[0] == b" 7" and t2[1] == b"7" and t2[2] == b"\n"


# ---------------------------------------------------------------------------
# constrained generation through the engine + batcher
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serving():
    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = TPUEngine(cfg, params, num_slots=2, max_context=128,
                    cache_dtype=jnp.float32)
    tok = ByteTokenizer()
    batcher = ContinuousBatcher(eng, tokenizer=tok)
    yield eng, tok, batcher
    batcher.shutdown()
    eng.close()


@pytest.mark.parametrize("max_tokens", [25, 40, 80])
def test_constrained_generation_parses(serving, max_tokens):
    _, tok, batcher = serving
    h = batcher.submit(Request(
        prompt_ids=tok.encode("emit json"),
        max_tokens=max_tokens,
        temperature=0.9,
        top_p=0.95,
        stop_ids=(tok.eos_id,),
        json_mode=True,
    ))
    text = tok.decode(h.tokens())
    parsed = json.loads(text)  # must not raise — the whole point
    assert isinstance(parsed, dict)


def test_mixed_constrained_and_plain_batch(serving):
    _, tok, batcher = serving
    h1 = batcher.submit(Request(
        prompt_ids=tok.encode("json"), max_tokens=40, temperature=0.8,
        stop_ids=(tok.eos_id,), json_mode=True,
    ))
    h2 = batcher.submit(Request(
        prompt_ids=tok.encode("plain"), max_tokens=15, temperature=0.8,
        stop_ids=(tok.eos_id,),
    ))
    t1, t2 = h1.tokens(), h2.tokens()
    assert isinstance(json.loads(tok.decode(t1)), dict)
    assert 0 < len(t2) <= 15  # co-resident unconstrained stream unaffected


def test_json_mode_without_tokenizer_fails_fast():
    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    eng = TPUEngine(cfg, params, num_slots=1, max_context=64,
                    cache_dtype=jnp.float32)
    batcher = ContinuousBatcher(eng)  # no tokenizer
    try:
        with pytest.raises(ValueError, match="tokenizer"):
            batcher.submit(Request(
                prompt_ids=[1, 2], max_tokens=8, json_mode=True,
            ))
    finally:
        batcher.shutdown()
        eng.close()


# ---------------------------------------------------------------------------
# reference-parity env switch at the service surface
# ---------------------------------------------------------------------------


def test_force_json_mode_over_grpc(monkeypatch):
    """AIOS_TPU_JSON_MODE=force restores the reference's non-streaming
    json_object behavior at the AIRuntime surface; streaming stays free."""
    monkeypatch.setenv("AIOS_TPU_JSON_MODE", "force")
    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    manager = ModelManager(num_slots=2, warm_compile=False)
    server, _service, port = serve(
        address="127.0.0.1:0", manager=manager, block=False
    )
    try:
        stub = services.AIRuntimeStub(
            rpc.insecure_channel(f"127.0.0.1:{port}")
        )
        r = stub.LoadModel(runtime_pb2.LoadModelRequest(
            model_name="tiny", model_path="synthetic://tiny-test",
            context_length=128,
        ))
        assert r.status == "ready"
        resp = stub.Infer(runtime_pb2.InferRequest(
            model="tiny", prompt="status report", max_tokens=48,
            temperature=0.9,
        ))
        parsed = json.loads(resp.text)
        assert isinstance(parsed, dict)
        # streaming is exempt (the reference only forces non-streaming)
        chunks = list(stub.StreamInfer(runtime_pb2.InferRequest(
            model="tiny", prompt="stream", max_tokens=8, temperature=0.9,
        )))
        assert chunks[-1].done
    finally:
        server.stop(0)
