"""Parallelism: TP decode equivalence, ring attention parity, sharded training.

Everything runs on the virtual 8-device CPU mesh (conftest); the same code
paths drive real ICI collectives on a TPU slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model as M
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.train import make_optimizer, make_train_step
from aios_tpu.parallel.ring_attention import ring_attention
from jax.sharding import PartitionSpec as P

from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_params():
    return M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)


def test_mesh_construction(cpu_devices):
    mesh = build_mesh(8, dp=2, sp=2)
    assert mesh.shape == {"dp": 2, "sp": 2, "ep": 1, "tp": 2}
    mesh2 = build_mesh(4, dp=2)
    assert mesh2.shape == {"dp": 2, "sp": 1, "ep": 1, "tp": 2}


def test_plan_validation(cpu_devices):
    plan = ShardingPlan(build_mesh(4, dp=2))  # tp=2
    plan.validate(TINY_TEST, num_slots=4)
    with pytest.raises(AssertionError):
        plan.validate(TINY_TEST, num_slots=3)  # slots % dp != 0


def test_tp_decode_matches_single_device(tiny_params, cpu_devices):
    """Greedy decode must be identical with and without (dp, tp) sharding."""
    prompt = [3, 17, 91, 4, 55, 8]
    ref_engine = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=64, cache_dtype=jnp.float32
    )
    want = ref_engine.generate(prompt, max_new_tokens=8)

    plan = ShardingPlan(build_mesh(4, dp=2))  # dp=2 x tp=2
    plan.validate(TINY_TEST, num_slots=4)
    tp_engine = TPUEngine(
        TINY_TEST,
        tiny_params,
        num_slots=4,
        max_context=64,
        cache_dtype=jnp.float32,
        shardings=plan,
    )
    got = tp_engine.generate(prompt, max_new_tokens=8)
    assert got == want


def test_tp_int8_weights_decode_matches_single_device(tiny_params, cpu_devices):
    """int8 serving weights compose with the TP plan (VERDICT r2 item 3):
    the unfused quantized layout under dp x tp must reproduce the
    single-device fused-int8 engine's greedy decode exactly — the per-column
    scales are identical in both layouts."""
    prompt = [3, 17, 91, 4, 55, 8]
    ref = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=64,
        cache_dtype=jnp.float32, quantize=True,
    )
    want = ref.generate(prompt, max_new_tokens=8)

    plan = ShardingPlan(build_mesh(4, dp=2))  # dp=2 x tp=2
    tp = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=64,
        cache_dtype=jnp.float32, quantize=True, shardings=plan,
    )
    got = tp.generate(prompt, max_new_tokens=8)
    assert got == want


def test_tp_int8_kv_cache_decode_matches_single_device(tiny_params, cpu_devices):
    """Full serving config under TP: int8 weights + int8 KV cache sharded
    (slots on dp, kv heads on tp, scales alongside)."""
    prompt = [3, 17, 91, 4, 55, 8]
    ref = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=64,
        cache_dtype=jnp.int8, quantize=True,
    )
    want = ref.generate(prompt, max_new_tokens=8)

    plan = ShardingPlan(build_mesh(4, dp=2))
    tp = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=64,
        cache_dtype=jnp.int8, quantize=True, shardings=plan,
    )
    got = tp.generate(prompt, max_new_tokens=8)
    assert got == want


def test_tp_speculative_decode_matches_plain(tiny_params, cpu_devices):
    """n-gram speculative rounds under a TP plan: the verify forward
    partitions under GSPMD (the proposer/history are replicated state), so
    greedy output must equal the plain sharded engine's exactly."""
    prompt = [1, 2, 3]
    plan = ShardingPlan(build_mesh(4, dp=2))
    ref = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=128,
        cache_dtype=jnp.float32, shardings=plan,
    )
    want = ref.generate(prompt, max_new_tokens=48, temperature=0.0)
    ref.close()
    eng = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=128,
        cache_dtype=jnp.float32, shardings=plan,
    )
    got = eng.generate(
        prompt, max_new_tokens=48, temperature=0.0, speculative=True
    )
    rounds = eng.decode_steps
    eng.close()
    assert got == want
    assert rounds < len(want) - 1  # drafts accepted across the mesh


def test_sharded_ragged_attention_matches_gspmd(tiny_params, cpu_devices):
    """The shard_mapped per-device ragged decode attention (the path the
    Pallas kernel takes on a TPU mesh; jnp body here) must match the plain
    GSPMD-partitioned attention."""
    prompt = [3, 17, 91, 4, 55, 8]
    plan = ShardingPlan(build_mesh(4, dp=2))
    kw = dict(num_slots=4, max_context=64, cache_dtype=jnp.float32,
              shardings=plan)
    want = TPUEngine(TINY_TEST, tiny_params, **kw).generate(
        prompt, max_new_tokens=8
    )
    got = TPUEngine(
        TINY_TEST, tiny_params, sharded_attention=True, **kw
    ).generate(prompt, max_new_tokens=8)
    assert got == want


def test_ring_attention_matches_full_attention(cpu_devices):
    B, T, H, KH, D = 2, 32, 4, 2, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)

    mask = M.causal_mask(T, None)
    want = M.gqa_attention(q, k, v, mask)

    mesh = build_mesh(4, dp=1, sp=4)  # sp=4 ring, tp=1
    got = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_ring_attention_in_forward(tiny_params, cpu_devices):
    """forward_full with ring attention == forward_full with core attention."""
    from aios_tpu.parallel.ring_attention import make_ring_attn_fn

    mesh = build_mesh(8, dp=1, sp=8)
    tokens = np.random.default_rng(1).integers(0, 256, size=(2, 64)).astype(np.int32)
    want = np.asarray(M.forward_full(tiny_params, TINY_TEST, tokens))
    got = np.asarray(
        M.forward_full(tiny_params, TINY_TEST, tokens, make_ring_attn_fn(mesh))
    )
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-4)


def test_ulysses_attention_matches_full_attention(cpu_devices):
    from aios_tpu.parallel.ulysses import ulysses_attention

    B, T, H, KH, D = 2, 32, 4, 2, 16
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)

    mask = M.causal_mask(T, None)
    want = M.gqa_attention(q, k, v, mask)

    mesh = build_mesh(2, dp=1, sp=2)  # sp=2 (KH=2 must divide sp)
    got = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_ulysses_attention_in_forward(tiny_params, cpu_devices):
    """forward_full with Ulysses a2a attention == core attention."""
    from aios_tpu.parallel.ulysses import make_ulysses_attn_fn

    mesh = build_mesh(2, dp=1, sp=2)
    tokens = (
        np.random.default_rng(4).integers(0, 256, size=(2, 64)).astype(np.int32)
    )
    want = np.asarray(M.forward_full(tiny_params, TINY_TEST, tokens))
    got = np.asarray(
        M.forward_full(tiny_params, TINY_TEST, tokens, make_ulysses_attn_fn(mesh))
    )
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-4)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_seq_parallel_sliding_window_parity(impl, cpu_devices):
    """Both sequence-parallel attentions must honor a sliding window —
    silently computing full causal attention for a windowed model would
    diverge gradients from the single-device path."""
    from aios_tpu.parallel.ulysses import ulysses_attention

    B, T, H, KH, D, W = 2, 32, 4, 2, 16, 8
    rng = np.random.default_rng(6)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, KH, D)), jnp.float32)
    want = M.gqa_attention(q, k, v, M.causal_mask(T, W))
    mesh = build_mesh(2, dp=1, sp=2)
    fn = ring_attention if impl == "ring" else ulysses_attention
    got = fn(q, k, v, mesh, window=W)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_ulysses_rejects_indivisible_heads(cpu_devices):
    from aios_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh(4, dp=1, sp=4)  # KH=2 does not divide sp=4
    q = jnp.zeros((1, 8, 4, 8), jnp.float32)
    kv = jnp.zeros((1, 8, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="divide the sp axis"):
        ulysses_attention(q, kv, kv, mesh)


def test_ulysses_train_step_reduces_loss(tiny_params, cpu_devices):
    """The Ulysses seq-parallel train step differentiates and learns."""
    mesh = build_mesh(4, dp=2, sp=2)
    plan = ShardingPlan(mesh)
    init_state, train_step = make_train_step(
        TINY_TEST,
        mesh,
        optimizer=make_optimizer(
            learning_rate=1e-2, warmup_steps=1, total_steps=50
        ),
        seq_parallel="ulysses",
    )
    state = init_state(plan.put_params(tiny_params))
    step_jit = jax.jit(train_step)
    rng = np.random.default_rng(5)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 256, size=(4, 32)), jnp.int32),
        "loss_mask": jnp.ones((4, 32), jnp.float32),
    }
    losses = []
    for _ in range(8):
        state, metrics = step_jit(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
    assert np.isfinite(losses).all()


def test_sharded_train_step_reduces_loss(tiny_params, cpu_devices):
    """Full (dp, sp, tp) train step: loss must drop when overfitting one batch."""
    mesh = build_mesh(8, dp=2, sp=2)  # 2 x 2 x 2
    plan = ShardingPlan(mesh)
    params = plan.put_params(tiny_params)

    init_state, train_step = make_train_step(
        TINY_TEST,
        mesh,
        optimizer=make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=50),
    )
    state = init_state(params)
    # no donation here: the module-scoped fixture params may be aliased into
    # the state, and donating would invalidate them for later tests
    step_jit = jax.jit(train_step)

    rng = np.random.default_rng(2)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 256, size=(4, 32)), jnp.int32),
        "loss_mask": jnp.ones((4, 32), jnp.float32),
    }
    losses = []
    for _ in range(8):
        state, metrics = step_jit(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
    assert np.isfinite(losses).all()
    assert int(state["step"]) == 8


def test_train_step_single_device_no_mesh(tiny_params):
    init_state, train_step = make_train_step(
        TINY_TEST,
        mesh=None,
        optimizer=make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=50),
    )
    state = init_state(tiny_params)
    rng = np.random.default_rng(3)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 256, size=(2, 16)), jnp.int32),
        "loss_mask": jnp.ones((2, 16), jnp.float32),
    }
    state, m1 = jax.jit(train_step)(state, batch)
    assert np.isfinite(float(m1["loss"]))


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------


def test_pp_loss_matches_plain_forward():
    import numpy as np

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.train import make_optimizer, make_train_step
    from aios_tpu.parallel.pipeline import (
        build_pp_mesh,
        make_pp_train_step,
        shard_pp_params,
    )

    cfg = TINY_TEST
    assert cfg.num_layers % 2 == 0
    mesh = build_pp_mesh(pp=2, dp=2)
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    sharded = shard_pp_params(params, mesh)

    rng = np.random.default_rng(1)
    B, T = 8, 16
    batch = {
        "tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32),
        "loss_mask": jnp.ones((B, T), jnp.float32),
    }

    opt = make_optimizer(warmup_steps=1, total_steps=10)
    pp_init, pp_step = make_pp_train_step(cfg, mesh, num_microbatches=4, optimizer=opt)
    state = pp_init(sharded)
    state, metrics = jax.jit(pp_step)(state, batch)
    pp_loss = float(metrics["loss"])
    assert int(state["step"]) == 1
    assert np.isfinite(pp_loss) and np.isfinite(float(metrics["grad_norm"]))

    plain_init, plain_step = make_train_step(cfg, mesh=None, optimizer=opt)
    pstate = plain_init(params)
    _, pmetrics = jax.jit(plain_step)(pstate, batch)
    plain_loss = float(pmetrics["loss"])
    np.testing.assert_allclose(pp_loss, plain_loss, rtol=2e-4)


def test_pp_training_reduces_loss():
    import numpy as np

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.train import make_optimizer
    from aios_tpu.parallel.pipeline import (
        build_pp_mesh,
        make_pp_train_step,
        shard_pp_params,
    )

    cfg = TINY_TEST
    mesh = build_pp_mesh(pp=2, dp=1)
    params = M.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    state_params = shard_pp_params(params, mesh)
    init, step = make_pp_train_step(
        cfg, mesh, num_microbatches=2,
        optimizer=make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=20),
    )
    state = init(state_params)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    batch = {"tokens": tokens, "loss_mask": jnp.ones((4, 16), jnp.float32)}
    step_fn = jax.jit(step)
    losses = []
    for _ in range(6):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses  # memorizes the fixed batch


# ---------------------------------------------------------------------------
# context-sharded KV cache (long-context serving over sp)
# ---------------------------------------------------------------------------


def test_seq_sharded_cache_decode_matches_single_device(cpu_devices):
    """KV sharded along the context axis over sp (CACHE_SPEC_SEQ): one
    slot's cache spans chips, outputs bit-match the unsharded engine.
    XLA partitions the attention softmax over the sharded contraction
    (partial stats + psum over sp) — no cache-sized all-gathers."""
    import jax.numpy as jnp

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    plan = ShardingPlan(build_mesh(8, dp=2, sp=2, tp=2))
    ref = TPUEngine(cfg, params, num_slots=4, max_context=64,
                    cache_dtype=jnp.float32)
    eng = TPUEngine(cfg, params, num_slots=4, max_context=64,
                    cache_dtype=jnp.float32, shardings=plan,
                    seq_sharded_cache=True)
    try:
        assert str(eng.state["k"].sharding.spec) == str(
            P(None, "dp", "sp", "tp", None)
        )
        prompt = [1, 2, 3, 4, 5] * 4
        assert eng.generate(prompt, max_new_tokens=16, temperature=0.0) == \
            ref.generate(prompt, max_new_tokens=16, temperature=0.0)
        for s in range(4):
            eng.prefill(s, list(range(1, 10 + s)), temperature=0.0)
            ref.prefill(s, list(range(1, 10 + s)), temperature=0.0)
        assert (eng.step(5) == ref.step(5)).all()
    finally:
        eng.close()
        ref.close()


def test_seq_sharded_cache_int8_kv(cpu_devices):
    import jax.numpy as jnp

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    plan = ShardingPlan(build_mesh(8, dp=2, sp=2, tp=2))
    eng = TPUEngine(cfg, params, num_slots=2, max_context=64,
                    cache_dtype=jnp.int8, shardings=plan,
                    seq_sharded_cache=True)
    ref = TPUEngine(cfg, params, num_slots=2, max_context=64,
                    cache_dtype=jnp.int8)
    try:
        assert eng.prefill(0, [1, 2, 3, 4], temperature=0.0) == \
            ref.prefill(0, [1, 2, 3, 4], temperature=0.0)
        assert (eng.step(3) == ref.step(3)).all()
    finally:
        eng.close()
        ref.close()


def test_seq_sharded_cache_guards(cpu_devices):
    import jax.numpy as jnp
    import pytest

    from aios_tpu.engine import model as M
    from aios_tpu.engine.config import TINY_TEST
    from aios_tpu.engine.engine import TPUEngine

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    with pytest.raises(ValueError, match="sharding plan"):
        TPUEngine(cfg, params, num_slots=2, max_context=64,
                  cache_dtype=jnp.float32, seq_sharded_cache=True)
    plan = ShardingPlan(build_mesh(8, dp=2, sp=2, tp=2))
    with pytest.raises(ValueError, match="paged"):
        TPUEngine(cfg, params, num_slots=2, max_context=64,
                  cache_dtype=jnp.float32, shardings=plan,
                  seq_sharded_cache=True, paged_pool_rows=128)


def test_tp_int4_weights_decode_matches_single_device(cpu_devices):
    """int4 packed-nibble weights compose with a TP plan (VERDICT r3
    item 3): the per-device shard_map int4 matmuls (col shards, row shards
    + tp psum — ShardingPlan.int4_matmul_impl) must reproduce the
    single-chip int4 engine's greedy decode exactly.

    Geometry is chosen so the row-parallel scale groups coincide between
    the single-chip and sharded quantizations (pick_group(K) ==
    pick_group(K/tp) needs K/tp >= 128) — with matching groups the stored
    q4/s4 values are identical and decode is bit-comparable.
    """
    from aios_tpu.engine.config import ModelConfig

    cfg = ModelConfig(
        name="tiny-int4-tp",
        vocab_size=512,
        hidden_size=256,
        intermediate_size=256,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=64,
        max_context=128,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = [3, 17, 91, 4, 55, 8]
    ref = TPUEngine(
        cfg, params, num_slots=4, max_context=64,
        cache_dtype=jnp.float32, quantize="int4",
    )
    want = ref.generate(prompt, max_new_tokens=8)
    assert ref.quant_mode == "int4"

    plan = ShardingPlan(build_mesh(4, dp=2))  # dp=2 x tp=2
    tp = TPUEngine(
        cfg, params, num_slots=4, max_context=64,
        cache_dtype=jnp.float32, quantize="int4", shardings=plan,
    )
    try:
        # the plan must NOT have downgraded to int8: q4 leaves present
        assert tp.quant_mode == "int4"
        assert any(
            isinstance(v, dict) and "q4" in v
            for v in tp.params["layers"].values()
        )
        got = tp.generate(prompt, max_new_tokens=8)
        assert got == want
        # batched decode too: four slots stepping together
        for s in range(4):
            tp.prefill(s, [1 + s, 2, 3], temperature=0.0)
            ref.prefill(s, [1 + s, 2, 3], temperature=0.0)
        assert (tp.step(4) == ref.step(4)).all()
    finally:
        tp.close()
        ref.close()


def test_tp_int4_ineligible_dims_fall_back_to_int8(tiny_params, cpu_devices):
    """TINY_TEST's row dims shard to K/tp < 128, where the shard-local
    scale groups would diverge from the single-chip layout; the engine
    still serves (per-leaf int8 fallback happens inside quantize_params
    when shards are ineligible ON TPU; on CPU the storage path keeps q4)
    and decode completes under the plan."""
    plan = ShardingPlan(build_mesh(4, dp=2))
    eng = TPUEngine(
        TINY_TEST, tiny_params, num_slots=4, max_context=64,
        cache_dtype=jnp.float32, quantize="int4", shardings=plan,
    )
    try:
        toks = eng.generate([3, 17, 91], max_new_tokens=4)
        assert len(toks) == 4
    finally:
        eng.close()


def test_paged_pool_dp_replicated_decode_matches_single_device(cpu_devices):
    """Paged KV pool under a dp x tp plan (VERDICT r3 item 3): the pool's
    page axis shards over dp with replica-local page tables, pool ops run
    per device under shard_map (ShardingPlan.paged_pool_impl /
    paged_prefill_scatter), and greedy decode matches the unreplicated
    paged engine slot for slot — including slots owned by replica 1."""
    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    kw = dict(
        num_slots=4, max_context=64, cache_dtype=jnp.float32,
        paged_pool_rows=256, page_size=16,
    )
    ref = TPUEngine(cfg, params, **kw)
    plan = ShardingPlan(build_mesh(4, dp=2))  # dp=2 x tp=2
    eng = TPUEngine(cfg, params, shardings=plan, **kw)
    try:
        assert eng.paged and eng.pool_replicas == 2
        assert eng.allocator.replicas == 2
        assert eng.prefix_index is None  # replica-local pages: no sharing
        # slot 0 (replica 0) and slot 3 (replica 1) prefill + batch decode
        for s in (0, 1, 2, 3):
            f_ref = ref.prefill(s, [2 + s, 7, 11, 13, 17], temperature=0.0)
            f_eng = eng.prefill(s, [2 + s, 7, 11, 13, 17], temperature=0.0)
            assert f_eng == f_ref, f"slot {s} first token diverged"
        got = eng.step(6)
        want = ref.step(6)
        assert (got == want).all()
        # replica-local allocation: slot 3's pages came from replica 1
        assert eng.allocator.replica_of(3) == 1
        # spec + chunked admission refuse cleanly under replication
        with pytest.raises(ValueError, match="speculative"):
            eng.spec_step(1, draft_len=2)
        with pytest.raises(ValueError, match="chunked"):
            eng.start_chunked_prefill(0, [1] * 40, chunk=16)
    finally:
        eng.close()
        ref.close()


def test_paged_pool_dp_replicated_int8_kv(cpu_devices):
    """Same dp-replicated pool with the int8 KV pool: scatter_quant and
    the dequantizing gather run inside the shard_map body."""
    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    kw = dict(
        num_slots=2, max_context=64, cache_dtype=jnp.int8,
        paged_pool_rows=192, page_size=16,
    )
    ref = TPUEngine(cfg, params, **kw)
    plan = ShardingPlan(build_mesh(4, dp=2))
    eng = TPUEngine(cfg, params, shardings=plan, **kw)
    try:
        assert eng.prefill(0, [1, 2, 3, 4], temperature=0.0) == \
            ref.prefill(0, [1, 2, 3, 4], temperature=0.0)
        assert eng.prefill(1, [9, 8, 7], temperature=0.0) == \
            ref.prefill(1, [9, 8, 7], temperature=0.0)
        assert (eng.step(4) == ref.step(4)).all()
    finally:
        eng.close()
        ref.close()


def test_dp_pool_cancel_frees_replica_pages(cpu_devices):
    """Request cancellation on a dp-replicated pool: the reap frees the
    victim's pages on ITS replica, the co-resident stream on the other
    replica is untouched, and the replica's free-page count returns to its
    baseline (no leak in the replica-local allocator)."""
    import time

    from aios_tpu.engine.batching import ContinuousBatcher, Request

    cfg = TINY_TEST
    params = M.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    plan = ShardingPlan(build_mesh(4, dp=2))
    eng = TPUEngine(
        cfg, params, shardings=plan, num_slots=4, max_context=4096,
        cache_dtype=jnp.float32, paged_pool_rows=1024, page_size=16,
    )
    b = ContinuousBatcher(eng, chunk_steps=2, admit_chunk_steps=2)
    try:
        alloc = eng.allocator
        baseline = [alloc.free_pages_for(0), alloc.free_pages_for(2)]
        # one long-running request per replica (slots 0-1 -> replica 0,
        # 2-3 -> replica 1; the batcher picks the emptier replica)
        h0 = b.submit(Request(prompt_ids=[1, 2, 3], max_tokens=100_000,
                              temperature=0.0))
        h1 = b.submit(Request(prompt_ids=[4, 5, 6], max_tokens=100_000,
                              temperature=0.0))
        deadline = time.time() + 60
        while b.active_count < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert b.active_count == 2
        # record the victim's placement BEFORE cancelling — the survivor's
        # later fate (it may self-evict at its replica's pool cap) must
        # not matter to the assertions
        victim_slot = h0._live.slot
        victim_replica = alloc.replica_of(victim_slot)
        survivor_replica = alloc.replica_of(h1._live.slot)
        assert {victim_replica, survivor_replica} == {0, 1}
        h0.cancel()
        deadline = time.time() + 30
        while b.cancellations < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert b.cancellations == 1
        assert not h1.aborted  # the other replica's stream was untouched
        # the cancelled stream's replica got all its pages back
        assert alloc.free_pages_for(victim_slot) == baseline[victim_replica]
    finally:
        b.shutdown()
        eng.close()
