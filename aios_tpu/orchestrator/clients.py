"""Lazy gRPC clients to the four services + discovery + health probing.

Reference parity:
  * ServiceClients (agent-core/src/clients.rs): lazily-connected channel per
    service with env-overridable addresses (clients.rs:37-44), 3-attempt
    connect retry then lazy reconnect (73-97), optional discovery resolution
    behind AIOS_USE_DISCOVERY (57-70);
  * ServiceRegistry (agent-core/src/discovery.rs): static-default registry
    with heartbeat expiry (discovery.rs:58-82);
  * HealthChecker (agent-core/src/health.rs): TCP-connect prober on a 10 s
    interval with consecutive-failure counting (health.rs:33-96).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .. import rpc
from ..services import (
    AIRuntimeStub,
    ApiGatewayStub,
    MemoryServiceStub,
    ToolRegistryStub,
    service_address,
)


class ServiceClients:
    """One lazily-created stub per service; channels cached and reset on
    failure by callers."""

    def __init__(
        self,
        runtime_addr: Optional[str] = None,
        tools_addr: Optional[str] = None,
        memory_addr: Optional[str] = None,
        gateway_addr: Optional[str] = None,
    ):
        self.addresses = {
            "runtime": runtime_addr or service_address("runtime"),
            "tools": tools_addr or service_address("tools"),
            "memory": memory_addr or service_address("memory"),
            "gateway": gateway_addr or service_address("gateway"),
        }
        self._stubs: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _stub(self, name: str, cls):
        with self._lock:
            stub = self._stubs.get(name)
            if stub is None:
                stub = cls(rpc.insecure_channel(self.addresses[name]))
                self._stubs[name] = stub
            return stub

    def reset(self, name: str) -> None:
        with self._lock:
            self._stubs.pop(name, None)

    @property
    def runtime(self) -> AIRuntimeStub:  # type: ignore[valid-type]
        return self._stub("runtime", AIRuntimeStub)

    @property
    def tools(self) -> ToolRegistryStub:  # type: ignore[valid-type]
        return self._stub("tools", ToolRegistryStub)

    @property
    def memory(self) -> MemoryServiceStub:  # type: ignore[valid-type]
        return self._stub("memory", MemoryServiceStub)

    @property
    def gateway(self) -> ApiGatewayStub:  # type: ignore[valid-type]
        return self._stub("gateway", ApiGatewayStub)


@dataclass
class ServiceEntry:
    name: str
    address: str
    port: int
    protocol: str = "grpc"
    status: str = "unknown"
    registered_at: float = field(default_factory=time.time)
    last_heartbeat: float = field(default_factory=time.monotonic)


class ServiceRegistry:
    """Static-default discovery registry with heartbeat expiry."""

    HEARTBEAT_EXPIRY = 60.0

    def __init__(self):
        self._services: Dict[str, ServiceEntry] = {}
        self._lock = threading.Lock()
        for name in ("orchestrator", "tools", "memory", "gateway", "runtime"):
            host, port = service_address(name).rsplit(":", 1)
            self.register(ServiceEntry(name=name, address=host, port=int(port)))

    def register(self, entry: ServiceEntry) -> None:
        with self._lock:
            self._services[entry.name] = entry

    def heartbeat(self, name: str) -> bool:
        with self._lock:
            e = self._services.get(name)
            if e is None:
                return False
            e.last_heartbeat = time.monotonic()
            return True

    def resolve(self, name: str) -> Optional[str]:
        with self._lock:
            e = self._services.get(name)
        if e is None:
            return None
        return f"{e.address}:{e.port}"

    def live_services(self) -> List[ServiceEntry]:
        with self._lock:
            return [
                e
                for e in self._services.values()
                if time.monotonic() - e.last_heartbeat < self.HEARTBEAT_EXPIRY
            ]


class HealthChecker:
    """TCP-connect prober with consecutive-failure counters."""

    def __init__(self, interval: float = 10.0,
                 on_failure: Optional[Callable[[str, int], None]] = None):
        self.interval = interval
        self.on_failure = on_failure
        self.targets: Dict[str, str] = {
            name: service_address(name)
            for name in ("runtime", "tools", "memory", "gateway")
        }
        self.consecutive_failures: Dict[str, int] = {}
        self.status: Dict[str, bool] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def failure_snapshot(self) -> Dict[str, int]:
        """Locked copy of the consecutive-failure counters — the health
        thread mutates the dict under the lock, so readers (proactive
        feed, console health panel) must not iterate it bare."""
        with self._lock:
            return dict(self.consecutive_failures)

    def probe(self, address: str, timeout: float = 2.0) -> bool:
        host, port = address.rsplit(":", 1)
        try:
            with socket.create_connection((host, int(port)), timeout=timeout):
                return True
        except OSError:
            return False

    def check_all(self) -> Dict[str, bool]:
        results = {}
        for name, addr in self.targets.items():
            healthy = self.probe(addr)
            results[name] = healthy
            with self._lock:
                self.status[name] = healthy
                if healthy:
                    self.consecutive_failures[name] = 0
                else:
                    n = self.consecutive_failures.get(name, 0) + 1
                    self.consecutive_failures[name] = n
                    if self.on_failure is not None:
                        self.on_failure(name, n)
        return results

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.check_all()
                except Exception:  # noqa: BLE001
                    pass

        self._thread = threading.Thread(target=loop, name="health-checker",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
