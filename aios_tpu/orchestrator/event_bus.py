"""Event bus: pub/sub with goal-creating subscriptions.

Reference parity (agent-core/src/event_bus.rs): bounded queue (1000),
subscriptions {pattern, min_severity, goal_template with {event_type}/
{source} substitution} that auto-create goals on match (event_bus.rs:94-171),
and a ring of the 100 most recent events.
"""

from __future__ import annotations

import collections
import fnmatch
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

SEVERITIES = {"debug": 0, "info": 1, "warning": 2, "error": 3, "critical": 4}


@dataclass
class Event:
    event_type: str
    source: str
    severity: str = "info"
    data: Dict = field(default_factory=dict)
    timestamp: float = field(default_factory=time.time)


@dataclass
class Subscription:
    pattern: str  # fnmatch over event_type
    min_severity: str = "info"
    goal_template: str = ""  # "{event_type}"/"{source}" substituted
    priority: int = 5
    callback: Optional[Callable[[Event], None]] = None


class EventBus:
    def __init__(
        self,
        submit_goal: Optional[Callable[[str, int], object]] = None,
        capacity: int = 1000,
        recent: int = 100,
    ):
        self.submit_goal = submit_goal
        self._queue: collections.deque = collections.deque(maxlen=capacity)
        self._recent: collections.deque = collections.deque(maxlen=recent)
        self._subs: List[Subscription] = []
        self._lock = threading.Lock()
        self.published = 0
        self.goals_created = 0

    def subscribe(self, sub: Subscription) -> None:
        with self._lock:
            self._subs.append(sub)

    def publish(self, event: Event) -> None:
        with self._lock:
            self._queue.append(event)
            self._recent.append(event)
            self.published += 1
            subs = list(self._subs)
        sev = SEVERITIES.get(event.severity, 1)
        for sub in subs:
            if not fnmatch.fnmatch(event.event_type, sub.pattern):
                continue
            if sev < SEVERITIES.get(sub.min_severity, 1):
                continue
            if sub.callback is not None:
                try:
                    sub.callback(event)
                except Exception:  # noqa: BLE001
                    pass
            if sub.goal_template and self.submit_goal is not None:
                description = sub.goal_template.format(
                    event_type=event.event_type, source=event.source
                )
                self.submit_goal(description, sub.priority)
                self.goals_created += 1

    def recent_events(self, limit: int = 100) -> List[Event]:
        with self._lock:
            return list(self._recent)[-limit:]
