#!/usr/bin/env bash
# Fetch the GGUF weights for the local model tiers.
#
# TPU-native equivalent of /root/reference/scripts/download-models.sh: same
# model set (the runtime's intelligence ladder, model_manager.rs:462-518),
# same GGUF artifacts — the TPU runtime dequantizes GGUF into HBM-resident
# int8/int4/bf16 params at load (aios_tpu/engine/gguf.py) instead of
# handing the file to llama.cpp.
#
# Integrity: pinned sha256 when the spec carries one (the artifacts are
# fixed public files with stable hashes — fill the pin field from the HF
# repo's published checksums on a networked host; this zero-egress build
# env cannot fetch them, and a made-up pin would reject every download).
# Unpinned entries fall back to trust-on-first-use: the first successful
# download records its sha256 into $DEST/SHA256SUMS and every later run
# (and --verify-only) checks against that record, so a corrupted
# re-download or bit-rotted file fails loudly instead of producing
# garbage decode.
#
# Usage: scripts/download-models.sh [--dest DIR] [--tier tiny|tactical|all]
#                                   [--verify-only]
set -euo pipefail

DEST=/var/lib/aios/models
TIER=tiny
VERIFY_ONLY=0

while [[ $# -gt 0 ]]; do
  case "$1" in
    --dest) DEST="$2"; shift 2 ;;
    --tier) TIER="$2"; shift 2 ;;
    --verify-only) VERIFY_ONLY=1; shift ;;
    *) echo "unknown flag: $1" >&2; exit 2 ;;
  esac
done

mkdir -p "$DEST"
SUMS="$DEST/SHA256SUMS"
touch "$SUMS"

# name|url|min_bytes|pinned_sha256 (min_bytes: a truncated or HTML-error
# download is smaller than any real quantized model of the tier; pin: the
# upstream file's published sha256, or empty for TOFU — populate pins on a
# networked host via `sha256sum` against the HF repo's checksum listing)
TINY="tinyllama-1.1b-chat-v1.0.Q4_K_M.gguf|https://huggingface.co/TheBloke/TinyLlama-1.1B-Chat-v1.0-GGUF/resolve/main/tinyllama-1.1b-chat-v1.0.Q4_K_M.gguf|500000000|"
MISTRAL="mistral-7b-instruct-v0.2.Q4_K_M.gguf|https://huggingface.co/TheBloke/Mistral-7B-Instruct-v0.2-GGUF/resolve/main/mistral-7b-instruct-v0.2.Q4_K_M.gguf|4000000000|"

case "$TIER" in
  tiny)     MODELS=("$TINY") ;;
  tactical) MODELS=("$MISTRAL") ;;
  all)      MODELS=("$TINY" "$MISTRAL") ;;
  *) echo "unknown tier: $TIER" >&2; exit 2 ;;
esac

verify() {  # verify <file> [pin]; 0=ok 1=bad 2=unrecorded-and-unpinned
  local f="$1" pin="${2:-}" rec
  if [[ -n "$pin" ]]; then
    # a pinned hash outranks the TOFU record: it came from the publisher,
    # not from whatever the first download happened to produce
    echo "$pin  $f" | sha256sum -c --quiet - >/dev/null 2>&1
    return $?
  fi
  rec=$(grep "  ${f##*/}\$" "$SUMS" | head -1 | cut -d' ' -f1) || true
  [[ -z "$rec" ]] && return 2
  echo "$rec  $f" | sha256sum -c --quiet - >/dev/null 2>&1
}

record() {  # record <file> [known_sum] — known_sum skips re-hashing a
  local f="$1" name sum  # multi-GB file whose hash was just verified
  name="${f##*/}"
  sum="${2:-$(sha256sum "$f" | cut -d' ' -f1)}"
  grep -v "  $name\$" "$SUMS" > "$SUMS.tmp" || true
  echo "$sum  $name" >> "$SUMS.tmp"
  mv "$SUMS.tmp" "$SUMS"
  echo "[models] recorded sha256 $sum for $name"
}

rc=0
for spec in "${MODELS[@]}"; do
  IFS='|' read -r name url min_bytes pin <<< "$spec"
  out="$DEST/$name"
  if [[ -f "$out" ]]; then
    if verify "$out" "$pin"; then
      echo "[models] $name present and verified, skipping"
      continue
    elif [[ $? -eq 2 ]]; then
      if [[ $VERIFY_ONLY -eq 1 ]]; then
        # verify-only must never bless unverifiable state: recording the
        # hash of a possibly-corrupt file would convert the corruption
        # into the trusted baseline
        echo "[models] $name present but UNRECORDED; re-run without" \
             "--verify-only to record its checksum" >&2
        rc=1
      else
        echo "[models] $name present (no recorded checksum); recording"
        record "$out"
      fi
      continue
    else
      kind=recorded; [[ -n "$pin" ]] && kind=pinned
      echo "[models] ERROR: $name fails its $kind sha256" >&2
      rc=1
      continue
    fi
  fi
  if [[ $VERIFY_ONLY -eq 1 ]]; then
    echo "[models] $name missing (verify-only mode)" >&2
    rc=1
    continue
  fi
  echo "[models] fetching $name"
  # -C - resumes a partial .part from a prior INTERRUPTED run (real prefix
  # bytes); a curl failure must not abort the other models (set -e)
  if ! curl -fL --retry 3 --retry-delay 5 -C - -o "$out.part" "$url"; then
    echo "[models] ERROR: download failed for $name; .part kept for" \
         "resume" >&2
    rc=1
    continue
  fi
  size=$(stat -c%s "$out.part")
  if [[ "$size" -lt "$min_bytes" ]]; then
    # a COMPLETED body below the floor is an interstitial/error page, not
    # a partial transfer — resuming onto it would splice real bytes after
    # garbage, so it must not survive
    echo "[models] ERROR: $name completed at $size bytes (< $min_bytes" \
         "floor) — error page or wrong artifact; discarding" >&2
    rm -f "$out.part"
    rc=1
    continue
  fi
  if [[ -n "$pin" ]] && ! verify "$out.part" "$pin"; then
    # a fresh download failing its publisher pin is tampering/corruption,
    # never a state to keep or to record as trusted
    echo "[models] ERROR: $name download fails pinned sha256; discarding" >&2
    rm -f "$out.part"
    rc=1
    continue
  fi
  mv "$out.part" "$out"
  record "$out" "$pin"
done

echo "[models] done; $(ls "$DEST"/*.gguf 2>/dev/null | wc -l) model file(s) in $DEST"
exit $rc
