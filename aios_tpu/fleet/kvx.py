"""KV transfer protocol (``aios.fleet.KvTransfer``): HostPageStore
entries over gRPC, crc32-verified at both ends.

The wire unit is one prefix-cache page: ``PageEntry(hash, crc32,
payload)`` where ``payload`` is :func:`aios_tpu.engine.paged.pack_entry`
bytes and ``crc32`` is ``HostPageStore._entry_crc`` over the ARRAYS (the
same checksum the host tier computes at spill time) — so the receiver
re-derives it from the unpacked entry and a flipped bit anywhere in
transit, or in the sender's host RAM, fails verification and never
scatters into live KV. Entries ride in ``PageChunk`` batches bounded by
``AIOS_TPU_FLEET_KVX_CHUNK_BYTES`` (the gRPC message ceiling is 64 MB;
chunking keeps one transfer from monopolizing the stream).

Two verbs move pages (the closed :data:`KVX_DIRECTIONS` enum):

  * ``push`` — the prefill host streams pages it just computed to its
    decode target (:func:`push_chain` -> the ``Push`` RPC);
  * ``pull`` — a decode host fetches a chain the fleet router promised
    (:func:`fetch_chain` -> the ``Fetch`` RPC; the server exports
    HBM-resident pages first, then its host tier).

Every failure mode is a closed-enum cause (:data:`KVX_FAIL_CAUSES`) on
``aios_tpu_fleet_kvx_failures_total`` and degrades to local prefill —
the PR 10 ``restore_fail`` contract: a failed transfer is a cache miss,
never a wrong answer. Client stubs are NEVER called under a declared
lock (the analyzer's rpc-under-lock rule).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import services
from ..engine import paged
from ..obs import instruments as obs

log = logging.getLogger("aios.fleet.kvx")

# Transfer directions — THE closed enum (pinned by test_obs_lint):
# push = prefill host streaming pages out, pull = decode host fetching
# a promised chain on miss.
KVX_DIRECTIONS = ("push", "pull")

# Transfer-failure causes — closed enum, iterated at registration:
#   unavailable   peer unreachable / RPC failed outright
#   timeout       RPC deadline expired mid-transfer
#   crc_mismatch  receiving end re-derived a different crc32 (the
#                 verified-at-both-ends contract rejecting a payload)
#   decode_error  payload failed pack_entry framing
#   empty         the promised chain came back with zero entries (the
#                 gossiped digest was stale, or a 64-bit tail collided)
#   breaker_open  the per-peer circuit breaker (fleet/breaker.py) refused
#                 the transfer locally — no wire traffic, no timeout
#                 stall; the peer is quarantined until probes clear it
KVX_FAIL_CAUSES = (
    "unavailable", "timeout", "crc_mismatch", "decode_error", "empty",
    "breaker_open",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def chunk_bytes() -> int:
    """Per-PageChunk payload budget (AIOS_TPU_FLEET_KVX_CHUNK_BYTES)."""
    return int(_env_float("AIOS_TPU_FLEET_KVX_CHUNK_BYTES", 8 << 20))


def transfer_timeout() -> float:
    """Per-RPC deadline (AIOS_TPU_FLEET_KVX_TIMEOUT_SECS)."""
    return _env_float("AIOS_TPU_FLEET_KVX_TIMEOUT_SECS", 5.0)


def fetch_budget() -> int:
    """Total bytes one Fetch may return (AIOS_TPU_FLEET_KVX_BUDGET_BYTES)
    — bounds how much host RAM a single pull can claim on either end."""
    return int(_env_float("AIOS_TPU_FLEET_KVX_BUDGET_BYTES", 128 << 20))


def register_kvx_metrics(model: str) -> None:
    """Pre-register every transfer metric child for ``model`` by
    iterating the closed enums (the fleet/autoscale registration
    pattern): a new direction or cause is a reviewed enum change, never
    a stray label value."""
    for direction in KVX_DIRECTIONS:
        obs.FLEET_KVX_PAGES.labels(model=model, direction=direction)
        obs.FLEET_KVX_BYTES.labels(model=model, direction=direction)
    for cause in KVX_FAIL_CAUSES:
        obs.FLEET_KVX_FAILURES.labels(model=model, cause=cause)


def count_failure(model: str, cause: str) -> None:
    """One failed transfer, by closed-enum cause."""
    obs.FLEET_KVX_FAILURES.labels(model=model, cause=cause).inc()


# -- wire helpers ------------------------------------------------------------

def entries_to_chunks(
    model: str, triples: Sequence[Tuple[bytes, int, bytes]]
) -> Iterator[object]:
    """``(hash, crc32, payload-bytes)`` triples -> a PageChunk stream
    bounded by :func:`chunk_bytes` per message."""
    from ..proto_gen import fleet_pb2

    budget = chunk_bytes()
    batch: List[object] = []
    size = 0
    for h, crc, payload in triples:
        entry = fleet_pb2.PageEntry(hash=h, crc32=crc, payload=payload)
        if batch and size + len(payload) > budget:
            yield fleet_pb2.PageChunk(model=model, entries=batch)
            batch, size = [], 0
        batch.append(entry)
        size += len(payload)
    if batch:
        yield fleet_pb2.PageChunk(model=model, entries=batch)


def verify_entry(e) -> Dict[str, np.ndarray]:
    """Receiving-end half of the verified-at-both-ends contract: unpack
    the payload and re-derive its crc32 from the ARRAYS. Raises
    ``ValueError`` on framing damage (a ``decode_error``) and
    :class:`CrcMismatch` when the checksum disagrees."""
    entry = paged.unpack_entry(e.payload)
    if paged.HostPageStore._entry_crc(entry) != e.crc32:
        raise CrcMismatch(f"page {e.hash.hex()[:16]} failed crc32")
    return entry


class CrcMismatch(ValueError):
    """A transferred page whose receiving-end crc32 disagrees with the
    wire's — distinct type so call sites count the right cause."""


# -- the servicer ------------------------------------------------------------

class KvxService(services.KvTransferServicer):
    """Fetch/Push halves of the transfer plane, backed by a
    :class:`~aios_tpu.runtime.model_manager.ModelManager`. ``Handoff``
    stays UNIMPLEMENTED here — :class:`aios_tpu.fleet.disagg
    .DisaggService` subclasses in the disaggregation half."""

    def __init__(self, manager) -> None:
        self.manager = manager

    def _engine_of(self, model: str, context):
        import grpc

        m = self.manager.get(model)
        if m is None or m.engine is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"model {model} not loaded"
            )
        return m.engine

    def Fetch(self, request, context):
        """Serve a promised chain: HBM-resident pages first (the
        engine's export pays the device->host copy), then the host
        spill tier for the remainder — mirroring ``_match_prefix``'s
        two-tier probe. Stops at the first gap (a chain transfer past a
        hole would restore nothing) and at the byte budget."""
        engine = self._engine_of(request.model, context)
        hashes = list(request.hashes)
        budget = int(request.budget_bytes) or fetch_budget()
        triples: List[Tuple[bytes, int, bytes]] = []
        total = 0
        hbm = engine.export_hashes(hashes)
        for h, entry in hbm:
            payload = paged.pack_entry(entry)
            crc = paged.HostPageStore._entry_crc(entry)
            if triples and total + len(payload) > budget:
                break
            triples.append((h, crc, payload))
            total += len(payload)
        store = engine.host_store
        if store is not None and len(triples) == len(hbm) and total < budget:
            for h, crc, entry in store.export_chain(
                hashes[len(hbm):], budget_bytes=budget - total
            ):
                payload = paged.pack_entry(entry)
                triples.append((h, crc, payload))
                total += len(payload)
        log.debug(
            "kvx fetch: %s serving %d/%d pages (%d bytes)",
            request.model, len(triples), len(hashes), total,
        )
        yield from entries_to_chunks(request.model, triples)

    def Push(self, request_iterator, context):
        """Accept pushed pages into the local host tier. Every entry is
        verified HERE (the receiving end): a crc mismatch or framing
        error rejects THAT entry and counts the closed-enum cause —
        accepting its siblings is safe because host-store entries are
        independent (`match_chain` just truncates at the hole)."""
        from ..proto_gen import fleet_pb2

        accepted = rejected = 0
        model = ""
        for chunk in request_iterator:
            model = chunk.model or model
            store = None
            m = self.manager.get(model) if model else None
            if m is not None and m.engine is not None:
                store = m.engine.host_store
            for e in chunk.entries:
                if store is None:
                    rejected += 1
                    continue
                try:
                    entry = verify_entry(e)
                except CrcMismatch:
                    count_failure(model, "crc_mismatch")
                    rejected += 1
                    continue
                except ValueError:
                    count_failure(model, "decode_error")
                    rejected += 1
                    continue
                store.put(e.hash, entry)
                accepted += 1
        if model:
            log.debug(
                "kvx push: %s accepted %d rejected %d", model, accepted,
                rejected,
            )
        return fleet_pb2.PushAck(accepted=accepted, rejected=rejected)


# -- client helpers ----------------------------------------------------------

# channel cache: one gRPC channel per peer address for process life
# (plain lock, never on a request hot path past the first call per addr)
_channels: Dict[str, object] = {}
_channels_lock = threading.Lock()


def _stub(addr: str):
    from .. import rpc

    with _channels_lock:
        ch = _channels.get(addr)
        if ch is None:
            ch = _channels[addr] = rpc.insecure_channel(addr)
    return services.KvTransferStub(ch)


def reset_channels() -> None:
    """Test isolation: drop cached peer channels."""
    with _channels_lock:
        chans = list(_channels.values())
        _channels.clear()
    for ch in chans:
        try:
            ch.close()
        except Exception:  # noqa: BLE001 - closing a dead channel is fine
            pass


def _rpc_cause(exc) -> str:
    import grpc

    if isinstance(exc, grpc.RpcError) and (
        exc.code() is grpc.StatusCode.DEADLINE_EXCEEDED
    ):
        return "timeout"
    return "unavailable"


def push_chain(
    addr: str, model: str,
    pairs: Sequence[Tuple[bytes, Dict[str, np.ndarray]]],
    peer: str = "",
) -> int:
    """Push ``(hash, entry)`` pairs (``engine.export_prefix`` output) to
    ``addr``'s host tier. Returns the count the receiver ACCEPTED (its
    crc verification may reject pages ours passed — that is the point of
    verifying at both ends); 0 on any RPC failure, with the cause
    counted. Never raises: a failed push just means the decode host
    pulls or recomputes. ``peer`` (the target's fleet host id) gates the
    transfer on — and feeds — the per-peer circuit breaker: a
    quarantined peer costs a local ``breaker_open`` count instead of a
    full transfer-timeout stall."""
    if not pairs:
        return 0
    from . import breaker

    if peer and not breaker.BOARD.allow(peer):
        count_failure(model, "breaker_open")
        log.debug("kvx push to %s (%s) refused: breaker open", addr, peer)
        return 0
    triples = [
        (h, paged.HostPageStore._entry_crc(e), paged.pack_entry(e))
        for h, e in pairs
    ]
    sent_bytes = sum(len(p) for _, _, p in triples)
    t0 = time.monotonic()
    try:
        ack = _stub(addr).Push(
            entries_to_chunks(model, triples), timeout=transfer_timeout()
        )
    except Exception as exc:  # noqa: BLE001 - any transport failure is the
        # same outcome: the pages do not arrive; the counter carries why
        cause = _rpc_cause(exc)
        count_failure(model, cause)
        if peer:
            breaker.BOARD.record_failure(peer, cause)
        log.warning("kvx push to %s failed: %r", addr, exc)
        return 0
    if peer:
        breaker.BOARD.record_ok(peer, time.monotonic() - t0)
    obs.FLEET_KVX_PAGES.labels(model=model, direction="push").inc(
        float(ack.accepted)
    )
    obs.FLEET_KVX_BYTES.labels(model=model, direction="push").inc(
        float(sent_bytes)
    )
    return int(ack.accepted)


def fetch_chain(
    addr: str, model: str, hashes: Sequence[bytes],
    budget_bytes: int = 0, peer: str = "",
) -> List[Tuple[bytes, Dict[str, np.ndarray]]]:
    """Pull a promised chain from ``addr``. Every received entry is
    verified HERE (receiving end); the chain truncates at the first bad
    or out-of-order entry — a prefix chain with a hole restores nothing
    past it. Returns verified ``(hash, entry)`` pairs, possibly empty
    (the caller falls back to local prefill); never raises. ``peer``
    (the source's fleet host id) gates on — and feeds — the per-peer
    circuit breaker, same contract as :func:`push_chain`."""
    from ..proto_gen import fleet_pb2
    from . import breaker

    if peer and not breaker.BOARD.allow(peer):
        count_failure(model, "breaker_open")
        log.debug("kvx fetch from %s (%s) refused: breaker open",
                  addr, peer)
        return []
    want = list(hashes)
    out: List[Tuple[bytes, Dict[str, np.ndarray]]] = []
    got_bytes = 0
    counted = False
    fail_cause = ""
    t0 = time.monotonic()
    try:
        stream = _stub(addr).Fetch(
            fleet_pb2.FetchRequest(
                model=model, hashes=want,
                budget_bytes=budget_bytes or fetch_budget(),
            ),
            timeout=transfer_timeout(),
        )
        for chunk in stream:
            for e in chunk.entries:
                if len(out) >= len(want) or e.hash != want[len(out)]:
                    log.warning(
                        "kvx fetch from %s: out-of-chain page; truncating",
                        addr,
                    )
                    raise _Truncate()
                try:
                    entry = verify_entry(e)
                except CrcMismatch:
                    count_failure(model, "crc_mismatch")
                    counted = True
                    fail_cause = "crc_mismatch"
                    raise _Truncate()
                except ValueError:
                    count_failure(model, "decode_error")
                    counted = True
                    fail_cause = "decode_error"
                    raise _Truncate()
                out.append((e.hash, entry))
                got_bytes += len(e.payload)
    except _Truncate:
        pass
    except Exception as exc:  # noqa: BLE001 - transport failure mid-pull:
        # keep the verified prefix, count why the rest never came
        fail_cause = _rpc_cause(exc)
        count_failure(model, fail_cause)
        counted = True
        log.warning("kvx fetch from %s failed: %r", addr, exc)
    if peer:
        if fail_cause:
            breaker.BOARD.record_failure(peer, fail_cause)
        else:
            # an "empty" chain from a healthy peer is a stale digest,
            # not a peer fault — it does not feed the breaker
            breaker.BOARD.record_ok(peer, time.monotonic() - t0)
    if not out:
        # a promise that yielded nothing is its own cause — unless a
        # more specific failure already explained it
        if not counted:
            count_failure(model, "empty")
        return []
    obs.FLEET_KVX_PAGES.labels(model=model, direction="pull").inc(
        float(len(out))
    )
    obs.FLEET_KVX_BYTES.labels(model=model, direction="pull").inc(
        float(got_bytes)
    )
    return out


class _Truncate(Exception):
    """Internal: stop consuming a fetch stream at a bad entry, keeping
    the verified prefix (the failure cause is already counted)."""
