"""Host-side page-table management for the paged KV cache.

The device holds a fixed page pool ([L, N, P, KH, D] per k/v) and reads it
through per-slot page tables; THIS module owns the mapping. Allocation is a
free-list pop, release a push — O(1), no compaction, no device traffic
beyond the [S, MAX_BLOCKS] int32 table that rides along with each dispatch
(a few hundred bytes). The scheduler's admission/retire cycle calls
`ensure`/`free_slot`; a pool that can't back a grow request raises
`PoolExhausted` so the batcher can retire a victim request instead of
corrupting anyone's cache.

Page 0 is reserved as the *sacrificial page*: never allocated, mapped by
every unbacked table entry, and the write target for inactive slots — the
paged twin of the dense engine's sacrificial last cache row.

Reference equivalence: llama.cpp's per-sequence KV cells behind
llama-server (SURVEY.md section 2.3); redesigned as vLLM/JetStream-style
paging because HBM reservation, not compute, is what caps co-resident
slots x context on a TPU chip (SURVEY.md section 7.2, hard part no. 1).
"""

from __future__ import annotations

from typing import List

import numpy as np

SACRIFICIAL_PAGE = 0


class PoolExhausted(RuntimeError):
    """No free pages left to back a prefill/decode grow request."""

    def __init__(self, needed: int, free: int):
        super().__init__(
            f"KV page pool exhausted: need {needed} page(s), {free} free"
        )
        self.needed = needed
        self.free = free


class PageAllocator:
    """Free-list allocator over ``num_pages`` physical pages of ``page_size``
    rows, mapping ``num_slots`` slots x ``max_blocks`` logical blocks."""

    def __init__(self, num_pages: int, page_size: int, num_slots: int,
                 max_blocks: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one is sacrificial)")
        self.num_pages = num_pages
        self.page_size = page_size
        self.num_slots = num_slots
        self.max_blocks = max_blocks
        # page 0 is the sacrificial page — never on the free list
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        # host copy of the device tables; unbacked entries map page 0
        self.tables = np.full((num_slots, max_blocks), SACRIFICIAL_PAGE,
                              dtype=np.int32)
        self._blocks_used = np.zeros(num_slots, dtype=np.int64)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def blocks_for(self, rows: int) -> int:
        return -(-rows // self.page_size)  # ceil

    def ensure(self, slot: int, rows: int) -> bool:
        """Back slot ``slot`` for ``rows`` logical rows; allocates any
        missing pages. Returns True iff the table changed. Raises
        PoolExhausted (leaving existing pages intact) if the free list
        can't cover the growth."""
        need = min(self.blocks_for(rows), self.max_blocks)
        have = int(self._blocks_used[slot])
        if need <= have:
            return False
        grow = need - have
        if grow > len(self._free):
            raise PoolExhausted(grow, len(self._free))
        for b in range(have, need):
            self.tables[slot, b] = self._free.pop()
        self._blocks_used[slot] = need
        return True

    def free_slot(self, slot: int) -> None:
        """Return all of a slot's pages to the free list."""
        used = int(self._blocks_used[slot])
        for b in range(used):
            self._free.append(int(self.tables[slot, b]))
            self.tables[slot, b] = SACRIFICIAL_PAGE
        self._blocks_used[slot] = 0

    def slot_rows_backed(self, slot: int) -> int:
        return int(self._blocks_used[slot]) * self.page_size
