"""Trace-driven storm load generation for the serving plane.

The million-user storm harness (docs/TESTING.md, docs/RUNBOOK.md §8): a
SEEDED, deterministic workload generator that drives the full gRPC
surface (``Infer``/``StreamInfer`` through the real runtime service —
never the batcher directly) with production-shaped traffic:

  * declarative scenarios (TOML/JSON — :mod:`scenario`) composing tenant
    mixes, diurnal/burst/Poisson arrival curves, long-tail prompt/output
    length distributions, shared-prefix fork-shaped agent-loop call
    patterns (the radix cache's workload), abusive-tenant quota storms,
    and deadline-carrying reactive-tier requests;
  * a pure trace builder (:mod:`trace`) — the whole call schedule is a
    deterministic function of (scenario, seed), so two runs submit
    byte-identical work;
  * a wall-clock driver (:mod:`driver`) replaying the trace over gRPC
    and recording per-request outcomes (TTFT/TPOT, shed causes,
    retry-after hints, stream text);
  * a verdict builder (:mod:`report`) separating the DETERMINISTIC
    fingerprint (counts, greedy stream hashes, pass/fail against the
    scenario's declared SLO targets) from timing measurements, plus the
    live ``/debug/slo`` surface readback.

``bench.py --storm`` runs a committed scenario twice and fails on any
fingerprint divergence — the contention-realistic regression gate beside
tier-1 and the chaos storm (it composes with ``--chaos``: same storm,
seeded faults armed).
"""

from .scenario import SLOTargets, StormScenario, TenantSpec, load_scenario
from .trace import Call, build_trace, trace_fingerprint
from .driver import FleetStormDriver, Outcome, StormDriver, target_of
from .report import build_report

__all__ = [
    "Call",
    "FleetStormDriver",
    "Outcome",
    "SLOTargets",
    "StormDriver",
    "StormScenario",
    "TenantSpec",
    "build_report",
    "build_trace",
    "load_scenario",
    "target_of",
    "trace_fingerprint",
]
