"""Hardware detection at boot.

Reference parity (initd/src/hardware.rs:37+): CPU/memory/disk discovery from
/proc and /sys. TPU-specific addition: detects attached TPU chips through
JAX (deferred import so boot works on hosts without accelerators) — the
reference's GPU detection has no TPU notion at all.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List

import psutil


@dataclass
class HardwareInfo:
    cpu_model: str = ""
    cpu_cores: int = 0
    cpu_threads: int = 0
    memory_total_mb: int = 0
    disks: List[Dict] = field(default_factory=list)
    tpu_devices: List[str] = field(default_factory=list)
    tpu_backend: str = ""

    @property
    def has_tpu(self) -> bool:
        return bool(self.tpu_devices)


def detect(probe_tpu: bool = True) -> HardwareInfo:
    info = HardwareInfo()
    try:
        for line in Path("/proc/cpuinfo").read_text().splitlines():
            if line.startswith("model name"):
                info.cpu_model = line.split(":", 1)[1].strip()
                break
    except OSError:
        pass
    info.cpu_cores = psutil.cpu_count(logical=False) or 0
    info.cpu_threads = psutil.cpu_count() or 0
    info.memory_total_mb = int(psutil.virtual_memory().total / 1e6)
    for part in psutil.disk_partitions(all=False):
        try:
            usage = psutil.disk_usage(part.mountpoint)
        except OSError:
            continue
        info.disks.append(
            {"mount": part.mountpoint, "total_gb": round(usage.total / 1e9, 1)}
        )
    if probe_tpu:
        try:
            import jax

            info.tpu_devices = [str(d) for d in jax.devices()]
            info.tpu_backend = jax.default_backend()
        except Exception:  # no accelerator / no jax — boot proceeds
            pass
    return info
