"""Fleet incident bundles: freeze *everything* around an anomaly trigger.

The anomaly system grew one trigger at a time — flightrec auto-snapshots
(abort / shed spike / crash-respawn / SLO breach), autoscale actions,
breaker open->quarantine edges, fired faults — and each freezes only its
own evidence. This module closes the loop: every trigger also produces
one **incident bundle** holding the cross-layer context an operator
actually pages through afterwards:

  * the tsdb window +/- ``window_secs`` around the trigger
    (:meth:`Tsdb.window_snapshot` — empty-but-marked when the ring is
    unarmed);
  * the matching flight-recorder snapshot (or a live model-lane slice
    when none fired);
  * the fault-injection journal tail (``faults.fired()``);
  * the devprof ledger state (``devprof.snapshot_all()``);
  * lock-watchdog trips (``analysis.locks.watchdog_trips()``).

Triggers funnel through :func:`notify` — a module-global None check when
the store is unarmed (the faults/devprof pattern), so hot paths pay
nothing. The trigger cause is the CLOSED :data:`TRIGGER_CAUSES` enum
(pinned by test_obs_lint, iterated at metric registration); causes
shared with flightrec.SNAPSHOT_CAUSES keep their names so one grep finds
both artifacts.

The store mirrors the flightrec snapshot discipline: the per-(model,
cause) cooldown stamp and incident id are claimed synchronously under
the lock (a burst of triggers freezes exactly one), then the bundle is
built on a background daemon thread — after waiting out the post-trigger
half of the window so the ring holds the aftermath — and appended to a
bounded deque served at ``GET /debug/incidents``. With
``AIOS_TPU_INCIDENT_DUMP_DIR`` set, each bundle also lands on disk as
JSON.

Arming: ``AIOS_TPU_INCIDENTS=1``, or implicitly with ``AIOS_TPU_TSDB``
(bundles center on tsdb windows); ``AIOS_TPU_INCIDENTS=0`` forces off.

Locking: ``_lock`` (registry role "incidents") guards the bundle deque,
cooldown stamps, and the id counter only. Bundle *construction* — which
reads tsdb, the recorder, faults, devprof, and the watchdog under their
own locks — runs outside it; metric/recorder emission likewise.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.locks import make_lock

log = logging.getLogger("aios.incidents")

# THE closed trigger-cause enum (pinned by test_obs_lint, AST-iterated
# at metric registration). The first five ride the flightrec snapshot
# path (SNAPSHOT_CAUSES + manual); "autoscale" hooks the controller's
# action journal, "breaker_open" the quarantine board's open edge,
# "fault" the injection layer's fired-fault record. A new trigger is a
# reviewed enum change, never a stray label value.
TRIGGER_CAUSES = ("abort", "autoscale", "breaker_open", "crash_respawn",
                  "fault", "manual", "shed_spike", "slo_breach")

# Bundle store bound: bundles are heavy (a tsdb window + a snapshot);
# 16 spans the recent past without letting /debug/incidents balloon.
MAX_INCIDENTS = 16

# Fault-journal slice folded into each bundle (the journal itself is
# already bounded; the tail is what surrounds the trigger).
_FAULT_TAIL = 64


def _env_float(name: str, default: float, lo: float, hi: float) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return min(max(v, lo), hi)


class IncidentConfig:
    """Knobs (docs/CONFIG.md "Incident bundles" rows). Read live from
    the environment at construction."""

    def __init__(self) -> None:
        raw = os.environ.get("AIOS_TPU_INCIDENTS", "").lower()
        if raw in ("0", "false", "off"):
            self.enabled = False
        elif raw in ("1", "true", "on"):
            self.enabled = True
        else:
            # default: ride the tsdb arming — bundles center on its
            # windows, and a process that wants history wants both
            self.enabled = os.environ.get(
                "AIOS_TPU_TSDB", ""
            ).lower() in ("1", "true", "on")
        self.window_secs = _env_float(
            "AIOS_TPU_INCIDENT_WINDOW_SECS", 60.0, 0.0, 600.0
        )
        self.cooldown_secs = _env_float(
            "AIOS_TPU_INCIDENT_COOLDOWN_SECS", 30.0, 0.0, 3600.0
        )
        self.dump_dir = os.environ.get("AIOS_TPU_INCIDENT_DUMP_DIR", "")


class IncidentStore:
    """Bounded bundle store + background builder. ``clock`` is wall
    time (bundle timestamps join tsdb points and dump filenames);
    injectable for tests."""

    def __init__(self, cfg: Optional[IncidentConfig] = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.cfg = cfg or IncidentConfig()
        self.clock = clock
        self._lock = make_lock("incidents")
        self._incidents: deque = deque(maxlen=MAX_INCIDENTS)  #: guarded_by _lock
        self._last_at: Dict[Tuple[str, str], float] = {}  #: guarded_by _lock
        self._seq = 0  #: guarded_by _lock
        self._stop = threading.Event()
        self._register_metrics()

    def _register_metrics(self) -> None:
        """Pre-register every trigger-cause child by iterating the
        closed TRIGGER_CAUSES enum (the autoscale/SLO registration
        pattern, pinned by test_obs_lint) — a healthy process renders 0
        for every cause instead of absence."""
        from . import instruments

        for cause in TRIGGER_CAUSES:
            instruments.INCIDENTS.labels(cause=cause)
            instruments.INCIDENTS_SUPPRESSED.labels(cause=cause)

    # -- the trigger funnel ---------------------------------------------------

    def notify(self, model: str, cause: str, sync: bool = False,
               **fields) -> Optional[dict]:
        """One trigger fired: claim the cooldown stamp + incident id
        synchronously (a burst freezes exactly one), then build the
        bundle on a daemon thread — the freeze never stalls a scheduler
        tick. ``sync=True`` (tests, smoke scripts) builds inline and
        returns the bundle."""
        from . import instruments

        if cause not in TRIGGER_CAUSES:
            cause = "manual"
        t = self.clock()
        with self._lock:
            last = self._last_at.get((model, cause))
            if last is not None and t - last < self.cfg.cooldown_secs:
                suppressed = True
            else:
                suppressed = False
                self._last_at[(model, cause)] = t
                self._seq += 1
                inc_id = self._seq
        if suppressed:
            instruments.INCIDENTS_SUPPRESSED.labels(cause=cause).inc()
            return None
        instruments.INCIDENTS.labels(cause=cause).inc()
        if not sync:
            threading.Thread(
                target=self._build, args=(inc_id, model, cause, t, fields),
                name="incident-build", daemon=True,
            ).start()
            return None
        return self._build(inc_id, model, cause, t, fields, wait=False)

    def _build(self, inc_id: int, model: str, cause: str, t: float,
               fields: dict, wait: bool = True) -> dict:
        """Assemble one bundle. Waits out the post-trigger half of the
        window first (background path only) so the tsdb ring holds the
        aftermath, not just the run-up."""
        if wait and self.cfg.window_secs > 0:
            self._stop.wait(self.cfg.window_secs)
        w = self.cfg.window_secs
        bundle = {
            "id": inc_id,
            "model": model,
            "cause": cause,
            "at": t,
            "fields": {k: v for k, v in sorted(fields.items())},
            "window": {"start": t - w, "end": t + w},
            "tsdb": self._tsdb_window(t - w, t + w),
            "flightrec": self._flightrec_slice(model, cause, t),
            "faults": self._fault_tail(),
            "devprof": self._devprof_state(),
            "lock_trips": self._lock_trips(),
        }
        with self._lock:
            self._incidents.append(bundle)
        from . import flightrec

        flightrec.RECORDER.model_event(
            model, "incident", cause=cause, incident_id=inc_id,
        )
        self._dump(bundle)
        return bundle

    # -- bundle sections (each section is fail-soft: a sick layer
    # becomes its own evidence, never a lost bundle) ---------------------------

    def _tsdb_window(self, start: float, end: float) -> dict:
        from . import tsdb

        ring = tsdb.TSDB
        if ring is None:
            return {"armed": False, "series": [], "truncated": 0}
        try:
            out = ring.window_snapshot(start, end)
            out["armed"] = True
            return out
        except Exception as exc:  # noqa: BLE001
            return {"armed": True, "series": [], "truncated": 0,
                    "error": repr(exc)[:200]}

    def _flightrec_slice(self, model: str, cause: str, t: float) -> dict:
        from . import flightrec

        try:
            for snap in reversed(flightrec.RECORDER.snapshots()):
                if snap.get("model") == model and snap.get("cause") == cause:
                    return {"snapshot_id": snap.get("id"),
                            "snapshot": snap}
            # no snapshot for this (model, cause) — e.g. autoscale /
            # breaker / fault triggers: freeze the live model lane
            return {
                "snapshot_id": None,
                "model_events": [
                    {"t_wall": w, "model": m, "kind": k, **f}
                    for w, m, k, f in flightrec.RECORDER.model_events(model)
                ],
            }
        except Exception as exc:  # noqa: BLE001
            return {"error": repr(exc)[:200]}

    def _fault_tail(self) -> List[dict]:
        from .. import faults

        try:
            return list(faults.fired())[-_FAULT_TAIL:]
        except Exception as exc:  # noqa: BLE001
            return [{"error": repr(exc)[:200]}]

    def _devprof_state(self) -> dict:
        from . import devprof

        try:
            return devprof.snapshot_all()
        except Exception as exc:  # noqa: BLE001
            return {"error": repr(exc)[:200]}

    def _lock_trips(self) -> List[dict]:
        from ..analysis import locks

        try:
            return locks.watchdog_trips()
        except Exception as exc:  # noqa: BLE001
            return [{"error": repr(exc)[:200]}]

    def _dump(self, bundle: dict) -> None:
        dump_dir = self.cfg.dump_dir
        if not dump_dir:
            log.warning(
                "incident bundle frozen (%s/%s, id %d); "
                "GET /debug/incidents to read it",
                bundle["model"], bundle["cause"], bundle["id"],
            )
            return
        try:
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(
                dump_dir,
                f"incident-{bundle['model']}-{bundle['cause']}-"
                f"{bundle['id']}.json",
            )
            with open(path, "w") as f:
                json.dump(bundle, f)
            log.warning("incident bundle (%s/%s) -> %s",
                        bundle["model"], bundle["cause"], path)
        except (OSError, TypeError, ValueError) as exc:
            log.warning("incident dump failed: %s", exc)

    # -- surfaces -------------------------------------------------------------

    def incidents(self) -> List[dict]:
        with self._lock:
            return list(self._incidents)

    def stop(self) -> None:
        self._stop.set()

    def clear(self) -> None:
        """Test isolation."""
        with self._lock:
            self._incidents.clear()
            self._last_at.clear()
            self._seq = 0


# -- process-wide instance ----------------------------------------------------

# The one store the trigger hooks and /debug/incidents read; None until
# maybe_start() arms it — notify() below is a single None check when off.
STORE: Optional[IncidentStore] = None


def enabled() -> bool:
    return STORE is not None


def notify(model: str, cause: str, **fields) -> None:
    """The trigger funnel every hook calls (flightrec.snapshot,
    autoscale._record, breaker._emit, faults._record). One None check
    when unarmed — hot paths pay nothing."""
    store = STORE
    if store is None:
        return
    store.notify(model, cause, **fields)


def maybe_start() -> Optional[IncidentStore]:
    """Arm the store when configured (AIOS_TPU_INCIDENTS, or riding
    AIOS_TPU_TSDB) — called by maybe_start_metrics_server. Idempotent."""
    global STORE
    cfg = IncidentConfig()
    if STORE is not None or not cfg.enabled:
        return STORE
    STORE = IncidentStore(cfg)
    log.info(
        "incident bundles armed: window=+/-%.0fs cooldown=%.0fs dump=%s",
        cfg.window_secs, cfg.cooldown_secs, cfg.dump_dir or "(store only)",
    )
    return STORE


def install(store: Optional[IncidentStore]) -> Optional[IncidentStore]:
    """Swap the process-wide store (tests); returns the previous."""
    global STORE
    prev, STORE = STORE, store
    return prev
