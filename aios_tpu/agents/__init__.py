"""The Python agent framework + the 10 system agents.

Reference: agent-core/python/aios_agent/ (SURVEY.md section 2.2). The
reference README claims 8 agents; the actual set is these 10
(agents/__init__.py:5-27 in the reference) — preserved here.
"""

AGENT_TYPES = [
    "system",
    "network",
    "security",
    "package",
    "monitoring",
    "learning",
    "storage",
    "task",
    "web",
    "creator",
]


def agent_class(agent_type: str):
    """Resolve an agent type name to its class (lazy imports)."""
    from . import catalog

    return catalog.CLASSES[agent_type]
