"""TLS certificate management (self-signed CA + per-service certs).

Reference parity (agent-core/src/tls.rs:52-80+): generates a self-signed CA
and CA-signed server certificates. The reference uses rcgen in-process; here
openssl does the work. As in the reference, servers currently start without
TLS (main.rs:794-798) — this is the scaffolding used by cert rotation and
the proactive generator's expiry checks.
"""

from __future__ import annotations

import subprocess
from pathlib import Path
from typing import Optional

from .proactive import cert_expiry_days  # re-exported for convenience

__all__ = ["TlsManager", "cert_expiry_days"]


def _openssl(*argv: str) -> None:
    proc = subprocess.run(
        ["openssl", *argv], capture_output=True, text=True, timeout=60
    )
    if proc.returncode != 0:
        raise RuntimeError(f"openssl {argv[0]} failed: {proc.stderr[:300]}")


class TlsManager:
    def __init__(self, cert_dir: str = "/tmp/aios/certs"):
        self.cert_dir = Path(cert_dir)
        self.cert_dir.mkdir(parents=True, exist_ok=True)

    @property
    def ca_cert(self) -> Path:
        return self.cert_dir / "ca.crt"

    @property
    def ca_key(self) -> Path:
        return self.cert_dir / "ca.key"

    def ensure_ca(self, days: int = 3650) -> Path:
        if self.ca_cert.exists():
            return self.ca_cert
        _openssl(
            "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(self.ca_key), "-out", str(self.ca_cert),
            "-days", str(days), "-subj", "/CN=aiOS-CA",
        )
        return self.ca_cert

    def server_cert(self, name: str, days: int = 365) -> tuple[Path, Path]:
        """CA-signed server cert for a service; returns (cert, key)."""
        self.ensure_ca()
        key = self.cert_dir / f"{name}.key"
        csr = self.cert_dir / f"{name}.csr"
        crt = self.cert_dir / f"{name}.crt"
        _openssl(
            "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(csr),
            "-subj", f"/CN={name}.aios.local",
        )
        _openssl(
            "x509", "-req", "-in", str(csr),
            "-CA", str(self.ca_cert), "-CAkey", str(self.ca_key),
            "-CAcreateserial", "-out", str(crt), "-days", str(days),
        )
        csr.unlink(missing_ok=True)
        return crt, key

    def rotate(self, name: str) -> tuple[Path, Path]:
        for suffix in (".crt", ".key"):
            (self.cert_dir / f"{name}{suffix}").unlink(missing_ok=True)
        return self.server_cert(name)

    def expiry_days(self, name: str) -> Optional[int]:
        return cert_expiry_days(str(self.cert_dir / f"{name}.crt"))
