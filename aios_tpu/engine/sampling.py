"""On-device token sampling: temperature, top-k, top-p, greedy.

Runs inside the jitted decode step (no host round-trip per token), vectorized
over slots with *per-slot* sampling parameters — different agents' requests in
the same continuous batch can use different temperatures (the reference's
per-request `temperature` field, runtime.proto InferRequest).

Replaces llama-server's sampler chain for the parameters the reference
actually exposes (temperature; plus top-k/top-p which llama-server applies
with its defaults — inference.rs:103-112 sends temperature only).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

GREEDY_EPS = 1e-4  # temperatures below this mean argmax


def top_p_filter(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Mask logits outside the nucleus. logits [B, V], top_p [B] in (0, 1]."""
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens while the cumulative mass *before* them is < top_p
    keep_sorted = (cumulative - sorted_probs) < top_p[:, None]
    # threshold = smallest logit still kept
    kept_logits = jnp.where(keep_sorted, sorted_logits, jnp.inf)
    threshold = jnp.min(kept_logits, axis=-1, keepdims=True)
    return jnp.where(logits >= threshold, logits, -jnp.inf)


def top_k_filter(logits: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Mask logits below the k-th largest. top_k [B] int32 (0 = disabled)."""
    V = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    k = jnp.where(top_k <= 0, V, jnp.minimum(top_k, V))
    threshold = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
    return jnp.where(logits >= threshold, logits, -jnp.inf)


# Candidate pool for the decode-loop sampler. A full-vocab sort per step is
# the naive approach and measurably slow on TPU; restricting top-p to the 64
# highest logits matches llama.cpp's own sampler chain, which applies
# top-k 40 *before* top-p by default (the reference sends temperature only,
# inference.rs:103-112, so llama-server uses those defaults).
# AIOS_TPU_SAMPLE_POOL overrides the pool size (read at trace time, so it
# must be set before the decode graph first compiles).
DEFAULT_TOPK_CAP = 64


def topk_cap() -> int:
    import os

    raw = os.environ.get("AIOS_TPU_SAMPLE_POOL", "")
    if not raw:
        return DEFAULT_TOPK_CAP
    try:
        cap = int(raw)
    except ValueError:
        raise ValueError(
            f"AIOS_TPU_SAMPLE_POOL={raw!r} is not an integer"
        ) from None
    if cap < 1:
        # fail loudly: 0 is NOT "disabled" here (that would put a full-vocab
        # sort in the decode graph); a silent pool of 1 would make all
        # sampling greedy
        raise ValueError("AIOS_TPU_SAMPLE_POOL must be >= 1")
    return cap


def sample(
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]; 1.0 keeps the whole candidate pool (the pool
    # itself is still capped, see below — NOT a full-vocab nucleus)
    top_k: jnp.ndarray | None = None,  # [B] int32; 0 => the whole pool
    exact: bool = False,  # exact top-k pool (grammar-masked steps)
) -> jnp.ndarray:
    """Sample one token per row; temperature < GREEDY_EPS rows take argmax.

    Nucleus + top-k filtering run on the ``topk_cap()`` highest logits via
    ``lax.top_k`` — no full-vocab sort in the decode graph. Consequently the
    candidate pool is capped: top_k values above the cap (or 0, "disabled")
    sample from the best ``topk_cap()`` tokens, and top-p mass beyond them is
    truncated — even at top_p=1.0 — matching llama-server, whose default
    chain applies top-k 40 before top-p. Raise AIOS_TPU_SAMPLE_POOL if a
    deployment needs a wider nucleus.

    This is also the per-tick sampler inside the multi-tick decode
    megagraph (TPUEngine._mega_impl): each while_loop iteration calls it
    with one key from the same fixed ``split(key, K + 1)`` fanout the
    single-dispatch scan uses, so a K-tick device window draws exactly
    the random stream K chained host dispatches would — the byte-identity
    contract for sampled slots rests on this function being cadence-blind.
    """
    B, V = logits.shape
    K = min(topk_cap(), V)
    greedy = jnp.argmax(logits, axis=-1)

    temp = jnp.maximum(temperature, GREEDY_EPS)[:, None]
    # approx_max_k hits the TPU-optimized partial-reduction path (~16%
    # faster whole-step decode on Mistral-7B batch 8 vs exact lax.top_k over
    # the 32k vocab); on CPU it lowers to the exact sort, so tests are
    # deterministic. Missing a tail candidate with ~5% probability is well
    # within the tolerance of a sampling pool (llama.cpp's own chain
    # truncates harder, top-k 40). Results come back sorted descending.
    if exact:
        # Grammar-constrained steps MUST use the exact pool: the additive
        # mask can leave only a handful of allowed tokens (sometimes just
        # EOS), and approx_max_k's ~5% per-token miss rate could build a
        # pool with zero allowed entries — softmax over uniform -1e30s
        # would then emit a forbidden token and break the JSON guarantee.
        vals, idx = jax.lax.top_k(logits / temp, K)
    else:
        vals, idx = jax.lax.approx_max_k(
            logits / temp, K, recall_target=0.95
        )  # [B, K] sorted desc
    if top_k is not None:
        kk = jnp.where(top_k <= 0, K, jnp.minimum(top_k, K))
        pos = jnp.arange(K)[None, :]
        vals = jnp.where(pos < kk[:, None], vals, -jnp.inf)
    probs = jax.nn.softmax(vals, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    keep = (cumulative - probs) < top_p[:, None]
    vals = jnp.where(keep, vals, -jnp.inf)
    choice = jax.random.categorical(key, vals, axis=-1)  # [B] in [0, K)
    sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]

    return jnp.where(temperature < GREEDY_EPS, greedy, sampled).astype(jnp.int32)
