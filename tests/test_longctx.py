"""Long-context tier (ISSUE 13): window+sink KV compression and
sequence-sharded prefill.

Covers the acceptance contract end to end:
  * token identity below the threshold — compression armed but never
    triggered is byte-identical to a plain paged engine;
  * page-accounting invariants under pruning — no page simultaneously
    free-listed and mapped by a live table position, pruned pages return
    to the pool, free_slot never double-frees;
  * pruned pages that the prefix index still holds spill through the
    PR 4 host tier with a valid crc32 and restore cleanly;
  * sequence-sharded prefill (ring attention over the sp axis) is
    greedy token-identical to single-replica prefill on a CPU mesh, and
    composes with compression;
  * the speculation guard — n-gram and draft proposers never propose
    from (or verify against) pruned positions.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from aios_tpu.engine import model as model_mod, spec
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.paged import PageAllocator, SACRIFICIAL_PAGE

CFG = TINY_TEST.scaled(name="longctx-test", max_context=512)


@pytest.fixture(scope="module")
def params():
    return model_mod.init_params(CFG, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)


def make_engine(params, **kw):
    base = dict(
        num_slots=2, max_context=512, cache_dtype=jnp.float32,
        paged_pool_rows=1024, page_size=32,
    )
    base.update(kw)
    return TPUEngine(CFG, params, **base)


def prompt_of(n, seed=0):
    rng = np.random.default_rng(seed)
    return [int(t) for t in rng.integers(1, 500, n)]


# -- allocator units --------------------------------------------------------


def test_prune_range_accounting():
    """prune_range releases the middle once, remaps the table entries to
    the sacrificial page, grows monotonically, and free_slot neither
    double-frees pruned blocks nor leaks the survivors."""
    alloc = PageAllocator(num_pages=32, page_size=16, num_slots=2,
                          max_blocks=16)
    alloc.ensure(0, 10 * 16)  # 10 blocks
    free0 = alloc.free_pages
    freed = alloc.prune_range(0, 1, 6)  # sink block 0, window from 6
    assert freed == 5
    assert alloc.free_pages == free0 + 5
    assert alloc.pruned_blocks(0) == 5
    assert all(
        int(alloc.tables[0, b]) == SACRIFICIAL_PAGE for b in range(1, 6)
    )
    # live positions map real pages with refcount 1, and none of them is
    # on the free list (the no-page-both-free-and-mapped invariant)
    free_set = set(alloc._free[0])
    for b in list(range(0, 1)) + list(range(6, 10)):
        page = int(alloc.tables[0, b])
        assert page != SACRIFICIAL_PAGE
        assert alloc.refcount(page) == 1
        assert page not in free_set
    # monotone: re-pruning the same range is a no-op; extending prunes
    # only the delta
    assert alloc.prune_range(0, 1, 6) == 0
    assert alloc.prune_range(0, 1, 8) == 2
    assert alloc.slot_pages_resident(0) == 10 - 7
    # free_slot returns exactly the live pages (pruned ones already went)
    alloc.free_slot(0)
    assert alloc.free_pages == 31  # every non-sacrificial page is free
    assert alloc.pruned_blocks(0) == 0


def test_prune_shared_page_survives_under_index_reference():
    """A pruned block whose page the prefix index still references keeps
    the page resident (refcount drops by one, never to zero)."""
    alloc = PageAllocator(num_pages=16, page_size=16, num_slots=1,
                          max_blocks=8)
    alloc.ensure(0, 4 * 16)
    shared = int(alloc.tables[0, 1])
    alloc.incref(shared)  # the index's reference
    free0 = alloc.free_pages
    alloc.prune_range(0, 1, 3)
    # block 2's page freed; block 1's page survives at refcount 1
    assert alloc.refcount(shared) == 1
    assert alloc.free_pages == free0 + 1
    alloc.decref(shared)
    assert alloc.free_pages == free0 + 2


# -- token identity below threshold ----------------------------------------


def test_below_threshold_token_identity(params):
    """Armed-but-untriggered compression is byte-identical to the plain
    paged engine: the win_starts operand stays 0, the mask is the
    identity, and nothing prunes."""
    plain = make_engine(params)
    armed = make_engine(params, kv_compress_after=320, kv_sink_pages=1,
                        kv_window_pages=4)
    assert armed.kv_compress_armed
    try:
        ids = prompt_of(100, seed=3)
        out_plain = plain.generate(ids, max_new_tokens=24, temperature=0.0)
        out_armed = armed.generate(ids, max_new_tokens=24, temperature=0.0)
        assert out_plain == out_armed
        assert armed.kv_pages_pruned == 0
        assert armed.kv_compress_slots == 0
        assert int(armed._win_starts.sum()) == 0
    finally:
        plain.close()
        armed.close()


# -- pruning under decode ---------------------------------------------------


@pytest.fixture(scope="module")
def armed_engine(params):
    # prefix cache off: above the threshold a prefix-hit readmission
    # takes the chunked path, whose mid-admission pruning is a different
    # (deterministic) attention schedule than the cold whole-prompt
    # prefill — each PATH repeats exactly, which is the contract
    # (docs/ENGINE_PERF.md "Long-context tier", determinism note)
    eng = make_engine(params, kv_compress_after=256, kv_sink_pages=1,
                      kv_window_pages=4, prefix_cache=False)
    yield eng
    eng.close()


def test_long_decode_prunes_and_stays_deterministic(armed_engine):
    """A slot crossing the threshold prunes to sink + window, decode
    continues, streams repeat exactly, and the page accounting holds."""
    eng = armed_engine
    ids = prompt_of(300, seed=4)
    out1 = eng.generate(ids, max_new_tokens=48, temperature=0.0)
    pruned1 = eng.kv_pages_pruned
    assert pruned1 > 0
    assert eng.kv_compress_slots >= 1
    out2 = eng.generate(ids, max_new_tokens=48, temperature=0.0)
    assert out1 == out2
    # all pages returned after release (prefix-index-held pages aside)
    alloc = eng.allocator
    mapped = {
        int(alloc.tables[s, b])
        for s in range(eng.num_slots)
        for b in range(int(alloc._blocks_used[s]))
    } - {SACRIFICIAL_PAGE}
    free_set = set(alloc._free[0])
    assert not (mapped & free_set), "page simultaneously free and mapped"


def test_prune_respects_live_window_accounting(armed_engine):
    """Mid-decode, the live window start is page-aligned, the resident
    pages match sink + window + partial, and no live table entry is on
    the free list."""
    eng = armed_engine
    ids = prompt_of(300, seed=5)
    eng.prefill(0, ids, temperature=0.0)
    eng.step(32)  # crosses the 256 threshold; prunes in _back_active_slots
    alloc = eng.allocator
    ws = int(eng._win_starts[0])
    P = alloc.page_size
    assert ws > 0 and ws % P == 0
    L = eng.slot_length(0)
    assert ws <= L - eng.kv_window_pages * P
    resident = alloc.slot_pages_resident(0)
    assert resident == int(alloc._blocks_used[0]) - alloc.pruned_blocks(0)
    assert eng.compressed_resident_pages() >= resident
    free_set = set(alloc._free[0])
    for b in range(int(alloc._blocks_used[0])):
        page = int(alloc.tables[0, b])
        if page != SACRIFICIAL_PAGE:
            assert page not in free_set
    eng.release(0)
    assert int(eng._win_starts[0]) == 0


def test_chunked_admission_prunes_midflight(params):
    """A prompt larger than the pool can back whole still admits through
    chunked admission: pruning frees the middle as chunks land and the
    peak residency stays near sink + window + chunk."""
    eng = TPUEngine(
        CFG, params, num_slots=2, max_context=512,
        cache_dtype=jnp.float32, paged_pool_rows=320, page_size=32,
        kv_compress_after=128, kv_sink_pages=1, kv_window_pages=2,
    )
    try:
        ids = prompt_of(400, seed=6)
        # 400 rows = 13 blocks > the 10-block capacity: only compression
        # makes this admissible
        assert eng.allocator.blocks_for(len(ids)) \
            > eng.allocator.capacity_blocks()
        pc = eng.start_chunked_prefill(0, ids, chunk=64)
        first = pc.step()
        while first is None:
            first = pc.step()
        assert int(eng._win_starts[0]) > 0
        assert eng.kv_pages_pruned > 0
        toks = eng.step(8)
        assert toks.shape == (8, 2)
        eng.release(0)
    finally:
        eng.close()


# -- pruned pages spill + restore through the host tier ---------------------


def test_pruned_pages_spill_with_valid_crc_and_restore(params):
    """Pages pruned from a slot but still held by the prefix index spill
    through the host tier under pool pressure (crc32 layer unchanged)
    and restore cleanly on a later chain hit."""
    eng = TPUEngine(
        CFG, params, num_slots=2, max_context=512,
        cache_dtype=jnp.float32, paged_pool_rows=1024, page_size=32,
        prefix_host_bytes=64 << 20,
        kv_compress_after=256, kv_sink_pages=1, kv_window_pages=4,
    )
    try:
        ids = prompt_of(250, seed=7)  # below threshold: full chain registers
        eng.prefill(0, ids, temperature=0.0)
        # decode in chunks (the batcher's shape): pruning runs between
        # dispatches, once the advancing length crosses the threshold
        for _ in range(8):
            eng.step(8)
        assert eng.kv_pages_pruned > 0
        eng.release(0)
        # the pruned blocks' pages survive only under the index; force a
        # reclaim so they spill to the host store
        import time as _time

        before = eng.host_store.spills
        with eng._lock:
            n = eng.prefix_index.reclaim(4)
        assert n > 0
        deadline = _time.time() + 10
        while eng.host_store.spills == before and _time.time() < deadline:
            _time.sleep(0.02)
        assert eng.host_store.spills > before
        assert eng.host_store.corruptions == 0
        # resubmit: the chain head hits HBM or the host tier; the
        # restore path must verify crc and produce the same stream
        out = eng.generate(ids, max_new_tokens=16, temperature=0.0)
        assert len(out) == 16
        assert eng.host_store.corruptions == 0
        # invariant after the round trip
        alloc = eng.allocator
        free_set = set(alloc._free[0])
        for h, page in eng.prefix_index.snapshot().items():
            assert page not in free_set, \
                "page simultaneously free-listed and index-mapped"
    finally:
        eng.close()


# -- sequence-sharded prefill ----------------------------------------------


def test_seq_sharded_prefill_token_identity(params, cpu_devices):
    """Ring-attention sequence-sharded prefill over a dp=1 x sp=2 CPU
    mesh produces the same greedy stream as the single-replica paged
    prefill, and the KV lands in the normal paged layout (decode and
    prefix registration just work)."""
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    plain = make_engine(params)
    seq = make_engine(
        params, shardings=ShardingPlan(build_mesh(2, dp=1, sp=2, tp=1)),
        seq_prefill_min=128,
    )
    assert seq._seq_attn is not None
    try:
        ids = prompt_of(300, seed=8)
        out_plain = plain.generate(ids, max_new_tokens=24, temperature=0.0)
        out_seq = seq.generate(ids, max_new_tokens=24, temperature=0.0)
        assert out_plain == out_seq
        assert seq.prefill_seq_sharded == 1
        # below the routing floor the normal bucket path serves
        short = prompt_of(64, seed=9)
        out_a = plain.generate(short, max_new_tokens=8, temperature=0.0)
        out_b = seq.generate(short, max_new_tokens=8, temperature=0.0)
        assert out_a == out_b
        assert seq.prefill_seq_sharded == 1
    finally:
        plain.close()
        seq.close()


def test_seq_prefill_composes_with_compression(params, cpu_devices):
    """A compressed long-context slot admitted via sharded prefill: the
    whole-mesh admission lands, pruning caps residency right after, and
    decode is deterministic — the two tentpole mechanisms compose."""
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    eng = make_engine(
        params, shardings=ShardingPlan(build_mesh(2, dp=1, sp=2, tp=1)),
        seq_prefill_min=128, kv_compress_after=256, kv_sink_pages=1,
        kv_window_pages=4,
    )
    try:
        ids = prompt_of(400, seed=10)
        out1 = eng.generate(ids, max_new_tokens=24, temperature=0.0)
        assert eng.prefill_seq_sharded == 1
        assert eng.kv_pages_pruned > 0
        out2 = eng.generate(ids, max_new_tokens=24, temperature=0.0)
        assert out1 == out2
    finally:
        eng.close()


def test_seq_prefill_warmup_keeps_compile_counters_flat(params,
                                                        cpu_devices):
    """The sp-sharded prefill graphs AOT-compile behind warmup() (the
    PR 6 invariant): serving a routed prompt afterwards compiles
    nothing."""
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    eng = make_engine(
        params, shardings=ShardingPlan(build_mesh(2, dp=1, sp=2, tp=1)),
        seq_prefill_min=128, kv_compress_after=256, kv_sink_pages=1,
        kv_window_pages=4,
    )
    try:
        eng.warmup(step_sizes=(1, 8))
        before = eng.compile_events
        ids = prompt_of(400, seed=11)
        eng.prefill(0, ids, temperature=0.0)
        eng.step(8)
        eng.step(1)
        eng.release(0)
        assert eng.compile_events == before
        assert eng.prefill_seq_sharded == 1
    finally:
        eng.close()


# -- speculation guard over pruned slots -----------------------------------


def test_propose_ngram_min_pos_clamps_to_live_rows():
    """With min_pos set, an n-gram match that exists only below the live
    window produces NO draft; the same match inside the window still
    proposes."""
    S, C = 1, 64
    hist = np.zeros((S, C + spec.HISTORY_PAD), np.int32)
    # pattern [5, 6] at positions 2..3 (pruned region) with continuation
    # 7, 8; trailing pattern ends at the pending token
    seqs = [5, 6, 7, 8] + [9] * 40 + [5, 6]
    hist[0, : len(seqs)] = seqs
    lengths = jnp.asarray([len(seqs) - 1], jnp.int32)
    h = jnp.asarray(hist)
    drafts, num = spec.propose_ngram(h, lengths, 4, 2, C)
    assert int(num[0]) > 0  # unclamped: the early match proposes
    drafts, num = spec.propose_ngram(
        h, lengths, 4, 2, C, min_pos=jnp.asarray([16], jnp.int32)
    )
    assert int(num[0]) == 0  # clamped: the only match is pruned away

    # a match INSIDE the live window still proposes under the clamp
    seqs2 = [9] * 20 + [5, 6, 7, 8] + [9] * 10 + [5, 6]
    hist2 = np.zeros((S, C + spec.HISTORY_PAD), np.int32)
    hist2[0, : len(seqs2)] = seqs2
    drafts, num = spec.propose_ngram(
        jnp.asarray(hist2), jnp.asarray([len(seqs2) - 1], jnp.int32),
        4, 2, C, min_pos=jnp.asarray([16], jnp.int32),
    )
    assert int(num[0]) > 0


def test_spec_on_pruned_slot_stays_greedy_exact(params):
    """n-gram speculation over a pruned slot emits exactly the plain
    decode stream of the SAME compressed engine — proposals are clamped
    to live rows and verify runs under the pruned mask, so acceptance
    is judged only against context the model actually sees."""
    a = make_engine(params, kv_compress_after=256, kv_sink_pages=1,
                    kv_window_pages=4)
    b = make_engine(params, kv_compress_after=256, kv_sink_pages=1,
                    kv_window_pages=4)
    try:
        # repetitive tail gives the proposer something to match
        ids = prompt_of(280, seed=12) + [5, 6, 7, 8] * 6
        plain = a.generate(ids, max_new_tokens=24, temperature=0.0)
        fast = b.generate(ids, max_new_tokens=24, temperature=0.0,
                          speculative=True, draft_len=4, ngram=2)
        assert plain == fast
        assert int(b._win_starts.sum()) == 0  # released
    finally:
        a.close()
        b.close()


def test_draft_proposer_skips_pruned_slots(params):
    """The draft-model proposer's ok gate excludes pruned slots: its
    dense KV mirrors the full history while the serving attention no
    longer sees the middle, so a pruned slot takes plain rounds
    (proposed == 0) and the stream still matches plain decode."""
    draft = spec.DraftModel(CFG, params, quantize=None)
    eng = make_engine(params, kv_compress_after=256, kv_sink_pages=1,
                      kv_window_pages=4, draft=draft)
    plain = make_engine(params, kv_compress_after=256, kv_sink_pages=1,
                        kv_window_pages=4)
    try:
        ids = prompt_of(300, seed=13)
        first = eng.prefill(0, ids, temperature=0.0)
        chain = [first]
        step_toks = eng.step(16)  # cross the threshold -> slot prunes
        chain += [int(t) for t in step_toks[:, 0]]
        assert int(eng._win_starts[0]) > 0
        toks, counts, proposed = eng.spec_step_draft(4, draft_len=3)
        assert int(proposed[:, 0].sum()) == 0
        assert (counts[:, 0] == 1).all()  # plain one-token rounds
        for r in range(toks.shape[0]):
            chain += [int(t) for t in toks[r, 0, : counts[r, 0]]]
        eng.release(0)
        ref = plain.generate(ids, max_new_tokens=len(chain),
                             temperature=0.0)
        assert chain == ref
    finally:
        eng.close()
        plain.close()
