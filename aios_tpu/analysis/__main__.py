"""CLI: ``python -m aios_tpu.analysis`` — run the concurrency &
dispatch-discipline rules over the tree.

Exit status 1 when any UNWAIVED finding remains (waived findings print
with their justification but never fail the run). The tier-1 test
(``tests/test_analysis.py::test_tree_is_clean``) calls :func:`main`
directly, so CI and local runs cannot diverge.

    python -m aios_tpu.analysis              # human-readable report
    python -m aios_tpu.analysis --json       # machine-readable findings
    python -m aios_tpu.analysis --rule lock-order --rule guarded-by
    python -m aios_tpu.analysis --list-rules
    python -m aios_tpu.analysis --waived     # include waived findings
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from .rules import RULE_IDS, run_analysis


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m aios_tpu.analysis",
        description="static concurrency/dispatch-discipline analyzer "
                    "(rule catalog: docs/ANALYSIS.md)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="RULE",
        choices=RULE_IDS,
        help="run only this rule (repeatable); default: all",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="emit findings as a JSON array",
    )
    parser.add_argument(
        "--waived", action="store_true",
        help="also print waived findings (always included in --json)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule ids and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in RULE_IDS:
            print(r)
        return 0

    findings = run_analysis(rules=args.rules)
    unwaived = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]

    if args.json:
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in unwaived:
            print(f.render())
        if args.waived:
            for f in waived:
                print(f"{f.render()}  # {f.waive_reason}")
        print(
            f"aios_tpu.analysis: {len(unwaived)} finding(s), "
            f"{len(waived)} waived",
            file=sys.stderr,
        )
    return 1 if unwaived else 0


if __name__ == "__main__":
    raise SystemExit(main())
