"""Ragged MULTI-QUERY decode attention: T in-flight queries per slot.

The speculative verify step scores a slot's pending token plus K draft
tokens in one forward (model.verify_step). Its attention is T queries per
slot over that slot's valid cache rows — without a kernel it falls back to
a full-cache masked read, paying C-row HBM traffic per slot regardless of
how short the slot actually is. This kernel generalizes the single-query
ragged decode kernel (decode_attention.py): same double-buffered
HBM→VMEM DMA over only the blocks that hold valid rows, but each block is
scored against all T queries, with the causal staircase applied per query
(query t sees cols <= base + t·stride).

``stride`` is 1 for active slots and 0 for inactive ones, matching
verify_step's convention that inactive slots expose only the
overwritten-before-read col 0 for every query.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _mq_kernel(
    len_ref,  # SMEM [B] int32 — base: row `len` holds query 0's row
    stride_ref,  # SMEM [B] int32 — 1 active (staircase), 0 inactive
    q_ref,  # VMEM [1, T, H, D]
    k_hbm,  # ANY  [B, C, KH*D]
    v_hbm,  # ANY  [B, C, KH*D]
    o_ref,  # VMEM [1, T, H, D]
    *,
    num_kv_heads: int,
    head_dim: int,
    block_kv: int,
    window: Optional[int],
    sm_scale: float,
):
    b = pl.program_id(0)
    KH, D, bk = num_kv_heads, head_dim, block_kv
    T, H = q_ref.shape[1], q_ref.shape[2]
    G = H // KH

    base = len_ref[b]
    stride = stride_ref[b]
    C = k_hbm.shape[1]
    # rows [0, base + (T-1)*stride] are visible to SOME query; clamp at the
    # cache end — a saturated slot's clamped writes collide there and its
    # outputs are unconsumed by contract, but the DMA must stay in bounds
    total = jnp.minimum(base + (T - 1) * stride + 1, C)
    n_blk = pl.cdiv(total, bk)
    if window is not None:
        # earliest col any query needs is query 0's window start
        start_blk = jnp.maximum(base + 1 - window, 0) // bk
    else:
        start_blk = jnp.int32(0)

    # [T*G, D] per kv head, rows ordered (t, g)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # [T, H, D]
    qpos = base + jnp.arange(T) * stride  # [T] each query's own row

    def body(k_buf, v_buf, sems):
        def dma(buf_hbm, scr, slot, blk, sem_idx):
            return pltpu.make_async_copy(
                buf_hbm.at[b, pl.ds(blk * bk, bk)],
                scr.at[slot],
                sems.at[slot, sem_idx],
            )

        dma(k_hbm, k_buf, 0, start_blk, 0).start()
        dma(v_hbm, v_buf, 0, start_blk, 1).start()

        def loop(i, carry):
            m, l, acc = carry  # [KH*T*G, 1], [KH*T*G, 1], [KH*T*G, D]
            slot = jax.lax.rem(i - start_blk, 2)

            @pl.when(i + 1 < n_blk)
            def _prefetch():
                nxt = 1 - slot
                dma(k_hbm, k_buf, nxt, i + 1, 0).start()
                dma(v_hbm, v_buf, nxt, i + 1, 1).start()

            dma(k_hbm, k_buf, slot, i, 0).wait()
            dma(v_hbm, v_buf, slot, i, 1).wait()
            kb = k_buf[slot]  # [bk, KH*D]
            vb = v_buf[slot]

            cols = i * bk + jax.lax.broadcasted_iota(jnp.int32, (T, bk), 1)
            valid = cols <= qpos[:, None]  # causal staircase per query
            if window is not None:
                valid = jnp.logical_and(valid, cols > qpos[:, None] - window)
            # [T, bk] -> [T*G, bk] (repeat per query's G heads)
            validg = jnp.repeat(valid, G, axis=0)

            parts = []
            for h in range(KH):
                qh = q[:, h * G : (h + 1) * G, :].reshape(T * G, D)
                kh = kb[:, h * D : (h + 1) * D]
                s = jax.lax.dot_general(
                    qh, kh, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [T*G, bk]
                parts.append(jnp.where(validg, s, NEG_INF))
            s_all = jnp.concatenate(parts, axis=0)  # [KH*T*G, bk]

            m_cur = jnp.max(s_all, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s_all - m_new)
            p = jnp.where(
                jnp.concatenate([validg] * KH, axis=0), p, 0.0
            )
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)

            outs = []
            for h in range(KH):
                ph = p[h * T * G : (h + 1) * T * G, :].astype(vb.dtype)
                vh = vb[:, h * D : (h + 1) * D]
                outs.append(
                    jax.lax.dot_general(
                        ph, vh, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            acc_new = acc * alpha + jnp.concatenate(outs, axis=0)
            return m_new, l_new, acc_new

        init = (
            jnp.full((KH * T * G, 1), NEG_INF, jnp.float32),
            jnp.zeros((KH * T * G, 1), jnp.float32),
            jnp.zeros((KH * T * G, D), jnp.float32),
        )
        m, l, acc = jax.lax.fori_loop(start_blk, n_blk, loop, init)
        safe_l = jnp.where(l <= 0.0, 1.0, l)
        out = acc / safe_l  # [KH*T*G, D]
        out = out.reshape(KH, T, G, D).transpose(1, 0, 2, 3)
        o_ref[0] = out.reshape(T, H, D).astype(o_ref.dtype)

    pl.run_scoped(
        body,
        k_buf=pltpu.VMEM((2, bk, KH * D), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, bk, KH * D), v_hbm.dtype),
        sems=pltpu.SemaphoreType.DMA((2, 2)),
    )


@functools.partial(
    jax.jit, static_argnames=("window", "block_kv", "interpret")
)
def multiquery_decode_attention(
    q: jnp.ndarray,  # [B, T, H, D] — T in-flight queries per slot
    k_cache: jnp.ndarray,  # [B, C, KH, D]
    v_cache: jnp.ndarray,  # [B, C, KH, D]
    lengths: jnp.ndarray,  # [B] int32 — query 0's own (just-written) row
    strides: jnp.ndarray,  # [B] int32 — 1 active, 0 inactive
    *,
    window: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged multi-query decode attention; returns [B, T, H, D]."""
    from .decode_attention import pick_block_kv

    B, T, H, D = q.shape
    C, KH = k_cache.shape[1], k_cache.shape[2]
    bk = pick_block_kv(C) if block_kv is None else min(block_kv, C)
    if C % bk:
        raise ValueError(f"block_kv {bk} must evenly divide cache length {C}")

    kernel = functools.partial(
        _mq_kernel,
        num_kv_heads=KH,
        head_dim=D,
        block_kv=bk,
        window=window,
        sm_scale=1.0 / float(np.sqrt(D)),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, T, H, D), q.dtype),
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths
            pl.BlockSpec(memory_space=pltpu.SMEM),  # strides
            pl.BlockSpec((1, T, H, D), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, T, H, D), lambda b: (b, 0, 0, 0)),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        strides.astype(jnp.int32),
        q,
        k_cache.reshape(B, C, KH * D),
        v_cache.reshape(B, C, KH * D),
    )


def multiquery_decode_attention_reference(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    lengths: jnp.ndarray,
    strides: jnp.ndarray,
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Naive jnp multi-query ragged attention (CPU fallback + parity)."""
    B, T, H, D = q.shape
    C, KH = k_cache.shape[1], k_cache.shape[2]
    G = H // KH
    qpos = lengths[:, None] + jnp.arange(T)[None, :] * strides[:, None]
    cols = jnp.arange(C)[None, None, :]
    mask = cols <= qpos[..., None]  # [B, T, C]
    if window is not None:
        mask = mask & (cols > qpos[..., None] - window)
    qg = q.reshape(B, T, KH, G, D)
    s = jnp.einsum("btkgd,bckd->bkgtc", qg, k_cache).astype(jnp.float32)
    s = s / np.sqrt(D)
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgtc,bckd->btkgd", p, v_cache)
    return out.reshape(B, T, H, D)
