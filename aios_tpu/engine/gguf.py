"""GGUF file reader with vectorized numpy dequantization.

Replaces the role of llama.cpp's GGUF loader in the reference
(runtime/src/model_manager.rs spawns `llama-server --model *.gguf`): here GGUF
weights are parsed host-side, dequantized block-wise to float, and handed to
the engine as numpy arrays ready for `jax.device_put` onto the TPU mesh.

Implements the GGUF v2/v3 container and the quantization formats that appear
in the model files aiOS ships (Q4_K_M family): F32, F16, BF16, Q4_0, Q4_1,
Q5_0, Q5_1, Q8_0, Q4_K, Q5_K, Q6_K. All dequantizers are pure-numpy and
vectorized over blocks (no per-element Python loops).

Format notes (GGUF spec + ggml block layouts):
  * header: magic "GGUF", u32 version, u64 tensor_count, u64 kv_count
  * metadata values are typed (u8..f64, bool, string, array)
  * tensor dims are stored innermost-first; we return numpy arrays with the
    outermost-first (row-major) shape, i.e. ``dims[::-1]``
  * the tensor data section is aligned to `general.alignment` (default 32)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from pathlib import Path
from typing import Any, BinaryIO, Dict, List

import numpy as np

GGUF_MAGIC = b"GGUF"
DEFAULT_ALIGNMENT = 32

# ---- metadata value types --------------------------------------------------

_VT_UINT8, _VT_INT8, _VT_UINT16, _VT_INT16 = 0, 1, 2, 3
_VT_UINT32, _VT_INT32, _VT_FLOAT32, _VT_BOOL = 4, 5, 6, 7
_VT_STRING, _VT_ARRAY, _VT_UINT64, _VT_INT64, _VT_FLOAT64 = 8, 9, 10, 11, 12

_SCALAR_FMT = {
    _VT_UINT8: "<B",
    _VT_INT8: "<b",
    _VT_UINT16: "<H",
    _VT_INT16: "<h",
    _VT_UINT32: "<I",
    _VT_INT32: "<i",
    _VT_FLOAT32: "<f",
    _VT_UINT64: "<Q",
    _VT_INT64: "<q",
    _VT_FLOAT64: "<d",
}

# ---- ggml tensor dtypes ----------------------------------------------------

F32, F16 = 0, 1
Q4_0, Q4_1, Q5_0, Q5_1, Q8_0 = 2, 3, 6, 7, 8
Q2_K, Q3_K, Q4_K, Q5_K, Q6_K, Q8_K = 10, 11, 12, 13, 14, 15
I8, I16, I32, I64, F64 = 24, 25, 26, 27, 28
BF16 = 30

GGML_TYPE_NAMES = {
    F32: "F32",
    F16: "F16",
    BF16: "BF16",
    Q4_0: "Q4_0",
    Q4_1: "Q4_1",
    Q5_0: "Q5_0",
    Q5_1: "Q5_1",
    Q8_0: "Q8_0",
    Q2_K: "Q2_K",
    Q3_K: "Q3_K",
    Q4_K: "Q4_K",
    Q5_K: "Q5_K",
    Q6_K: "Q6_K",
    I8: "I8",
    I32: "I32",
    F64: "F64",
}

# (elements per block, bytes per block)
BLOCK_LAYOUT = {
    F32: (1, 4),
    F16: (1, 2),
    BF16: (1, 2),
    F64: (1, 8),
    I8: (1, 1),
    I16: (1, 2),
    I32: (1, 4),
    I64: (1, 8),
    Q4_0: (32, 18),
    Q4_1: (32, 20),
    Q5_0: (32, 22),
    Q5_1: (32, 24),
    Q8_0: (32, 34),
    Q4_K: (256, 144),
    Q5_K: (256, 176),
    Q6_K: (256, 210),
}


@dataclass
class TensorInfo:
    name: str
    shape: tuple  # row-major (outermost first)
    ggml_type: int
    offset: int  # relative to data section start

    @property
    def n_elements(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def n_bytes(self) -> int:
        elems, nbytes = BLOCK_LAYOUT[self.ggml_type]
        assert self.n_elements % elems == 0, (self.name, self.shape, self.ggml_type)
        return self.n_elements // elems * nbytes


# ---------------------------------------------------------------------------
# Parsing
# ---------------------------------------------------------------------------


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    return struct.unpack(fmt, f.read(size))[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype in _SCALAR_FMT:
        return _read(f, _SCALAR_FMT[vtype])
    if vtype == _VT_BOOL:
        return bool(_read(f, "<B"))
    if vtype == _VT_STRING:
        return _read_string(f)
    if vtype == _VT_ARRAY:
        elem_type = _read(f, "<I")
        count = _read(f, "<Q")
        if elem_type in _SCALAR_FMT and elem_type != _VT_FLOAT64:
            # bulk-read homogeneous scalar arrays (token tables can be huge)
            fmt = _SCALAR_FMT[elem_type]
            itemsize = struct.calcsize(fmt)
            raw = f.read(itemsize * count)
            return np.frombuffer(raw, dtype=np.dtype(fmt[1:]).newbyteorder("<")).tolist()
        return [_read_value(f, elem_type) for _ in range(count)]
    raise ValueError(f"unknown GGUF metadata value type {vtype}")


class GGUFFile:
    """Parsed GGUF container: metadata dict + lazy tensor access."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.metadata: Dict[str, Any] = {}
        self.tensors: Dict[str, TensorInfo] = {}
        with open(self.path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            self.version = _read(f, "<I")
            if self.version < 2:
                raise ValueError(f"{path}: GGUF v{self.version} unsupported (need >=2)")
            n_tensors = _read(f, "<Q")
            n_kv = _read(f, "<Q")
            for _ in range(n_kv):
                key = _read_string(f)
                vtype = _read(f, "<I")
                self.metadata[key] = _read_value(f, vtype)
            infos: List[TensorInfo] = []
            for _ in range(n_tensors):
                name = _read_string(f)
                n_dims = _read(f, "<I")
                dims = [_read(f, "<Q") for _ in range(n_dims)]
                ggml_type = _read(f, "<I")
                offset = _read(f, "<Q")
                # GGUF stores dims innermost-first; numpy wants outermost-first
                infos.append(TensorInfo(name, tuple(reversed(dims)), ggml_type, offset))
            alignment = int(self.metadata.get("general.alignment", DEFAULT_ALIGNMENT))
            pos = f.tell()
            self.data_offset = (pos + alignment - 1) // alignment * alignment
            for info in infos:
                self.tensors[info.name] = info
        self._mmap = np.memmap(self.path, dtype=np.uint8, mode="r")

    @property
    def architecture(self) -> str:
        return self.metadata.get("general.architecture", "")

    def tensor_bytes(self, name: str) -> np.ndarray:
        info = self.tensors[name]
        start = self.data_offset + info.offset
        return np.asarray(self._mmap[start : start + info.n_bytes])

    def load_tensor(self, name: str, dtype=np.float32) -> np.ndarray:
        """Dequantize a tensor to ``dtype`` with its row-major shape."""
        info = self.tensors[name]
        flat = dequantize(self.tensor_bytes(name), info.ggml_type, info.n_elements)
        return flat.reshape(info.shape).astype(dtype, copy=False)

    def load_all(self, dtype=np.float32) -> Dict[str, np.ndarray]:
        return {name: self.load_tensor(name, dtype) for name in self.tensors}


# ---------------------------------------------------------------------------
# Dequantization (vectorized numpy; block layouts per ggml)
# ---------------------------------------------------------------------------


def _f16(raw: np.ndarray) -> np.ndarray:
    return raw.view(np.float16).astype(np.float32)


def _deq_q4_0(blocks: np.ndarray) -> np.ndarray:
    # block: d f16 | 16B nibbles. elem i in [0,16) = low nibble of qs[i],
    # elem i+16 = high nibble of qs[i]; value = d * (q - 8)
    d = _f16(blocks[:, 0:2].reshape(-1).view(np.uint8)).reshape(-1, 1)
    qs = blocks[:, 2:18]
    lo = (qs & 0x0F).astype(np.int8) - 8
    hi = (qs >> 4).astype(np.int8) - 8
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (d * q).reshape(-1)


def _deq_q4_1(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks[:, 0:2]).reshape(-1, 1)
    m = _f16(blocks[:, 2:4]).reshape(-1, 1)
    qs = blocks[:, 4:20]
    q = np.concatenate([(qs & 0x0F), (qs >> 4)], axis=1).astype(np.float32)
    return (d * q + m).reshape(-1)


def _q5_high_bits(qh_bytes: np.ndarray) -> np.ndarray:
    """Expand the packed u32 of per-element 5th bits -> (nblocks, 32) in {0,1}."""
    qh = qh_bytes.reshape(-1, 4).view(np.uint32).reshape(-1, 1)  # little-endian
    shifts = np.arange(32, dtype=np.uint32).reshape(1, -1)
    return ((qh >> shifts) & 1).astype(np.uint8)


def _deq_q5_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks[:, 0:2]).reshape(-1, 1)
    xh = _q5_high_bits(blocks[:, 2:6])
    qs = blocks[:, 6:22]
    lo = (qs & 0x0F).astype(np.int16)
    hi = (qs >> 4).astype(np.int16)
    q = np.concatenate([lo, hi], axis=1)
    q = (q | (xh.astype(np.int16) << 4)) - 16
    return (d * q.astype(np.float32)).reshape(-1)


def _deq_q5_1(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks[:, 0:2]).reshape(-1, 1)
    m = _f16(blocks[:, 2:4]).reshape(-1, 1)
    xh = _q5_high_bits(blocks[:, 4:8])
    qs = blocks[:, 8:24]
    q = np.concatenate([(qs & 0x0F), (qs >> 4)], axis=1).astype(np.uint16)
    q = q | (xh.astype(np.uint16) << 4)
    return (d * q.astype(np.float32) + m).reshape(-1)


def _deq_q8_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks[:, 0:2]).reshape(-1, 1)
    q = blocks[:, 2:34].view(np.int8).astype(np.float32)
    return (d * q).reshape(-1)


def _k_scale_min(scales: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unpack the 12-byte 6-bit scales/mins of Q4_K/Q5_K -> 8 each per block.

    For sub-block j < 4:  sc = s[j] & 63,            m = s[j+4] & 63
    for j >= 4:           sc = (s[j+4] & 0xF) | ((s[j-4] >> 6) << 4)
                          m  = (s[j+4] >> 4)  | ((s[j]   >> 6) << 4)
    """
    s = scales.astype(np.uint8)
    sc = np.empty(s.shape[:-1] + (8,), dtype=np.float32)
    mn = np.empty_like(sc)
    for j in range(4):
        sc[..., j] = (s[..., j] & 63).astype(np.float32)
        mn[..., j] = (s[..., j + 4] & 63).astype(np.float32)
    for j in range(4, 8):
        sc[..., j] = ((s[..., j + 4] & 0x0F) | ((s[..., j - 4] >> 6) << 4)).astype(
            np.float32
        )
        mn[..., j] = ((s[..., j + 4] >> 4) | ((s[..., j] >> 6) << 4)).astype(np.float32)
    return sc, mn


def _deq_q4_k(blocks: np.ndarray) -> np.ndarray:
    # super-block of 256: d f16 | dmin f16 | scales[12] | qs[128]
    # elements come in 4 chunks of 64: chunk c uses qs[32c:32c+32],
    # low nibbles = first 32 (sub-block 2c), high = next 32 (sub-block 2c+1)
    n = blocks.shape[0]
    d = _f16(blocks[:, 0:2]).reshape(-1, 1)
    dmin = _f16(blocks[:, 2:4]).reshape(-1, 1)
    sc, mn = _k_scale_min(blocks[:, 4:16])
    qs = blocks[:, 16:144].reshape(n, 4, 32)
    lo = (qs & 0x0F).astype(np.float32)
    hi = (qs >> 4).astype(np.float32)
    q = np.stack([lo, hi], axis=2).reshape(n, 8, 32)  # sub-block major
    scale = (d * sc).reshape(n, 8, 1)
    offset = (dmin * mn).reshape(n, 8, 1)
    return (scale * q - offset).reshape(-1)


def _deq_q5_k(blocks: np.ndarray) -> np.ndarray:
    # d f16 | dmin f16 | scales[12] | qh[32] | qs[128]
    n = blocks.shape[0]
    d = _f16(blocks[:, 0:2]).reshape(-1, 1)
    dmin = _f16(blocks[:, 2:4]).reshape(-1, 1)
    sc, mn = _k_scale_min(blocks[:, 4:16])
    qh = blocks[:, 16:48]  # (n, 32): bit j of qh[l] is the 5th bit of
    # element l within sub-block j
    qs = blocks[:, 48:176].reshape(n, 4, 32)
    lo = (qs & 0x0F).astype(np.uint16)
    hi = (qs >> 4).astype(np.uint16)
    q4 = np.stack([lo, hi], axis=2).reshape(n, 8, 32)
    jbits = (
        (qh.reshape(n, 1, 32) >> np.arange(8, dtype=np.uint8).reshape(1, 8, 1)) & 1
    ).astype(np.uint16)
    q = q4 | (jbits << 4)
    scale = (d * sc).reshape(n, 8, 1)
    offset = (dmin * mn).reshape(n, 8, 1)
    return (scale * q.astype(np.float32) - offset).reshape(-1)


def _deq_q6_k(blocks: np.ndarray) -> np.ndarray:
    # ql[128] | qh[64] | scales[16] i8 | d f16; two half-blocks of 128.
    # In each half (ql 64B, qh 32B, sc 8):
    #   q1 = (ql[l]    & 0xF) | ((qh[l] >> 0 & 3) << 4) - 32 -> y[l],    sc[l/16]
    #   q2 = (ql[l+32] & 0xF) | ((qh[l] >> 2 & 3) << 4) - 32 -> y[l+32], sc[2+l/16]
    #   q3 = (ql[l]    >> 4)  | ((qh[l] >> 4 & 3) << 4) - 32 -> y[l+64], sc[4+l/16]
    #   q4 = (ql[l+32] >> 4)  | ((qh[l] >> 6 & 3) << 4) - 32 -> y[l+96], sc[6+l/16]
    n = blocks.shape[0]
    ql = blocks[:, 0:128].reshape(n, 2, 2, 32)  # [half, (l<32 | l>=32), l]
    qh = blocks[:, 128:192].reshape(n, 2, 32)
    scales = blocks[:, 192:208].view(np.int8).reshape(n, 2, 8).astype(np.float32)
    d = _f16(blocks[:, 208:210]).reshape(n, 1, 1, 1)

    lo1 = (ql[:, :, 0, :] & 0x0F).astype(np.int16)
    lo2 = (ql[:, :, 1, :] & 0x0F).astype(np.int16)
    hi1 = (ql[:, :, 0, :] >> 4).astype(np.int16)
    hi2 = (ql[:, :, 1, :] >> 4).astype(np.int16)
    b = qh.astype(np.int16)
    q1 = (lo1 | ((b >> 0 & 3) << 4)) - 32
    q2 = (lo2 | ((b >> 2 & 3) << 4)) - 32
    q3 = (hi1 | ((b >> 4 & 3) << 4)) - 32
    q4 = (hi2 | ((b >> 6 & 3) << 4)) - 32
    q = np.stack([q1, q2, q3, q4], axis=2).astype(np.float32)  # (n, 2, 4, 32)

    # scale index within a half: group g of 4 (one per 32-run), sub l//16
    sidx = scales.reshape(n, 2, 4, 2)  # sc[g*2 + l//16]
    sel = np.repeat(sidx, 16, axis=3)  # (n, 2, 4, 32)
    return (d * sel * q).reshape(-1)


_DEQUANT = {
    Q4_0: _deq_q4_0,
    Q4_1: _deq_q4_1,
    Q5_0: _deq_q5_0,
    Q5_1: _deq_q5_1,
    Q8_0: _deq_q8_0,
    Q4_K: _deq_q4_k,
    Q5_K: _deq_q5_k,
    Q6_K: _deq_q6_k,
}


def dequantize(raw: np.ndarray, ggml_type: int, n_elements: int) -> np.ndarray:
    """Dequantize a flat byte buffer of ``n_elements`` values to float32."""
    raw = np.asarray(raw, dtype=np.uint8)
    if ggml_type == F32:
        return raw.view(np.float32)[:n_elements]
    if ggml_type == F16:
        return raw.view(np.float16)[:n_elements].astype(np.float32)
    if ggml_type == BF16:
        as_u16 = raw.view(np.uint16)[:n_elements].astype(np.uint32) << 16
        return as_u16.view(np.float32)
    if ggml_type == F64:
        return raw.view(np.float64)[:n_elements].astype(np.float32)
    if ggml_type in (I8, I16, I32, I64):
        dt = {I8: np.int8, I16: np.int16, I32: np.int32, I64: np.int64}[ggml_type]
        return raw.view(dt)[:n_elements].astype(np.float32)
    fn = _DEQUANT.get(ggml_type)
    if fn is None:
        name = GGML_TYPE_NAMES.get(ggml_type, ggml_type)
        raise NotImplementedError(f"dequantization for ggml type {name}")
    elems, nbytes = BLOCK_LAYOUT[ggml_type]
    n_blocks = n_elements // elems
    out = fn(raw[: n_blocks * nbytes].reshape(n_blocks, nbytes))
    return out[:n_elements]


# ---------------------------------------------------------------------------
# Quantization (test support + GGUF->safetensors conversion tooling)
# ---------------------------------------------------------------------------


def quantize_q8_0(values: np.ndarray) -> np.ndarray:
    """Quantize float32 -> Q8_0 block bytes (round-trip testing support)."""
    v = values.reshape(-1, 32).astype(np.float32)
    amax = np.abs(v).max(axis=1, keepdims=True)
    d = (amax / 127.0).astype(np.float16)
    scale = np.where(amax == 0, 1.0, amax / 127.0)
    q = np.clip(np.round(v / scale), -127, 127).astype(np.int8)
    blocks = np.empty((v.shape[0], 34), dtype=np.uint8)
    blocks[:, 0:2] = d.view(np.uint8).reshape(-1, 2)
    blocks[:, 2:34] = q.view(np.uint8)
    return blocks.reshape(-1)


def quantize_q4_0(values: np.ndarray) -> np.ndarray:
    """Quantize float32 -> Q4_0 block bytes (round-trip testing support)."""
    v = values.reshape(-1, 32).astype(np.float32)
    idx_absmax = np.abs(v).argmax(axis=1)
    maxv = v[np.arange(v.shape[0]), idx_absmax]
    d = maxv / -8.0
    scale = np.where(d == 0, 1.0, d)
    q = np.clip(np.round(v / scale[:, None]) + 8, 0, 15).astype(np.uint8)
    blocks = np.empty((v.shape[0], 18), dtype=np.uint8)
    blocks[:, 0:2] = d.astype(np.float16).view(np.uint8).reshape(-1, 2)
    blocks[:, 2:18] = q[:, :16] | (q[:, 16:] << 4)
    return blocks.reshape(-1)


# ---------------------------------------------------------------------------
# Writer (synthetic files for tests + conversion tooling)
# ---------------------------------------------------------------------------


def _write_value(out: list, value: Any) -> int:
    """Append encoded metadata value; returns its type tag."""
    if isinstance(value, bool):
        out.append(struct.pack("<B", int(value)))
        return _VT_BOOL
    if isinstance(value, int):
        out.append(struct.pack("<q", value))
        return _VT_INT64
    if isinstance(value, float):
        out.append(struct.pack("<f", value))
        return _VT_FLOAT32
    if isinstance(value, str):
        raw = value.encode("utf-8")
        out.append(struct.pack("<Q", len(raw)) + raw)
        return _VT_STRING
    if isinstance(value, (list, tuple, np.ndarray)):
        items = list(value)
        probe: list = []
        elem_type = _write_value(probe, items[0]) if items else _VT_INT64
        out.append(struct.pack("<IQ", elem_type, len(items)))
        for item in items:
            sub: list = []
            t = _write_value(sub, item)
            assert t == elem_type, "heterogeneous GGUF arrays unsupported"
            out.extend(sub)
        return _VT_ARRAY
    raise TypeError(f"cannot encode GGUF metadata value {value!r}")


def write_gguf(
    path: str | Path,
    metadata: Dict[str, Any],
    tensors: Dict[str, tuple],
    alignment: int = DEFAULT_ALIGNMENT,
) -> None:
    """Write a GGUF v3 file. ``tensors`` maps name -> (shape, ggml_type, raw_bytes)."""
    header = [GGUF_MAGIC, struct.pack("<IQQ", 3, len(tensors), len(metadata))]
    for key, value in metadata.items():
        kraw = key.encode("utf-8")
        body: list = []
        vtype = _write_value(body, value)
        header.append(struct.pack("<Q", len(kraw)) + kraw + struct.pack("<I", vtype))
        header.extend(body)

    offset = 0
    data_parts: List[bytes] = []
    for name, (shape, ggml_type, raw) in tensors.items():
        nraw = name.encode("utf-8")
        dims = tuple(reversed(shape))  # innermost-first on disk
        header.append(struct.pack("<Q", len(nraw)) + nraw)
        header.append(struct.pack("<I", len(dims)))
        header.append(struct.pack(f"<{len(dims)}Q", *dims))
        header.append(struct.pack("<IQ", ggml_type, offset))
        raw = bytes(raw)
        pad = (-len(raw)) % alignment
        data_parts.append(raw + b"\x00" * pad)
        offset += len(raw) + pad

    head = b"".join(bytes(h) for h in header)
    head_pad = (-len(head)) % alignment
    with open(path, "wb") as f:
        f.write(head + b"\x00" * head_pad)
        for part in data_parts:
            f.write(part)
