"""Numeric parity: engine logits vs HuggingFace transformers on CPU fp32.

The reference has no numeric tests (its kernels live in llama.cpp); this
suite is the TPU build's ground truth (SURVEY.md section 4 "ours to invent").
Tiny random-weight models exercise every architectural feature: GQA
(TinyLlama/Llama shapes), sliding-window attention (Mistral), QK-norm
(Qwen3), and the llama.cpp GGUF q/k permutation.
"""

import numpy as np
import pytest
import torch

from aios_tpu.engine import gguf as gguf_mod
from aios_tpu.engine import model as M
from aios_tpu.engine import weights as W
from aios_tpu.engine.config import ModelConfig

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow

ATOL = 2e-4
RTOL = 2e-4


def _hf_logits(hf_model, tokens):
    with torch.no_grad():
        out = hf_model(torch.tensor(tokens, dtype=torch.long))
    return out.logits.float().numpy()


def _engine_logits(hf_model, cfg, tokens):
    params = W.params_from_hf_state_dict(hf_model.state_dict(), cfg)
    return np.asarray(M.forward_full(params, cfg, tokens))


def _tokens(cfg, batch=2, seq=24, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)


@pytest.fixture(scope="module")
def llama_pair():
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=3,
        num_attention_heads=8,
        num_key_value_heads=2,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg).eval()
    cfg = ModelConfig(
        name="tiny-llama-test",
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_layers=3,
        num_heads=8,
        num_kv_heads=2,
        head_dim=8,
        max_context=64,
    )
    return hf, cfg


def test_llama_logits_parity(llama_pair):
    hf, cfg = llama_pair
    tokens = _tokens(cfg)
    np.testing.assert_allclose(
        _engine_logits(hf, cfg, tokens), _hf_logits(hf, tokens), atol=ATOL, rtol=RTOL
    )


def test_mistral_sliding_window_parity():
    from transformers import MistralConfig, MistralForCausalLM

    hf_cfg = MistralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=3,
        num_attention_heads=8,
        num_key_value_heads=2,
        max_position_embeddings=128,
        sliding_window=8,  # shorter than seq so the window actually bites
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    hf = MistralForCausalLM(hf_cfg).eval()
    cfg = ModelConfig(
        name="tiny-mistral-test",
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_layers=3,
        num_heads=8,
        num_kv_heads=2,
        head_dim=8,
        max_context=128,
        sliding_window=8,
    )
    tokens = _tokens(cfg, seq=32, seed=3)
    np.testing.assert_allclose(
        _engine_logits(hf, cfg, tokens), _hf_logits(hf, tokens), atol=ATOL, rtol=RTOL
    )


def test_qwen3_qk_norm_parity():
    from transformers import Qwen3Config, Qwen3ForCausalLM

    hf_cfg = Qwen3Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=2,
        head_dim=8,
        max_position_embeddings=64,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    hf = Qwen3ForCausalLM(hf_cfg).eval()
    cfg = ModelConfig(
        name="tiny-qwen3-test",
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_layers=2,
        num_heads=8,
        num_kv_heads=2,
        head_dim=8,
        max_context=64,
        rms_norm_eps=1e-6,
        qk_norm=True,
    )
    tokens = _tokens(cfg, seq=16, seed=5)
    np.testing.assert_allclose(
        _engine_logits(hf, cfg, tokens), _hf_logits(hf, tokens), atol=ATOL, rtol=RTOL
    )


def _permute_llamacpp(w, n_heads):
    """The forward permutation convert_hf_to_gguf applies to q/k rows."""
    out_dim, in_dim = w.shape
    half = out_dim // n_heads // 2
    return w.reshape(n_heads, 2, half, in_dim).swapaxes(1, 2).reshape(out_dim, in_dim)


def test_gguf_roundtrip_matches_hf(llama_pair, tmp_path):
    """HF weights -> GGUF container (with llama.cpp q/k permutation) ->
    params_from_gguf must equal the HF-direct path bit-for-bit (F32)."""
    hf, cfg = llama_pair
    sd = {k: v.detach().numpy().astype(np.float32) for k, v in hf.state_dict().items()}

    tensors = {}

    def put(name, arr):
        tensors[name] = (arr.shape, gguf_mod.F32, np.ascontiguousarray(arr).tobytes())

    put("token_embd.weight", sd["model.embed_tokens.weight"])
    put("output_norm.weight", sd["model.norm.weight"])
    put("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_layers):
        hp = f"model.layers.{i}."
        gp = f"blk.{i}."
        put(gp + "attn_norm.weight", sd[hp + "input_layernorm.weight"])
        put(gp + "ffn_norm.weight", sd[hp + "post_attention_layernorm.weight"])
        put(
            gp + "attn_q.weight",
            _permute_llamacpp(sd[hp + "self_attn.q_proj.weight"], cfg.num_heads),
        )
        put(
            gp + "attn_k.weight",
            _permute_llamacpp(sd[hp + "self_attn.k_proj.weight"], cfg.num_kv_heads),
        )
        put(gp + "attn_v.weight", sd[hp + "self_attn.v_proj.weight"])
        put(gp + "attn_output.weight", sd[hp + "self_attn.o_proj.weight"])
        put(gp + "ffn_gate.weight", sd[hp + "mlp.gate_proj.weight"])
        put(gp + "ffn_up.weight", sd[hp + "mlp.up_proj.weight"])
        put(gp + "ffn_down.weight", sd[hp + "mlp.down_proj.weight"])

    meta = {
        "general.architecture": "llama",
        "general.name": "tiny-llama-test",
        "llama.block_count": cfg.num_layers,
        "llama.embedding_length": cfg.hidden_size,
        "llama.feed_forward_length": cfg.intermediate_size,
        "llama.attention.head_count": cfg.num_heads,
        "llama.attention.head_count_kv": cfg.num_kv_heads,
        "llama.attention.layer_norm_rms_epsilon": cfg.rms_norm_eps,
        "llama.context_length": cfg.max_context,
        "llama.rope.freq_base": cfg.rope_theta,
        "llama.vocab_size": cfg.vocab_size,
    }
    path = tmp_path / "tiny.gguf"
    gguf_mod.write_gguf(path, meta, tensors)

    gguf_params, gguf_cfg = W.params_from_gguf(str(path), cfg)
    hf_params = W.params_from_hf_state_dict(hf.state_dict(), cfg)

    def flatten(d, prefix=""):
        for k, v in d.items():
            if isinstance(v, dict):
                yield from flatten(v, prefix + k + "/")
            else:
                yield prefix + k, v

    hf_flat = dict(flatten(hf_params))
    for name, arr in flatten(gguf_params):
        np.testing.assert_array_equal(arr, hf_flat[name], err_msg=name)

    tokens = _tokens(cfg, seq=12, seed=9)
    np.testing.assert_allclose(
        np.asarray(M.forward_full(gguf_params, cfg, tokens)),
        _hf_logits(hf, tokens),
        atol=ATOL,
        rtol=RTOL,
    )


def test_config_from_gguf_metadata():
    from aios_tpu.engine.config import from_gguf_metadata

    md = {
        "general.architecture": "llama",
        "general.name": "TinyLlama 1.1B",
        "llama.block_count": 22,
        "llama.embedding_length": 2048,
        "llama.feed_forward_length": 5632,
        "llama.attention.head_count": 32,
        "llama.attention.head_count_kv": 4,
        "llama.context_length": 2048,
        "llama.vocab_size": 32000,
    }
    cfg = from_gguf_metadata(md)
    assert cfg.num_layers == 22
    assert cfg.num_kv_heads == 4
    assert cfg.head_dim == 64
    assert cfg.vocab_size == 32000


def test_preset_param_counts_sane():
    from aios_tpu.engine.config import MISTRAL_7B, TINYLLAMA_1_1B

    assert 1.0e9 < TINYLLAMA_1_1B.num_params() < 1.2e9
    assert 7.0e9 < MISTRAL_7B.num_params() < 7.5e9
