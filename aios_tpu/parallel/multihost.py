"""Multi-host distributed backend: process group + global device mesh.

The reference scales across nodes with gRPC-dispatched remote execution
(orchestrator cluster manager + remote executor,
/root/reference/agent-core/src/cluster.rs:1, remote_exec.rs:29-41) and
leaves model execution single-node (one llama-server per host). Here the
control plane stays exactly that gRPC cluster layer — but the *data
plane* scales below the runtime service boundary the TPU way: one JAX
process per host joins a process group (`jax.distributed`, the NCCL/MPI
bootstrap equivalent), and a single GLOBAL mesh spans every host's chips.
XLA then inserts the cross-host collectives: axes that span hosts ride
DCN, axes inside a host ride ICI, and the same `ShardingPlan` /
`make_train_step` / TP-decode code runs unchanged whether the mesh is one
chip or a pod slice.

Axis policy (the scaling-book recipe): the OUTER factor of `dp` spans
hosts — data parallelism tolerates DCN latency because it communicates
once per step (gradient all-reduce) — while `sp`/`tp` stay inside a
host's ICI domain where per-layer collectives are cheap.

Env contract (set by deploy scripts / systemd units, one process per
host):
  AIOS_TPU_COORDINATOR   host:port of process 0
  AIOS_TPU_NUM_PROCESSES total process count
  AIOS_TPU_PROCESS_ID    this process's rank
  AIOS_TPU_MULTIHOST     "auto" => no-arg `jax.distributed.initialize()`
                         (Cloud TPU pods self-describe their topology)

Unset => single-host operation, no process group. The explicit
coordinator contract is what the CPU e2e test and bare-metal deployments
use; pods set only AIOS_TPU_MULTIHOST=auto.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Mapping, Optional, Tuple

log = logging.getLogger("aios.multihost")

_initialized = False


@dataclass(frozen=True)
class EnvContract:
    """Parsed multihost env contract. ``auto`` means
    AIOS_TPU_MULTIHOST=auto|1 (pod self-describe); the explicit path
    carries coordinator + num_processes + process_id."""

    coordinator: str = ""
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    auto: bool = False


def env_contract(
    env: Optional[Mapping[str, str]] = None,
) -> Optional[EnvContract]:
    """Parse the AIOS_TPU_* multihost contract WITHOUT touching jax —
    the fleet telemetry plane reads rank/coordinator from here, and the
    fast CPU unit tests drive it with fake environments. Returns None
    for single-host (neither AIOS_TPU_COORDINATOR nor
    AIOS_TPU_MULTIHOST set); raises ValueError when the explicit
    coordinator path is missing its companion vars."""
    e = os.environ if env is None else env
    coord = e.get("AIOS_TPU_COORDINATOR", "")
    auto = e.get("AIOS_TPU_MULTIHOST", "").lower() in ("1", "auto")
    if not coord and not auto:
        return None
    num = e.get("AIOS_TPU_NUM_PROCESSES")
    pid = e.get("AIOS_TPU_PROCESS_ID")
    if coord and not auto and not (num and pid is not None and pid != ""):
        # fail with OUR contract in the message, not JAX's cluster-detect
        # internals: the explicit coordinator path needs all three vars
        raise ValueError(
            "AIOS_TPU_COORDINATOR requires AIOS_TPU_NUM_PROCESSES and "
            "AIOS_TPU_PROCESS_ID (or set AIOS_TPU_MULTIHOST=auto on a "
            "self-describing Cloud TPU pod)"
        )
    return EnvContract(
        coordinator=coord,
        num_processes=int(num) if num else None,
        process_id=int(pid) if pid else None,
        auto=auto,
    )


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> bool:
    """Join the process group. Returns True if a multi-process group was
    initialized (idempotent; False means single-process operation).
    ``auto=True`` with no coordinator calls the no-arg
    ``jax.distributed.initialize()`` — Cloud TPU pods self-describe their
    topology through the TPU metadata."""
    global _initialized
    if _initialized:
        return True
    import jax

    if coordinator is None:
        if not auto:
            return False
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True
    log.info(
        "joined process group: rank %d/%d via %s",
        jax.process_index(), jax.process_count(), coordinator or "auto-detect",
    )
    return True


def initialize_from_env() -> bool:
    """Join the process group iff AIOS_TPU_COORDINATOR (explicit contract)
    or AIOS_TPU_MULTIHOST=auto (pod auto-detect) is set — the service
    startup hook; a no-op in the common single-host deployment."""
    contract = env_contract()
    if contract is None:
        return False
    return initialize(
        contract.coordinator or None,
        contract.num_processes,
        contract.process_id,
        auto=contract.auto,
    )


def build_global_mesh(dp: int = 0, sp: int = 1, tp: int = 1):
    """A ("dp", "sp", "ep", "tp") mesh over EVERY process's devices, so
    dp's outer factor spans hosts (DCN) and sp/tp stay within a host
    (ICI). dp=0 means "whatever is left". The result drops straight into
    the existing ShardingPlan / train / TP-decode stack — multi-host scale
    without touching any model code."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    n_proc = jax.process_count()
    local = jax.local_device_count()
    total = n_proc * local
    if sp * tp > local or local % (sp * tp):
        raise ValueError(
            f"sp*tp={sp * tp} must divide the {local} devices of one host "
            f"— sp/tp collectives must ride ICI, never DCN"
        )
    local_dp = local // (sp * tp)
    want_dp = n_proc * local_dp
    if dp and dp != want_dp:
        raise ValueError(
            f"dp={dp} inconsistent: {n_proc} hosts x {local_dp} local dp "
            f"gives {want_dp}"
        )
    if n_proc > 1:
        try:
            from jax.experimental import mesh_utils

            devs = mesh_utils.create_hybrid_device_mesh(
                (local_dp, sp, tp), (n_proc, 1, 1)
            )
            return Mesh(
                devs.reshape(n_proc * local_dp, sp, 1, tp),
                ("dp", "sp", "ep", "tp"),
            )
        except Exception as e:  # noqa: BLE001 — CPU backends lack topology
            log.debug("hybrid mesh unavailable (%s); process-sorted grid", e)
        # group by process explicitly: devices sorted (process, local) so
        # the dp axis's outer stride is the host boundary
        devs = sorted(
            jax.devices(), key=lambda d: (d.process_index, d.id)
        )
        grid = np.array(devs).reshape(n_proc * local_dp, sp, 1, tp)
        return Mesh(grid, ("dp", "sp", "ep", "tp"))
    grid = np.array(jax.devices()[:total]).reshape(local_dp, sp, 1, tp)
    return Mesh(grid, ("dp", "sp", "ep", "tp"))


def cross_host_allreduce_check(mesh) -> float:
    """One psum across the full mesh — the data plane's liveness probe
    (the collective analog of the reference cluster's TCP heartbeat,
    cluster.rs:144-151). Each process contributes rank+1 once per local dp
    shard, so the result on EVERY host must equal
    ``sum(1..n_proc) * (local_device_count // (sp*tp))``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dp = mesh.shape["dp"]
    contrib = np.full(
        (n_dp // max(jax.process_count(), 1),),
        float(jax.process_index() + 1),
        np.float32,
    )
    sharding = NamedSharding(mesh, P(("dp",)))
    arr = jax.make_array_from_process_local_data(sharding, contrib)

    def f(x):
        # the input varies over dp only (sp/tp replicate it), so dp is the
        # axis the all-reduce must cross — which is exactly the axis that
        # spans hosts
        s = jax.lax.psum(x.sum(), "dp")
        return s.reshape(1)

    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P())
    )(arr)
    return float(jax.device_get(out)[0])


def process_info() -> Tuple[int, int, int]:
    """(process_index, process_count, local_device_count)."""
    import jax

    return jax.process_index(), jax.process_count(), jax.local_device_count()
