"""Proactive goal generation.

Reference parity (agent-core/src/proactive.rs): a 60 s loop that auto-creates
remediation goals on CPU > 90%, memory > 85%, disk > 90%, failed agents,
>= 6 consecutive service-health failures, TLS certs expiring within 30 days,
and backups staler than 24 h (proactive.rs:74-200), deduplicating against
already-active goals.

TPU-serving extension (no reference counterpart — llama-server exposes no
serving counters): the runtime HealthCheck's per-model serving stats feed
two escalations, mirroring the reference's health->goal pattern
(proactive.rs:144-159):
  * KV page-pool exhaustion — pool_evictions GREW since the last pass:
    live streams are being truncated to admit new work (pool undersized
    or a runaway long context);
  * slot starvation — requests queued behind full slots
    (waiting > 0 with every slot active) on two CONSECUTIVE passes, so a
    transient burst does not page anyone.
"""

from __future__ import annotations

import subprocess
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, List, Optional

import psutil


@dataclass
class ProactiveConfig:
    interval: float = 60.0
    cpu_threshold: float = 90.0
    memory_threshold: float = 85.0
    disk_threshold: float = 90.0
    health_failure_threshold: int = 6
    cert_warning_days: int = 30
    backup_max_age_hours: float = 24.0
    cert_dir: str = "/tmp/aios/certs"
    backup_dir: str = "/tmp/aios/backups"
    # serving escalations: consecutive starved passes before a goal
    starvation_threshold: int = 2


class ProactiveGenerator:
    def __init__(
        self,
        submit_goal: Callable[[str, int], object],
        active_goal_descriptions: Callable[[], List[str]],
        health_failures: Optional[Callable[[], dict]] = None,
        failed_agents: Optional[Callable[[], List[str]]] = None,
        serving_stats: Optional[Callable[[], dict]] = None,
        config: Optional[ProactiveConfig] = None,
    ):
        self.submit_goal = submit_goal
        self.active_goal_descriptions = active_goal_descriptions
        self.health_failures = health_failures
        self.failed_agents = failed_agents
        # model name -> {counter: float} from the runtime HealthCheck
        # (orchestrator/main.py parses the `<model>.serving` details)
        self.serving_stats = serving_stats
        self.config = config or ProactiveConfig()
        self._evictions_seen: dict = {}
        self._starved_passes: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _maybe_submit(self, description: str, priority: int) -> bool:
        """Dedupe against active goals (proactive.rs dedupe). The key is
        the description up to its first parenthetical — the parentheses
        hold volatile readings (percentages, counts) while the prefix
        carries the condition AND its subject (e.g. the model name), so
        per-model escalations never collapse into one key."""
        key = description.split("(")[0].strip().lower()[:80]
        for active in self.active_goal_descriptions():
            if key in active.lower():
                return False
        self.submit_goal(description, priority)
        return True

    def check_once(self) -> List[str]:
        """One pass; returns descriptions of goals created."""
        cfg = self.config
        created: List[str] = []

        cpu = psutil.cpu_percent(interval=None)
        if cpu > cfg.cpu_threshold:
            if self._maybe_submit(
                f"Investigate and reduce high CPU usage ({cpu:.0f}%)", 8
            ):
                created.append("cpu")

        mem = psutil.virtual_memory().percent
        if mem > cfg.memory_threshold:
            if self._maybe_submit(
                f"Investigate and reduce high memory usage ({mem:.0f}%)", 8
            ):
                created.append("memory")

        disk = psutil.disk_usage("/").percent
        if disk > cfg.disk_threshold:
            if self._maybe_submit(
                f"Free disk space on / (at {disk:.0f}%)", 9
            ):
                created.append("disk")

        if self.failed_agents is not None:
            for agent in self.failed_agents():
                if self._maybe_submit(
                    f"Recover failed agent {agent}", 7
                ):
                    created.append(f"agent:{agent}")

        if self.health_failures is not None:
            for service, failures in self.health_failures().items():
                if failures >= cfg.health_failure_threshold:
                    if self._maybe_submit(
                        f"Remediate unhealthy service {service}"
                        f" ({failures} consecutive failures)", 9
                    ):
                        created.append(f"service:{service}")

        created.extend(self._check_certs())
        created.extend(self._check_backups())
        created.extend(self._check_serving())
        return created

    def _check_serving(self) -> List[str]:
        """TPU serving escalations from the runtime's per-model counters."""
        if self.serving_stats is None:
            return []
        created: List[str] = []
        try:
            per_model = self.serving_stats() or {}
        except Exception:  # noqa: BLE001 — runtime down is the health
            return []      # checker's escalation, not this one's
        for model, stats in per_model.items():
            ev = stats.get("pool_evictions", 0)
            first_sighting = model not in self._evictions_seen
            last = self._evictions_seen.get(model, ev)
            self._evictions_seen[model] = ev
            # pool_evictions is cumulative since RUNTIME start: on this
            # generator's first sighting only record the baseline, or an
            # orchestrator restart would report days-old evictions as new
            if not first_sighting and ev > last:
                if self._maybe_submit(
                    f"Investigate KV page-pool exhaustion on model {model}"
                    f" ({int(ev - last)} stream(s) evicted since last"
                    " check; grow paged_kv_rows or shorten contexts)", 8,
                ):
                    created.append(f"pool:{model}")
            slots = stats.get("num_slots", 0)
            starved = (
                stats.get("waiting", 0) > 0
                and slots > 0
                and stats.get("active_slots", 0) >= slots
            )
            if starved:
                n = self._starved_passes.get(model, 0) + 1
                self._starved_passes[model] = n
                if n >= self.config.starvation_threshold:
                    if self._maybe_submit(
                        f"Relieve request starvation on model {model}"
                        f" (all {int(slots)} slots busy with"
                        f" {int(stats.get('waiting', 0))} request(s)"
                        " queued; raise num_slots or add a replica)", 7,
                    ):
                        created.append(f"starvation:{model}")
            else:
                self._starved_passes[model] = 0
        return created

    def _check_certs(self) -> List[str]:
        created = []
        cert_dir = Path(self.config.cert_dir)
        if not cert_dir.is_dir():
            return created
        for cert in cert_dir.glob("*.crt"):
            days = cert_expiry_days(str(cert))
            if days is not None and days < self.config.cert_warning_days:
                if self._maybe_submit(
                    f"Rotate TLS certificate {cert.name}"
                    f" (expires in {days} days)", 6
                ):
                    created.append(f"cert:{cert.name}")
        return created

    def _check_backups(self) -> List[str]:
        backup_dir = Path(self.config.backup_dir)
        if not backup_dir.is_dir():
            return []
        newest = 0.0
        for f in backup_dir.iterdir():
            try:
                newest = max(newest, f.stat().st_mtime)
            except OSError:
                continue
        if newest == 0.0:
            return []
        age_hours = (time.time() - newest) / 3600
        if age_hours > self.config.backup_max_age_hours:
            if self._maybe_submit(
                f"Run system backup (last backup {age_hours:.0f}h ago)", 5
            ):
                return ["backup"]
        return []

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.config.interval):
                try:
                    self.check_once()
                except Exception:  # noqa: BLE001
                    pass

        self._thread = threading.Thread(target=loop, name="proactive",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


def cert_expiry_days(cert_path: str) -> Optional[int]:
    """Days until a PEM cert expires (openssl-based; rcgen in the reference)."""
    try:
        out = subprocess.run(
            ["openssl", "x509", "-enddate", "-noout", "-in", cert_path],
            capture_output=True, text=True, timeout=10,
        )
        if out.returncode != 0:
            return None
        # notAfter=Jan  1 00:00:00 2027 GMT
        raw = out.stdout.strip().split("=", 1)[1]
        expiry = time.mktime(time.strptime(raw, "%b %d %H:%M:%S %Y %Z"))
        return int((expiry - time.time()) / 86400)
    except (OSError, ValueError, IndexError):
        return None
