"""SHA-256 hash-chained audit ledger.

Reference parity (tools/src/audit.rs): every tool execution appends a record
whose hash covers the previous record's hash — `verify_chain` recomputes the
whole chain and reports the first break (audit.rs:54-150). SQLite-backed.
"""

from __future__ import annotations

import hashlib
import json
import sqlite3
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..obs import instruments as obs

_SCHEMA = """
CREATE TABLE IF NOT EXISTS audit_log (
    seq INTEGER PRIMARY KEY AUTOINCREMENT,
    id TEXT NOT NULL,
    timestamp INTEGER NOT NULL,
    agent_id TEXT,
    tool_name TEXT,
    input_hash TEXT,
    output_hash TEXT,
    success INTEGER,
    reason TEXT,
    prev_hash TEXT NOT NULL,
    hash TEXT NOT NULL
);
"""

GENESIS = "0" * 64


def _sha256(data: str) -> str:
    from .. import native

    if native.available():
        return native.sha256_hex(data.encode("utf-8"))
    return hashlib.sha256(data.encode("utf-8")).hexdigest()


class AuditLog:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.executescript(_SCHEMA)
        self._lock = threading.Lock()

    def record(
        self,
        agent_id: str,
        tool_name: str,
        input_bytes: bytes,
        output_bytes: bytes,
        success: bool,
        reason: str = "",
    ) -> str:
        """Append one chained record; returns its id."""
        with self._lock:
            row = self._conn.execute(
                "SELECT hash FROM audit_log ORDER BY seq DESC LIMIT 1"
            ).fetchone()
            prev_hash = row[0] if row else GENESIS
            rec_id = str(uuid.uuid4())
            ts = int(time.time())
            input_hash = hashlib.sha256(input_bytes).hexdigest()
            output_hash = hashlib.sha256(output_bytes).hexdigest()
            payload = json.dumps(
                [rec_id, ts, agent_id, tool_name, input_hash, output_hash,
                 int(success), reason, prev_hash],
                separators=(",", ":"),
            )
            h = _sha256(payload)
            self._conn.execute(
                "INSERT INTO audit_log (id, timestamp, agent_id, tool_name,"
                " input_hash, output_hash, success, reason, prev_hash, hash)"
                " VALUES (?,?,?,?,?,?,?,?,?,?)",
                (rec_id, ts, agent_id, tool_name, input_hash, output_hash,
                 int(success), reason, prev_hash, h),
            )
            self._conn.commit()
        # every tool execution flows through this ledger (executor records
        # success and failure alike), so this is THE invocation counter
        obs.TOOL_INVOCATIONS.labels(
            tool=tool_name, outcome="success" if success else "failure"
        ).inc()
        return rec_id

    def verify_chain(self) -> Tuple[bool, Optional[int]]:
        """Recompute the whole chain; returns (ok, first_bad_seq)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT seq, id, timestamp, agent_id, tool_name, input_hash,"
                " output_hash, success, reason, prev_hash, hash FROM audit_log"
                " ORDER BY seq"
            ).fetchall()
        expected_prev = GENESIS
        for (seq, rec_id, ts, agent, tool, ih, oh, success, reason,
             prev_hash, h) in rows:
            if prev_hash != expected_prev:
                return False, seq
            payload = json.dumps(
                [rec_id, ts, agent, tool, ih, oh, success, reason, prev_hash],
                separators=(",", ":"),
            )
            if _sha256(payload) != h:
                return False, seq
            expected_prev = h
        return True, None

    def query(
        self,
        agent_id: str = "",
        tool_name: str = "",
        limit: int = 100,
    ) -> List[Dict[str, Any]]:
        sql = (
            "SELECT seq, id, timestamp, agent_id, tool_name, success, reason"
            " FROM audit_log WHERE 1=1"
        )
        args: list = []
        if agent_id:
            sql += " AND agent_id=?"
            args.append(agent_id)
        if tool_name:
            sql += " AND tool_name=?"
            args.append(tool_name)
        sql += " ORDER BY seq DESC LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(sql, tuple(args)).fetchall()
        keys = ["seq", "id", "timestamp", "agent_id", "tool_name", "success", "reason"]
        return [dict(zip(keys, r)) for r in rows]

    def count(self) -> int:
        with self._lock:
            return self._conn.execute("SELECT COUNT(*) FROM audit_log").fetchone()[0]

    def tamper_for_test(self, seq: int) -> None:
        """Corrupt a record (tests of verify_chain only)."""
        with self._lock:
            self._conn.execute(
                "UPDATE audit_log SET reason='tampered' WHERE seq=?", (seq,)
            )
            self._conn.commit()
