"""GGUF loader: container round-trip + dequantization correctness.

The K-quant dequantizers are validated against independent scalar
implementations written directly from the ggml block-layout spec, evaluated
on random block bytes — any disagreement between the vectorized numpy path
and the scalar path fails the test.
"""

import numpy as np
import pytest

from aios_tpu.engine import gguf

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


def _rand_blocks(n_blocks, n_bytes, seed):
    rng = np.random.default_rng(seed)
    blocks = rng.integers(0, 256, size=(n_blocks, n_bytes), dtype=np.uint8)
    # keep the f16 scale fields finite and sane: overwrite with small floats
    return blocks


def _set_f16(blocks, col, values):
    blocks[:, col : col + 2] = (
        np.asarray(values, dtype=np.float16).view(np.uint8).reshape(-1, 2)
    )


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------


def test_container_roundtrip(tmp_path):
    path = tmp_path / "m.gguf"
    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 64)).astype(np.float32)
    h = rng.standard_normal((4, 32)).astype(np.float16)
    meta = {
        "general.architecture": "llama",
        "llama.block_count": 22,
        "llama.rope.freq_base": 10000.0,
        "tokenizer.ggml.tokens": ["<s>", "</s>", "hello"],
        "tokenizer.ggml.scores": [0.0, -1.0, -2.0],
        "some.flag": True,
    }
    gguf.write_gguf(
        path,
        meta,
        {
            "blk.0.attn_q.weight": (w.shape, gguf.F32, w.tobytes()),
            "blk.0.attn_k.weight": (h.shape, gguf.F16, h.tobytes()),
        },
    )
    f = gguf.GGUFFile(path)
    assert f.architecture == "llama"
    assert f.metadata["llama.block_count"] == 22
    assert f.metadata["tokenizer.ggml.tokens"] == ["<s>", "</s>", "hello"]
    assert f.metadata["some.flag"] is True
    assert f.metadata["llama.rope.freq_base"] == pytest.approx(10000.0)

    got_w = f.load_tensor("blk.0.attn_q.weight")
    np.testing.assert_array_equal(got_w, w)
    got_h = f.load_tensor("blk.0.attn_k.weight")
    np.testing.assert_allclose(got_h, h.astype(np.float32))


def test_bf16_dequant():
    x = np.array([1.5, -2.25, 0.0, 1e10], dtype=np.float32)
    bf = (x.view(np.uint32) >> 16).astype(np.uint16)
    out = gguf.dequantize(bf.view(np.uint8), gguf.BF16, 4)
    # bf16 truncation: compare against numpy's own truncation
    expected = (bf.astype(np.uint32) << 16).view(np.float32)
    np.testing.assert_array_equal(out, expected)


# ---------------------------------------------------------------------------
# Simple quant round-trips
# ---------------------------------------------------------------------------


def test_q8_0_roundtrip():
    rng = np.random.default_rng(1)
    x = rng.standard_normal(32 * 64).astype(np.float32)
    raw = gguf.quantize_q8_0(x)
    y = gguf.dequantize(raw, gguf.Q8_0, x.size)
    # 8-bit block quant: relative block error bounded by ~1/127 of block max
    err = np.abs(x - y).max()
    assert err < np.abs(x).max() / 127 * 1.1


def test_q4_0_roundtrip():
    rng = np.random.default_rng(2)
    x = rng.standard_normal(32 * 64).astype(np.float32)
    raw = gguf.quantize_q4_0(x)
    y = gguf.dequantize(raw, gguf.Q4_0, x.size)
    blocks = x.reshape(-1, 32)
    per_block_scale = np.abs(blocks).max(axis=1, keepdims=True) / 8.0
    assert np.all(np.abs(blocks - y.reshape(-1, 32)) <= per_block_scale * 1.01)


# ---------------------------------------------------------------------------
# K-quants vs independent scalar reference
# ---------------------------------------------------------------------------


def _scale_min_k4(j, s):
    if j < 4:
        return s[j] & 63, s[j + 4] & 63
    sc = (s[j + 4] & 0x0F) | ((s[j - 4] >> 6) << 4)
    mn = (s[j + 4] >> 4) | ((s[j] >> 6) << 4)
    return sc, mn


def _scalar_q4_k(block):
    d = np.frombuffer(block[0:2].tobytes(), dtype=np.float16)[0].astype(np.float32)
    dmin = np.frombuffer(block[2:4].tobytes(), dtype=np.float16)[0].astype(np.float32)
    s = block[4:16]
    qs = block[16:144]
    out = np.zeros(256, dtype=np.float32)
    y = 0
    is_ = 0
    q = 0
    for _ in range(4):  # chunks of 64
        sc1, m1 = _scale_min_k4(is_, s)
        sc2, m2 = _scale_min_k4(is_ + 1, s)
        for l in range(32):
            out[y + l] = d * sc1 * (qs[q + l] & 0x0F) - dmin * m1
        for l in range(32):
            out[y + 32 + l] = d * sc2 * (qs[q + l] >> 4) - dmin * m2
        y += 64
        q += 32
        is_ += 2
    return out


def _scalar_q5_k(block):
    d = np.frombuffer(block[0:2].tobytes(), dtype=np.float16)[0].astype(np.float32)
    dmin = np.frombuffer(block[2:4].tobytes(), dtype=np.float16)[0].astype(np.float32)
    s = block[4:16]
    qh = block[16:48]
    ql = block[48:176]
    out = np.zeros(256, dtype=np.float32)
    y = 0
    is_ = 0
    q = 0
    u1, u2 = 1, 2
    for _ in range(4):
        sc1, m1 = _scale_min_k4(is_, s)
        sc2, m2 = _scale_min_k4(is_ + 1, s)
        for l in range(32):
            hi = 16 if (qh[l] & u1) else 0
            out[y + l] = d * sc1 * ((ql[q + l] & 0x0F) + hi) - dmin * m1
        for l in range(32):
            hi = 16 if (qh[l] & u2) else 0
            out[y + 32 + l] = d * sc2 * ((ql[q + l] >> 4) + hi) - dmin * m2
        y += 64
        q += 32
        is_ += 2
        u1 <<= 2
        u2 <<= 2
    return out


def _scalar_q6_k(block):
    ql = block[0:128]
    qh = block[128:192]
    sc = block[192:208].view(np.int8)
    d = np.frombuffer(block[208:210].tobytes(), dtype=np.float16)[0].astype(np.float32)
    out = np.zeros(256, dtype=np.float32)
    for n in (0, 128):
        lo = n // 2
        ho = n // 4
        so = n // 16
        for l in range(32):
            is_ = l // 16
            q1 = ((int(ql[lo + l]) & 0x0F) | (((int(qh[ho + l]) >> 0) & 3) << 4)) - 32
            q2 = ((int(ql[lo + l + 32]) & 0x0F) | (((int(qh[ho + l]) >> 2) & 3) << 4)) - 32
            q3 = ((int(ql[lo + l]) >> 4) | (((int(qh[ho + l]) >> 4) & 3) << 4)) - 32
            q4 = ((int(ql[lo + l + 32]) >> 4) | (((int(qh[ho + l]) >> 6) & 3) << 4)) - 32
            out[n + l] = d * sc[so + is_] * q1
            out[n + l + 32] = d * sc[so + is_ + 2] * q2
            out[n + l + 64] = d * sc[so + is_ + 4] * q3
            out[n + l + 96] = d * sc[so + is_ + 6] * q4
    return out


@pytest.mark.parametrize(
    "ggml_type,scalar_fn,d_cols",
    [
        (gguf.Q4_K, _scalar_q4_k, (0, 2)),
        (gguf.Q5_K, _scalar_q5_k, (0, 2)),
        (gguf.Q6_K, _scalar_q6_k, (208,)),
    ],
)
def test_k_quants_match_scalar_reference(ggml_type, scalar_fn, d_cols):
    elems, nbytes = gguf.BLOCK_LAYOUT[ggml_type]
    n_blocks = 16
    blocks = _rand_blocks(n_blocks, nbytes, seed=ggml_type)
    rng = np.random.default_rng(99)
    for col in d_cols:
        _set_f16(blocks, col, rng.uniform(0.001, 0.1, size=n_blocks))
    vectorized = gguf.dequantize(blocks.reshape(-1), ggml_type, n_blocks * elems)
    scalar = np.concatenate([scalar_fn(blocks[i]) for i in range(n_blocks)])
    np.testing.assert_allclose(vectorized, scalar, rtol=1e-5, atol=1e-6)


def test_q5_0_against_scalar():
    n_blocks = 8
    blocks = _rand_blocks(n_blocks, 22, seed=7)
    _set_f16(blocks, 0, np.full(n_blocks, 0.05))
    out = gguf.dequantize(blocks.reshape(-1), gguf.Q5_0, n_blocks * 32)
    for i in range(n_blocks):
        b = blocks[i]
        d = np.frombuffer(b[0:2].tobytes(), dtype=np.float16)[0].astype(np.float32)
        qh = int.from_bytes(b[2:6].tobytes(), "little")
        qs = b[6:22]
        for l in range(32):
            nib = (int(qs[l]) & 0x0F) if l < 16 else (int(qs[l - 16]) >> 4)
            q = (nib | (((qh >> l) & 1) << 4)) - 16
            assert out[i * 32 + l] == pytest.approx(d * q, rel=1e-5)


def test_tensor_info_byte_sizes():
    info = gguf.TensorInfo("t", (64, 256), gguf.Q4_K, 0)
    assert info.n_elements == 64 * 256
    assert info.n_bytes == 64 * 256 // 256 * 144
    info2 = gguf.TensorInfo("t2", (4, 32), gguf.Q8_0, 0)
    assert info2.n_bytes == 4 * 34
