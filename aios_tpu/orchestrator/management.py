"""Management console: REST + WebSocket + embedded dashboard on :9090.

Reference parity (agent-core/src/management.rs:43-54 routes, 757+ dashboard):
  GET  /api/status            system summary
  GET  /api/goals             goal list        POST /api/goals  submit
  GET  /api/goals/{id}/tasks  task list
  GET  /api/goals/{id}/messages  conversation thread
  POST /api/chat              chat-style goal submission
  GET  /api/agents            live agents
  GET  /api/health            liveness
  WS   /ws                    event push with subscribe_goal
plus a single-file embedded HTML dashboard at /.

Implemented with aiohttp on a dedicated thread/event loop (the reference
uses axum inside tokio).
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from typing import Optional, Set

from aiohttp import WSMsgType, web

log = logging.getLogger("aios.console")

DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>aiOS-TPU Console</title>
<style>
 :root{--bg:#0d1117;--panel:#161b22;--border:#30363d;--dim:#7d8590;
       --fg:#e6edf3;--accent:#1f6feb;--ok:#238636;--bad:#da3633}
 body{font-family:system-ui,sans-serif;margin:0;background:var(--bg);
      color:var(--fg)}
 header{display:flex;align-items:center;gap:16px;padding:12px 20px;
        background:var(--panel);border-bottom:1px solid var(--border)}
 h1{font-size:16px;margin:0}
 #conn{font-size:11px;padding:2px 10px;border-radius:10px;background:#da363333}
 #conn.live{background:#23863633}
 main{display:grid;grid-template-columns:340px 1fr;gap:16px;padding:16px}
 section{background:var(--panel);border:1px solid var(--border);
         border-radius:8px;padding:12px;margin-bottom:16px}
 h2{font-size:13px;margin:0 0 8px;color:var(--dim);text-transform:uppercase}
 .row{padding:6px;border-bottom:1px solid #21262d;font-size:13px;
      cursor:default}
 .row.sel{background:#1f6feb22}
 .goal-row{cursor:pointer}
 .goal-row:hover{background:#1f6feb11}
 .status{float:right;font-size:11px;padding:1px 8px;border-radius:10px;
         background:#1f6feb33}
 .completed{background:#23863633}.failed{background:#da363333}
 .in_progress{background:#9e6a0333}.awaiting_input{background:#8957e533}
 form{display:flex;gap:8px;margin-top:8px}
 input{flex:1;background:var(--bg);border:1px solid var(--border);
       color:var(--fg);padding:8px;border-radius:6px}
 button{background:var(--ok);color:#fff;border:0;padding:8px 16px;
        border-radius:6px;cursor:pointer}
 #chat{height:200px;overflow-y:auto;font-size:13px}
 #chat p{margin:4px 0}.role{color:var(--dim)}
 #stats,#serving,#healthp{font-size:13px;line-height:1.8}
 .bar{height:6px;border-radius:3px;background:#21262d;margin:2px 0 6px}
 .bar i{display:block;height:100%;border-radius:3px;background:var(--accent)}
 #detail{display:none}
 #detail.open{display:block}
 #thread{max-height:220px;overflow-y:auto;font-size:13px;
         border-top:1px solid var(--border);margin-top:8px;padding-top:8px}
 #thread p{margin:4px 0}
 .task-err{color:#f85149;font-size:12px;display:block}
 .tag{font-size:11px;color:var(--dim);margin-left:6px}
 small{color:var(--dim)}
</style></head><body>
<header><h1>aiOS-TPU — orchestrator console</h1>
 <span id="conn">connecting…</span>
 <small id="uptime"></small></header>
<main>
 <div><!-- left column -->
  <section><h2>Chat / submit goal</h2>
   <div id="chat"></div>
   <form onsubmit="return send(event)">
    <input id="msg" placeholder="Describe a goal..." autocomplete="off">
    <button>Send</button></form>
  </section>
  <section><h2>System</h2><div id="stats">loading…</div></section>
  <section><h2>TPU serving</h2><div id="serving">no models</div></section>
  <section><h2>Service health</h2><div id="healthp">…</div></section>
 </div>
 <div><!-- right column -->
  <section><h2>Goals <small>(click for tasks + conversation)</small></h2>
   <div id="goals"></div></section>
  <section id="detail"><h2 id="dtitle">Goal</h2>
  <button id="cancelbtn" onclick="cancelGoal()">cancel goal</button>
   <div id="dprog" class="bar"><i style="width:0"></i></div>
   <div id="tasks"></div>
   <div id="thread"></div>
  </section>
  <section><h2>Agents</h2><div id="agents"></div></section>
 </div>
</main>
<script>
let selected=null, ws=null;
const $=(id)=>document.getElementById(id);
const esc=(t)=>String(t).replace(/[&<>"]/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));

async function refresh(){
 try{
  const s=await (await fetch('/api/status')).json();
  $('stats').innerHTML=
   `goals: ${s.active_goals} active · tasks pending: ${s.pending_tasks}`+
   `<br>agents: ${s.active_agents} · models: `+
   `${s.loaded_models.map(esc).join(', ')||'none'}`+
   `<br>cpu ${s.cpu_percent.toFixed(0)}%`+
   `<div class="bar"><i style="width:${Math.min(s.cpu_percent,100)}%"></i></div>`+
   `mem ${(s.memory_used_mb/1024).toFixed(1)} / `+
   `${(s.memory_total_mb/1024).toFixed(1)} GB`+
   `<div class="bar"><i style="width:${(100*s.memory_used_mb/s.memory_total_mb).toFixed(0)}%"></i></div>`;
  $('uptime').textContent=`up ${Math.floor(s.uptime_seconds/60)}m`;
 }catch(e){}
 try{
  const gs=await (await fetch('/api/goals')).json();
  $('goals').innerHTML=gs.goals.slice(0,20).map(g=>
   `<div class="row goal-row${g.id===selected?' sel':''}" onclick="openGoal('${g.id}')">`+
   `${esc(g.description.slice(0,80))}`+
   `<span class="tag">${(100*g.progress).toFixed(0)}%</span>`+
   `<span class="status ${g.status}">${g.status}</span></div>`).join('')
   ||'<div class="row">no goals yet</div>';
 }catch(e){}
 try{
  const ag=await (await fetch('/api/agents')).json();
  $('agents').innerHTML=ag.agents.map(a=>
   `<div class="row">${esc(a.agent_id)}<span class="tag">${esc(a.agent_type)}`+
   ` · ${a.tasks_completed} done</span>`+
   `<span class="status ${a.status==='dead'?'failed':''}">${esc(a.status)}</span></div>`)
   .join('')||'<div class="row">none</div>';
 }catch(e){}
 try{
  const sv=await (await fetch('/api/serving')).json();
  const names=Object.keys(sv.models||{});
  $('serving').innerHTML=names.length?names.map(m=>{
   const st=sv.models[m];
   const extra=[];
   if(st.kv_pages_in_use!==undefined)
    extra.push(`pages ${st.kv_pages_in_use}/${st.kv_pages_in_use+st.kv_pages_free}`);
   if(st.prefix_hits!==undefined)
    extra.push(`prefix ${st.prefix_hits}h/${st.prefix_misses}m`);
   if(st.spec_tokens_per_round!==undefined)
    extra.push(`spec ${st.spec_tokens_per_round} tok/rnd`);
   if(st.waiting) extra.push(`<b>${st.waiting} queued</b>`);
   if(st.pool_evictions) extra.push(`${st.pool_evictions} evicted`);
   return `<b>${esc(m)}</b> — slots ${st.active_slots||0}/${st.num_slots||'?'}, `+
    `${st.decode_steps||0} steps<br><small>${extra.join(' · ')}</small>`;
  }).join('<br>'):'no models';
 }catch(e){}
 try{
  const h=await (await fetch('/api/health')).json();
  const svc=h.services||{};
  $('healthp').innerHTML=Object.keys(svc).length?
   Object.entries(svc).map(([n,ok])=>
    `${esc(n)} <span class="status ${ok?'completed':'failed'}">`+
    `${ok?'healthy':'down'}</span><br>`).join(''):
   `orchestrator <span class="status completed">healthy</span>`;
 }catch(e){}
 if(selected) loadDetail(selected);
}

async function openGoal(id){
 selected=id;
 $('detail').classList.add('open');
 if(ws&&ws.readyState===1)
  ws.send(JSON.stringify({action:'subscribe_goal',goal_id:id}));
 await loadDetail(id); refresh();
}

async function cancelGoal(){
 if(!selected)return;
 try{
  const r=await fetch(`/api/goals/${selected}/cancel`,{method:'POST'});
  if(!r.ok){
   const b=await r.json().catch(()=>({}));
   $('dtitle').textContent+=` — cancel failed (${b.error||'already terminal'})`;
  }
 }catch(e){$('dtitle').textContent+=' — cancel failed (console unreachable)';}
 refresh();
}

async function loadDetail(id){
 try{
  const ts=await (await fetch(`/api/goals/${id}/tasks`)).json();
  $('dtitle').textContent=`Goal ${id.slice(0,8)} — ${ts.tasks.length} task(s)`;
  $('tasks').innerHTML=ts.tasks.map(t=>
   `<div class="row">${esc(t.description.slice(0,90))}`+
   `<span class="tag">${esc(t.agent||'unassigned')}</span>`+
   `<span class="status ${t.status}">${t.status}</span>`+
   (t.error?`<span class="task-err">${esc(t.error.slice(0,120))}</span>`:'')+
   `</div>`).join('')||'<div class="row">no tasks yet</div>';
  const ms=await (await fetch(`/api/goals/${id}/messages`)).json();
  $('thread').innerHTML=ms.messages.map(m=>
   `<p><span class="role">${esc(m.role)}:</span> ${esc(m.content)}</p>`)
   .join('')||'<p class="role">no conversation yet</p>';
 }catch(e){}
}

async function send(e){
 e.preventDefault();
 const input=$('msg');
 const text=input.value.trim(); if(!text)return false; input.value='';
 chatAdd('you',text);
 try{
  const r=await (await fetch('/api/chat',{method:'POST',
    headers:{'Content-Type':'application/json'},
    body:JSON.stringify({message:text})})).json();
  chatAdd('aios',r.reply);
  if(r.goal_id) openGoal(r.goal_id);
 }catch(err){chatAdd('aios','(submit failed)');}
 refresh(); return false;
}
function chatAdd(role,text){
 const c=$('chat');
 c.innerHTML+=`<p><span class="role">${esc(role)}:</span> ${esc(text)}</p>`;
 c.scrollTop=c.scrollHeight;
}

function connect(){
 try{
  ws=new WebSocket(`ws://${location.host}/ws`);
  ws.onopen=()=>{$('conn').textContent='live';$('conn').classList.add('live');
   if(selected)ws.send(JSON.stringify({action:'subscribe_goal',goal_id:selected}));};
  ws.onclose=()=>{$('conn').textContent='polling';
   $('conn').classList.remove('live');setTimeout(connect,5000);};
  ws.onmessage=(m)=>{
   try{
    const d=JSON.parse(m.data);
    if(d.goal_id&&d.goal_id===selected)loadDetail(selected);
   }catch(e){}
   refresh();
  };
 }catch(e){}
}
refresh(); setInterval(refresh,3000); connect();
</script></body></html>
"""


class ManagementConsole:
    def __init__(self, orchestrator, host: str = "127.0.0.1", port: int = 9090,
                 serving_stats=None, service_health=None):
        """``orchestrator`` is an OrchestratorService (shared state).

        ``serving_stats`` — optional () -> {model: {counter: float}} feed
        (orchestrator/main.py parses the runtime HealthCheck) behind the
        dashboard's "TPU serving" panel. ``service_health`` — optional
        () -> {service: healthy} snapshot (the HealthChecker's
        consecutive-failure map) behind the health panel."""
        self.orch = orchestrator
        self.serving_stats = serving_stats
        self.service_health = service_health
        self.host = host
        self.port = port
        self._ws_clients: Set[web.WebSocketResponse] = set()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.bound_port: Optional[int] = None

    # -- handlers -----------------------------------------------------------

    async def _index(self, request):
        return web.Response(text=DASHBOARD_HTML, content_type="text/html")

    async def _status(self, request):
        engine = self.orch.engine
        import psutil

        vm = psutil.virtual_memory()
        # loaded_models is a synchronous gRPC ListModels (5 s timeout when
        # the runtime is down) — keep it off the event loop too
        loop = asyncio.get_running_loop()
        try:
            models = await loop.run_in_executor(
                None, lambda: list(self.orch.loaded_models())
            )
        except Exception:  # noqa: BLE001
            models = []
        return web.json_response(
            {
                "active_goals": len(engine.active_goals()),
                "pending_tasks": len(engine.unblocked_pending_tasks(limit=1000)),
                "active_agents": sum(
                    1 for a in self.orch.router.agents() if a.alive
                ),
                "loaded_models": models,
                "cpu_percent": psutil.cpu_percent(interval=None),
                "memory_used_mb": vm.used / 1e6,
                "memory_total_mb": vm.total / 1e6,
                "uptime_seconds": int(time.time() - self.orch.started_at),
            }
        )

    async def _goals(self, request):
        goals = self.orch.engine.list_goals(limit=100)
        return web.json_response(
            {
                "goals": [
                    {
                        "id": g.id,
                        "description": g.description,
                        "status": g.status,
                        "priority": g.priority,
                        "progress": self.orch.engine.progress(g.id),
                        "created_at": g.created_at,
                    }
                    for g in goals
                ]
            }
        )

    async def _submit_goal(self, request):
        body = await request.json()
        goal = self.orch.engine.submit_goal(
            body.get("description", ""),
            priority=int(body.get("priority", 5)),
            source="console",
        )
        await self._broadcast({"event": "goal_submitted", "goal_id": goal.id})
        return web.json_response({"goal_id": goal.id})

    async def _cancel_goal(self, request):
        goal_id = request.match_info["goal_id"]
        if goal_id not in self.orch.engine.goals:
            # a typo'd id is NOT the same as an already-terminal goal
            return web.json_response(
                {"cancelled": False, "error": "unknown goal"}, status=404
            )
        # same semantics as the CancelGoal RPC: engine cancel + in-flight
        # AI inference abort
        ok = self.orch.cancel_goal_by_id(goal_id)
        if ok:
            await self._broadcast(
                {"event": "goal_cancelled", "goal_id": goal_id}
            )
        return web.json_response(
            {"cancelled": ok}, status=200 if ok else 409
        )

    async def _goal_tasks(self, request):
        goal_id = request.match_info["goal_id"]
        tasks = self.orch.engine.tasks_for_goal(goal_id)
        return web.json_response(
            {
                "tasks": [
                    {
                        "id": t.id,
                        "description": t.description,
                        "status": t.status,
                        "agent": t.assigned_agent,
                        "error": t.error,
                    }
                    for t in tasks
                ]
            }
        )

    async def _goal_messages(self, request):
        goal_id = request.match_info["goal_id"]
        msgs = self.orch.engine.messages_for_goal(goal_id)
        return web.json_response(
            {
                "messages": [
                    {"role": m.role, "content": m.content,
                     "timestamp": m.timestamp}
                    for m in msgs
                ]
            }
        )

    async def _chat(self, request):
        body = await request.json()
        text = body.get("message", "").strip()
        if not text:
            return web.json_response({"error": "empty message"}, status=400)
        goal = self.orch.engine.submit_goal(text, source="chat")
        self.orch.engine.add_message(goal.id, "user", text)
        await self._broadcast({"event": "goal_submitted", "goal_id": goal.id})
        return web.json_response(
            {
                "goal_id": goal.id,
                "reply": f"Goal accepted ({goal.id[:8]}). I'll work on it.",
            }
        )

    async def _agents(self, request):
        return web.json_response(
            {
                "agents": [
                    {
                        "agent_id": a.agent_id,
                        "agent_type": a.agent_type,
                        "status": a.status if a.alive else "dead",
                        "tasks_completed": a.tasks_completed,
                    }
                    for a in self.orch.router.agents()
                ]
            }
        )

    async def _health(self, request):
        out = {"healthy": True, "service": "orchestrator"}
        if self.service_health is not None:
            try:
                out["services"] = dict(self.service_health())
            except Exception:  # noqa: BLE001
                pass
        return web.json_response(out)

    async def _serving(self, request):
        """Per-model TPU serving counters (decode steps, KV pages, prefix
        hits, queue depth) — the operator view the reference's llama-server
        backend could never offer. The feed is a synchronous gRPC call
        (runtime HealthCheck, up to 5 s when the runtime is down), so it
        runs in the executor — blocking the event loop would freeze every
        console route exactly when the operator needs it."""
        models = {}
        if self.serving_stats is not None:
            loop = asyncio.get_running_loop()
            try:
                models = await loop.run_in_executor(
                    None, self.serving_stats
                ) or {}
            except Exception:  # noqa: BLE001
                models = {}
        return web.json_response({"models": models})

    async def _ws(self, request):
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        self._ws_clients.add(ws)
        try:
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    try:
                        data = json.loads(msg.data)
                    except ValueError:
                        continue
                    if data.get("action") == "subscribe_goal":
                        goal_id = data.get("goal_id", "")
                        goal = self.orch.engine.goals.get(goal_id)
                        if goal:
                            await ws.send_json(
                                {
                                    "event": "goal_status",
                                    "goal_id": goal_id,
                                    "status": goal.status,
                                    "progress": self.orch.engine.progress(goal_id),
                                }
                            )
        finally:
            self._ws_clients.discard(ws)
        return ws

    async def _broadcast(self, payload: dict) -> None:
        dead = []
        for ws in self._ws_clients:
            try:
                await ws.send_json(payload)
            except Exception:  # noqa: BLE001
                dead.append(ws)
        for ws in dead:
            self._ws_clients.discard(ws)

    def notify(self, payload: dict) -> None:
        """Thread-safe push to all WS clients."""
        if self._loop is not None:
            asyncio.run_coroutine_threadsafe(self._broadcast(payload), self._loop)

    # -- lifecycle ----------------------------------------------------------

    def _build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/status", self._status)
        app.router.add_get("/api/goals", self._goals)
        app.router.add_post("/api/goals", self._submit_goal)
        app.router.add_post("/api/goals/{goal_id}/cancel", self._cancel_goal)
        app.router.add_get("/api/goals/{goal_id}/tasks", self._goal_tasks)
        app.router.add_get("/api/goals/{goal_id}/messages", self._goal_messages)
        app.router.add_post("/api/chat", self._chat)
        app.router.add_get("/api/agents", self._agents)
        app.router.add_get("/api/health", self._health)
        app.router.add_get("/api/serving", self._serving)
        app.router.add_get("/ws", self._ws)
        return app

    def start(self) -> None:
        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def boot():
                self._runner = web.AppRunner(self._build_app())
                await self._runner.setup()
                site = web.TCPSite(self._runner, self.host, self.port)
                await site.start()
                for s in self._runner.sites:
                    sock = s._server.sockets[0]  # noqa: SLF001
                    self.bound_port = sock.getsockname()[1]
                self._started.set()

            self._loop.run_until_complete(boot())
            self._loop.run_forever()

        self._thread = threading.Thread(target=run, name="console", daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        if self._loop is None:
            return

        async def shutdown():
            if self._runner:
                await self._runner.cleanup()

        asyncio.run_coroutine_threadsafe(shutdown(), self._loop).result(timeout=5)
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread:
            self._thread.join(timeout=5)
