"""Programmatic gRPC stub/servicer construction.

The environment has no grpcio-tools, so instead of checked-in generated
`*_pb2_grpc.py` files each service is described once by a `ServiceSpec`
(method name -> request/response classes + streaming flags) and this module
builds, at import time, the same three artifacts grpcio-tools would emit:

  * ``make_stub(spec)``      -> a Stub class taking a ``grpc.Channel``
  * ``make_servicer(spec)``  -> an abstract Servicer base class
  * ``add_to_server(spec, servicer, server)`` -> registers generic handlers

All aiOS services are unary-unary or unary-stream; the builder supports all
four cardinalities anyway for completeness.

Reference parity: replaces the generated tonic (Rust) / grpcio (Python) stubs
of agent-core/proto (SURVEY.md section 1, "IPC protos" row).
"""

from __future__ import annotations

import concurrent.futures
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Tuple

import grpc


def _obs_enabled() -> bool:
    """Observability interceptors are on by default on every server and
    channel this module builds; AIOS_OBS_DISABLED=1 opts out (perf A/B,
    debugging the interceptors themselves)."""
    return os.environ.get("AIOS_OBS_DISABLED", "") not in ("1", "true", "on")


@dataclass(frozen=True)
class Method:
    """One RPC: request/response message classes and streaming flags."""

    request: Any
    response: Any
    server_streaming: bool = False
    client_streaming: bool = False

    @property
    def cardinality(self) -> str:
        lhs = "stream" if self.client_streaming else "unary"
        rhs = "stream" if self.server_streaming else "unary"
        return f"{lhs}_{rhs}"


@dataclass(frozen=True)
class ServiceSpec:
    """A full gRPC service: package-qualified name plus its method table."""

    full_name: str  # e.g. "aios.runtime.AIRuntime"
    methods: Dict[str, Method] = field(default_factory=dict)

    def path(self, method: str) -> str:
        return f"/{self.full_name}/{method}"


def make_stub(spec: ServiceSpec) -> type:
    """Build a Stub class equivalent to grpcio-tools' ``<Service>Stub``."""

    def __init__(self, channel: grpc.Channel) -> None:
        for name, m in spec.methods.items():
            factory = getattr(channel, m.cardinality)
            setattr(
                self,
                name,
                factory(
                    spec.path(name),
                    request_serializer=m.request.SerializeToString,
                    response_deserializer=m.response.FromString,
                ),
            )

    return type(
        spec.full_name.rsplit(".", 1)[-1] + "Stub",
        (object,),
        {"__init__": __init__, "__doc__": f"Client stub for {spec.full_name}."},
    )


def make_servicer(spec: ServiceSpec) -> type:
    """Build an abstract Servicer base (methods default to UNIMPLEMENTED)."""

    def _unimplemented(name: str) -> Callable:
        def method(self, request, context):  # noqa: ANN001
            context.set_code(grpc.StatusCode.UNIMPLEMENTED)
            context.set_details(f"{name} is not implemented")
            raise NotImplementedError(name)

        method.__name__ = name
        return method

    body = {name: _unimplemented(name) for name in spec.methods}
    body["__doc__"] = f"Servicer base for {spec.full_name}."
    return type(spec.full_name.rsplit(".", 1)[-1] + "Servicer", (object,), body)


def add_to_server(spec: ServiceSpec, servicer: Any, server: grpc.Server) -> None:
    """Register ``servicer``'s methods on ``server`` under ``spec.full_name``."""
    handlers = {}
    for name, m in spec.methods.items():
        handler_factory = getattr(grpc, f"{m.cardinality}_rpc_method_handler")
        handlers[name] = handler_factory(
            getattr(servicer, name),
            request_deserializer=m.request.FromString,
            response_serializer=m.response.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(spec.full_name, handlers),)
    )


class _FaultUnavailableInterceptor(grpc.ServerInterceptor):
    """Chaos hook (docs/FAULTS.md): when the ``rpc.unavailable`` fault
    point fires, the RPC aborts UNAVAILABLE with ``retry-after-ms``
    trailing metadata instead of reaching the servicer — the exact shape
    a client sees when a whole serving process is mid-restart, for
    driving client retry/backoff paths on demand. A no-op (one global
    load in ``faults.point``) unless a fault schedule is armed."""

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None:
            return None
        from . import faults

        act = faults.point("rpc.unavailable")
        if act is None:
            return handler

        def abort(request, context):
            context.set_trailing_metadata(
                (("retry-after-ms", str(act.retry_after_ms)),)
            )
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"injected rpc.unavailable (hit {act.hit})",
            )

        if handler.unary_unary is not None:
            return grpc.unary_unary_rpc_method_handler(
                abort,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        if handler.unary_stream is not None:
            return grpc.unary_stream_rpc_method_handler(
                abort,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        return handler  # stream-request cardinalities: not injected


class _NetFaultClientInterceptor(
    grpc.UnaryUnaryClientInterceptor,
    grpc.UnaryStreamClientInterceptor,
    grpc.StreamUnaryClientInterceptor,
    grpc.StreamStreamClientInterceptor,
):
    """Chaos hook (docs/FAULTS.md "Per-edge network faults"): every
    channel this module builds carries one of these, so KVX, Handoff,
    and every other cross-host RPC traverse the same seeded per-edge
    fault surface as the fleet HTTP helpers. A fired partition raises
    :class:`aios_tpu.faults.net.NetFaultRefused` (an UNAVAILABLE-coded
    grpc.RpcError) before the wire; a fired ``net.drop_after`` lets a
    unary-stream call start and severs it after ``after_msgs``
    messages. A no-op — one global None check — unless a fault schedule
    is armed."""

    def __init__(self, address: str) -> None:
        self._address = address

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        from . import faults

        if faults.active():
            from .faults import net

            net.check_send(self._address, "rpc")
        return continuation(client_call_details, request)

    def intercept_unary_stream(self, continuation, client_call_details,
                               request):
        from . import faults

        if not faults.active():
            return continuation(client_call_details, request)
        from .faults import net

        net.check_send(self._address, "rpc")
        return net.sever_stream(
            self._address, continuation(client_call_details, request)
        )

    def intercept_stream_unary(self, continuation, client_call_details,
                               request_iterator):
        from . import faults

        if faults.active():
            from .faults import net

            net.check_send(self._address, "rpc")
        return continuation(client_call_details, request_iterator)

    def intercept_stream_stream(self, continuation, client_call_details,
                                request_iterator):
        from . import faults

        if not faults.active():
            return continuation(client_call_details, request_iterator)
        from .faults import net

        net.check_send(self._address, "rpc")
        return net.sever_stream(
            self._address,
            continuation(client_call_details, request_iterator),
        )


def create_server(
    max_workers: int = 16, options: Tuple[Tuple[str, Any], ...] | None = None
) -> grpc.Server:
    """A threaded gRPC server with aiOS-standard channel options and the
    observability interceptors (per-RPC span + rpc_* metrics)."""
    opts = list(
        options
        or (
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
        )
    )
    # the fault interceptor goes INNERMOST (last): an injected
    # UNAVAILABLE must still flow through the obs interceptors' metrics
    # and spans — the operator drilling chaos is watching exactly those
    interceptors: Tuple[Any, ...] = (_FaultUnavailableInterceptor(),)
    if _obs_enabled():
        from .obs.interceptors import server_interceptors

        interceptors = tuple(server_interceptors()) + interceptors
    return grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=max_workers),
        options=opts,
        interceptors=interceptors,
    )


def insecure_channel(address: str) -> grpc.Channel:
    channel = grpc.insecure_channel(
        address,
        options=[
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
        ],
    )
    if _obs_enabled():
        from .obs.interceptors import intercept_client_channel

        channel = intercept_client_channel(channel)
    # the net-fault interceptor goes OUTERMOST: a refused send never
    # happened, so it must not count on the client rpc_* metrics — the
    # caller's recovery path (and the faults journal) carry the evidence
    return grpc.intercept_channel(
        channel, _NetFaultClientInterceptor(address)
    )
