"""Standalone orchestrator client + CLI.

The reference ships a high-level retrying client wrapper outside the service
tree (agent-core/python/aios_agent/orchestrator_client.py:33-100: submit
goals, poll status, list agents, system status, wait_for_goal with retries)
so operators and external programs can drive the orchestrator without the
agent framework. This is that surface for the TPU stack, synchronous like
the rest of the gRPC layer here, plus an argparse CLI:

    python -m aios_tpu.orchestrator.client submit "check disk usage"
    python -m aios_tpu.orchestrator.client status <goal-id>
    python -m aios_tpu.orchestrator.client wait <goal-id> --timeout 120
    python -m aios_tpu.orchestrator.client goals --filter active
    python -m aios_tpu.orchestrator.client agents
    python -m aios_tpu.orchestrator.client system
    python -m aios_tpu.orchestrator.client cancel <goal-id>
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import grpc

from .. import rpc
from ..proto_gen import common_pb2, orchestrator_pb2
from ..services import OrchestratorStub, service_address

TERMINAL_GOAL_STATES = {"completed", "failed", "cancelled"}


@dataclass
class ClientConfig:
    """Connection settings (reference orchestrator_client.py:23-30)."""

    address: str = ""
    timeout_s: float = 30.0
    max_retries: int = 3
    retry_delay_s: float = 1.0

    def __post_init__(self) -> None:
        if not self.address:
            self.address = os.getenv(
                "AIOS_ORCHESTRATOR_ADDR", service_address("orchestrator")
            )


class OrchestratorClient:
    """Retrying synchronous client for the Orchestrator gRPC service.

    Usage::

        with OrchestratorClient() as client:
            goal_id = client.submit_goal("check disk usage")
            status = client.wait_for_goal(goal_id, timeout_s=120)
    """

    def __init__(self, config: Optional[ClientConfig] = None) -> None:
        self.config = config or ClientConfig()
        self._channel = None
        self._stub = None

    def __enter__(self) -> "OrchestratorClient":
        self.connect()
        return self

    def __exit__(self, *_: Any) -> None:
        self.close()

    def connect(self) -> None:
        if self._channel is None:
            self._channel = rpc.insecure_channel(self.config.address)
            self._stub = OrchestratorStub(self._channel)

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._stub = None

    # -- internal -----------------------------------------------------------

    def _call(self, method: str, request):
        """Unary call with bounded retries on transient errors
        (UNAVAILABLE / DEADLINE_EXCEEDED, like the reference's _call)."""
        self.connect()
        attempts = max(1, self.config.max_retries)
        delay = self.config.retry_delay_s
        for attempt in range(attempts):
            try:
                return getattr(self._stub, method)(
                    request, timeout=self.config.timeout_s
                )
            except grpc.RpcError as exc:
                if exc.code() not in (
                    grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                ):
                    raise
                if attempt == attempts - 1:
                    raise  # no point sleeping after the final attempt
                time.sleep(delay)
                delay *= 2

    # -- goals --------------------------------------------------------------

    def submit_goal(
        self,
        description: str,
        priority: int = 5,
        source: str = "client",
        tags: Optional[List[str]] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> str:
        resp = self._call(
            "SubmitGoal",
            orchestrator_pb2.SubmitGoalRequest(
                description=description,
                priority=priority,
                source=source,
                tags=tags or [],
                metadata_json=json.dumps(metadata or {}).encode(),
            ),
        )
        return resp.id

    def get_goal_status(self, goal_id: str) -> Dict[str, Any]:
        resp = self._call("GetGoalStatus", common_pb2.GoalId(id=goal_id))
        return {
            "goal_id": resp.goal.id,
            "description": resp.goal.description,
            "status": resp.goal.status,
            "current_phase": resp.current_phase,
            "progress_percent": resp.progress_percent,
            "tasks": [
                {"id": t.id, "description": t.description, "status": t.status}
                for t in resp.tasks
            ],
        }

    def cancel_goal(self, goal_id: str) -> bool:
        return self._call("CancelGoal", common_pb2.GoalId(id=goal_id)).success

    def list_goals(
        self, status_filter: str = "", limit: int = 20, offset: int = 0
    ) -> List[Dict[str, Any]]:
        resp = self._call(
            "ListGoals",
            orchestrator_pb2.ListGoalsRequest(
                status_filter=status_filter, limit=limit, offset=offset
            ),
        )
        return [
            {"id": g.id, "description": g.description, "status": g.status}
            for g in resp.goals
        ]

    def wait_for_goal(
        self, goal_id: str, timeout_s: float = 300.0, poll_s: float = 1.0
    ) -> Dict[str, Any]:
        """Poll until the goal reaches a terminal state (reference
        wait_for_goal, orchestrator_client.py:290+)."""
        deadline = time.time() + timeout_s
        while True:
            status = self.get_goal_status(goal_id)
            if status["status"] in TERMINAL_GOAL_STATES:
                return status
            if time.time() >= deadline:
                raise TimeoutError(
                    f"goal {goal_id} still {status['status']} "
                    f"after {timeout_s:.0f}s"
                )
            time.sleep(poll_s)

    # -- agents / system ----------------------------------------------------

    def list_agents(self) -> List[Dict[str, Any]]:
        resp = self._call("ListAgents", common_pb2.Empty())
        return [
            {
                "id": a.agent_id,
                "type": a.agent_type,
                "status": a.status,
                "capabilities": list(a.capabilities),
            }
            for a in resp.agents
        ]

    def get_system_status(self) -> Dict[str, Any]:
        resp = self._call("GetSystemStatus", common_pb2.Empty())
        return {
            "active_goals": resp.active_goals,
            "pending_tasks": resp.pending_tasks,
            "active_agents": resp.active_agents,
            "loaded_models": list(resp.loaded_models),
            "autonomy_level": resp.autonomy_level,
            "uptime_seconds": resp.uptime_seconds,
        }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="aios-orchestrator-client",
        description="Drive the aiOS-TPU orchestrator from the command line.",
    )
    ap.add_argument("--address", default="", help="host:port (default: env "
                    "AIOS_ORCHESTRATOR_ADDR or the service registry)")
    ap.add_argument("--timeout", type=float, default=30.0)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("submit", help="submit a goal")
    p.add_argument("description")
    p.add_argument("--priority", type=int, default=5)
    p.add_argument("--wait", action="store_true", help="block until terminal")

    p = sub.add_parser("status", help="goal status")
    p.add_argument("goal_id")

    p = sub.add_parser("wait", help="wait for a goal to finish")
    p.add_argument("goal_id")
    p.add_argument("--timeout", dest="wait_timeout", type=float, default=300.0)

    p = sub.add_parser("cancel", help="cancel a goal")
    p.add_argument("goal_id")

    p = sub.add_parser("goals", help="list goals")
    p.add_argument("--filter", default="", dest="status_filter")
    p.add_argument("--limit", type=int, default=20)

    sub.add_parser("agents", help="list registered agents")
    sub.add_parser("system", help="system status")

    args = ap.parse_args(argv)
    cfg = ClientConfig(address=args.address, timeout_s=args.timeout)

    with OrchestratorClient(cfg) as client:
        if args.cmd == "submit":
            goal_id = client.submit_goal(args.description, priority=args.priority)
            if args.wait:
                out: Any = client.wait_for_goal(goal_id)
            else:
                out = {"goal_id": goal_id}
        elif args.cmd == "status":
            out = client.get_goal_status(args.goal_id)
        elif args.cmd == "wait":
            out = client.wait_for_goal(args.goal_id, timeout_s=args.wait_timeout)
        elif args.cmd == "cancel":
            out = {"cancelled": client.cancel_goal(args.goal_id)}
        elif args.cmd == "goals":
            out = client.list_goals(args.status_filter, limit=args.limit)
        elif args.cmd == "agents":
            out = client.list_agents()
        else:
            out = client.get_system_status()

    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
