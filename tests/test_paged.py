"""Paged KV cache: allocator, paged attention parity, engine equivalence.

The paged cache must be OBSERVABLY identical to the dense slot cache —
same tokens, same masks — while reserving HBM per page in use instead of
per num_slots x max_context (SURVEY.md section 7.2, hard part no. 1's
fixed-shape half). Kernel parity runs under the Pallas interpreter on CPU,
like the other kernels (tests/test_ops.py pattern).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.paged import PageAllocator, PoolExhausted
from aios_tpu.ops import (
    decode_attention_reference,
    paged_decode_attention,
    paged_decode_attention_reference,
)

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def params():
    return model.init_params(TINY_TEST, jax.random.PRNGKey(1), dtype=jnp.float32)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------


def test_allocator_ensure_and_free():
    a = PageAllocator(num_pages=9, page_size=16, num_slots=2, max_blocks=8)
    assert a.free_pages == 8  # page 0 is sacrificial
    assert a.ensure(0, 17) is True  # 2 blocks
    assert a.ensure(0, 17) is False  # idempotent
    assert a.pages_in_use() == 2
    assert a.slot_rows_backed(0) == 32
    assert (a.tables[0, :2] > 0).all()
    assert (a.tables[0, 2:] == 0).all()
    a.free_slot(0)
    assert a.pages_in_use() == 0
    assert (a.tables[0] == 0).all()


def test_allocator_exhaustion_keeps_state():
    a = PageAllocator(num_pages=4, page_size=16, num_slots=2, max_blocks=8)
    a.ensure(0, 32)  # 2 of 3 free pages
    with pytest.raises(PoolExhausted):
        a.ensure(1, 33)  # needs 3, only 1 free
    assert a.free_pages == 1
    assert a.slot_rows_backed(1) == 0
    a.free_slot(0)
    assert a.ensure(1, 33) is True  # now it fits


def test_allocator_pages_are_exclusive():
    a = PageAllocator(num_pages=9, page_size=16, num_slots=4, max_blocks=2)
    for s in range(4):
        a.ensure(s, 32)
    pages = a.tables[:, :2].ravel().tolist()
    assert len(set(pages)) == 8  # no page handed to two slots
    assert 0 not in pages


# ---------------------------------------------------------------------------
# paged attention parity
# ---------------------------------------------------------------------------


def _scattered_equivalent(rng, B, C, KH, D, P, dtype=jnp.float32):
    """Dense [B, C, KH, D] caches and a paged pool holding the same rows
    behind a shuffled page table."""
    MB = C // P
    dense = jnp.asarray(rng.normal(size=(B, C, KH, D)), dtype)
    # physical pages shuffled: logical block b of slot s -> some unique page
    perm = rng.permutation(B * MB)
    tables = jnp.asarray(1 + perm.reshape(B, MB), jnp.int32)
    pool = jnp.zeros((1 + B * MB, P, KH, D), dtype)
    for s in range(B):
        for b in range(MB):
            pool = pool.at[int(tables[s, b])].set(
                dense[s, b * P : (b + 1) * P]
            )
    return dense, pool, tables


@pytest.mark.parametrize("window", [None, 24])
def test_paged_reference_matches_dense_reference(window):
    rng = np.random.default_rng(0)
    B, C, KH, D, H, P = 3, 64, 2, 8, 4, 16
    kd, kp, tables = _scattered_equivalent(rng, B, C, KH, D, P)
    vd, vp, _ = _scattered_equivalent(rng, B, C, KH, D, P)
    # v pool must use the same tables as k: rebuild it under k's tables
    vp = jnp.zeros_like(kp)
    for s in range(B):
        for b in range(C // P):
            vp = vp.at[int(tables[s, b])].set(vd[s, b * P : (b + 1) * P])
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    lengths = jnp.asarray([5, 31, 63], jnp.int32)
    ref = decode_attention_reference(q, kd, vd, lengths, window=window)
    got = paged_decode_attention_reference(
        q, kp, vp, tables, lengths, window=window
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("window", [None, 24])
def test_paged_kernel_matches_reference(window):
    rng = np.random.default_rng(1)
    B, C, KH, D, H, P = 2, 64, 2, 8, 4, 16
    kd, kp, tables = _scattered_equivalent(rng, B, C, KH, D, P)
    vd, vp0, _ = _scattered_equivalent(rng, B, C, KH, D, P)
    vp = jnp.zeros_like(kp)
    for s in range(B):
        for b in range(C // P):
            vp = vp.at[int(tables[s, b])].set(vd[s, b * P : (b + 1) * P])
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    lengths = jnp.asarray([9, 50], jnp.int32)
    ref = paged_decode_attention_reference(
        q, kp, vp, tables, lengths, window=window
    )
    got = paged_decode_attention(
        q, kp, vp, tables, lengths, window=window, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5,
                               atol=1e-5)


def test_paged_kernel_ignores_unmapped_pages():
    """Rows beyond a slot's length live on pages the table never maps —
    poisoning every unmapped pool page must not change the output."""
    rng = np.random.default_rng(2)
    B, C, KH, D, H, P = 1, 64, 2, 8, 4, 16
    kd, kp, tables = _scattered_equivalent(rng, B, C, KH, D, P)
    vd, _, _ = _scattered_equivalent(rng, B, C, KH, D, P)
    vp = jnp.zeros_like(kp)
    for b in range(C // P):
        vp = vp.at[int(tables[0, b])].set(vd[0, b * P : (b + 1) * P])
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    lengths = jnp.asarray([20], jnp.int32)  # blocks 0-1 valid; 2-3 unread
    base = paged_decode_attention(q, kp, vp, tables, lengths, interpret=True)
    # poison the pages holding blocks 2..3 AND the sacrificial page
    for pg in (0, int(tables[0, 2]), int(tables[0, 3])):
        kp = kp.at[pg].set(1e9)
        vp = vp.at[pg].set(1e9)
    got = paged_decode_attention(q, kp, vp, tables, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base), rtol=1e-6,
                               atol=1e-6)


# ---------------------------------------------------------------------------
# engine equivalence
# ---------------------------------------------------------------------------


def make_dense(params, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_context", 256)
    kw.setdefault("cache_dtype", jnp.float32)
    return TPUEngine(TINY_TEST, params, **kw)


def make_paged(params, pool_rows=4 * 256, page_size=32, **kw):
    return make_dense(
        params, paged_pool_rows=pool_rows, page_size=page_size, **kw
    )


def test_paged_generate_matches_dense(params):
    prompt = [1, 2, 3, 4, 5]
    dense = make_dense(params)
    ref = dense.generate(prompt, max_new_tokens=48, temperature=0.0)
    dense.close()
    pg = make_paged(params)
    got = pg.generate(prompt, max_new_tokens=48, temperature=0.0)
    pg.close()
    assert got == ref


def test_paged_batched_slots_match_dense(params):
    prompts = {0: [1, 2, 3], 1: list(range(7, 47)), 3: [9, 8, 7, 6]}
    outs = {}
    for paged in (False, True):
        eng = make_paged(params) if paged else make_dense(params)
        for s, p in prompts.items():
            eng.prefill(s, p, temperature=0.0)
        toks = eng.step(12)  # [12, S]
        outs[paged] = {s: toks[:, s].tolist() for s in prompts}
        eng.close()
    assert outs[True] == outs[False]


def test_paged_oversubscription_and_reuse(params):
    """Logical capacity (4 slots x 256) is 4x the physical pool; short
    requests run fine and released pages recycle."""
    eng = make_paged(params, pool_rows=256, page_size=32)
    for round_ in range(3):
        for s in range(4):
            eng.prefill(s, [1 + s, 2, 3], temperature=0.0)
        eng.step(4)
        for s in range(4):
            eng.release(s)
        assert eng.allocator.pages_in_use() == 0
    eng.close()


def test_paged_chunked_prefill_matches_monolithic(params):
    """Chunk-admitting a prompt through the page tables must land exactly
    where a monolithic paged prefill does — same first token, same
    follow-on decode."""
    prompt = [int(t) for t in np.random.default_rng(5).integers(1, 500, 150)]
    eng = make_paged(params)
    first_mono = eng.prefill(0, prompt, temperature=0.0)
    mono = [first_mono] + eng.step(8)[:, 0].tolist()
    eng.close()

    eng = make_paged(params)
    pc = eng.start_chunked_prefill(0, prompt, temperature=0.0, chunk=64)
    first = None
    while first is None:
        first = pc.step()
    got = [first] + eng.step(8)[:, 0].tolist()
    eng.close()
    assert got == mono


def test_paged_chunked_prefill_interleaved_decode(params):
    """A paged chunk admission with decode dispatches interleaved must
    match the dense engine's chunked admission output for both slots."""
    long_prompt = [int(t) for t in np.random.default_rng(6).integers(1, 500, 150)]
    prompts = [[1, 2, 3], long_prompt]
    outs = {}
    for paged in (False, True):
        eng = make_paged(params) if paged else make_dense(params)
        b = ContinuousBatcher(eng, prefill_chunk=64)
        hs = [
            b.submit(Request(prompt_ids=p, max_tokens=24, temperature=0.0))
            for p in prompts
        ]
        outs[paged] = [h.tokens() for h in hs]
        b.shutdown()
        assert b.last_error is None
        eng.close()
    assert outs[True] == outs[False]


def test_paged_chunked_admission_exhaustion_survives(params):
    """Mid-admission pool exhaustion must never kill the scheduler: either
    a victim is evicted or the admission itself fails cleanly."""
    eng = make_paged(params, pool_rows=128, page_size=32, num_slots=2,
                     prefix_cache=False)  # isolate the eviction policy
    b = ContinuousBatcher(eng, prefill_chunk=64)
    small = b.submit(Request(prompt_ids=[1, 2, 3], max_tokens=60,
                             temperature=0.0))
    # feasible alone (4 pages) but not alongside the decoding request
    big = b.submit(Request(prompt_ids=[2] * 120, max_tokens=8,
                           temperature=0.0))
    small_out = small.tokens()
    big_out = big.tokens()
    b.shutdown()
    assert b.last_error is None
    assert len(small_out) > 0
    # whichever resolution happened (admission failed, or admitted and
    # later evicted when decode needed one page more than the pool), every
    # stream terminated and all pages recycled
    assert eng.allocator.pages_in_use() == 0
    assert len(big_out) <= 8
    eng.close()


def test_paged_pool_exhaustion_raises(params):
    eng = make_paged(params, pool_rows=64, page_size=32)  # 2 usable pages
    eng.prefill(0, [1] * 30, temperature=0.0)  # 1 page
    eng.prefill(1, [2] * 30, temperature=0.0)  # 1 page
    with pytest.raises(PoolExhausted):
        eng.step(8)  # slot 0 needs rows 30..37 -> a third page
    eng.close()


def test_batcher_evicts_longest_on_exhaustion(params):
    eng = make_paged(params, pool_rows=96, page_size=32, num_slots=3)
    b = ContinuousBatcher(eng)
    hs = [
        b.submit(Request(prompt_ids=[s + 1, 2, 3], max_tokens=80,
                         temperature=0.0))
        for s in range(3)
    ]
    outs = [h.tokens() for h in hs]
    b.shutdown()
    assert b.last_error is None
    assert b.pool_evictions >= 1  # someone was retired early
    assert all(len(o) > 0 for o in outs)
    assert any(len(o) == 80 for o in outs)  # and someone ran to completion
    assert eng.allocator.pages_in_use() == 0
    eng.close()


# ---------------------------------------------------------------------------
# sliding-window page trimming
# ---------------------------------------------------------------------------


def test_windowed_paged_trims_dead_pages(params):
    """On sliding-window models, pages wholly below the window free back
    to the pool mid-generation — physical usage stays bounded by the
    window while the logical length keeps growing; output matches dense."""
    cfg = TINY_TEST.scaled(sliding_window=16)
    wparams = model.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    dense = TPUEngine(cfg, wparams, num_slots=2, max_context=128,
                      cache_dtype=jnp.float32)
    dense.prefill(0, [1, 2, 3], temperature=0.0)
    ref = [int(t) for t in dense.step(96)[:, 0]]
    dense.close()

    eng = TPUEngine(cfg, wparams, num_slots=2, max_context=128,
                    cache_dtype=jnp.float32, paged_pool_rows=256, page_size=8)
    eng.prefill(0, [1, 2, 3], temperature=0.0)
    got = []
    peak = 0
    for _ in range(12):
        got.extend(int(t) for t in eng.step(8)[:, 0])
        peak = max(peak, eng.allocator.pages_in_use())
    assert got == ref
    # window 16 rows = 2 pages + in-flight block + growth headroom; far
    # below the ~13 pages a 99-row untrimmed slot would hold
    assert peak <= 6, peak
    eng.close()
    assert len(got) == 96


def test_windowed_chunked_admission_fits_small_pool(params):
    """A windowed prompt LARGER than the physical pool chunk-admits fine:
    blocks the remaining chunks can't attend to free as admission
    advances, so residency is bounded by the window, not the prompt."""
    cfg = TINY_TEST.scaled(sliding_window=16)
    wparams = model.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    prompt = [int(t) for t in np.random.default_rng(15).integers(1, 500, 150)]
    dense = TPUEngine(cfg, wparams, num_slots=2, max_context=256,
                      cache_dtype=jnp.float32)
    pc = dense.start_chunked_prefill(0, prompt, temperature=0.0, chunk=16)
    first = None
    while first is None:
        first = pc.step()
    ref = [first] + [int(t) for t in dense.step(8)[:, 0]]
    dense.close()

    eng = TPUEngine(cfg, wparams, num_slots=2, max_context=256,
                    cache_dtype=jnp.float32, paged_pool_rows=80, page_size=8)
    pc = eng.start_chunked_prefill(0, prompt, temperature=0.0, chunk=16)
    first = None
    while first is None:
        first = pc.step()  # 150 rows through a 80-row pool
    got = [first] + [int(t) for t in eng.step(8)[:, 0]]
    assert eng.allocator.pages_in_use() <= 10
    eng.release(0)
    assert got == ref

    # the batcher's feasibility fast-fail must account for the trimming
    # too: the same pool-exceeding prompt admits through the scheduler
    b = ContinuousBatcher(eng, prefill_chunk=16)
    out = b.generate(prompt, max_tokens=6, temperature=0.0)
    b.shutdown()
    assert b.last_error is None
    assert out == ref[:6]
    eng.close()


# ---------------------------------------------------------------------------
# int8 pool
# ---------------------------------------------------------------------------


def test_paged_int8_pool_matches_dense_int8(params):
    """int8 paged pool must decode exactly like the dense int8 KV cache —
    same quantizer on write, same dequantized values on read."""
    prompt = [3, 17, 91, 4, 55, 8]
    dense = make_dense(params, cache_dtype=jnp.int8)
    ref = dense.generate(prompt, max_new_tokens=24, temperature=0.0)
    dense.close()
    eng = make_paged(params, cache_dtype=jnp.int8)
    got = eng.generate(prompt, max_new_tokens=24, temperature=0.0)
    eng.close()
    assert got == ref


def test_paged_int8_chunked_and_prefix(params):
    """Chunk admission and prefix reuse both run over the int8 pool."""
    prompt = [int(t) for t in np.random.default_rng(14).integers(1, 500, 150)]
    dense = make_dense(params, cache_dtype=jnp.int8)
    ref = dense.generate(prompt, max_new_tokens=16, temperature=0.0)
    dense.close()
    eng = make_paged(params, cache_dtype=jnp.int8)
    pc = eng.start_chunked_prefill(0, prompt, temperature=0.0, chunk=64)
    first = None
    while first is None:
        first = pc.step()
    got = [first] + [int(t) for t in eng.step(15)[:, 0]]
    eng.release(0)
    hit = eng.generate(prompt, max_new_tokens=16, temperature=0.0)
    assert eng.prefix_rows_reused > 0
    eng.close()
    assert got == ref
    assert hit == ref


def test_paged_int8_speculative(params):
    prompt = [1, 2, 3]
    dense = make_dense(params, cache_dtype=jnp.int8)
    ref = dense.generate(prompt, max_new_tokens=48, temperature=0.0)
    dense.close()
    eng = make_paged(params, cache_dtype=jnp.int8)
    got = eng.generate(
        prompt, max_new_tokens=48, temperature=0.0, speculative=True
    )
    eng.close()
    assert got == ref


# ---------------------------------------------------------------------------
# speculative decoding over the paged cache
# ---------------------------------------------------------------------------


def test_paged_spec_generate_matches_dense_and_plain(params):
    prompt = [1, 2, 3]
    dense = make_dense(params)
    ref = dense.generate(prompt, max_new_tokens=64, temperature=0.0)
    dense.close()
    eng = make_paged(params)
    got = eng.generate(
        prompt, max_new_tokens=64, temperature=0.0, speculative=True
    )
    rounds = eng.decode_steps
    eng.close()
    assert got == ref
    assert rounds < len(ref) - 1  # drafts actually accepted


def test_paged_spec_backs_pages_for_accepted_runs(params):
    """Full-draft acceptance grows lengths by K+1 per round — the worst
    case must be page-backed up front so the scan can't write unbacked
    rows."""
    eng = make_paged(params, pool_rows=4 * 256, page_size=32)
    eng.prefill(0, [5, 6, 5, 6, 5, 6, 5, 6], temperature=0.0)
    for _ in range(6):
        eng.spec_step(4, draft_len=7)
    backed = eng.allocator.slot_rows_backed(0)
    assert backed >= eng.slot_length(0) + 1
    eng.close()


def test_paged_spec_batcher_evicts_on_exhaustion(params):
    """Speculative dispatches hit the same eviction policy as plain steps
    when the worst-case growth can't be page-backed."""
    eng = make_paged(params, pool_rows=96, page_size=32, num_slots=3,
                     prefix_cache=False)
    b = ContinuousBatcher(eng, speculative=True)
    hs = [
        b.submit(Request(prompt_ids=[s + 1, 2, 3], max_tokens=80,
                         temperature=0.0))
        for s in range(3)
    ]
    outs = [h.tokens() for h in hs]
    b.shutdown()
    assert b.last_error is None  # exhaustion evicted, never aborted
    assert b.pool_evictions >= 1
    assert all(len(o) > 0 for o in outs)
    assert eng.allocator.pages_in_use() == 0
    eng.close()


def test_paged_prefix_plus_spec_agent_fast_path(params):
    """The full agent fast path: resubmitted preamble maps cached pages,
    then speculative rounds decode — output identical to the dense plain
    engine."""
    prompt = [int(t) for t in np.random.default_rng(13).integers(1, 500, 100)]
    dense = make_dense(params)
    ref = dense.generate(prompt, max_new_tokens=32, temperature=0.0)
    dense.close()
    eng = make_paged(params)
    eng.generate(prompt, max_new_tokens=4, temperature=0.0)  # registers
    got = eng.generate(
        prompt, max_new_tokens=32, temperature=0.0, speculative=True
    )
    assert eng.prefix_rows_reused > 0
    eng.close()
    assert got == ref


# ---------------------------------------------------------------------------
# prefix caching
# ---------------------------------------------------------------------------


def test_prefix_hit_reuses_pages_and_matches_cold(params):
    """Resubmitting a prompt must map its cached prefix pages instead of
    recomputing them — and decode exactly the same tokens as a cold run."""
    prompt = [int(t) for t in np.random.default_rng(7).integers(1, 500, 100)]
    cold = make_paged(params)  # page_size 32: 100 tokens -> 3 full blocks
    ref = cold.generate(prompt, max_new_tokens=24, temperature=0.0)
    cold.close()

    eng = make_paged(params)
    first = eng.generate(prompt, max_new_tokens=24, temperature=0.0)
    assert eng.prefix_rows_reused == 0  # cold: nothing to match
    again = eng.generate(prompt, max_new_tokens=24, temperature=0.0)
    assert eng.prefix_rows_reused == 96  # 3 x 32-row blocks mapped, not computed
    assert eng.prefix_index.hits == 1
    eng.close()
    assert first == ref
    assert again == ref


def test_prefix_divergent_tails_share_only_common_blocks(params):
    base = [int(t) for t in np.random.default_rng(8).integers(1, 500, 64)]
    a, btail = base + [7, 8, 9], base + [11, 12, 13]
    dense = make_dense(params)
    ref_a = dense.generate(a, max_new_tokens=16, temperature=0.0)
    ref_b = dense.generate(btail, max_new_tokens=16, temperature=0.0)
    dense.close()

    eng = make_paged(params)
    got_a = eng.generate(a, max_new_tokens=16, temperature=0.0)
    got_b = eng.generate(btail, max_new_tokens=16, temperature=0.0)
    assert eng.prefix_rows_reused == 64  # the 2 shared base blocks
    eng.close()
    assert (got_a, got_b) == (ref_a, ref_b)


def test_prefix_shared_pages_survive_owner_release(params):
    """Slot A releases while slot B still maps the shared prefix — B's
    decode must stay correct and the pages must not be recycled."""
    prompt = [int(t) for t in np.random.default_rng(9).integers(1, 500, 80)]
    dense = make_dense(params)
    dense.prefill(1, prompt, temperature=0.0)
    ref = dense.step(12)[:, 1].tolist()
    dense.close()

    eng = make_paged(params)
    eng.prefill(0, prompt, temperature=0.0)  # registers blocks
    eng.prefill(1, prompt, temperature=0.0)  # shares them
    assert eng.prefix_rows_reused > 0
    eng.release(0)  # owner goes away; index + slot 1 still hold refs
    got = eng.step(12)[:, 1].tolist()
    eng.close()
    assert got == ref


def test_prefix_hit_tail_overrun_is_safe(params):
    """A prefix match de-aligns the tail's chunk starts, so the final
    bucket's padding can overrun max_context (start=32 + bucket=512 > 512
    here): the padded table slice must route overflow rows to the
    sacrificial page instead of clamping a block early — output must match
    the dense engine exactly."""
    rng = np.random.default_rng(12)
    base = [int(t) for t in rng.integers(1, 500, 40)]
    y = base[:32] + [int(t) for t in rng.integers(1, 500, 479)]  # len 511
    dense = make_dense(params, max_context=512)
    ref = dense.generate(y, max_new_tokens=8, temperature=0.0)
    dense.close()

    eng = make_paged(params, pool_rows=1024, page_size=32, max_context=512)
    eng.generate(base, max_new_tokens=4, temperature=0.0)  # registers block 0
    got = eng.generate(y, max_new_tokens=8, temperature=0.0)
    assert eng.prefix_rows_reused == 32  # the de-aligning 1-block match
    eng.close()
    assert got == ref


def test_prefix_index_reclaims_under_pressure(params):
    """Cold index pages are reclaimed instead of raising PoolExhausted."""
    eng = make_paged(params, pool_rows=256, page_size=32, num_slots=2)
    # fill the index: 3 distinct prompts x 2+ full blocks each
    rng = np.random.default_rng(10)
    for i in range(3):
        p = [int(t) for t in rng.integers(1, 500, 70)]
        eng.prefill(0, p, temperature=0.0)
        eng.release(0)
    assert eng.allocator.free_pages < 8  # index is holding pages
    # a fresh prompt needing more pages than the free list has
    big = [int(t) for t in rng.integers(1, 500, 200)]
    first = eng.prefill(0, big, temperature=0.0)  # must NOT raise
    assert 0 <= first < TINY_TEST.vocab_size
    eng.close()


def test_prefix_chunked_admission_hit(params):
    """A long prompt resubmitted through chunked admission maps its prefix
    and produces the dense engine's exact output."""
    prompt = [int(t) for t in np.random.default_rng(11).integers(1, 500, 180)]
    outs = {}
    for paged in (False, True):
        eng = make_paged(params) if paged else make_dense(params)
        b = ContinuousBatcher(eng, prefill_chunk=64)
        o1 = b.generate(prompt, max_tokens=12, temperature=0.0)
        o2 = b.generate(prompt, max_tokens=12, temperature=0.0)
        outs[paged] = (o1, o2)
        if paged:
            assert eng.prefix_rows_reused > 0
        b.shutdown()
        eng.close()
    assert outs[True] == outs[False]


def test_warmup_leaves_prefix_index_empty(params):
    eng = make_paged(params, pool_rows=1024, page_size=32)
    eng.warmup(step_sizes=(1,))
    assert len(eng.prefix_index.snapshot()) == 0
    assert eng.allocator.pages_in_use() == 0
    out1 = eng.generate([1, 2, 3], max_new_tokens=8, temperature=0.0)
    assert len(out1) == 8
    eng.close()


def test_batcher_fails_only_oversized_prompt(params):
    eng = make_paged(params, pool_rows=64, page_size=32, num_slots=2)
    b = ContinuousBatcher(eng)
    big = b.submit(Request(prompt_ids=[1] * 120, max_tokens=4,
                           temperature=0.0))  # needs 4 pages, pool has 2
    small = b.submit(Request(prompt_ids=[1, 2, 3], max_tokens=8,
                             temperature=0.0))
    big_out = big.tokens()
    small_out = small.tokens()
    b.shutdown()
    assert b.last_error is None
    assert big_out == []  # failed cleanly, iterator ended
    assert len(small_out) == 8  # unaffected
    eng.close()


# ---------------------------------------------------------------------------
# paged pool under tensor parallelism (dp=sp=1)
# ---------------------------------------------------------------------------


def test_paged_pool_composes_with_tp(params, cpu_devices):
    """Pages shard kv heads over tp; outputs bit-match single-chip paged,
    prefix caching still hits, and the int8 pool rides along."""
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    plan = ShardingPlan(build_mesh(2, dp=1, tp=2))
    kw = dict(num_slots=4, max_context=256, cache_dtype=jnp.float32,
              paged_pool_rows=4 * 256, page_size=32)
    ref = TPUEngine(TINY_TEST, params, **kw)
    tp = TPUEngine(TINY_TEST, params, shardings=plan, **kw)
    try:
        assert str(tp.state["k"].sharding.spec).find("'tp'") != -1
        prompt = [1, 2, 3, 4, 5] * 3
        assert tp.generate(prompt, max_new_tokens=24, temperature=0.0) == \
            ref.generate(prompt, max_new_tokens=24, temperature=0.0)
        pre = list(range(1, 70))
        tp.prefill(0, pre + [7], temperature=0.0)
        tp.release(0)
        before = tp.prefix_rows_reused
        tp.prefill(1, pre + [9], temperature=0.0)
        assert tp.prefix_rows_reused > before  # prefix hit under TP
    finally:
        tp.close()
        ref.close()


def test_paged_pool_int8_under_tp(params, cpu_devices):
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    plan = ShardingPlan(build_mesh(2, dp=1, tp=2))
    kw = dict(num_slots=2, max_context=128, cache_dtype=jnp.int8,
              paged_pool_rows=256, page_size=32)
    ref = TPUEngine(TINY_TEST, params, **kw)
    tp = TPUEngine(TINY_TEST, params, shardings=plan, **kw)
    try:
        assert tp.generate([1, 2, 3, 4], max_new_tokens=12,
                           temperature=0.0) == \
            ref.generate([1, 2, 3, 4], max_new_tokens=12, temperature=0.0)
    finally:
        tp.close()
        ref.close()


def test_paged_pool_composes_with_sp_mesh(params, cpu_devices):
    """An sp>1 MESH no longer disables paging: the pool's shard_map specs
    name only dp/tp, so it replicates over the sp axis and decode matches
    the sp-free paged engine. (A context that must SHARD over sp uses
    seq_sharded_cache instead — the model manager's HBM-budget check
    picks per model; see test_runtime_service.py.)"""
    from aios_tpu.parallel.sharding import ShardingPlan, build_mesh

    plan = ShardingPlan(build_mesh(4, sp=2, tp=2))
    eng = TPUEngine(TINY_TEST, params, num_slots=4, max_context=256,
                    cache_dtype=jnp.float32, paged_pool_rows=256,
                    page_size=32, shardings=plan)
    ref_plan = ShardingPlan(build_mesh(2, tp=2))
    ref = TPUEngine(TINY_TEST, params, num_slots=4, max_context=256,
                    cache_dtype=jnp.float32, paged_pool_rows=256,
                    page_size=32, shardings=ref_plan)
    for e in (eng, ref):
        e.prefill(0, [1, 2, 3, 4], temperature=0.0)
    got = eng.step(2)
    want = ref.step(2)
    assert got.tolist() == want.tolist(), (
        "paged decode over an sp mesh diverged from the sp-free pool"
    )

    # seq-sharded + paged on the SAME engine stays impossible (pages hold
    # contiguous rows of one slot and cannot split across sp shards)
    with pytest.raises(ValueError, match="exclusive"):
        TPUEngine(TINY_TEST, params, num_slots=4, max_context=256,
                  cache_dtype=jnp.float32, paged_pool_rows=256,
                  page_size=32, shardings=plan, seq_sharded_cache=True)


# ---------------------------------------------------------------------------
# int8 page pool through the paged kernel (interpret mode)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("window", [None, 24])
def test_paged_int8_kernel_parity(window):
    from aios_tpu.ops import (
        paged_decode_attention_int8,
        paged_decode_attention_int8_reference,
    )

    rng = np.random.default_rng(9)
    B, H, KH, D, N, P, MB = 3, 8, 2, 16, 16, 16, 4
    q = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    k = jnp.asarray(rng.integers(-127, 128, (N, P, KH, D)), jnp.int8)
    v = jnp.asarray(rng.integers(-127, 128, (N, P, KH, D)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.005, 0.02, (N, P, KH)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.005, 0.02, (N, P, KH)), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, N))[: B * MB].reshape(B, MB), jnp.int32
    )
    lens = jnp.asarray([0, 29, 63], jnp.int32)
    got = paged_decode_attention_int8(
        q, k, v, ks, vs, tables, lens, window=window, interpret=True
    )
    ref = paged_decode_attention_int8_reference(
        q, k, v, ks, vs, tables, lens, window=window
    )
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)


def test_paged_decode_step_int8_kernel_wiring(monkeypatch):
    """AIOS_TPU_INT8_RAGGED=1 routes the int8 POOL decode through the
    paged kernel (reference body stands in on CPU); outputs match the
    gather-dequant XLA path."""
    import aios_tpu.ops as ops_mod

    cfg = TINY_TEST
    params = model.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    B, N, P, MB = 2, 9, 16, 4
    toks = jnp.asarray([1, 2], jnp.int32)
    lens = jnp.asarray([5, 11], jnp.int32)
    k = jnp.zeros((cfg.num_layers, N, P, cfg.num_kv_heads, cfg.head_dim),
                  jnp.int8)
    v = jnp.zeros_like(k)
    scales = (
        jnp.ones((cfg.num_layers, N, P, cfg.num_kv_heads), jnp.float32),
        jnp.ones((cfg.num_layers, N, P, cfg.num_kv_heads), jnp.float32),
    )
    tables = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)

    ref = model.decode_step_paged(
        params, cfg, toks, lens, k, v, tables, kernels=False,
        cache_scales=scales,
    )[0]

    called = {}

    def fake_kernel(q, k_l, v_l, k_s, v_s, tbl, lengths, window=None):
        called["hit"] = True
        return ops_mod.paged_decode_attention_int8_reference(
            q, k_l, v_l, k_s, v_s, tbl, lengths, window=window
        )

    monkeypatch.setenv("AIOS_TPU_INT8_RAGGED", "1")
    monkeypatch.setattr(
        ops_mod, "paged_decode_attention_int8", fake_kernel
    )
    got = model.decode_step_paged(
        params, cfg, toks, lens, k, v, tables, kernels=True,
        cache_scales=scales,
    )[0]
    assert called.get("hit")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_paged_cancel_eviction_prefix_soak(params):
    """Randomized soak over the riskiest composition: shared-prefix pages
    (refcounted), pool-exhaustion eviction, and request CANCELLATION all
    interleaving on one paged engine. Invariant at quiesce: page
    accounting balances exactly — every page is free or pinned by the
    prefix index; nothing leaks, nothing double-frees. The pool is sized
    to GUARANTEE exhaustion (asserted below), so the eviction path really
    interleaves with the cancel reaping."""
    import random
    import threading
    import time

    rng = random.Random(7)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=4, max_context=256,
        cache_dtype=jnp.float32, paged_pool_rows=256, page_size=32,
    )
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=1)
    preamble = [7] * 64  # two full pages shared across most requests
    handles = []
    try:
        for i in range(24):
            prompt = (preamble if i % 3 else [5, i + 1]) + [
                rng.randrange(1, 250) for _ in range(rng.randrange(1, 30))
            ]
            handles.append(b.submit(Request(
                prompt_ids=prompt, max_tokens=rng.randrange(40, 150),
                temperature=0.0,
            )))
            if i % 2:
                victim = rng.choice(handles)
                victim.cancel()  # may be queued, live, or already done
            time.sleep(rng.random() * 0.02)
        drainers = [threading.Thread(target=h.tokens, daemon=True)
                    for h in handles]
        for t in drainers:
            t.start()
        end = time.time() + 120  # shared deadline, not 120 s per thread
        for t in drainers:
            t.join(timeout=max(0.1, end - time.time()))
        assert all(not t.is_alive() for t in drainers), "stranded consumer"
        assert b.active_count == 0 and b.queue_depth() == 0
        # the composition actually happened: evictions AND cancellations
        assert b.pool_evictions > 0, "pool never exhausted; soak is vacuous"
        assert b.cancellations > 0
        alloc = engine.allocator
        # quiesced accounting: usable pages (total minus the sacrificial
        # page) = free pages + pages pinned by the prefix index
        pinned = len(set(engine.prefix_index.snapshot().values()))
        usable = alloc.num_pages - alloc.replicas
        assert alloc.free_pages + pinned == usable, (
            alloc.free_pages, pinned, usable,
        )
        # no slot holds rows after quiesce
        for s in range(engine.num_slots):
            assert alloc.slot_rows_backed(s) == 0
    finally:
        b.shutdown()
        engine.close()


def test_eviction_prefers_low_priority_victims(params):
    """Pool-exhaustion eviction retires the LOWEST-priority live request
    (longest within a level) — a strategic stream survives while a longer
    operational one is sacrificed."""
    import time

    engine = TPUEngine(
        TINY_TEST, params, num_slots=3, max_context=256,
        cache_dtype=jnp.float32, paged_pool_rows=160, page_size=16,
        prefix_cache=False,
    )
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=2)
    try:
        # 9 usable pages (1 sacrificial); one low and one high stream
        # both growing until the pool exhausts
        low1 = b.submit(Request(prompt_ids=[1] * 40, max_tokens=500,
                                temperature=0.0, priority=0))
        high = b.submit(Request(prompt_ids=[2] * 40, max_tokens=500,
                                temperature=0.0, priority=3))
        deadline = time.time() + 30
        while b.active_count < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert b.active_count == 2
        # both grow until the pool exhausts; eviction must hit the
        # priority-0 stream even when lengths are close
        deadline = time.time() + 60
        while b.pool_evictions < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert b.pool_evictions >= 1
        low_toks = low1.tokens()
        high_toks = high.tokens()
        # the low-priority stream was cut short; the high one ran longer
        assert len(high_toks) > len(low_toks), (len(high_toks), len(low_toks))
    finally:
        b.shutdown()
        engine.close()


def test_low_priority_admission_waits_instead_of_evicting_high(params):
    """A low-priority admission must NOT evict strictly higher-priority
    live streams; it waits queued and admits once they drain."""
    import time

    engine = TPUEngine(
        TINY_TEST, params, num_slots=2, max_context=256,
        cache_dtype=jnp.float32, paged_pool_rows=256, page_size=16,
        prefix_cache=False,
    )
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=2)
    try:
        # 15 usable pages; each high peaks at 7 pages (40-row prompt + 60
        # tokens), so the two FIT together and never self-evict — only
        # the low admission conflicts
        highs = [b.submit(Request(prompt_ids=[2 + i] * 40, max_tokens=60,
                                  temperature=0.0, priority=3))
                 for i in range(2)]
        deadline = time.time() + 60
        while engine.allocator.pages_in_use() < 12 and time.time() < deadline:
            time.sleep(0.02)  # highs near peak: <= 3 pages free
        assert engine.allocator.pages_in_use() >= 12
        # 64-row prompt needs 4 pages > free margin -> PoolExhausted, and
        # the only victims outrank the requester -> admission must WAIT
        low = b.submit(Request(prompt_ids=[1] * 64, max_tokens=4,
                               temperature=0.0, priority=0))
        high_toks = [h.tokens() for h in highs]
        # the high streams ran their FULL budgets — never evicted to make
        # room for the low request
        assert all(len(t) == 60 for t in high_toks), [len(t) for t in high_toks]
        low_toks = low.tokens()  # admits after the highs drain
        assert len(low_toks) == 4
        assert b.pool_evictions == 0
    finally:
        b.shutdown()
        engine.close()
