"""Continuous batcher: correctness under concurrency, streaming, recycling."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aios_tpu.engine import model as M
from aios_tpu.engine.batching import ContinuousBatcher, Request
from aios_tpu.engine.config import TINY_TEST
from aios_tpu.engine.engine import TPUEngine
from aios_tpu.engine.tokenizer import ByteTokenizer, SentencePieceBPE, render_chat

# compile-heavy tier: excluded from the fast commit gate (pytest -m fast)
pytestmark = pytest.mark.slow


@pytest.fixture()
def batcher():
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=4, max_context=128, cache_dtype=jnp.float32
    )
    b = ContinuousBatcher(engine, chunk_steps=4, admit_chunk_steps=2)
    yield b
    b.shutdown()


def test_single_request_matches_generate(batcher):
    prompt = [3, 17, 91, 4, 55, 8]
    want = batcher.engine.generate(prompt, max_new_tokens=10, temperature=0.0)
    got = batcher.generate(prompt, max_tokens=10, temperature=0.0)
    assert got == want


def test_many_concurrent_requests_greedy_identical(batcher):
    """10 requests over 4 slots: every request must match its solo output."""
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 255, size=rng.integers(3, 20)).tolist() for _ in range(10)]
    solo = [
        batcher.engine.generate(p, max_new_tokens=8, temperature=0.0) for p in prompts
    ]

    results = [None] * len(prompts)

    def worker(i):
        results[i] = batcher.generate(prompts[i], max_tokens=8, temperature=0.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for i, (got, want) in enumerate(zip(results, solo)):
        assert got == want, f"request {i}: {got} != {want}"
    assert batcher.completed == len(prompts)
    assert batcher.active_count == 0


def test_streaming_yields_incrementally(batcher):
    handle = batcher.submit(
        Request(prompt_ids=[5, 6, 7], max_tokens=6, temperature=0.0)
    )
    toks = []
    for tok in handle:
        toks.append(tok)
    assert len(toks) == 6
    assert handle.ttft_ms >= 0.0


def test_stop_tokens_end_request(batcher):
    prompt = [3, 17, 91, 4, 55, 8]
    free_run = batcher.generate(prompt, max_tokens=10, temperature=0.0)
    stopper = free_run[2]
    stopped = batcher.generate(
        prompt, max_tokens=10, temperature=0.0, stop_ids=(stopper,)
    )
    assert stopped == free_run[:3]


def test_max_tokens_respected(batcher):
    out = batcher.generate([1, 2, 3], max_tokens=3, temperature=0.0)
    assert len(out) == 3


def test_scheduler_failure_aborts_requests_instead_of_hanging():
    """If the scheduler thread hits an engine error, every caller's iterator
    must terminate (and the error be inspectable) — not block forever."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=2, max_context=128, cache_dtype=jnp.float32
    )
    b = ContinuousBatcher(engine, chunk_steps=4)
    try:
        def boom(n=1):
            raise RuntimeError("synthetic engine failure")

        engine.step = boom
        handle = b.submit(Request(prompt_ids=[1, 2, 3], max_tokens=8))
        toks = handle.tokens()  # must return, not hang
        assert len(toks) <= 8
        assert isinstance(b.last_error, RuntimeError)
        assert b.active_count == 0
    finally:
        b.shutdown()

    with pytest.raises(ValueError):
        b.submit(Request(prompt_ids=[]))


def test_long_admission_interleaves_decode_and_stays_correct():
    """Admitting a long prompt must NOT stall decode for active slots
    (VERDICT r2 weak #5: prefill head-of-line blocking), and the chunked
    admission must produce exactly the tokens a solo run produces (i.e. the
    interleaved decode dispatches don't corrupt the half-prefilled slot)."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=2, max_context=128, cache_dtype=jnp.float32
    )
    solo = TPUEngine(
        TINY_TEST, params, num_slots=2, max_context=128, cache_dtype=jnp.float32
    )
    prompt_a = [1, 2, 3]
    prompt_b = (np.arange(1, 100) % 250 + 1).tolist()  # 99 tokens, 7 chunks
    want_a = solo.generate(prompt_a, max_new_tokens=40, temperature=0.0)
    want_b = solo.generate(prompt_b, max_new_tokens=4, temperature=0.0)

    b = ContinuousBatcher(
        engine, chunk_steps=4, admit_chunk_steps=1, prefill_chunk=16
    )
    events = []
    orig_step = engine.step
    engine.step = lambda n=1: (events.append("decode"), orig_step(n))[1]
    orig_scp = engine.start_chunked_prefill

    def recording_scp(*a, **kw):
        pc = orig_scp(*a, **kw)
        orig = pc.step
        pc.step = lambda: (events.append("chunk"), orig())[1]
        return pc

    engine.start_chunked_prefill = recording_scp
    try:
        ha = b.submit(Request(prompt_ids=prompt_a, max_tokens=40, temperature=0.0))
        it_a = iter(ha)
        got_a = [next(it_a)]  # A is live and decoding
        hb = b.submit(Request(prompt_ids=prompt_b, max_tokens=4, temperature=0.0))
        got_b = hb.tokens()
        got_a += list(it_a)
    finally:
        b.shutdown()

    assert got_b == want_b
    assert got_a == want_a
    chunk_idx = [i for i, e in enumerate(events) if e == "chunk"]
    assert len(chunk_idx) == 7  # 99 tokens / 16-token chunks
    interleaved = [
        e for e in events[chunk_idx[0] + 1 : chunk_idx[-1]] if e == "decode"
    ]
    assert interleaved, "no decode dispatch ran during the long admission"


# ---------------------------------------------------------------------------
# Tokenizers
# ---------------------------------------------------------------------------


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    ids = t.encode("hello world")
    assert ids[0] == t.bos_id
    assert t.decode(ids) == "hello world"


def test_sentencepiece_bpe_merges_by_score():
    # every longer piece is reachable by pairwise merges:
    # h+e, l+o, l+lo, he+llo, ▁+hello
    tokens = ["<unk>", "<s>", "</s>", "▁", "h", "e", "l", "o",
              "he", "lo", "llo", "hello", "▁hello"]
    scores = [0, 0, 0, -10, -1, -1, -1, -1, -0.9, -1.0, -0.8, -0.3, -0.1]
    types = [2, 3, 3] + [1] * 10
    tok = SentencePieceBPE(tokens=tokens, scores=scores, token_types=types)
    ids = tok.encode("hello", add_bos=False)
    assert ids == [tokens.index("▁hello")]
    assert tok.decode(ids) == "hello"


def test_sentencepiece_byte_fallback():
    tokens = ["<unk>", "<s>", "</s>", "▁"] + [f"<0x{i:02X}>" for i in range(256)]
    scores = [0.0] * len(tokens)
    types = [2, 3, 3, 1] + [6] * 256
    tok = SentencePieceBPE(tokens=tokens, scores=scores, token_types=types)
    ids = tok.encode("hi", add_bos=False)
    # "▁" is in vocab; h and i fall back to bytes
    assert tok.decode(ids) == "hi"


def test_chat_templates():
    assert "[INST]" in render_chat("mistral-7b", "hi", "be brief")
    assert "<|system|>" in render_chat("tinyllama-1.1b", "hi", "be brief")
    assert "<|im_start|>" in render_chat("qwen3-14b", "hi")
    out = render_chat("unknown-model", "hi", "sys")
    assert "User: hi" in out and "System: sys" in out


def test_batcher_serves_int4_engine():
    """The production batcher over an int4-quantized engine: batched greedy
    output must match the same engine's direct generate (slot scheduling is
    weight-format-agnostic)."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(7), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=4, max_context=128,
        cache_dtype=jnp.float32, quantize="int4",
    )
    assert engine.quant_mode == "int4"
    b = ContinuousBatcher(engine, chunk_steps=4, admit_chunk_steps=2)
    try:
        prompt = [3, 17, 91, 4, 55, 8]
        want = engine.generate(prompt, max_new_tokens=10, temperature=0.0)
        got = b.generate(prompt, max_tokens=10, temperature=0.0)
        assert got == want
    finally:
        b.shutdown()


def test_cancel_frees_slot_and_ends_iterator(batcher):
    """cancel() mid-stream releases the request's slot at the next tick and
    its iterator ends — the disconnect-abort path (llama-server parity:
    decode stops when the client goes away)."""
    import time

    h = batcher.submit(Request(
        prompt_ids=[3, 17, 91], max_tokens=10_000, temperature=0.0
    ))
    it = iter(h)
    next(it)  # live: slot held
    assert batcher.active_count == 1
    h.cancel()
    remaining = list(it)  # ends without producing max_tokens
    assert len(remaining) < 10_000
    deadline = time.time() + 5
    while batcher.active_count and time.time() < deadline:
        time.sleep(0.01)
    assert batcher.active_count == 0
    assert batcher.cancellations == 1
    # the cancelled slot itself was recycled, not just the other 3
    assert len(batcher.engine.free_slots()) == batcher.engine.num_slots
    # the engine still serves new requests afterwards
    out = batcher.generate([5, 6, 7], max_tokens=4, temperature=0.0)
    assert len(out) == 4


def test_cancel_queued_request_never_occupies_slot():
    """Cancelling while still queued drops the request from the wait list
    without touching any slot."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(1), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=1, max_context=128,
        cache_dtype=jnp.float32,
    )
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=2)
    try:
        hog = b.submit(Request(prompt_ids=[1, 2], max_tokens=64,
                               temperature=0.0))
        queued = b.submit(Request(prompt_ids=[3, 4], max_tokens=64,
                                  temperature=0.0))
        assert b.queue_depth() >= 1
        queued.cancel()
        assert queued.tokens() == []  # ended without ever running
        assert len(hog.tokens()) == 64  # the live request is unaffected
        assert b.cancellations == 1
    finally:
        b.shutdown()


def test_grpc_disconnect_cancels_request():
    """Closing the gRPC channel mid-StreamInfer aborts the request server-
    side (context callback -> handle.cancel), freeing the slot."""
    import time

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    mgr = ModelManager(num_slots=2, warm_compile=False)
    # budget the request CANNOT finish quickly (big context, huge
    # max_tokens): the tiny model decodes thousands of tok/s on CPU, so a
    # small context would let out_of_cache complete the request before the
    # client's cancel crosses the wire (measured: 2048 rows lose the race; 8192 wins with seconds to spare)
    mgr.load_model("tiny", "synthetic://tiny-test", context_length=8192)
    server, service, port = serve(address="127.0.0.1:0", manager=mgr,
                                  block=False)
    try:
        channel = rpc.insecure_channel(f"127.0.0.1:{port}")
        stub = services.AIRuntimeStub(channel)
        stream = stub.StreamInfer(runtime_pb2.InferRequest(
            prompt="hello", max_tokens=50_000, temperature=0.5
        ))
        next(stream)  # request is live server-side
        batcher = mgr.models["tiny"].batcher
        stream.cancel()  # client walks away
        channel.close()
        # poll the CANCELLATION counter, not active_count: the live entry
        # is popped before the counter increments (engine.release sits
        # between them), so active_count==0 can be observed in that gap
        deadline = time.time() + 10
        while batcher.cancellations < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert batcher.cancellations >= 1
        assert batcher.active_count == 0
    finally:
        server.stop(grace=None)
        mgr.unload_model("tiny")


def test_gateway_disconnect_propagates_cancel_to_runtime(monkeypatch):
    """The FULL abort chain: agent disconnects from the gateway mid-stream
    -> gateway's generator closes -> it cancels its downstream runtime
    call -> the runtime frees the slot. Without propagation the runtime
    would stream to an abandoned iterator until max_tokens."""
    import time

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import api_gateway_pb2
    from aios_tpu.gateway.router import RequestRouter
    from aios_tpu.gateway.service import serve as serve_gateway
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve as serve_runtime

    for var in ("CLAUDE_API_KEY", "OPENAI_API_KEY", "QWEN3_API_KEY"):
        monkeypatch.delenv(var, raising=False)
    channel = gw_server = rt_server = None
    mgr = ModelManager(num_slots=2, warm_compile=False)
    try:
        mgr.load_model("tiny", "synthetic://tiny-test", context_length=8192)
        rt_server, _, rt_port = serve_runtime(
            address="127.0.0.1:0", manager=mgr, block=False
        )
        gw_server, _, gw_port = serve_gateway(
            address="127.0.0.1:0",
            router=RequestRouter(runtime_address=f"127.0.0.1:{rt_port}"),
            block=False,
        )
        channel = rpc.insecure_channel(f"127.0.0.1:{gw_port}")
        gw = services.ApiGatewayStub(channel)
        stream = gw.StreamInfer(api_gateway_pb2.ApiInferRequest(
            prompt="hello", max_tokens=50_000, temperature=0.5
        ))
        next(stream)  # live through gateway -> runtime -> engine
        batcher = mgr.models["tiny"].batcher
        stream.cancel()
        deadline = time.time() + 15
        while batcher.cancellations < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert batcher.cancellations >= 1
        assert batcher.active_count == 0
    finally:
        if channel is not None:
            channel.close()
        for server in (gw_server, rt_server):
            if server is not None:
                server.stop(grace=None)
        if mgr.get("tiny") is not None:
            mgr.unload_model("tiny")


def test_gateway_disconnect_while_queued_cancels_without_slot(monkeypatch):
    """Disconnect before ANY delta flows (request still queued behind busy
    slots): no GeneratorExit can reach the gateway handler — the RPC-
    termination callback must cancel the registered downstream call, and
    the queued request must be reaped without ever taking a slot."""
    import time

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import api_gateway_pb2, runtime_pb2
    from aios_tpu.gateway.router import RequestRouter
    from aios_tpu.gateway.service import serve as serve_gateway
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve as serve_runtime

    for var in ("CLAUDE_API_KEY", "OPENAI_API_KEY", "QWEN3_API_KEY"):
        monkeypatch.delenv(var, raising=False)
    channel = rt_channel = gw_server = rt_server = None
    mgr = ModelManager(num_slots=1, warm_compile=False)
    try:
        mgr.load_model("tiny", "synthetic://tiny-test", context_length=8192)
        # DEFLAKE: the hog must NOT retire while the disconnect is in
        # flight, or the freed slot admits the queued request and the
        # active_count==1 assert races. Two stochastic retirements
        # existed: sampling the EOS stop id at temperature 0.5 (the
        # random-init model emits it eventually — the dominant flake),
        # and hitting the ctx cap / max_tokens on a fast host. Pin both:
        # every decode dispatch is throttled (the hog cannot burn its
        # budget inside any test deadline) and the hog's sampled EOS is
        # rewritten to a benign token, so only its explicit cancel can
        # end it. The first token still flows instantly (it comes from
        # prefill). Budgets are pinned LOW below (3000/512, not 50k) and
        # the observed decode rate is pinned HIGH: the gateway's local
        # stream carries a 300 s gRPC deadline, and the admission
        # feasibility gate ((outstanding + decode_cost) / observed
        # tok/s) otherwise sheds the queued request whenever the first
        # rate window lands before it — with warm_compile=False that
        # window is compile-polluted (~3 tok/s), so the seed test only
        # passed when "queued" won the race against the first
        # measurement. Feasibility is not what this test is about.
        import numpy as np

        eng = mgr.models["tiny"].engine
        eos = mgr.models["tiny"].tokenizer.eos_id
        real_step, real_prefill = eng.step, eng.prefill

        def never_stopping_step(n=1):
            time.sleep(0.2)
            toks = np.array(real_step(n))
            toks[toks == eos] = 7
            return toks

        def never_stopping_prefill(slot, ids, temperature=0.0, top_p=1.0):
            first = real_prefill(slot, ids, temperature, top_p)
            if first == eos:
                eng.force_pending_token(slot, 7)
                first = 7
            return first

        monkeypatch.setattr(eng, "step", never_stopping_step)
        monkeypatch.setattr(eng, "prefill", never_stopping_prefill)
        batcher0 = mgr.models["tiny"].batcher
        monkeypatch.setattr(batcher0, "tokens_per_second", lambda: 500.0)
        rt_server, _, rt_port = serve_runtime(
            address="127.0.0.1:0", manager=mgr, block=False
        )
        gw_server, _, gw_port = serve_gateway(
            address="127.0.0.1:0",
            router=RequestRouter(runtime_address=f"127.0.0.1:{rt_port}"),
            block=False,
        )
        channel = rpc.insecure_channel(f"127.0.0.1:{gw_port}")
        rt_channel = rpc.insecure_channel(f"127.0.0.1:{rt_port}")
        rt = services.AIRuntimeStub(rt_channel)
        gw = services.ApiGatewayStub(channel)
        batcher = mgr.models["tiny"].batcher

        # occupy the ONLY slot directly on the runtime
        hog = rt.StreamInfer(runtime_pb2.InferRequest(
            prompt="hog", max_tokens=3000, temperature=0.5
        ))
        next(hog)
        # gateway request queues behind it (no delta can flow)
        queued = gw.StreamInfer(api_gateway_pb2.ApiInferRequest(
            prompt="queued", max_tokens=512, temperature=0.5
        ))
        deadline = time.time() + 10
        while batcher.queue_depth() < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert batcher.queue_depth() >= 1
        queued.cancel()  # disconnect with zero deltas received
        deadline = time.time() + 15
        while batcher.cancellations < 1 and time.time() < deadline:
            time.sleep(0.05)
        assert batcher.cancellations >= 1
        assert batcher.queue_depth() == 0
        # the hog stream is untouched and still live
        assert batcher.active_count == 1
        hog.cancel()
    finally:
        for ch in (channel, rt_channel):
            if ch is not None:
                ch.close()
        for server in (gw_server, rt_server):
            if server is not None:
                server.stop(grace=None)
        if mgr.get("tiny") is not None:
            mgr.unload_model("tiny")


def test_shutdown_terminates_outstanding_requests():
    """shutdown() (the UnloadModel path) must end every in-flight and
    queued request's iterator — after the scheduler thread dies nothing
    else will ever deliver their end-of-stream."""
    import queue as _q

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(2), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=1, max_context=8192,
        cache_dtype=jnp.float32,
    )
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=2)
    live = b.submit(Request(prompt_ids=[1, 2], max_tokens=100_000,
                            temperature=0.0))
    queued = b.submit(Request(prompt_ids=[3, 4], max_tokens=100_000,
                              temperature=0.0))
    results = _q.Queue()

    def consume(h):
        results.put(len(h.tokens()))

    t1 = threading.Thread(target=consume, args=(live,), daemon=True)
    t2 = threading.Thread(target=consume, args=(queued,), daemon=True)
    t1.start(); t2.start()
    # wait until the first request is actually decoding
    deadline = __import__("time").time() + 30
    while b.active_count < 1 and __import__("time").time() < deadline:
        __import__("time").sleep(0.05)
    b.shutdown()
    t1.join(timeout=30); t2.join(timeout=30)
    assert not t1.is_alive() and not t2.is_alive(), (
        "consumers still blocked after shutdown"
    )
    assert results.qsize() == 2  # both iterators ended
    # terminated ≠ completed: both handles carry the abort marker so the
    # serving layer reports an error, not a short success
    assert live.aborted and "unload" in live.abort_reason
    assert queued.aborted
    # and the closed batcher refuses new work instead of stranding it
    with pytest.raises(RuntimeError, match="shut down"):
        b.submit(Request(prompt_ids=[9], max_tokens=4))


def test_unload_mid_stream_surfaces_aborted_to_client():
    """UnloadModel while a StreamInfer is mid-generation: the client gets
    an ABORTED status, not a truncated stream that looks complete."""
    import time

    import grpc as grpc_mod

    from aios_tpu import rpc, services
    from aios_tpu.proto_gen import runtime_pb2
    from aios_tpu.runtime.model_manager import ModelManager
    from aios_tpu.runtime.service import serve

    mgr = ModelManager(num_slots=2, warm_compile=False)
    mgr.load_model("tiny", "synthetic://tiny-test", context_length=8192)
    server, _, port = serve(address="127.0.0.1:0", manager=mgr, block=False)
    channel = rpc.insecure_channel(f"127.0.0.1:{port}")
    try:
        stub = services.AIRuntimeStub(channel)
        stream = stub.StreamInfer(runtime_pb2.InferRequest(
            prompt="hello", max_tokens=50_000, temperature=0.5
        ))
        next(stream)  # live
        t = threading.Thread(target=mgr.unload_model, args=("tiny",),
                             daemon=True)
        t.start()
        with pytest.raises(grpc_mod.RpcError) as err:
            deadline = time.time() + 60
            while time.time() < deadline:
                next(stream)
        assert err.value.code() == grpc_mod.StatusCode.ABORTED
        assert "unload" in err.value.details()
        t.join(timeout=30)
        assert not t.is_alive()
    finally:
        channel.close()
        server.stop(grace=None)


def test_priority_admission_order():
    """Under slot contention, a higher-priority queued request admits
    before earlier lower-priority ones; FIFO holds within a level."""
    params = M.init_params(TINY_TEST, jax.random.PRNGKey(4), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=1, max_context=128,
        cache_dtype=jnp.float32,
    )
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=2)
    order = []
    orig_prefill = engine.prefill

    def recording_prefill(slot, ids, **kw):
        order.append(tuple(ids[:2]))
        return orig_prefill(slot, ids, **kw)

    engine.prefill = recording_prefill
    try:
        import time

        hog = b.submit(Request(prompt_ids=[9, 9], max_tokens=24,
                               temperature=0.0))
        deadline = time.time() + 20
        while b.active_count < 1 and time.time() < deadline:
            time.sleep(0.01)  # the hog must hold the slot before the rest queue
        low_a = b.submit(Request(prompt_ids=[1, 1], max_tokens=4,
                                 temperature=0.0, priority=0))
        low_b = b.submit(Request(prompt_ids=[1, 2], max_tokens=4,
                                 temperature=0.0, priority=0))
        high = b.submit(Request(prompt_ids=[5, 5], max_tokens=4,
                                temperature=0.0, priority=3))
        for h in (hog, high, low_a, low_b):
            h.tokens()
        assert order == [(9, 9), (5, 5), (1, 1), (1, 2)], order
        assert b.completed == 4
    finally:
        b.shutdown()


def test_priority_aging_prevents_starvation():
    """A long-queued low-priority request outranks a fresh high-priority
    one once its age boost exceeds the priority gap (admission uses
    effective priority = priority + age/PRIORITY_AGING_SECS)."""
    import time as _time

    from aios_tpu.engine import batching as batching_mod

    params = M.init_params(TINY_TEST, jax.random.PRNGKey(5), dtype=jnp.float32)
    engine = TPUEngine(
        TINY_TEST, params, num_slots=1, max_context=128,
        cache_dtype=jnp.float32,
    )
    b = ContinuousBatcher(engine, chunk_steps=2, admit_chunk_steps=2)
    order = []
    orig_prefill = engine.prefill

    def recording_prefill(slot, ids, **kw):
        order.append(tuple(ids[:2]))
        return orig_prefill(slot, ids, **kw)

    engine.prefill = recording_prefill
    try:
        hog = b.submit(Request(prompt_ids=[9, 9], max_tokens=24,
                               temperature=0.0))
        deadline = _time.time() + 20
        while b.active_count < 1 and _time.time() < deadline:
            _time.sleep(0.01)
        old_low = b.submit(Request(prompt_ids=[1, 1], max_tokens=4,
                                   temperature=0.0, priority=0))
        # age the queued request past the whole priority gap
        old_low._live.submitted_at -= 4 * batching_mod.PRIORITY_AGING_SECS
        fresh_high = b.submit(Request(prompt_ids=[5, 5], max_tokens=4,
                                      temperature=0.0, priority=3))
        for h in (hog, old_low, fresh_high):
            h.tokens()
        assert order == [(9, 9), (1, 1), (5, 5)], order
    finally:
        b.shutdown()
