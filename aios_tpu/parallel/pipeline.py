"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Layers are stacked on a leading axis (model.py's param layout), so pipeline
stages fall out of GSPMD sharding alone: `P("pp")` on that axis gives every
device a contiguous block of layers. The schedule is expressed as one
`lax.scan` over ticks inside `shard_map`:

  tick t: stage 0 ingests microbatch t's embeddings; every stage applies its
  local layer block; the last stage (which at tick t holds microbatch
  t-(S-1)) folds that microbatch's cross-entropy into an accumulator behind
  `lax.cond`; activations rotate one hop stage->stage+1 via `lax.ppermute`
  (ICI neighbor exchange). After MB + S - 1 ticks every microbatch has
  crossed all stages; the pipeline bubble is the standard GPipe S-1 ticks.

Activation memory per device is ONE microbatch regardless of batch size, and
weight memory is num_layers/S of the stack — the axis that lets models
deeper than one chip's HBM train. Composes with the ``dp`` axis (microbatch
rows sharded across dp inside the same shard_map); tensor/sequence
parallelism live on the GSPMD path (sharding.py / ring_attention.py).

The reference has no training and no model parallelism of any kind
(SURVEY.md section 2.4); this module is part of the TPU build's
"distributed is first-class" mandate.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import model
from ..engine.config import ModelConfig


def build_pp_mesh(
    pp: int, dp: int = 1, devices=None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    assert pp * dp <= len(devices), (pp, dp, len(devices))
    arr = np.asarray(devices[: pp * dp]).reshape(pp, dp)
    return Mesh(arr, axis_names=("pp", "dp"))


def pp_param_specs(params) -> dict:
    """PartitionSpecs: layer stack sharded over pp, everything else replicated."""

    def walk(tree, under_layers):
        out = {}
        for key, leaf in tree.items():
            if isinstance(leaf, dict):
                out[key] = walk(leaf, under_layers or key == "layers")
            else:
                out[key] = P("pp") if under_layers else P()
        return out

    return walk(params, False)


def shard_pp_params(params, mesh: Mesh):
    specs = pp_param_specs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(jnp.asarray(x), NamedSharding(mesh, s)),
        params,
        specs,
    )


def make_pp_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    num_microbatches: int,
    optimizer: Optional[optax.GradientTransformation] = None,
    remat: bool = True,
    moe_aux_coef: float = 0.01,
) -> Tuple[Callable, Callable]:
    """Returns (init_state, train_step) for pipeline-parallel training.

    Batches are {"tokens": [B, T], "loss_mask": [B, T]} with
    B % (num_microbatches * dp) == 0; the step reshapes to
    [MB, mb, T] microbatches internally. MoE configs fold the router
    load-balancing aux (weighted by ``moe_aux_coef``) into the loss, same
    contract as the GSPMD train step (engine/train.py).
    """
    from ..engine.train import make_optimizer

    optimizer = optimizer or make_optimizer()
    S = mesh.shape["pp"]
    MB = num_microbatches
    assert cfg.num_layers % S == 0, (
        f"layers {cfg.num_layers} not divisible by pp={S}"
    )

    def stage_apply(layers_local, x):
        """Run this stage's layer block on activations x [mb, T, E];
        returns (x', stage aux sum over local layers)."""
        mb, T, _ = x.shape
        positions = jnp.broadcast_to(jnp.arange(T), (mb, T))
        cos, sin = model.rope_tables(positions, cfg.head_dim, cfg.rope_theta)
        mask = model.causal_mask(T, cfg.sliding_window)

        def blk(x, lp):
            x, (_, _, aux) = model.apply_block(
                x, lp, cfg, cos, sin, mask, with_aux=True
            )
            return x, aux

        blk_fn = jax.checkpoint(blk) if remat else blk
        x, auxs = jax.lax.scan(blk_fn, x, layers_local)
        return x, jnp.sum(auxs)

    def pp_loss(params, tokens_mb, mask_mb):
        """Inside shard_map: tokens_mb [MB, mb_local, T] per device."""
        s = jax.lax.axis_index("pp")
        mb, T = tokens_mb.shape[1], tokens_mb.shape[2]
        E = cfg.hidden_size
        layers_local = params["layers"]
        embed = params["embed"]
        head = params.get("lm_head")
        if head is None:
            head = params["embed"].T

        perm = [(i, (i + 1) % S) for i in range(S)]

        def microbatch_loss(y, mb_idx):
            from ..engine.train import token_cross_entropy

            h = model.rms_norm(y, params["final_norm"], cfg.rms_norm_eps)
            logits = model.matmul(h, head).astype(jnp.float32)
            return token_cross_entropy(
                logits, tokens_mb[mb_idx], mask_mb[mb_idx]
            )

        def tick(carry, t):
            x_in, loss_acc, denom_acc, aux_acc = carry
            in_idx = jnp.clip(t, 0, MB - 1)
            fresh = embed[tokens_mb[in_idx]].astype(x_in.dtype)  # [mb, T, E]
            x = jnp.where(s == 0, fresh, x_in)
            y, aux_t = stage_apply(layers_local, x)
            # stage s holds microbatch t-s at tick t; bubble ticks run the
            # router on garbage activations, so their aux must not count
            holds_mb = jnp.logical_and(t - s >= 0, t - s < MB)
            aux_acc = aux_acc + jnp.where(holds_mb, aux_t, 0.0)

            out_idx = t - (S - 1)
            is_producer = jnp.logical_and(
                s == S - 1, jnp.logical_and(out_idx >= 0, out_idx < MB)
            )
            dl, dd = jax.lax.cond(
                is_producer,
                lambda: microbatch_loss(y, jnp.clip(out_idx, 0, MB - 1)),
                lambda: (jnp.float32(0.0), jnp.float32(0.0)),
            )
            x_next = jax.lax.ppermute(y, "pp", perm)
            return (x_next, loss_acc + dl, denom_acc + dd, aux_acc), None

        x0 = jnp.zeros((mb, T, E), embed.dtype)
        (_, loss_sum, denom, aux_sum), _ = jax.lax.scan(
            tick,
            (x0, jnp.float32(0.0), jnp.float32(0.0), jnp.float32(0.0)),
            jnp.arange(MB + S - 1),
        )
        loss_sum = jax.lax.psum(loss_sum, ("pp", "dp"))
        denom = jax.lax.psum(denom, ("pp", "dp"))
        # sum over (stages x valid ticks x local layers) = layers x MB,
        # summed again over dp shards -> mean per (layer, microbatch, shard)
        aux_sum = jax.lax.psum(aux_sum, ("pp", "dp"))
        aux_mean = aux_sum / jnp.float32(
            cfg.num_layers * MB * mesh.shape["dp"]
        )
        return loss_sum / jnp.maximum(denom, 1.0), aux_mean

    def loss_fn(params, tokens, loss_mask):
        B, T = tokens.shape
        dp = mesh.shape["dp"]
        assert B % (MB * dp) == 0, (
            f"batch {B} must be divisible by microbatches*dp = {MB}*{dp}"
        )
        mb = B // MB
        tokens_mb = tokens.reshape(MB, mb, T)
        mask_mb = loss_mask.reshape(MB, mb, T)

        specs = pp_param_specs(params)
        sharded = partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                specs,
                P(None, "dp", None),
                P(None, "dp", None),
            ),
            out_specs=(P(), P()),
            check_rep=False,
        )
        ce, aux = sharded(pp_loss)(params, tokens_mb, mask_mb)
        return ce + moe_aux_coef * aux, aux

    def init_state(params):
        return {
            "params": params,
            "opt_state": optimizer.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    def train_step(state, batch):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], batch["tokens"], batch["loss_mask"]
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "moe_aux": aux,
        }

    return init_state, train_step
