#!/usr/bin/env bash
# Provision a TPU VM host to run aiOS-TPU.
#
# TPU-native equivalent of the reference's installer + first-boot pair
# (/root/reference/scripts/install.sh:1, first-boot.sh): where the reference
# builds a bootable ISO with llama.cpp compiled in, a TPU deployment is a
# managed Cloud TPU VM — so "install" means: verify the JAX/TPU stack, lay
# down the directory tree and default config, install a systemd unit for the
# boot supervisor, and (optionally) pull model weights.
#
# Usage:
#   scripts/install-tpu-vm.sh [--prefix /opt/aios] [--with-models] [--systemd]
#
# Idempotent: safe to re-run.
set -euo pipefail

PREFIX=/opt/aios
WITH_MODELS=0
WITH_SYSTEMD=0
REPO_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"

while [[ $# -gt 0 ]]; do
  case "$1" in
    --prefix) PREFIX="$2"; shift 2 ;;
    --with-models) WITH_MODELS=1; shift ;;
    --systemd) WITH_SYSTEMD=1; shift ;;
    *) echo "unknown flag: $1" >&2; exit 2 ;;
  esac
done

log() { echo "[install] $*"; }

# --- 1. sanity: python + jax + TPU ----------------------------------------
log "checking python environment"
PYTHON=${PYTHON:-python3}
"$PYTHON" - <<'EOF'
import sys
assert sys.version_info >= (3, 11), f"need python >= 3.11, have {sys.version}"
import jax
print(f"[install] jax {jax.__version__}")
try:
    devs = jax.devices()
    kinds = {d.platform for d in devs}
    print(f"[install] devices: {devs}")
    if "tpu" not in kinds:
        print("[install] WARNING: no TPU visible — serving will run on CPU")
except Exception as exc:
    print(f"[install] WARNING: backend init failed ({exc}); "
          "the runtime retries at boot")
EOF

# --- 2. directory tree -----------------------------------------------------
log "creating directory tree under $PREFIX and /var/lib/aios"
DIRS=(
  "$PREFIX"
  /var/lib/aios/models
  /var/lib/aios/data
  /etc/aios
)
for d in "${DIRS[@]}"; do
  if [[ -w "$(dirname "$d")" || -w "$d" ]] 2>/dev/null; then
    mkdir -p "$d"
  else
    sudo mkdir -p "$d"
    sudo chown "$(id -u):$(id -g)" "$d"
  fi
done

# --- 3. default config (9-section TOML, aios_tpu/boot/config.py schema) ----
CONFIG=/etc/aios/config.toml
if [[ ! -f "$CONFIG" ]]; then
  log "writing default $CONFIG"
  cat > "$CONFIG" <<EOF
[system]
hostname = "$(hostname)"
log_level = "info"
data_dir = "/var/lib/aios/data"

[boot]
health_timeout_seconds = 120
max_restart_attempts = 5
restart_window_seconds = 300

[models]
model_dir = "/var/lib/aios/models"
default_context = 4096
num_slots = 8
warm_compile = true
autoload = true
EOF
else
  log "$CONFIG already exists; leaving it alone"
fi

# --- 4. code ----------------------------------------------------------------
if [[ "$REPO_DIR" != "$PREFIX/repo" ]]; then
  log "syncing repo -> $PREFIX/repo"
  mkdir -p "$PREFIX/repo"
  rsync -a --delete --exclude .git --exclude __pycache__ \
    "$REPO_DIR/" "$PREFIX/repo/"
fi

# --- 5. optional model weights ---------------------------------------------
if [[ "$WITH_MODELS" == 1 ]]; then
  "$REPO_DIR/scripts/download-models.sh" --dest /var/lib/aios/models
fi

# --- 6. optional systemd unit ----------------------------------------------
if [[ "$WITH_SYSTEMD" == 1 ]]; then
  UNIT=/etc/systemd/system/aios.service
  log "installing $UNIT"
  sudo tee "$UNIT" > /dev/null <<EOF
[Unit]
Description=aiOS-TPU boot supervisor
After=network-online.target

[Service]
Type=simple
WorkingDirectory=$PREFIX/repo
Environment=PYTHONPATH=$PREFIX/repo
Environment=AIOS_DATA_DIR=/var/lib/aios/data
Environment=AIOS_MODEL_DIR=/var/lib/aios/models
ExecStart=$PYTHON -m aios_tpu.boot.supervisor
Restart=on-failure
RestartSec=5

[Install]
WantedBy=multi-user.target
EOF
  sudo systemctl daemon-reload
  sudo systemctl enable aios.service
  log "enabled aios.service (start with: sudo systemctl start aios)"
fi

log "done. start manually with: scripts/run-aios.sh"
