"""Serving-layer configuration: replica count, quotas, queues, deadlines.

One dataclass read once at pool construction (ModelManager.load_model),
so a running pool's policy is immutable — the same lenient-env pattern
as the sibling AIOS_TPU_* parsers in runtime/model_manager.py: a
malformed knob logs and falls back instead of taking down a model load.
Every knob here is documented in docs/SERVING.md and docs/CONFIG.md.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

log = logging.getLogger("aios.serving")


def _env_float(name: str, default: float, minimum: float = 0.0) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
        if v < minimum:
            raise ValueError(f"must be >= {minimum}")
        return v
    except ValueError as exc:
        log.warning("%s=%r ignored (%s); using %s", name, raw, exc, default)
        return default


def _env_int(name: str, default: int, minimum: int = 0) -> int:
    return int(_env_float(name, float(default), float(minimum)))


@dataclass(frozen=True)
class ServingConfig:
    # replicas per managed model (AIOS_TPU_REPLICAS overrides
    # ModelConfig.replicas; each replica is its own engine + batcher)
    replicas: int = 1
    # per-tenant token-bucket quota: sustained tokens/sec refill and burst
    # capacity (tokens). 0 tokens/sec = quotas off. A request costs
    # prompt_tokens + max_tokens up front (the reservation is the bound —
    # admission cannot know the true decode length).
    tenant_tokens_per_sec: float = 0.0
    tenant_burst_tokens: float = 0.0  # 0 -> 4 s of refill
    # tenant identity: "agent" = requesting_agent, falling back to the
    # task_id prefix; "task_prefix" = always the task_id prefix
    tenant_by: str = "agent"
    # bounded queues: shed (RESOURCE_EXHAUSTED + retry-after-ms) instead
    # of queueing more than this many waiting requests per replica;
    # 0 = unbounded (the pre-serving behavior)
    max_queue: int = 64
    # cache-aware routing: route to the best prefix-overlapping replica
    # only when the overlap covers at least this fraction of the prompt;
    # below it, least-outstanding-tokens wins
    overlap_min_ratio: float = 0.25
    # deadline admission: a request is shed when
    # (replica outstanding tokens + request max_tokens) / observed
    # tokens-per-sec exceeds the propagated gRPC deadline. When the
    # observed rate is 0 (cold pool), assumed_tokens_per_sec substitutes;
    # 0 disables the feasibility check until a rate is observed.
    assumed_tokens_per_sec: float = 0.0
    # transparent failover (serving/failover.py): how many times an
    # in-flight request whose replica died (or was evicted, on a
    # multi-replica pool) is re-routed to a surviving replica before the
    # abort surfaces as UNAVAILABLE + retry-after. 0 disables wrapping
    # (the pre-failover truncate-and-error behavior).
    failover_retries: int = 2
    # base of the failover exponential backoff (doubles per attempt,
    # +-50% jitter, capped at failover.MAX_BACKOFF_S)
    failover_backoff_ms: float = 50.0
    # draft-model speculation source paired with this managed model
    # (AIOS_TPU_DRAFT_MODEL overrides ModelConfig.draft_model): a preset
    # name or weights path loaded as an int4 draft (engine/spec.py
    # DraftModel). "" = n-gram prompt-lookup speculation only. The pool
    # falls back to n-gram when it cannot carry a draft (dp-replicated
    # pools, sharded plans, vocab mismatch) — see docs/ENGINE_PERF.md.
    draft_model: str = ""

    @classmethod
    def from_env(
        cls, replicas_default: int = 1, draft_model_default: str = "",
    ) -> "ServingConfig":
        replicas = _env_int("AIOS_TPU_REPLICAS", replicas_default, minimum=1)
        tps = _env_float("AIOS_TPU_TENANT_TOKENS_PER_SEC", 0.0)
        burst = _env_float("AIOS_TPU_TENANT_BURST_TOKENS", 0.0)
        if tps > 0 and burst <= 0:
            burst = 4.0 * tps
        tenant_by = os.environ.get("AIOS_TPU_TENANT_BY", "agent").lower()
        if tenant_by not in ("agent", "task_prefix"):
            log.warning(
                "AIOS_TPU_TENANT_BY=%r ignored (expected agent|task_prefix)",
                tenant_by,
            )
            tenant_by = "agent"
        return cls(
            replicas=replicas,
            tenant_tokens_per_sec=tps,
            tenant_burst_tokens=burst,
            tenant_by=tenant_by,
            max_queue=_env_int("AIOS_TPU_MAX_QUEUE", 64),
            overlap_min_ratio=_env_float(
                "AIOS_TPU_ROUTE_OVERLAP_MIN", 0.25
            ),
            assumed_tokens_per_sec=_env_float("AIOS_TPU_ASSUMED_TPS", 0.0),
            failover_retries=_env_int("AIOS_TPU_FAILOVER_RETRIES", 2),
            failover_backoff_ms=_env_float(
                "AIOS_TPU_FAILOVER_BACKOFF_MS", 50.0
            ),
            draft_model=os.environ.get(
                "AIOS_TPU_DRAFT_MODEL", draft_model_default
            ).strip(),
        )
