"""Storm verdicts: a deterministic fingerprint + measured SLO judgment.

The verdict JSON has two parts with different contracts:

  * ``verdict`` — DETERMINISTIC across two seeded runs of the same
    scenario on the same tree: the trace fingerprint, per-tenant
    submitted/completed/shed counts, the sorted (task_id, sha) stream
    hashes of every ``hash_stream`` call, and the PASS/FAIL booleans
    against the scenario's declared targets. ``bench.py --storm`` runs
    twice and compares this dict with ``==``; any divergence fails the
    gate. Three deliberate exclusions keep the contract honest:
    deadline-carrying tenants pin NOTHING (a feasibility verdict is a
    function of live backlog + observed rate at arrival — pure load
    timing; their counts ride ``measured.deadline_tenants``);
    quota-storm tenants pin their admitted/shed COUNTS (every storm
    call costs the same, so bucket math is order-independent) but not
    which task ids won the bucket race; and cache-COUPLED tenants
    (shared preambles, fork families) pin counts + completion but not
    stream CONTENT — whether a fork child's prompt hits the radix index
    depends on when its parent's pages registered, and a prefix HIT
    prefills through different XLA graph shapes than a MISS, whose
    bitwise-different KV can legally flip an argmax at a near-tie
    (the same reason bf16 spec-vs-plain comparisons are confined to
    fp32 in the engine's identity tests).
  * ``measured`` — wall-clock evidence (TTFT/TPOT percentiles per
    class, the live /debug/slo readback, shed-cause tallies) for humans
    and dashboards; never compared across runs.

The PASS line: no errors, no stuck workers, every deterministic call
completed, measured attainment over the declared SLO targets, and
availability (ok / (ok + non-quota sheds + errors)) over its floor.
"""

from __future__ import annotations

import hashlib
from collections import defaultdict
from typing import Dict, List

from .scenario import StormScenario
from .trace import Call, trace_fingerprint
from .driver import Outcome


def _pct(vals: List[float], p: float) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    idx = min(int(p * (len(vals) - 1) + 0.5), len(vals) - 1)
    return round(vals[idx], 3)


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:12]


def build_report(sc: StormScenario, calls: List[Call],
                 outcomes: List[Outcome], slo_surface: dict) -> dict:
    by_tenant: Dict[str, List[Outcome]] = defaultdict(list)
    for o in outcomes:
        by_tenant[o.call.tenant].append(o)

    tenants_det: dict = {}
    tenants_measured: dict = {}
    stream_hashes: List[tuple] = []
    errors: List[dict] = []
    stuck = 0
    det_missing: List[str] = []
    for name, outs in sorted(by_tenant.items()):
        counts = {
            "submitted": len(outs),
            "completed": sum(1 for o in outs if o.status == "ok"),
            "shed": sum(1 for o in outs if o.status == "shed"),
            "rejected": sum(1 for o in outs if o.status == "rejected"),
        }
        # deadline-carrying tenants' outcomes are load-timing verdicts:
        # real evidence, but not a determinism contract
        if any(o.call.deadline_ms > 0 for o in outs):
            tenants_measured[name] = counts
        else:
            tenants_det[name] = counts
        for o in outs:
            if o.status == "error":
                stuck += int(o.detail == "stuck")
                errors.append({
                    "task": o.call.task_id, "code": o.code,
                    "detail": o.detail,
                })
            if o.call.must_complete and o.status != "ok":
                det_missing.append(o.call.task_id)
            if o.call.hash_stream and o.status == "ok":
                stream_hashes.append((o.call.task_id, _sha(o.text)))

    # driver-side latency evidence per tenant class
    classes: dict = {}
    for klass in sorted({c.klass for c in calls}):
        outs = [o for o in outcomes if o.call.klass == klass]
        oks = [o for o in outs if o.status == "ok"]
        ttfts = [o.ttft_ms for o in oks if o.ttft_ms > 0]
        tpots = [
            (o.wall_ms - o.ttft_ms) / (o.chunks - 1)
            for o in oks if o.ttft_ms > 0 and o.chunks > 1
        ]
        classes[klass] = {
            "requests": len(outs),
            "ok": len(oks),
            "ttft_p50_ms": _pct(ttfts, 0.5),
            "ttft_p99_ms": _pct(ttfts, 0.99),
            "tpot_p50_ms": _pct(tpots, 0.5),
            "tpot_p99_ms": _pct(tpots, 0.99),
            "wall_p50_ms": _pct([o.wall_ms for o in oks], 0.5),
        }

    # SLO judgment from the driver's own measurements (the live surface
    # is recorded beside it; its window also contains warmup traffic)
    lat = [o for o in outcomes if o.status == "ok" and o.ttft_ms > 0]
    ttft_ok = sum(1 for o in lat if o.ttft_ms <= sc.slo.ttft_ms)
    ttft_attain = ttft_ok / len(lat) if lat else 1.0
    tp = [
        (o.wall_ms - o.ttft_ms) / (o.chunks - 1)
        for o in lat if o.chunks > 1
    ]
    tpot_ok = sum(1 for v in tp if v <= sc.slo.tpot_ms)
    tpot_attain = tpot_ok / len(tp) if tp else 1.0
    n_ok = sum(1 for o in outcomes if o.status == "ok")
    # availability over the work the plane OWED: quota sheds/rejections
    # are the tenant's own policy violation (the SLO-engine convention),
    # and a DEADLINE shed is the feasibility gate correctly refusing
    # work that could not finish in time (RTP-LLM's point — shedding it
    # protects the requests that can) — neither is the plane failing
    # admitted or admissible work
    owed = [
        o for o in outcomes
        if not (o.status in ("shed", "rejected")
                and o.shed_cause in ("quota", "deadline"))
    ]
    availability = n_ok / len(owed) if owed else 1.0

    passed = (
        not errors
        and stuck == 0
        and not det_missing
        and ttft_attain >= sc.slo.attainment
        and tpot_attain >= sc.slo.attainment
        and availability >= sc.slo.availability
    )

    verdict = {
        "scenario": sc.name,
        "seed": sc.seed,
        "trace_sha": trace_fingerprint(calls),
        "calls": len(calls),
        "tenants": tenants_det,
        "stream_hashes": sorted(stream_hashes),
        "deterministic_missing": sorted(det_missing),
        "errors": len(errors),
        "stuck": stuck,
        "pass": passed,
    }
    per_target = _per_target(outcomes)
    if per_target:
        # multi-endpoint storms (FleetStormDriver): one fingerprint per
        # target. Routing is a pure function of the tenant name, so
        # submitted counts are deterministic; completion pins only for
        # non-deadline tenants (the same exclusion as the tenant table)
        verdict["per_target"] = per_target
    measured = {
        "classes": classes,
        "deadline_tenants": tenants_measured,
        "ttft_attainment": round(ttft_attain, 4),
        "tpot_attainment": round(tpot_attain, 4),
        "availability": round(availability, 4),
        "targets": {
            "ttft_ms": sc.slo.ttft_ms, "tpot_ms": sc.slo.tpot_ms,
            "attainment": sc.slo.attainment,
            "availability": sc.slo.availability,
        },
        "shed_causes": _cause_tally(outcomes),
        "error_detail": errors[:8],
        "slo_surface": slo_surface,
    }
    return {"verdict": verdict, "measured": measured, "pass": passed}


def _per_target(outcomes: List[Outcome]) -> dict:
    """Per-target deterministic fingerprint for multi-endpoint storms:
    submitted counts per target (pure trace+routing function), plus
    completed/shed/rejected restricted to non-deadline tenants (a
    deadline verdict is load timing — the build_report exclusion).
    Empty when no outcome carries a target (single-endpoint storms keep
    their verdict shape unchanged)."""
    rows: Dict[int, dict] = {}
    for o in outcomes:
        t = o.extras.get("target")
        if t is None:
            return {}
        row = rows.setdefault(int(t), {
            "submitted": 0, "completed": 0, "shed": 0, "rejected": 0,
        })
        row["submitted"] += 1
        if o.call.deadline_ms > 0:
            continue
        if o.status == "ok":
            row["completed"] += 1
        elif o.status == "shed":
            row["shed"] += 1
        elif o.status == "rejected":
            row["rejected"] += 1
    return {str(k): rows[k] for k in sorted(rows)}


def _cause_tally(outcomes: List[Outcome]) -> dict:
    tally: Dict[str, int] = defaultdict(int)
    for o in outcomes:
        if o.status in ("shed", "rejected") and o.shed_cause:
            tally[f"{o.status}:{o.shed_cause}"] += 1
    return dict(sorted(tally.items()))
