"""ReplicaPool: N engine+batcher replicas behind one managed model.

Sits between ``RuntimeService`` and the engines with no wire-format
change: ``LoadModel``/``UnloadModel`` operate on the pool, every
``Infer``/``StreamInfer`` goes admission -> routing -> one replica's
continuous batcher. Lifecycle is coordinated here:

  * **spawn** — the pool builds one batcher per engine through a factory
    (the same factory respawns crashed ones);
  * **drain** — stop admitting, let in-flight streams finish;
  * **hot-swap** — ModelManager builds the NEW pool first, swaps it into
    the registry, then drains and shuts this one down in the background;
  * **crash-restart** — a replica whose scheduler thread died (or
    recorded a fatal error) gets a fresh batcher over the same engine,
    counted by the spawner-style restart counter
    (``aios_tpu_serving_replica_restarts_total``).

Everything reports through the PR-1 obs layer (``aios_tpu_serving_*``)
and ``pool.stats()`` — the pool-level twin of ``engine.stats()``.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence

from ..analysis.locks import make_lock
from ..obs import instruments as obs
from ..obs import flightrec
from ..obs.flightrec import SHED_CAUSES
from .admission import AdmissionController, AdmissionError
from .config import ServingConfig
from .failover import FailoverHandle
from .router import Router

log = logging.getLogger("aios.serving")

ROUTE_REASONS = ("prefix", "sticky", "least_loaded", "spill", "single")


class Replica:
    """One engine + its continuous batcher, with the live numbers the
    router and admission gates read."""

    def __init__(self, idx: int, engine, batcher) -> None:
        self.idx = idx
        self.engine = engine
        self.batcher = batcher

    def overlap_rows(self, prompt_ids: List[int], hashes=None) -> int:
        fn = getattr(self.engine, "prefix_overlap_rows", None)
        return fn(prompt_ids, hashes=hashes) if fn is not None else 0

    def prefix_hashes(self, prompt_ids: List[int]):
        fn = getattr(self.engine, "prefix_hashes", None)
        return fn(prompt_ids) if fn is not None else []

    def outstanding_tokens(self) -> int:
        return self.batcher.outstanding_tokens()

    def queue_depth(self) -> int:
        return self.batcher.queue_depth()

    def tokens_per_second(self) -> float:
        return self.batcher.tokens_per_second()

    def occupancy(self) -> float:
        n = self.engine.num_slots
        return float(self.engine.active.sum()) / n if n else 0.0

    def idle(self) -> bool:
        return self.queue_depth() == 0 and self.batcher.active_count == 0

    def dead(self) -> bool:
        """A replica needing a respawn: its scheduler thread exited
        outside shutdown, or recorded a fatal scheduler error (which
        aborted every outstanding request — a fresh batcher gives the
        next request a clean slate)."""
        b = self.batcher
        if b._closed:
            return False  # shutting down, not crashed
        return b.last_error is not None or not b._thread.is_alive()


class ReplicaPool:
    def __init__(
        self,
        name: str,
        engines: Sequence,
        batcher_factory: Callable,
        config: Optional[ServingConfig] = None,
    ) -> None:
        if not engines:
            raise ValueError("a pool needs at least one engine")
        self.name = name
        self.cfg = config or ServingConfig()
        self._factory = batcher_factory
        self.router = Router(overlap_min_ratio=self.cfg.overlap_min_ratio)
        self.admission = AdmissionController(self.cfg, name)
        self.replicas: List[Replica] = []
        try:
            for i, e in enumerate(engines):
                self.replicas.append(Replica(i, e, self._spawn_batcher(e)))
        except BaseException:
            # a failed spawn must not leave earlier replicas' scheduler
            # threads running (the caller will close the engines)
            for r in self.replicas:
                try:
                    r.batcher.shutdown()
                # aios: waive(silent-except): best-effort cleanup of a failed pool spawn — the root cause re-raises right below
                except Exception:  # noqa: BLE001
                    pass
            raise
        self.restarts = 0  # spawner-style: batchers respawned after crash
        # Degrade ladder position (serving/autoscale.py): 0 = healthy,
        # 1 = speculation off, 2 = + jump-ahead off, 3 = + best-effort
        # tiers shed at admission. Mechanism lives HERE (fresh batchers
        # from crash-respawn or scale-up inherit the level); policy —
        # when to move — lives in the controller. Plain int, flipped
        # cross-thread by set_degrade_level.
        self.degrade_level = 0
        # set by an attached AutoscaleController; shutdown() stops it so
        # an unload/hot-swap can never leave a controller scaling a
        # drained pool
        self.autoscaler = None
        # cold-start deadline feasibility: seed the assumed decode rate
        # from the devprof ledger's per-graph step means when devprof is
        # armed (env knob wins — see AdmissionController.assumed_rate)
        self.admission.devprof_rate_fn = self._devprof_rate
        # optional hook fired as on_respawn(replica_idx, new_batcher) —
        # ModelManager uses it to keep ManagedModel's replica-0 batcher
        # snapshot from going stale after a crash-respawn
        self.on_respawn: Optional[Callable] = None
        self._draining = False
        self._closed = False
        self._lock = make_lock("pool")
        #: guarded_by _lock
        self._routed: Dict[str, int] = {r: 0 for r in ROUTE_REASONS}
        #: guarded_by _lock
        self._shed: Dict[str, int] = {c: 0 for c in SHED_CAUSES}
        self._obs_routed = {
            r: obs.SERVING_ROUTING_DECISIONS.labels(model=name, reason=r)
            for r in ROUTE_REASONS
        }
        self._obs_restarts = obs.SERVING_REPLICA_RESTARTS.labels(model=name)
        self._register_gauges()

    def _spawn_batcher(self, engine):
        b = self._factory(engine)
        # serving-side queue-wait histogram: observed by the batcher at
        # slot assignment (see ContinuousBatcher.queue_wait_obs)
        b.queue_wait_obs = obs.SERVING_QUEUE_WAIT.labels(model=self.name)
        # a batcher spawned mid-degrade (crash-respawn, scale-up)
        # inherits the pool's current ladder position
        level = getattr(self, "degrade_level", 0)
        b.degrade_spec = level >= 1
        b.degrade_jump = level >= 2
        return b

    def _devprof_rate(self) -> float:
        """Devprof-seeded cold-start decode rate: chunk_steps tokens per
        decode dispatch over the ledger's mean sampled step seconds — a
        conservative single-slot tokens/sec floor for the deadline
        feasibility gate. 0.0 (gate stays cold-disabled) when devprof is
        unarmed or has no step samples yet."""
        from ..obs import devprof

        reps = self.replicas
        if not reps:
            return 0.0
        steps = getattr(reps[0].batcher, "chunk_steps", 0)
        if steps <= 0:
            return 0.0
        means = [
            m for m in (
                led.mean_s("step") for led in devprof.ledgers_for(self.name)
            ) if m
        ]
        if not means:
            return 0.0
        return steps / (sum(means) / len(means))

    def _register_gauges(self) -> None:
        ref = weakref.ref(self)
        # (child, bound fn, removal) triples: shutdown drops any series
        # STILL bound to this pool — a replacement pool of fewer replicas
        # must not leave the old higher-index series scraping 0.0 forever,
        # while series a replacement already rebound are left alone
        self._gauge_bindings = []

        def nrep():
            p = ref()
            return float(len(p.replicas)) \
                if p is not None and not p._closed else 0.0

        child = obs.SERVING_REPLICAS.labels(model=self.name)
        child.set_function(nrep)
        self._gauge_bindings.append((
            child, nrep,
            lambda: obs.SERVING_REPLICAS.remove(model=self.name),
        ))
        for i in range(len(self.replicas)):
            self._bind_occupancy(i)

    def _bind_occupancy(self, i: int) -> None:
        """Bind the per-index occupancy gauge (shared by construction
        and autoscale add_replica; an index past the live list — a
        scaled-down or crashed replica — reads 0.0)."""
        ref = weakref.ref(self)

        def occ(i=i):
            p = ref()
            if p is None or p._closed or i >= len(p.replicas):
                return 0.0
            return p.replicas[i].occupancy()

        child = obs.SERVING_REPLICA_OCCUPANCY.labels(
            model=self.name, replica=str(i)
        )
        child.set_function(occ)
        self._gauge_bindings.append((
            child, occ,
            lambda i=i: obs.SERVING_REPLICA_OCCUPANCY.remove(
                model=self.name, replica=str(i)
            ),
        ))

    # -- serving ------------------------------------------------------------

    def submit(self, req, tenant: str = "anonymous",
               deadline_s: Optional[float] = None):
        """Admission -> routing -> replica submit. Raises
        :class:`AdmissionError` when the request is shed (the service
        maps it to RESOURCE_EXHAUSTED + retry-after-ms metadata).
        Eligible requests come back wrapped in a
        :class:`~aios_tpu.serving.failover.FailoverHandle`: a replica
        crash mid-stream resumes on a surviving replica instead of
        truncating (grammar-constrained requests are not wrapped — a
        mid-stream resume cannot reproduce their forced first token)."""
        # flight recorder: the runtime service opens the timeline with
        # tenant + trace context; direct pool callers (tests, bench) get
        # one here so every request through the front door is recorded
        if getattr(req, "rec", None) is None:
            req.rec = flightrec.RECORDER.begin(
                self.name, req.request_id, tenant,
                prompt_tokens=len(req.prompt_ids),
                priority=getattr(req, "priority", 0),
            )
        fo = None
        if (
            self.cfg.failover_retries > 0
            and getattr(req, "json_schema", None) is None
            and not getattr(req, "json_mode", False)
            and getattr(req, "failover", None) is None
        ):
            # installed BEFORE the batcher sees the request: a crash in
            # the window between submit and wrap would otherwise finish
            # the timeline as aborted and strand the retry
            fo = FailoverHandle(
                self, req, tenant, self.cfg.failover_retries,
                self.cfg.failover_backoff_ms,
            )
            req.failover = fo
        try:
            handle = self._submit(req, tenant, deadline_s)
        except AdmissionError as e:
            with self._lock:
                self._shed[e.cause] = self._shed.get(e.cause, 0) + 1
            # the shed IS the request's terminal event: record cause +
            # retry-after and run spike detection (a shed storm freezes
            # an anomaly snapshot even with the recorder disabled)
            flightrec.RECORDER.finish_shed(
                req.rec, e.cause, e.retry_after_ms, model=self.name
            )
            raise
        if fo is None:
            return handle
        fo._inner = handle
        return fo

    def submit_failover(self, req, cause: str, attempt: int,
                        backoff_ms: float):
        """Re-route an in-flight request whose replica failed
        (serving/failover.py). Admission is SKIPPED: the quota was
        debited and the queue/deadline gates judged this request at
        first admission — a crashed replica must not double-bill the
        tenant or shed a stream the client is already consuming.
        Crashed replicas respawn first; then the grown prompt (prompt +
        already-emitted tokens) routes normally — the radix index / host
        tier make the re-prefill a cache hit. An ``evicted`` failover
        routes least-loaded instead (sticky/prefix would send it
        straight back to the starved replica that just evicted it)."""
        if self._draining or self._closed:
            raise RuntimeError(f"model {self.name} is draining")
        self._respawn_dead()
        # snapshot: a concurrent autoscale add/remove rebinding
        # self.replicas must not tear index selection mid-route
        reps = self.replicas
        route_ids, _ = self._route_ids(req)
        route_detail: Dict[str, int] = {}
        if cause == "evicted" and len(reps) > 1:
            idx, reason = self.router.least_loaded(reps), \
                "least_loaded"
        else:
            hashes = reps[0].prefix_hashes(route_ids)
            idx, reason = self.router.select(
                reps, route_ids, req.request_id, hashes=hashes,
                detail=route_detail,
            )
        rec = getattr(req, "rec", None)
        if rec is not None:
            rec.replica, rec.route_reason = idx, reason
            rec.event(
                "failover", attempt=attempt, cause=cause,
                backoff_ms=backoff_ms, replica=idx, reason=reason,
                resumed_tokens=len(req.prompt_ids), **route_detail,
            )
        task_id = req.request_id
        handle = reps[idx].batcher.submit(req)
        self._count_route(reason, task_id, idx)
        return handle

    def _route_ids(self, req):
        """The ADMISSION-TRUNCATED prompt (engines keep only the last
        max_context-1 ids) + the cap — shared by first-admission routing
        and failover re-routing: the router's overlap threshold is a
        fraction of the prompt it compares against cacheable rows, so an
        over-length raw prompt would make the prefix route
        unreachable."""
        cap = getattr(self.replicas[0].engine, "max_context", None)
        route_ids = req.prompt_ids
        if cap is not None and len(route_ids) > cap - 1:
            route_ids = route_ids[-(cap - 1):]
        return route_ids, cap

    def _count_route(self, reason: str, task_id: str, idx: int) -> None:
        """Routing bookkeeping shared by _submit and submit_failover:
        tallies + metric, and the sticky binding — except for ``spill``
        (a one-off overflow must not REBIND the task away from its
        cache-holding replica: sticky outranks prefix at select time, so
        recording the spill index would pin every later continuation to
        the wrong replica after the full one drains)."""
        with self._lock:
            self._routed[reason] = self._routed.get(reason, 0) + 1
        self._obs_routed[reason].inc()
        if reason != "spill":
            self.router.note_routed(task_id, idx)

    def _submit(self, req, tenant: str, deadline_s: Optional[float]):
        if self._draining or self._closed:
            raise self.admission.shed(
                "draining", f"model {self.name} is draining", 2000
            )
        # host-level graceful drain (fleet/drain.py): the whole host is
        # leaving — shed before any gate debits quota or queues work
        self.admission.check_host_drain()
        # degrade ladder rung 3 (clock-free policy gate, before any
        # routing work): best-effort tiers shed while the autoscaler digs
        # the pool out of an SLO burn; priority >= 1 stays protected
        self.admission.check_priority(getattr(req, "priority", 0))
        self._respawn_dead()
        # snapshot: a concurrent autoscale add/remove rebinding
        # self.replicas must not tear index selection mid-route
        reps = self.replicas
        # hash the blocks ONCE; every replica's probe reuses the digests
        # (replicas share page size and truncation — see _route_ids)
        route_ids, cap = self._route_ids(req)
        hashes = reps[0].prefix_hashes(route_ids)
        rec = getattr(req, "rec", None)
        route_detail: Dict[str, int] = {}
        idx, reason = self.router.select(
            reps, route_ids, req.request_id, hashes=hashes,
            detail=route_detail,
        )
        if (
            self.cfg.max_queue > 0
            and len(reps) > 1
            and reps[idx].queue_depth() >= self.cfg.max_queue
        ):
            # spill: a full cache-preferred replica must not shed while a
            # sibling has queue room (losing the prefix hit beats a shed)
            # — least-loaded AMONG the replicas with room, not overall
            # (the global minimum can itself be full of small budgets)
            with_room = [
                i for i, rep in enumerate(reps)
                if rep.queue_depth() < self.cfg.max_queue
            ]
            if with_room:
                alt = min(
                    with_room,
                    key=lambda i: reps[i].outstanding_tokens(),
                )
                idx, reason = alt, "spill"
        r = reps[idx]
        self.admission.check_queue(
            r.queue_depth(), r.outstanding_tokens(), r.tokens_per_second()
        )
        # the cache caps what this request can actually decode — a giant
        # max_tokens on a small context (or after a long prompt) is not a
        # giant deadline requirement; the truncated prompt length is what
        # actually occupies cache rows
        decode_cost = req.max_tokens
        if cap is not None:
            decode_cost = min(
                req.max_tokens, max(cap - len(route_ids), 0)
            )
        self.admission.check_deadline(
            deadline_s, r.outstanding_tokens(), decode_cost,
            r.tokens_per_second(),
        )
        # quota debits LAST, once nothing further can shed: a request
        # rejected by the queue/deadline gates was never served, so it
        # must not burn the tenant's bucket (shed->retry loops would
        # starve the tenant's feasible traffic). Cost = the work the pool
        # will actually do: truncated prompt + cache-capped decode.
        self.admission.check_quota(tenant, len(route_ids) + decode_cost)
        if rec is not None:
            rec.replica, rec.route_reason = idx, reason
            rec.event("route", replica=idx, reason=reason, **route_detail)
            # admission verdict AFTER the last gate that can shed: the
            # admit event means every gate passed, with the evidence the
            # gates judged (queue depth, decode budget, deadline)
            rec.event(
                "admit", replica=idx, queue_depth=r.queue_depth(),
                outstanding_tokens=r.outstanding_tokens(),
                decode_cost=decode_cost,
                deadline_s=round(deadline_s, 3)
                if deadline_s is not None else None,
            )
        # capture BEFORE batcher.submit: it assigns an auto id to blank
        # request_ids, which must not enter the sticky map (auto ids are
        # per-batcher counters and collide across replicas)
        task_id = req.request_id
        handle = r.batcher.submit(req)
        self._count_route(reason, task_id, idx)
        return handle

    def _respawn_dead(self) -> None:
        with self._lock:
            for r in self.replicas:
                if not r.dead():
                    continue
                err = r.batcher.last_error
                log.warning(
                    "%s replica %d scheduler crashed (%r); respawning its "
                    "batcher", self.name, r.idx, err,
                )
                try:
                    r.batcher.shutdown()
                # aios: waive(silent-except): the crashed batcher's thread may already be gone — the crash itself is logged + counted just above/below
                except Exception:  # noqa: BLE001 - old thread may be gone
                    pass
                r.batcher = self._spawn_batcher(r.engine)
                self.restarts += 1
                self._obs_restarts.inc()
                # the crashed scheduler aborted every outstanding request
                # — freeze the evidence (their timelines, with the abort
                # causes) before the ring churns past it
                flightrec.RECORDER.model_event(
                    self.name, "respawn", replica=r.idx,
                    error=repr(err)[:200],
                )
                flightrec.RECORDER.snapshot(
                    self.name, "crash_respawn", sync=False  # submit path
                )
                if self.on_respawn is not None:
                    self.on_respawn(r.idx, r.batcher)

    # -- elastic lifecycle (serving/autoscale.py drives these) --------------

    def set_degrade_level(self, level: int) -> int:
        """Move the degrade ladder: 0 healthy, 1 speculation off, 2 +
        jump-ahead off, 3 + best-effort admission shed (priority < 1;
        the reactive/operational tiers stay protected). Applies to every
        live replica batcher and to admission; fresh batchers (respawn,
        scale-up) inherit via _spawn_batcher. Greedy token streams are
        pinned identical across any transition — both switched paths are
        token-identical on/off by construction. Returns the clamped
        level actually applied."""
        level = max(0, min(int(level), 3))
        self.degrade_level = level
        for r in self.replicas:
            r.batcher.degrade_spec = level >= 1
            r.batcher.degrade_jump = level >= 2
        self.admission.min_priority = 1 if level >= 3 else 0
        return level

    def add_replica(self, engine) -> int:
        """Scale up: attach one more engine+batcher replica (the
        autoscaler builds the engine OUTSIDE any pool lock — warmup
        compiles take seconds). The new replica starts cold (no prefix
        pages) so the router's least-loaded fallback naturally sends it
        the overflow. Returns the new replica index."""
        if self._closed or self._draining:
            raise RuntimeError(f"model {self.name} is draining")
        r = Replica(len(self.replicas), engine, self._spawn_batcher(engine))
        # atomic list rebind: submit paths snapshot self.replicas once,
        # so they see either the old or the new list, never a torn one
        self.replicas = self.replicas + [r]
        self._bind_occupancy(r.idx)
        return r.idx

    def remove_replica(self, drain_timeout: float = 30.0):
        """Scale down: detach the LAST replica (sticky bindings past the
        new length self-invalidate — Router._sticky_for clamps), drain
        its in-flight streams, shut its batcher down, and return the
        detached :class:`Replica` (the caller owns the engine and closes
        it if it created it). Returns None when the pool is at one
        replica — a pool never scales to zero."""
        reps = self.replicas
        if len(reps) <= 1 or self._closed:
            return None
        victim = reps[-1]
        # unroute first (atomic rebind), then drain: new submissions can
        # no longer land on the victim while its in-flight streams finish
        self.replicas = reps[:-1]
        deadline = time.monotonic() + drain_timeout
        while time.monotonic() < deadline and not victim.idle():
            time.sleep(0.02)
        victim.batcher.shutdown()
        return victim

    # -- lifecycle ----------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting and wait for in-flight streams to finish.
        Returns True when every replica went idle within ``timeout``."""
        self._draining = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(r.idle() for r in self.replicas):
                return True
            time.sleep(0.02)
        return all(r.idle() for r in self.replicas)

    def shutdown(self, drain_timeout: float = 0.0) -> None:
        """Shut every replica down (optionally draining first) and free
        engine HBM deterministically."""
        self._draining = True
        # stop the attached autoscaler FIRST: a controller tick racing
        # shutdown must not spawn a replica onto a draining pool
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if drain_timeout > 0:
            self.drain(drain_timeout)
        self._closed = True
        for r in self.replicas:
            r.batcher.shutdown()
            r.engine.close()
        # drop the gauge series this pool still owns; a hot-swap
        # replacement rebound its own indices already (fn differs), and
        # those must stay
        for child, fn, remove in getattr(self, "_gauge_bindings", ()):
            if child._fn is fn:
                remove()

    # -- observability -------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """Pool-level twin of ``engine.stats()``: engine counters summed
        across replicas, batcher counters, routing/shed tallies. Flat
        scalars only — HealthCheck renders it as k=v pairs."""
        out: Dict[str, float] = {
            "replicas": len(self.replicas),
            "replica_restarts": self.restarts,
            "degrade_level": self.degrade_level,
        }
        occ = []
        for r in self.replicas:
            for k, v in r.engine.stats().items():
                if k == "batch_occupancy":
                    occ.append(v)
                    continue
                out[k] = out.get(k, 0) + v
            out["waiting"] = out.get("waiting", 0) + r.queue_depth()
            out["completed"] = out.get("completed", 0) + r.batcher.completed
            out["cancelled"] = (
                out.get("cancelled", 0) + r.batcher.cancellations
            )
            out["pool_evictions"] = (
                out.get("pool_evictions", 0) + r.batcher.pool_evictions
            )
            out["num_slots"] = out.get("num_slots", 0) + r.engine.num_slots
            out[f"replica{r.idx}_occupancy"] = round(r.occupancy(), 3)
        if occ:
            out["batch_occupancy"] = round(sum(occ) / len(occ), 3)
        # the armed megagraph window (PR 19): engines emit the summed
        # mega_dispatches/mega_ticks counters; K itself is config, so
        # surface it here — dispatches * K - ticks is the early-exit
        # savings fleetctl top renders fleet-wide
        mega_k = max(
            (r.engine.mega_ticks for r in self.replicas), default=0
        )
        if mega_k:
            out["mega_k"] = mega_k
        with self._lock:
            for reason, n in self._routed.items():
                out[f"routed_{reason}"] = n
            for cause, n in self._shed.items():
                out[f"shed_{cause}"] = n
        return out

    def heartbeat_stats(self) -> Dict[str, float]:
        """The compact per-pool slice a fleet heartbeat carries
        (obs/fleet.py): enough for peers to rank hosts by load and spot
        degraded pools, small enough to ride every announce."""
        s = self.stats()
        return {
            k: s[k]
            for k in ("replicas", "replica_restarts", "degrade_level",
                      "batch_occupancy", "waiting", "completed",
                      "num_slots", "mega_dispatches", "mega_ticks",
                      "mega_k")
            if k in s
        }
