"""Disaggregated prefill/decode roles over the transfer plane.

``AIOS_TPU_FLEET_ROLE`` splits a fleet into *prefill* hosts (admission
+ prefill + first token, then hand the stream off), *decode* hosts
(serve ``Handoff`` RPCs — resumed decode off transferred KV), and
*mixed* hosts (serve everything; the fleet router's pull-on-miss rung
applies). The handoff reuses the PR 10 resume-from-emitted contract
(serving/failover.py ``build_resume_request``): the decode host
resubmits ``prompt + emitted`` with the remaining budget, its prefill
of the grown prompt is a host-tier restore of the pushed pages, and it
samples exactly the token the prefill host would have produced next —
greedy streams are token-identical to a single-host run.

Failure ladder, every rung counted on the closed
``router.FLEET_ROUTE_REASONS`` enum:

  1. ``handoff``        — first decode target accepted the stream;
  2. ``handoff_resume`` — the target died mid-stream (real crash, or
     the ``fleet.host_kill`` chaos point); the prefill host re-hands
     ``prompt + ALL emitted tokens`` to a surviving decode host —
     tokens already relayed to the client are never re-sent;
  3. ``fallback_local`` — no survivor took it (or a transfer failed):
     the request resumes on the prefill host itself via
     ``pool.submit_failover``, admission skipped (it was judged once).

A failed/corrupt KV push never blocks the handoff: the decode host
pulls on miss (``kv_pushed=false`` -> ``Fetch`` back to the source) and,
when that also fails, simply recomputes the prefill locally — the PR 10
``restore_fail`` contract, one hop out.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Iterator, List, Optional, Tuple

import grpc

from .. import services
from ..analysis.locks import make_lock
from ..engine.batching import Request
from ..faults import inject as faults
from ..obs import flightrec
from ..serving.failover import build_resume_request
from . import kvx
from .router import FleetRouter, count_route, register_route_metrics

log = logging.getLogger("aios.fleet.disagg")

ROLES = ("prefill", "decode", "mixed")

# exit status for an injected fleet.host_kill with exit=1: distinct from
# crash-loop codes so the disagg smoke can assert the kill it scheduled
# is the death it observed
KILL_EXIT_STATUS = 17


def role() -> str:
    """This process's data-plane role (AIOS_TPU_FLEET_ROLE). Unknown
    values degrade to "mixed" — the lenient-env pattern; a typo must
    not silently turn a serving host into a prefill-only one."""
    r = os.environ.get("AIOS_TPU_FLEET_ROLE", "").strip().lower()
    return r if r in ROLES else "mixed"


def handoff_retries() -> int:
    """Decode-target re-handoff budget (AIOS_TPU_FLEET_HANDOFF_RETRIES)
    before the stream falls back to local decode."""
    try:
        return int(os.environ.get("AIOS_TPU_FLEET_HANDOFF_RETRIES", "") or 2)
    except ValueError:
        return 2


# -- decode-host half: the Handoff servicer ----------------------------------

class DisaggService(kvx.KvxService):
    """The full KvTransfer servicer: Fetch/Push from
    :class:`~aios_tpu.fleet.kvx.KvxService` plus the Handoff stream —
    registered on the runtime's gRPC server whenever the fleet plane
    could be armed (answering is harmless on a solo host)."""

    def Handoff(self, request, context) -> Iterator[object]:
        from ..proto_gen import fleet_pb2
        from . import drain

        if drain.draining():
            # a draining host refuses NEW handoffs immediately — the
            # source's retry ladder re-hands to a surviving peer
            context.abort(
                grpc.StatusCode.UNAVAILABLE, "handoff refused: draining"
            )
        m = self.manager.get(request.model)
        if m is None or m.pool is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"model {request.model} not loaded here",
            )
        prompt = list(request.prompt_ids)
        emitted = list(request.emitted_ids)
        engine = m.engine
        # pull-on-miss: the source pushed pages before handing off; when
        # that push failed (kv_pushed=false) fetch the chain back from
        # the source before submitting, so the local prefill of
        # prompt+emitted is a restore, not a recompute. RPC happens
        # HERE, before any lock, on this handler thread.
        if (
            engine is not None and not request.kv_pushed
            and request.source_addr and engine.host_store is not None
        ):
            hashes = engine.prefix_hashes(prompt)
            if hashes:
                n_hbm = engine.prefix_index.peek(hashes)
                n_host = engine.host_store.peek_chain(hashes[n_hbm:])
                missing = hashes[n_hbm + n_host:]
                if missing:
                    from ..faults import net

                    for h, entry in kvx.fetch_chain(
                        request.source_addr, m.name, missing,
                        peer=net.host_of(request.source_addr),
                    ):
                        engine.host_store.put(h, entry)
        req = Request(
            prompt_ids=prompt + emitted,
            max_tokens=max(int(request.max_tokens), 1),
            temperature=request.temperature,
            top_p=request.top_p or 1.0,
            stop_ids=tuple(request.stop_ids),
            request_id=request.request_id,
            priority=int(request.priority),
        )
        req.rec = flightrec.RECORDER.begin(
            m.name, req.request_id, request.tenant or "fleet",
            prompt_tokens=len(req.prompt_ids), priority=req.priority,
        )
        req.rec.event(
            "handoff", source=request.source_addr,
            attempt=int(request.attempt), kv_pushed=bool(request.kv_pushed),
            resumed_tokens=len(emitted),
        )
        try:
            # admission is SKIPPED by design: the prefill host's gates
            # judged this request and debited its quota at first
            # admission — a handoff must not double-bill or shed a
            # stream the client is already consuming
            handle = m.pool.submit_failover(
                req, cause="handoff", attempt=int(request.attempt),
                backoff_ms=0.0,
            )
        except Exception as exc:  # noqa: BLE001 - a draining/teardown pool
            # refuses; the source falls back (the abort IS the signal)
            flightrec.RECORDER.finish(
                req.rec, "aborted", abort_reason="handoff_refused"
            )
            context.abort(
                grpc.StatusCode.UNAVAILABLE, f"handoff refused: {exc}"
            )
        try:
            for tok in handle:
                if drain.draining():
                    # drain arrived mid-stream: abort so the SOURCE's
                    # resume ladder re-hands prompt+emitted to a
                    # survivor — tokens already relayed are never lost
                    handle.cancel()
                    context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        "draining_host: stream re-handed",
                    )
                act = faults.point("fleet.host_kill", m.name)
                if act is not None:
                    if act.exit:
                        log.error(
                            "fleet.host_kill(exit=1): killing decode host"
                        )
                        os._exit(KILL_EXIT_STATUS)
                    handle.cancel()
                    context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        "fleet.host_kill injected",
                    )
                yield fleet_pb2.HandoffChunk(token=tok, done=False)
            yield fleet_pb2.HandoffChunk(
                done=True,
                abort_reason=handle.abort_reason,
                retry_after_ms=handle.retry_after_ms if handle.aborted else 0,
            )
        finally:
            # source gone / stream torn down: free the slot now
            handle.cancel()


# -- prefill-host half: the handoff handle -----------------------------------

class HandoffHandle:
    """Caller-side view of a disaggregated request: iterates like a
    RequestHandle, splicing the local first token and the remote decode
    stream (plus any re-handoffs and the local fallback) into one
    token-identical stream. The LOCAL submit runs eagerly in the
    constructor so admission sheds raise where the runtime service
    expects them; everything after the first token is lazy."""

    def __init__(self, plane: "DisaggPlane", m, req: Request, tenant: str,
                 deadline_s: Optional[float]) -> None:
        self._plane = plane
        self._m = m
        self._req = req
        self._tenant_label = tenant
        self._emitted: List[int] = []
        self._attempts = 0
        self._t0 = time.monotonic()
        self._deadline_s = deadline_s
        self._ttft_at = 0.0
        self._terminal_abort = ""
        self._terminal_retry_ms = 0
        self._lock = make_lock("handoff")
        #: guarded_by _lock
        self._cancelled = False
        #: guarded_by _lock — the live local handle (first token / fallback)
        self._local = m.submit(req, tenant=tenant, deadline_s=deadline_s)

    # -- RequestHandle surface ----------------------------------------------

    def __iter__(self):
        with self._lock:
            local = self._local
        first = next(iter(local), None)
        if first is None or local.aborted:
            self._finish_local(local)
            return
        self._ttft_at = time.monotonic()
        self._emitted.append(first)
        yield first
        if (
            len(self._emitted) >= self._req.max_tokens
            or first in (self._req.stop_ids or ())
        ):
            # the stream is already complete — nothing to disaggregate
            return
        # the prefill host's job ends here: free the local slot (the
        # prefix pages it computed stay cached for the export) and move
        # the stream to a decode host
        local.cancel()
        yield from self._relay()

    def _relay(self):
        """Hand off to decode hosts until the stream completes; local
        fallback when the retry budget or the peer set runs dry."""
        from ..proto_gen import fleet_pb2

        from . import breaker

        pool = self._m.pool
        route_ids, _ = pool._route_ids(self._req)
        pairs = None
        tried: List[str] = []
        while self._attempts <= handoff_retries():
            with self._lock:
                if self._cancelled:
                    return
            timeout = self._remaining_deadline()
            if timeout is not None and timeout <= 0.0:
                # the client's own gRPC deadline has passed — a gray
                # decode host must not hold this stream any longer, and
                # no survivor could deliver tokens the client will see
                self._terminal("handoff_deadline", 0)
                return
            target = self._plane.pick_decode(self._m.name, exclude=tried)
            if target is None:
                break
            host, addr = target
            tried.append(host)
            self._attempts += 1
            reason = "handoff" if self._attempts == 1 else "handoff_resume"
            if pairs is None:
                # export once: the chain is content-addressed, so every
                # retry pushes the same pages (a survivor that already
                # received them just overwrites identical entries)
                pairs = self._m.engine.export_prefix(route_ids)
            pushed = kvx.push_chain(addr, self._m.name, pairs, peer=host) > 0
            hreq = fleet_pb2.HandoffRequest(
                model=self._m.name,
                prompt_ids=route_ids,
                emitted_ids=self._emitted,
                max_tokens=self._req.max_tokens - len(self._emitted),
                temperature=self._req.temperature,
                top_p=self._req.top_p,
                stop_ids=list(self._req.stop_ids or ()),
                request_id=self._req.request_id,
                priority=self._req.priority,
                source_addr=self._plane.self_addr(),
                kv_pushed=pushed,
                attempt=self._attempts,
                tenant=self._tenant_label,
            )
            count_route(self._m.name, reason)
            rec = getattr(self._req, "rec", None)
            if rec is not None:
                rec.event(
                    "handoff", target=host, attempt=self._attempts,
                    kv_pushed=pushed, emitted=len(self._emitted),
                )
            log.info(
                "%s: handing off %s to %s (attempt %d, %d tokens "
                "emitted, kv_pushed=%s)", self._m.name,
                self._req.request_id or "<anon>", host, self._attempts,
                len(self._emitted), pushed,
            )
            t_call = time.monotonic()
            try:
                stream = kvx._stub(addr).Handoff(hreq, timeout=timeout)
                for chunk in stream:
                    if chunk.done:
                        breaker.BOARD.record_ok(
                            host, time.monotonic() - t_call
                        )
                        if chunk.abort_reason and not self._retryable(
                            chunk.abort_reason
                        ):
                            self._terminal(
                                chunk.abort_reason, chunk.retry_after_ms
                            )
                            return
                        if chunk.abort_reason:
                            raise _RemoteDied(chunk.abort_reason)
                        return  # clean completion on the decode host
                    self._emitted.append(chunk.token)
                    yield chunk.token
                breaker.BOARD.record_ok(host, time.monotonic() - t_call)
                return  # stream closed without a done-chunk: treat as done
            except (_RemoteDied, grpc.RpcError) as exc:
                # a _RemoteDied is the DECODE host aborting its own
                # pool — that is the remote's replica health, not the
                # network edge, so only transport failures feed the
                # breaker
                if not isinstance(exc, _RemoteDied):
                    breaker.BOARD.record_failure(host, _handoff_cause(exc))
                with self._lock:
                    if self._cancelled:
                        return
                log.warning(
                    "%s: decode host %s lost mid-handoff (%s, %d tokens "
                    "relayed); resuming", self._m.name, host,
                    getattr(exc, "code", lambda: exc)(),
                    len(self._emitted),
                )
                continue
        yield from self._fallback_local()

    def _fallback_local(self):
        """No decode host could finish the stream: resume it HERE off
        the resume-from-emitted contract — the prefill host still holds
        the prefix pages, so this is a cache-hit re-prefill."""
        count_route(self._m.name, "fallback_local")
        resumed = build_resume_request(self._m.pool, self._req, self._emitted)
        try:
            handle = self._m.pool.submit_failover(
                resumed, cause="handoff", attempt=self._attempts,
                backoff_ms=0.0,
            )
        except Exception as exc:  # noqa: BLE001 - pool draining/teardown:
            # surface the abort, never a silent truncation
            log.warning(
                "%s: local fallback submit failed: %r", self._m.name, exc
            )
            self._terminal("handoff_exhausted", 0)
            return
        with self._lock:
            self._local = handle
            if self._cancelled:
                handle.cancel()
        for tok in handle:
            self._emitted.append(tok)
            yield tok
        if handle.aborted:
            self._terminal(handle.abort_reason, handle.retry_after_ms)

    def _remaining_deadline(self) -> Optional[float]:
        """Seconds left of the client's deadline budget, measured from
        the submit — propagated as the Handoff RPC timeout so a gray
        decode host can never hold this stream past the point where the
        client's own gRPC call has already expired."""
        if self._deadline_s is None:
            return None
        return self._deadline_s - (time.monotonic() - self._t0)

    def _retryable(self, abort_reason: str) -> bool:
        return (
            flightrec.abort_cause(abort_reason)
            in flightrec.RETRYABLE_ABORT_CAUSES
        )

    def _finish_local(self, local) -> None:
        if local.aborted:
            self._terminal(
                local.abort_reason, getattr(local, "retry_after_ms", 0)
            )

    def _terminal(self, reason: str, retry_ms: int) -> None:
        with self._lock:
            if not self._terminal_abort:
                self._terminal_abort = reason
                self._terminal_retry_ms = int(retry_ms or 0)

    def tokens(self) -> List[int]:
        return list(self)

    def cancel(self) -> None:
        with self._lock:
            self._cancelled = True
            local = self._local
        if local is not None:
            local.cancel()

    @property
    def aborted(self) -> bool:
        return bool(self._terminal_abort)

    @property
    def abort_reason(self) -> str:
        return self._terminal_abort

    @property
    def retry_after_ms(self) -> int:
        return self._terminal_retry_ms

    @property
    def ttft_ms(self) -> float:
        if not self._ttft_at:
            return 0.0
        return (self._ttft_at - self._t0) * 1000.0


class _RemoteDied(Exception):
    """Internal: the decode host reported a retryable abort in its final
    chunk — same recovery as a transport-level stream failure."""


def _handoff_cause(exc: Exception) -> str:
    """Map a transport-level handoff failure onto the breaker's
    cause vocabulary (kvx.KVX_FAIL_CAUSES flavors)."""
    code = getattr(exc, "code", lambda: None)()
    if code == grpc.StatusCode.DEADLINE_EXCEEDED:
        return "timeout"
    return "unavailable"


# -- the plane ---------------------------------------------------------------

class DisaggPlane:
    """Per-process handle on the fleet data plane: the manager, the
    fleet router rung, and this process's transfer endpoint."""

    def __init__(self, manager) -> None:
        self.manager = manager
        self.router = FleetRouter(manager)

    def self_addr(self) -> str:
        from ..obs import fleet

        return fleet._transfer_addr

    def _members(self) -> List[dict]:
        from ..obs import fleet

        reg = fleet.FLEET
        return reg.members() if reg is not None else []

    def pick_decode(self, model: str,
                    exclude: Optional[List[str]] = None
                    ) -> Optional[Tuple[str, str]]:
        """Choose a decode target: live, not self, transfer-capable,
        role ``decode`` (falling back to ``mixed`` peers when no
        dedicated decode host survives), least heartbeat-reported load
        first. Quarantined peers (gray hosts — the breaker overlay, NOT
        membership state) and draining/leaving peers are treated as
        absent. -> (host, kvx_addr) or None."""
        from . import breaker

        skip = set(exclude or ())
        candidates: List[Tuple[float, str, str]] = []
        fallback: List[Tuple[float, str, str]] = []
        for p in self._members():
            if (
                p.get("self") or p.get("state") != "up"
                or not p.get("kvx_addr") or p["host"] in skip
                or (p.get("phase") or "serving") != "serving"
                or breaker.BOARD.quarantined(p["host"])
            ):
                continue
            load = 0.0
            for stats in (p.get("pools") or {}).values():
                if isinstance(stats, dict):
                    load += float(stats.get("occupancy", 0.0) or 0.0)
                    load += float(stats.get("waiting", 0.0) or 0.0)
            row = (load, p["host"], p["kvx_addr"])
            if p.get("role") == "decode":
                candidates.append(row)
            elif p.get("role") == "mixed":
                fallback.append(row)
        pool = candidates or fallback
        if not pool:
            return None
        _, host, addr = min(pool)
        return host, addr


# the armed plane; None = disaggregation off (solo host / telemetry-only
# fleet) and route_submit degrades to a plain pool submit
PLANE: Optional[DisaggPlane] = None


def arm(manager) -> DisaggPlane:
    """Arm the data plane for this process (runtime serve() calls this
    once the KvTransfer servicer is registered) and pre-register every
    ready model's transfer/routing metric children."""
    global PLANE
    PLANE = DisaggPlane(manager)
    for m in manager.ready_models():
        kvx.register_kvx_metrics(m.name)
        register_route_metrics(m.name)
    log.info("fleet data plane armed (role=%s)", role())
    return PLANE


def disarm() -> None:
    """Test isolation."""
    global PLANE
    PLANE = None


def route_submit(m, req: Request, tenant: str = "anonymous",
                 deadline_s: Optional[float] = None):
    """The serving front door's fleet rung: exactly ``m.submit`` when
    the plane is disarmed; otherwise the role decides —

      * ``prefill``: admission + prefill + first token locally, then a
        :class:`HandoffHandle` moves the stream to a decode host;
      * ``mixed``: the fleet router's pull-on-miss rung runs first (a
        peer's deeper chain lands in the local host tier before the
        pool routes), then a plain local submit;
      * ``decode``: plain local submit (handoffs arrive via RPC, not
        through this door).

    Grammar-constrained requests never disaggregate — the same
    first-token-reproducibility limitation as PR 10 failover."""
    plane = PLANE
    if plane is None or m.pool is None:
        return m.submit(req, tenant=tenant, deadline_s=deadline_s)
    r = role()
    eligible = (
        getattr(req, "json_schema", None) is None
        and not getattr(req, "json_mode", False)
    )
    if r == "prefill" and eligible:
        if plane.pick_decode(m.name) is None:
            count_route(m.name, "no_peer")
            return m.submit(req, tenant=tenant, deadline_s=deadline_s)
        return HandoffHandle(plane, m, req, tenant, deadline_s)
    if r == "mixed" and eligible:
        route_ids, _ = m.pool._route_ids(req)
        plane.router.pull_before_submit(m, route_ids)
    return m.submit(req, tenant=tenant, deadline_s=deadline_s)
