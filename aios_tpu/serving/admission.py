"""The pool's front door: quotas, deadlines, bounded queues, load shedding.

RTP-LLM (arXiv:2605.29639) frames the overload problem: an unbounded
admission queue converts overload into client-side timeouts for EVERY
request; deadline/priority-aware admission sheds the requests that cannot
succeed anyway and keeps the rest inside their budgets. Three gates:

  1. **queue bound** — a replica whose waiting queue is full sheds
     instead of queueing (the batcher's deque would otherwise grow
     without limit while clients time out one by one).
  2. **deadline feasibility** — the propagated gRPC deadline is compared
     with (replica outstanding tokens + this request's cache-capped
     decode budget) / observed decode rate; an infeasible request is
     shed IMMEDIATELY, before it consumes a slot or queue position.
  3. **quota** — per-tenant token buckets (tenant = agent id or task-id
     prefix). A request reserves prompt + max_tokens; an empty bucket
     rejects with a retry-after derived from the refill rate. One noisy
     tenant exhausts its own bucket, not the pool. Quota runs LAST —
     debiting is a side effect, and a request the other gates shed must
     not burn the tenant's bucket.

Every rejection raises :class:`AdmissionError`, which the runtime service
maps to ``RESOURCE_EXHAUSTED`` with a ``retry-after-ms`` trailing
metadata hint — clients back off instead of hammering a saturated pool.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from .. import faults
from ..analysis.locks import make_lock
from ..obs import instruments as obs
from ..obs.flightrec import SHED_CAUSES
from .config import ServingConfig

# Bound the retry-after hint: past this, the client should re-resolve /
# re-plan rather than sleep (also caps what a huge quota deficit emits).
MAX_RETRY_AFTER_MS = 30_000

# per-process tenant-bucket cap: tenant names derive from client input
_MAX_TENANTS = 4096

# Host-level drain flag (fleet/drain.py flips it): module-level so EVERY
# pool in the process sheds new work while the host drains — unlike the
# per-pool "draining" cause, "draining_host" tells clients the whole
# host is leaving and they should resubmit to a surviving fleet peer.
# Plain bool store/load, no lock (same cross-thread pattern as
# min_priority below).
_host_draining = False


def set_host_draining(active: bool) -> None:
    """Flip the process-wide drain gate (fleet/drain.py owns this)."""
    global _host_draining
    _host_draining = bool(active)


def host_draining() -> bool:
    return _host_draining


class AdmissionError(Exception):
    """A request shed at the front door. ``cause`` is one of
    quota|deadline|queue_full|draining; ``retry_after_ms`` is the backoff
    hint the service returns as trailing metadata. ``retriable=False``
    marks a PERMANENT condition (e.g. a cost no bucket refill can ever
    cover) — the service maps it to a non-retriable status so compliant
    clients don't retry forever."""

    def __init__(self, message: str, cause: str, retry_after_ms: int = 1000,
                 retriable: bool = True):
        super().__init__(message)
        self.cause = cause
        self.retriable = retriable
        self.retry_after_ms = max(0, min(int(retry_after_ms),
                                         MAX_RETRY_AFTER_MS))


def tenant_of(request, mode: str = "agent") -> str:
    """Tenant identity from an InferRequest-shaped object: the requesting
    agent id, falling back to the task id's prefix (the segment before
    the first separator — agent task ids are "<agent>-<seq>"-shaped)."""
    agent = getattr(request, "requesting_agent", "") or ""
    task = getattr(request, "task_id", "") or ""
    if mode == "agent" and agent:
        return agent
    if task:
        for sep in ("-", ":", "/"):
            if sep in task:
                return task.split(sep, 1)[0]
        return task
    return agent or "anonymous"


class TokenBucket:
    """Lazy-refill token bucket (monotonic clock; caller holds no lock —
    the bucket locks itself)."""

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = rate
        self.burst = burst
        self.tokens = burst  #: guarded_by _lock
        self._at = time.monotonic()
        self._lock = make_lock("token_bucket")

    def try_take(self, cost: float) -> float:
        """Take ``cost`` tokens; returns 0.0 on success, else the seconds
        until the bucket could cover the cost (capped at the burst — a
        cost the bucket can NEVER cover reports the full-refill time)."""
        with self._lock:
            now = time.monotonic()
            self.tokens = min(
                self.burst, self.tokens + (now - self._at) * self.rate
            )
            self._at = now
            if self.tokens >= cost:
                self.tokens -= cost
                return 0.0
            deficit = min(cost, self.burst) - self.tokens
            return deficit / self.rate if self.rate > 0 else float("inf")


class AdmissionController:
    """Per-pool admission policy. Stateless w.r.t. replicas — the pool
    passes the chosen replica's live numbers in, so unit tests drive the
    policy with plain fakes."""

    def __init__(self, cfg: ServingConfig, model: str) -> None:
        self.cfg = cfg
        self.model = model
        # the "0 -> 4 s of refill" burst default applies at USE site, not
        # just in the env parser — a directly-constructed config with a
        # rate but no burst must not hand TokenBucket(burst=0), which
        # rejects 100% of traffic
        self._burst = (
            cfg.tenant_burst_tokens
            if cfg.tenant_burst_tokens > 0
            else 4.0 * cfg.tenant_tokens_per_sec
        )
        self._buckets: Dict[str, TokenBucket] = {}  #: guarded_by _lock
        self._lock = make_lock("admission")
        # Degrade gate (autoscale ladder rung 3): requests below this
        # priority floor shed with cause "degraded" while the pool digs
        # out of an SLO burn. 0 = gate off. Flipped cross-thread by
        # ReplicaPool.set_degrade_level — plain int store, no lock.
        self.min_priority = 0
        # Cold-start decode-rate seed: when no rate has been observed
        # AND the operator set no AIOS_TPU_ASSUMED_TPS floor, the pool
        # installs a callable deriving tokens/sec from the devprof
        # ledger's per-graph step means (docs/RUNBOOK.md §8) — a stale
        # hardcoded floor mis-sheds deadline requests on fast hardware.
        # The env knob (cfg.assumed_tokens_per_sec > 0) always wins.
        self.devprof_rate_fn: Optional[Callable[[], float]] = None
        # one closed enum end to end: the shed counter's label set, the
        # AdmissionError causes, and the flight recorder's shed events
        # all draw from obs.flightrec.SHED_CAUSES
        self._obs_shed = {
            cause: obs.SERVING_SHED.labels(model=model, cause=cause)
            for cause in SHED_CAUSES
        }

    def shed(self, cause: str, message: str, retry_after_ms: int = 1000,
             retriable: bool = True) -> AdmissionError:
        """Count and build (not raise) the shed error for ``cause``."""
        self._obs_shed[cause].inc()
        return AdmissionError(message, cause, retry_after_ms, retriable)

    # -- host drain gate (before every other gate: a leaving host must
    # not debit quota or queue work it will never finish) ------------------

    def check_host_drain(self) -> None:
        if not _host_draining:
            return
        raise self.shed(
            "draining_host",
            "host is draining (graceful drain in progress): resubmit to "
            "a surviving fleet peer",
            2000,
        )

    # -- gate 0: degrade-ladder priority floor (clock-free, runs first) ----

    def check_priority(self, priority: int) -> None:
        """Autoscale ladder rung 3: shed best-effort traffic (priority
        below the protected floor) while the controller is digging the
        pool out of an SLO burn. Reactive/operational tiers (priority
        >= 1) keep admitting — the preemption order the batcher's
        priority-aware slot admission already enforces continues to
        protect them once admitted."""
        if self.min_priority <= 0 or priority >= self.min_priority:
            return
        raise self.shed(
            "degraded",
            f"pool degraded under SLO burn: best-effort traffic "
            f"(priority {priority} < floor {self.min_priority}) is "
            f"temporarily shed",
            5000,
        )

    # -- gate 3 (runs LAST — debiting is a side effect): tenant quota ------

    def check_quota(self, tenant: str, cost_tokens: float) -> None:
        if self.cfg.tenant_tokens_per_sec <= 0:
            return
        if cost_tokens > self._burst:
            # no refill can EVER cover this cost — a retriable shed would
            # put compliant clients in an infinite retry loop; fail it as
            # permanent so they resize the request (or the operator the
            # burst)
            obs.SERVING_QUOTA_REJECTIONS.labels(tenant=tenant).inc()
            raise self.shed(
                "quota",
                f"request cost ({cost_tokens:g} tokens) exceeds the "
                f"tenant burst capacity ({self._burst:g}); shrink the "
                "prompt/max_tokens or raise "
                "AIOS_TPU_TENANT_BURST_TOKENS",
                MAX_RETRY_AFTER_MS, retriable=False,
            )
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                if len(self._buckets) >= _MAX_TENANTS:
                    # refuse-new rather than evict-old: evicting refills
                    # a drained bucket, which is exactly what a tenant
                    # spraying fresh ids would want
                    raise self.shed(
                        "quota", "tenant table full", MAX_RETRY_AFTER_MS
                    )
                bucket = TokenBucket(
                    self.cfg.tenant_tokens_per_sec, self._burst
                )
                self._buckets[tenant] = bucket
        wait_s = bucket.try_take(cost_tokens)
        if wait_s > 0:
            obs.SERVING_QUOTA_REJECTIONS.labels(tenant=tenant).inc()
            raise self.shed(
                "quota",
                f"tenant {tenant!r} over token quota "
                f"({self.cfg.tenant_tokens_per_sec:g} tok/s, burst "
                f"{self._burst:g})",
                int(wait_s * 1000) or 1,
            )

    # -- gate 1: bounded queue ---------------------------------------------

    def check_queue(self, queue_depth: int, outstanding_tokens: int,
                    rate_tps: float) -> None:
        if self.cfg.max_queue <= 0 or queue_depth < self.cfg.max_queue:
            return
        raise self.shed(
            "queue_full",
            f"admission queue full ({queue_depth} waiting, bound "
            f"{self.cfg.max_queue})",
            self._drain_ms(outstanding_tokens, rate_tps),
        )

    # -- gate 2: deadline feasibility --------------------------------------

    def check_deadline(self, deadline_s: Optional[float],
                       outstanding_tokens: int, max_tokens: int,
                       rate_tps: float) -> None:
        if deadline_s is None:
            return
        act = faults.point("admission.clock_skew", self.model)
        if act is not None and act.skew_s:
            # chaos: the gate's clock runs fast — deadlines look closer
            # than they are, driving deadline sheds (and their
            # retry-after metadata) on demand
            deadline_s = deadline_s - act.skew_s
        rate = rate_tps or self.assumed_rate()
        if rate <= 0:
            return  # no observed rate yet: cannot estimate, never shed
        need_s = (outstanding_tokens + max_tokens) / rate
        if need_s > deadline_s:
            raise self.shed(
                "deadline",
                f"deadline infeasible: ~{need_s:.2f}s of queued+requested "
                f"decode at {rate:.0f} tok/s exceeds the {deadline_s:.2f}s "
                f"deadline",
                self._drain_ms(outstanding_tokens, rate),
            )

    def assumed_rate(self) -> float:
        """Cold-start decode-rate floor for the feasibility gate: the
        operator's AIOS_TPU_ASSUMED_TPS knob when set, else the
        devprof-seeded estimate installed by the pool (0.0 when devprof
        is unarmed or has no step samples yet — the gate then never
        sheds, the pre-existing cold behavior)."""
        if self.cfg.assumed_tokens_per_sec > 0:
            return self.cfg.assumed_tokens_per_sec
        fn = self.devprof_rate_fn
        return float(fn() or 0.0) if fn is not None else 0.0

    @staticmethod
    def _drain_ms(outstanding_tokens: int, rate_tps: float) -> int:
        if rate_tps <= 0:
            return 1000
        return int(outstanding_tokens / rate_tps * 1000) or 1
