"""The TPU decode engine: slot KV cache, bucketed prefill, batched decode.

This is the component that replaces llama.cpp end-to-end (SURVEY.md
section 2.3, "TPU equivalence requirement"): weights live in HBM, prefill and
the single-token decode step are jitted graphs with static shapes, sampling
happens on device, and the KV caches are donated so XLA updates them in place.

Shape discipline (the TPU contract):
  * decode is ONE graph for the lifetime of the engine: [S] tokens ->
    [S] tokens, S = num_slots. Continuous batching inserts/retires requests
    by mutating slot state, never by changing shapes.
  * prefill is compiled per power-of-two length bucket, so an arbitrary
    prompt costs at most 2x its length and never recompiles after warmup.

A slot lifecycle: prefill(slot, prompt) writes K/V rows [0, len) and samples
the first token -> repeated step() calls extend the slot by one row each ->
release(slot). Inactive slots keep decoding garbage (their rows are ignored);
that is the price of a fixed-shape graph and it is what keeps XLA fast.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import model, sampling
from .config import ModelConfig

DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


class TPUEngine:
    """Single-model decode engine over a fixed set of batch slots."""

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        num_slots: int = 8,
        max_context: Optional[int] = None,
        cache_dtype=jnp.bfloat16,
        seed: int = 0,
        shardings=None,  # optional ShardingPlan (aios_tpu.engine.sharding)
    ) -> None:
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_context = int(max_context or cfg.max_context)
        self.buckets = tuple(
            b for b in DEFAULT_BUCKETS if b <= self.max_context
        ) or (self.max_context,)
        self._lock = threading.Lock()
        self.plan = shardings

        if shardings is not None:
            self.params = shardings.put_params(params)
        else:
            self.params = jax.tree.map(jnp.asarray, params)

        k, v = model.init_kv_cache(cfg, num_slots, self.max_context, cache_dtype)
        if shardings is not None:
            k, v = shardings.put_cache(k), shardings.put_cache(v)
        self.k_cache, self.v_cache = k, v
        self.lengths = jnp.zeros((num_slots,), jnp.int32)

        # host-side per-slot state (scheduler-facing)
        self.active = np.zeros(num_slots, dtype=bool)
        self.temps = np.zeros(num_slots, dtype=np.float32)
        self.top_ps = np.ones(num_slots, dtype=np.float32)
        self.last_tokens = np.zeros(num_slots, dtype=np.int32)

        self.key = jax.random.PRNGKey(seed)

        self._decode_fn = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        self._prefill_fns: Dict[int, object] = {}
        self.decode_steps = 0

    # -- jitted cores -------------------------------------------------------

    def _decode_impl(self, params, k_cache, v_cache, tokens, lengths, temps, top_ps, key):
        logits, k_cache, v_cache = model.decode_step(
            params, self.cfg, tokens, lengths, k_cache, v_cache
        )
        next_tokens = sampling.sample(logits, key, temps, top_ps)
        return next_tokens, logits, k_cache, v_cache

    def _prefill_impl(self, params, k_cache, v_cache, tokens, slot, true_len, temp, top_p, key):
        logits, ks, vs = model.prefill(params, self.cfg, tokens)
        # ks: [L, 1, T, KH, D] -> insert as rows [0, T) of the slot
        start = (0, slot, 0, 0, 0)
        k_cache = jax.lax.dynamic_update_slice(k_cache, ks.astype(k_cache.dtype), start)
        v_cache = jax.lax.dynamic_update_slice(v_cache, vs.astype(v_cache.dtype), start)
        last = logits[0, true_len - 1][None, :]  # [1, V]
        first_token = sampling.sample(last, key, temp[None], top_p[None])[0]
        return first_token, k_cache, v_cache

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = jax.jit(self._prefill_impl, donate_argnums=(1, 2))
            self._prefill_fns[bucket] = fn
        return fn

    # -- public API ---------------------------------------------------------

    def bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        return self.buckets[-1]

    def free_slots(self) -> List[int]:
        return [i for i in range(self.num_slots) if not self.active[i]]

    def prefill(
        self,
        slot: int,
        token_ids: List[int],
        temperature: float = 0.0,
        top_p: float = 1.0,
    ) -> int:
        """Fill ``slot`` with a prompt; returns the first generated token."""
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        token_ids = list(token_ids)[-(self.max_context - 1) :]
        true_len = len(token_ids)
        if true_len == 0:
            raise ValueError("empty prompt")
        bucket = self.bucket_for(true_len)
        padded = np.zeros((1, bucket), dtype=np.int32)
        padded[0, :true_len] = token_ids

        with self._lock:
            self.key, sub = jax.random.split(self.key)
            first, self.k_cache, self.v_cache = self._prefill_fn(bucket)(
                self.params,
                self.k_cache,
                self.v_cache,
                jnp.asarray(padded),
                jnp.int32(slot),
                jnp.int32(true_len),
                jnp.float32(temperature),
                jnp.float32(top_p),
                sub,
            )
            self.lengths = self.lengths.at[slot].set(true_len)
            self.active[slot] = True
            self.temps[slot] = temperature
            self.top_ps[slot] = top_p
            token = int(first)
            self.last_tokens[slot] = token
            return token

    def step(self) -> np.ndarray:
        """One batched decode step; returns the next token for every slot.

        Only consult entries where ``self.active`` — inactive slots decode
        garbage by design (fixed shapes).
        """
        with self._lock:
            self.key, sub = jax.random.split(self.key)
            tokens = jnp.asarray(self.last_tokens)
            next_tokens, _logits, self.k_cache, self.v_cache = self._decode_fn(
                self.params,
                self.k_cache,
                self.v_cache,
                tokens,
                self.lengths,
                jnp.asarray(self.temps),
                jnp.asarray(self.top_ps),
                sub,
            )
            # every slot's cache grew one row (inactive rows are garbage);
            # clamp so long-idle slots never walk past the cache end
            self.lengths = jnp.minimum(self.lengths + 1, self.max_context - 1)
            self.decode_steps += 1
            out = np.asarray(next_tokens)
            np.copyto(self.last_tokens, out)
            return out

    def release(self, slot: int) -> None:
        self.active[slot] = False
        with self._lock:
            self.lengths = self.lengths.at[slot].set(0)

    def slot_length(self, slot: int) -> int:
        return int(self.lengths[slot])

    def warmup(self, prompt_buckets: Optional[Tuple[int, ...]] = None) -> None:
        """Pre-compile decode + prefill buckets (LoadModel readiness gate —
        the reference's /health polling equivalent, model_manager.rs:222-263;
        without this the first Infer would eat 20-40 s of XLA compile)."""
        for bucket in prompt_buckets or self.buckets:
            dummy = [1] * min(4, bucket)
            self.prefill(0, dummy)
            self.release(0)
        self.step()

    # -- convenience (tests, single-shot CLI) -------------------------------

    def generate(
        self,
        token_ids: List[int],
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_p: float = 1.0,
        stop_tokens: Tuple[int, ...] = (),
        slot: int = 0,
    ) -> List[int]:
        """Single-request generation loop (the continuous-batching scheduler
        in engine/batching.py is the production path)."""
        first = self.prefill(slot, token_ids, temperature, top_p)
        out = [first]
        if first in stop_tokens:
            self.release(slot)
            return out
        for _ in range(max_new_tokens - 1):
            if self.slot_length(slot) >= self.max_context - 1:
                break
            tok = int(self.step()[slot])
            out.append(tok)
            if tok in stop_tokens:
                break
        self.release(slot)
        return out
