"""The tool execution pipeline.

Reference parity (tools/src/executor.rs:503-633): every Execute runs
  validate -> capability check -> rate limit -> backup-if-reversible ->
  handler -> audit
with the hash-chained ledger recording success and failure alike. The
executor also owns the dynamic side of the registry: plugin-backed tools
(auto-registered on plugin.create, main.rs:171-174) and externally
Register()-ed tool definitions.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Optional

from .audit import AuditLog
from .backup import BackupManager
from .capabilities import CapabilityChecker, requirements_for
from .handlers import ToolError, ToolSpec, collect_all
from .plugins import PluginManager
from .ratelimit import RateLimiter
from .secrets import SecretManager


@dataclass
class ExecutionResult:
    success: bool
    output: Dict[str, Any]
    error: str = ""
    execution_id: str = ""
    duration_ms: int = 0
    backup_id: str = ""


class ToolExecutor:
    def __init__(
        self,
        audit_path: str = ":memory:",
        backup_dir: str = "/tmp/aios/backups",
        plugin_dir: str = "/tmp/aios/plugins",
        secrets_path: str = "/etc/aios/secrets.toml",
    ):
        self.registry: Dict[str, ToolSpec] = collect_all()
        self.capabilities = CapabilityChecker()
        self.rate_limiter = RateLimiter()
        self.audit = AuditLog(audit_path)
        self.backups = BackupManager(backup_dir)
        self.plugins = PluginManager(plugin_dir)
        self.secrets = SecretManager(secrets_path)
        self.external_tools: Dict[str, dict] = {}  # Register()-ed definitions
        self._lock = threading.Lock()
        self._wire_context_tools()
        self._register_plugin_namespace()
        self.rescan_plugins()

    # -- context-dependent handlers ----------------------------------------

    def _wire_context_tools(self) -> None:
        """Replace placeholder handlers that need executor state."""

        def sec_grant(args: dict) -> dict:
            agent, caps = args.get("agent_id"), args.get("capabilities", [])
            if not agent or not caps:
                raise ToolError("need agent_id and capabilities")
            self.capabilities.grant(agent, caps)
            return {"agent_id": agent, "granted": caps}

        def sec_revoke(args: dict) -> dict:
            agent = args.get("agent_id")
            if not agent:
                raise ToolError("need agent_id")
            self.capabilities.revoke(
                agent, args.get("capabilities", []), all_=args.get("all", False)
            )
            return {"agent_id": agent, "revoked": args.get("capabilities", [])}

        def sec_audit(args: dict) -> dict:
            ok, bad_seq = self.audit.verify_chain()
            return {"chain_valid": ok, "first_bad_seq": bad_seq,
                    "records": self.audit.count()}

        def sec_audit_query(args: dict) -> dict:
            return {
                "records": self.audit.query(
                    agent_id=args.get("agent_id", ""),
                    tool_name=args.get("tool_name", ""),
                    limit=int(args.get("limit", 100)),
                )
            }

        self.registry["sec.grant"] = ToolSpec(
            sec_grant, "Grant capabilities to an agent")
        self.registry["sec.revoke"] = ToolSpec(
            sec_revoke, "Revoke capabilities from an agent")
        self.registry["sec.audit"] = ToolSpec(
            sec_audit, "Verify the audit hash chain", idempotent=True)
        self.registry["sec.audit_query"] = ToolSpec(
            sec_audit_query, "Query the audit ledger", idempotent=True)

    def _register_plugin_namespace(self) -> None:
        pm = self.plugins

        def plugin_create(args: dict) -> dict:
            meta = pm.create(
                name=args.get("name", ""),
                code=args.get("code", ""),
                description=args.get("description", ""),
                capabilities=args.get("capabilities"),
                requirements=args.get("requirements"),
                next_plugins=args.get("next_plugins"),
                output_mode=args.get("output_mode", "pipe"),
            )
            self.rescan_plugins()  # auto-register (main.rs:171-174)
            return {"created": meta["name"], "registered_tool": f"plugin.x.{meta['name']}"}

        def plugin_from_template(args: dict) -> dict:
            meta = pm.from_template(args.get("name", ""), args.get("template", ""))
            self.rescan_plugins()
            return {"created": meta["name"]}

        def plugin_list(args: dict) -> dict:
            return {"plugins": pm.list()}

        def plugin_delete(args: dict) -> dict:
            name = args.get("name", "")
            removed = pm.delete(name)
            self.registry.pop(f"plugin.x.{name}", None)
            return {"deleted": removed}

        def plugin_install_deps(args: dict) -> dict:
            return pm.install_deps(args.get("name", ""))

        self.registry["plugin.create"] = ToolSpec(
            plugin_create, "Create (and register) a Python plugin")
        self.registry["plugin.from_template"] = ToolSpec(
            plugin_from_template, "Create a plugin from a template")
        self.registry["plugin.list"] = ToolSpec(
            plugin_list, "List installed plugins", idempotent=True)
        self.registry["plugin.delete"] = ToolSpec(
            plugin_delete, "Delete a plugin")
        self.registry["plugin.install_deps"] = ToolSpec(
            plugin_install_deps, "pip-install a plugin's requirements")

    def rescan_plugins(self) -> int:
        """(Re)register every stored plugin as tool `plugin.x.<name>`."""
        count = 0
        for meta in self.plugins.list():
            name = meta["name"]

            def run_plugin(args: dict, _name=name) -> dict:
                return self.plugins.execute(_name, args)

            self.registry[f"plugin.x.{name}"] = ToolSpec(
                run_plugin, meta.get("description") or f"plugin {name}"
            )
            count += 1
        return count

    # -- pipeline -----------------------------------------------------------

    def execute(
        self,
        agent_id: str,
        tool_name: str,
        input_json: bytes,
        task_id: str = "",
        reason: str = "",
    ) -> ExecutionResult:
        t0 = time.time()
        execution_id = str(uuid.uuid4())

        def fail(error: str) -> ExecutionResult:
            self.audit.record(agent_id, tool_name, input_json, b"", False, error)
            return ExecutionResult(
                success=False,
                output={},
                error=error,
                execution_id=execution_id,
                duration_ms=int((time.time() - t0) * 1000),
            )

        # 1. validate
        spec = self.registry.get(tool_name)
        if spec is None:
            return fail(f"unknown tool {tool_name}")
        try:
            args = json.loads(input_json.decode("utf-8")) if input_json else {}
            if not isinstance(args, dict):
                raise ValueError("input must be a JSON object")
        except ValueError as exc:
            return fail(f"invalid input JSON: {exc}")

        # 2. capability check
        ok, why = self.capabilities.check(agent_id, tool_name)
        if not ok:
            return fail(why)

        # 3. rate limit
        ok, why = self.rate_limiter.check(agent_id, tool_name)
        if not ok:
            return fail(why)

        # 4. backup if reversible
        backup_id = ""
        if spec.reversible and spec.target_arg and args.get(spec.target_arg):
            try:
                self.backups.backup_path_for(
                    execution_id, str(args[spec.target_arg])
                )
                backup_id = execution_id
            except OSError as exc:
                return fail(f"backup failed: {exc}")

        # 5. execute
        try:
            output = spec.fn(args)
            success, error = True, ""
        except ToolError as exc:
            output, success, error = {}, False, str(exc)
        except Exception as exc:  # noqa: BLE001 — handler bug, not a crash
            output, success, error = {}, False, f"handler error: {exc!r}"

        # 6. audit
        out_bytes = json.dumps(output).encode()
        self.audit.record(agent_id, tool_name, input_json, out_bytes, success, reason)

        return ExecutionResult(
            success=success,
            output=output,
            error=error,
            execution_id=execution_id,
            duration_ms=int((time.time() - t0) * 1000),
            backup_id=backup_id,
        )

    def rollback(self, execution_id: str, reason: str = "") -> tuple[bool, str]:
        ok, msg = self.backups.rollback(execution_id)
        self.audit.record("rollback", "rollback", execution_id.encode(),
                          msg.encode(), ok, reason)
        return ok, msg

    # -- definitions --------------------------------------------------------

    def definition(self, tool_name: str) -> Optional[dict]:
        spec = self.registry.get(tool_name)
        if spec is None:
            return self.external_tools.get(tool_name)
        caps, risk = requirements_for(tool_name)
        namespace = tool_name.split(".", 1)[0]
        return {
            "name": tool_name,
            "namespace": namespace,
            "version": spec.version,
            "description": spec.description,
            "required_capabilities": caps,
            "risk_level": risk,
            "requires_confirmation": spec.requires_confirmation,
            "idempotent": spec.idempotent,
            "reversible": spec.reversible,
            "timeout_ms": spec.timeout_ms,
            "rollback_tool": "rollback" if spec.reversible else "",
        }

    def list_definitions(self, namespace: str = "") -> list[dict]:
        names = sorted(self.registry) + sorted(self.external_tools)
        defs = [self.definition(n) for n in names]
        if namespace:
            defs = [d for d in defs if d and d["namespace"] == namespace]
        return [d for d in defs if d]

    def register_external(self, definition: dict, handler_address: str) -> None:
        definition = dict(definition)
        definition["handler_address"] = handler_address
        with self._lock:
            self.external_tools[definition["name"]] = definition

    def deregister(self, tool_name: str) -> bool:
        with self._lock:
            if tool_name in self.external_tools:
                del self.external_tools[tool_name]
                return True
        if tool_name.startswith("plugin.x."):
            return self.registry.pop(tool_name, None) is not None
        return False
