"""Secret store: TOML file loader with TTL cache and shutdown wipe.

Reference parity (tools/src/secrets.rs:1-31): loads /etc/aios/secrets.toml,
caches values in memory for 1 hour, wipes the cache on shutdown.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

from .._compat import tomllib
from typing import Dict, Optional

CACHE_TTL = 3600.0


class SecretManager:
    def __init__(self, path: str = "/etc/aios/secrets.toml", ttl: float = CACHE_TTL):
        self.path = Path(path)
        self.ttl = ttl
        self._cache: Dict[str, str] = {}
        self._loaded_at = 0.0
        self._lock = threading.Lock()

    def _flatten(self, data: dict, prefix: str = "") -> Dict[str, str]:
        out: Dict[str, str] = {}
        for k, v in data.items():
            key = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
            if isinstance(v, dict):
                out.update(self._flatten(v, key))
            else:
                out[key] = str(v)
        return out

    def _ensure_loaded(self) -> None:
        now = time.monotonic()
        if self._cache and now - self._loaded_at < self.ttl:
            return
        try:
            data = tomllib.loads(self.path.read_text())
            self._cache = self._flatten(data)
        except (OSError, ValueError):
            self._cache = {}
        self._loaded_at = now

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            self._ensure_loaded()
            return self._cache.get(key)

    def wipe(self) -> None:
        with self._lock:
            for k in list(self._cache):
                self._cache[k] = ""
            self._cache.clear()
            self._loaded_at = 0.0
