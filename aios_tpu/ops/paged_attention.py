"""Paged ragged decode attention: the slot cache behind a page table.

Same online-softmax recurrence as `decode_attention` (one query per slot
against that slot's valid cache rows, double-buffered HBM→VMEM DMA), except
K/V rows live in a shared *page pool* instead of one contiguous slab per
slot: logical block i of slot b is physical page `tables[b, i]` of
`[N, P, KH*D]`. The kernel reads the table from SMEM and DMAs only the
pages that hold valid rows, so HBM is reserved per *page in use*, not per
`num_slots x max_context` — that decoupling is what lets many long-context
slots oversubscribe a fixed pool (SURVEY.md section 7.2 "paged KV cache in
HBM"; the fixed-shape-jit half of hard part #1).

The pool never moves: growth is a host-side free-list allocation plus a new
table row passed with the next dispatch. Shapes stay static everywhere —
the table is [B, MAX_BLOCKS] with garbage entries beyond each slot's
length, never read because the loop bound comes from `lengths`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_decode_kernel(
    len_ref,  # SMEM [B] int32
    tbl_ref,  # SMEM [B, MB] int32 — logical block -> physical page
    *args,  # [ws_ref SMEM [B] when sink is not None,] q_ref, k_pool,
    #         v_pool, then quantized: ks_pool [N, KH, P] f32 (head-major —
    #         the lane dim must be the 128-aligned page axis), vs_pool,
    #         o_ref; else o_ref
    num_kv_heads: int,
    head_dim: int,
    page_size: int,
    window: Optional[int],
    sink: Optional[int],
    sm_scale: float,
    quantized: bool = False,
):
    # window+sink KV compression (docs/ENGINE_PERF.md "Long-context
    # tier"): ws_ref[b] is where slot b's live trailing window begins —
    # rows in [sink, ws_ref[b]) were pruned from the pool and their table
    # entries remap the sacrificial page, so they must score as invalid.
    # ws = 0 makes the extra mask a no-op (uncompressed slot).
    if sink is not None:
        ws_ref, q_ref, k_pool, v_pool, *rest = args
    else:
        ws_ref = None
        q_ref, k_pool, v_pool, *rest = args
    if quantized:
        ks_pool, vs_pool, o_ref = rest
    else:
        (o_ref,) = rest
    b = pl.program_id(0)
    KH, D, P = num_kv_heads, head_dim, page_size
    H = q_ref.shape[1]
    G = H // KH

    length = len_ref[b]  # row `length` holds the just-written token
    total = length + 1
    n_blk = pl.cdiv(total, P)
    if window is not None:
        start_blk = jnp.maximum(total - window, 0) // P
    else:
        start_blk = jnp.int32(0)

    if quantized:
        q = q_ref[0].astype(jnp.float32) * sm_scale  # [H, D]
    else:
        q = q_ref[0] * sm_scale

    def body(k_buf, v_buf, sems, ks_buf=None, vs_buf=None):
        def dma(pool, scr, slot, blk, sem_idx):
            # THE paged indirection: logical block -> physical page
            return pltpu.make_async_copy(
                pool.at[tbl_ref[b, blk]],
                scr.at[slot],
                sems.at[slot, sem_idx],
            )

        def start_all(slot, blk):
            dma(k_pool, k_buf, slot, blk, 0).start()
            dma(v_pool, v_buf, slot, blk, 1).start()
            if quantized:
                dma(ks_pool, ks_buf, slot, blk, 2).start()
                dma(vs_pool, vs_buf, slot, blk, 3).start()

        def wait_all(slot, blk):
            dma(k_pool, k_buf, slot, blk, 0).wait()
            dma(v_pool, v_buf, slot, blk, 1).wait()
            if quantized:
                dma(ks_pool, ks_buf, slot, blk, 2).wait()
                dma(vs_pool, vs_buf, slot, blk, 3).wait()

        start_all(0, start_blk)

        def loop(i, carry):
            m, l, acc = carry  # [H, 1], [H, 1], [H, D] f32
            slot = jax.lax.rem(i - start_blk, 2)

            @pl.when(i + 1 < n_blk)
            def _prefetch():
                start_all(1 - slot, i + 1)

            wait_all(slot, i)
            kb = k_buf[slot]  # [P, KH*D]
            vb = v_buf[slot]
            ksb = ks_buf[slot] if quantized else None  # [KH, P] f32
            vsb = vs_buf[slot] if quantized else None

            cols = i * P + jax.lax.broadcasted_iota(jnp.int32, (1, P), 1)
            valid = cols <= length
            if window is not None:
                valid = jnp.logical_and(valid, cols > length - window)
            if sink is not None:
                valid = jnp.logical_and(
                    valid,
                    jnp.logical_or(cols < sink, cols >= ws_ref[b]),
                )

            parts = []
            for h in range(KH):
                qh = q[h * G : (h + 1) * G, :]  # [G, D]
                kh = kb[:, h * D : (h + 1) * D]  # [P, D]
                if quantized:
                    kh = kh.astype(jnp.float32)
                sh = jax.lax.dot_general(
                    qh,
                    kh,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if quantized:
                    sh = sh * ksb[h][None, :]
                parts.append(sh)
            s = jnp.concatenate(parts, axis=0)  # [H, P]
            s = jnp.where(valid, s, NEG_INF)

            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s - m_new)
            p = jnp.where(valid, p, 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)

            outs = []
            pv = p if quantized else p.astype(vb.dtype)
            for h in range(KH):
                ph = pv[h * G : (h + 1) * G, :]  # [G, P]
                if quantized:
                    ph = ph * vsb[h][None, :]
                vh = vb[:, h * D : (h + 1) * D]  # [P, D]
                if quantized:
                    vh = vh.astype(jnp.float32)
                outs.append(
                    jax.lax.dot_general(
                        ph,
                        vh,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            acc_new = acc * alpha + jnp.concatenate(outs, axis=0)
            return m_new, l_new, acc_new

        init = (
            jnp.full((H, 1), NEG_INF, jnp.float32),
            jnp.zeros((H, 1), jnp.float32),
            jnp.zeros((H, D), jnp.float32),
        )
        m, l, acc = jax.lax.fori_loop(start_blk, n_blk, loop, init)
        safe_l = jnp.where(l <= 0.0, 1.0, l)
        o_ref[0] = (acc / safe_l).astype(o_ref.dtype)

    if quantized:
        pl.run_scoped(
            body,
            k_buf=pltpu.VMEM((2, P, KH * D), jnp.int8),
            v_buf=pltpu.VMEM((2, P, KH * D), jnp.int8),
            sems=pltpu.SemaphoreType.DMA((2, 4)),
            ks_buf=pltpu.VMEM((2, KH, P), jnp.float32),
            vs_buf=pltpu.VMEM((2, KH, P), jnp.float32),
        )
    else:
        pl.run_scoped(
            body,
            k_buf=pltpu.VMEM((2, P, KH * D), k_pool.dtype),
            v_buf=pltpu.VMEM((2, P, KH * D), v_pool.dtype),
            sems=pltpu.SemaphoreType.DMA((2, 2)),
        )


def _paged_call(q, k_pool, v_pool, tables, lengths, scales, *, window,
                win_starts, sink, interpret):
    """Shared pallas_call plumbing for both pool dtypes."""
    B, H, D = q.shape
    N, P, KH = k_pool.shape[0], k_pool.shape[1], k_pool.shape[2]
    quantized = scales is not None
    compressed = win_starts is not None
    if compressed and sink is None:
        raise ValueError("win_starts needs a static sink row count")
    if quantized and P % 128 and not interpret:
        # Same Mosaic lane constraint as the ragged int8 kernel
        # (decode_attention.py): the scale transpose below puts the page
        # axis on lanes, so a non-128-aligned page_size would fail deep
        # inside Mosaic instead of here.
        raise ValueError(
            f"int8 paged kernel needs a 128-aligned page_size, got {P}"
        )
    kernel = functools.partial(
        _paged_decode_kernel,
        num_kv_heads=KH,
        head_dim=D,
        page_size=P,
        window=window,
        sink=sink if compressed else None,
        sm_scale=1.0 / float(np.sqrt(D)),
        quantized=quantized,
    )
    pool_specs = [pl.BlockSpec(memory_space=pltpu.ANY)] * (
        2 + (2 if quantized else 0)
    )
    args = [
        lengths.astype(jnp.int32),
        tables.astype(jnp.int32),
    ]
    ws_specs = []
    if compressed:
        args.append(win_starts.astype(jnp.int32))
        ws_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)]
    args += [
        q,
        k_pool.reshape(N, P, KH * D),
        v_pool.reshape(N, P, KH * D),
    ]
    if quantized:
        # [N, P, KH] -> head-major [N, KH, P]: the whole-page DMA then has
        # the 128-row page axis on lanes (see decode_attention.py)
        args.extend(s.transpose(0, 2, 1) for s in scales)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths
            pl.BlockSpec(memory_space=pltpu.SMEM),  # page tables
            *ws_specs,  # window starts (compressed engines only)
            pl.BlockSpec((1, H, D), lambda b: (b, 0, 0)),
            *pool_specs,  # pools (+ scales) stay in HBM
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b: (b, 0, 0)),
        interpret=interpret,
    )(*args)


@functools.partial(jax.jit, static_argnames=("window", "sink", "interpret"))
def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, D] — one new query per slot
    k_pool: jnp.ndarray,  # [N, P, KH, D] — shared page pool
    v_pool: jnp.ndarray,  # [N, P, KH, D]
    tables: jnp.ndarray,  # [B, MB] int32 — logical block -> physical page
    lengths: jnp.ndarray,  # [B] int32; row `lengths[b]` is the newest token
    *,
    window: Optional[int] = None,
    win_starts: Optional[jnp.ndarray] = None,  # [B] int32 live-window start
    sink: Optional[int] = None,  # static sink row count (with win_starts)
    interpret: bool = False,
) -> jnp.ndarray:
    """Paged ragged decode attention; returns [B, H, D]. With
    ``win_starts``/``sink`` (window+sink KV compression) slot b attends
    only rows < sink or >= win_starts[b] — the pruned middle is masked."""
    return _paged_call(
        q, k_pool, v_pool, tables, lengths, None,
        window=window, win_starts=win_starts, sink=sink,
        interpret=interpret,
    )


@functools.partial(jax.jit, static_argnames=("window", "sink", "interpret"))
def paged_decode_attention_int8(
    q: jnp.ndarray,  # [B, H, D]
    k_pool: jnp.ndarray,  # [N, P, KH, D] int8
    v_pool: jnp.ndarray,  # [N, P, KH, D] int8
    k_scales: jnp.ndarray,  # [N, P, KH] f32 (layer slice of the pool scales)
    v_scales: jnp.ndarray,  # [N, P, KH] f32
    tables: jnp.ndarray,  # [B, MB] int32
    lengths: jnp.ndarray,  # [B] int32
    *,
    window: Optional[int] = None,
    win_starts: Optional[jnp.ndarray] = None,  # [B] int32 live-window start
    sink: Optional[int] = None,  # static sink row count (with win_starts)
    interpret: bool = False,
) -> jnp.ndarray:
    """Paged ragged decode attention over an INT8 page pool: pages stream
    as int8 (half the HBM bytes) with per-(page-row, kv-head) scales
    folded into the score/value dots — same contract as
    decode_attention_int8 with the page-table indirection (and the same
    ``win_starts``/``sink`` compressed mask as the bf16 kernel)."""
    return _paged_call(
        q, k_pool, v_pool, tables, lengths, (k_scales, v_scales),
        window=window, win_starts=win_starts, sink=sink,
        interpret=interpret,
    )


def paged_decode_attention_int8_reference(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,  # [N, P, KH, D] int8
    v_pool: jnp.ndarray,
    k_scales: jnp.ndarray,  # [N, P, KH] f32
    v_scales: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    window: Optional[int] = None,
    win_starts: Optional[jnp.ndarray] = None,
    sink: Optional[int] = None,
) -> jnp.ndarray:
    """Dequantize-then-attend ground truth for the int8 paged kernel."""
    kf = k_pool.astype(jnp.float32) * k_scales[..., None]
    vf = v_pool.astype(jnp.float32) * v_scales[..., None]
    return paged_decode_attention_reference(
        q, kf, vf, tables, lengths, window=window,
        win_starts=win_starts, sink=sink,
    )


def gather_pages(pool: jnp.ndarray, table_row: jnp.ndarray) -> jnp.ndarray:
    """Materialize one slot's logical cache view [MB*P, KH, D] from the
    pool. Copies — used by the CPU reference path and by prefill-chunk
    attention (compute-bound, so the copy is cheap there); the decode hot
    path reads pages in place via the kernel."""
    MB = table_row.shape[0]
    P, KH, D = pool.shape[1], pool.shape[2], pool.shape[3]
    return pool[table_row].reshape(MB * P, KH, D)


def paged_decode_attention_reference(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    window: Optional[int] = None,
    win_starts: Optional[jnp.ndarray] = None,  # [B] int32 live-window start
    sink: Optional[int] = None,  # static sink row count (with win_starts)
) -> jnp.ndarray:
    """Naive jnp paged decode attention (CPU fallback + parity truth):
    gathers each slot's pages into a contiguous view, then does the same
    masked attention as the dense reference. ``win_starts``/``sink``
    apply the window+sink compressed mask (rows in [sink, win_starts[b])
    are pruned and must not score)."""
    B, H, D = q.shape
    KH = k_pool.shape[2]
    G = H // KH
    k = jax.vmap(lambda t: gather_pages(k_pool, t))(tables)  # [B, C, KH, D]
    v = jax.vmap(lambda t: gather_pages(v_pool, t))(tables)
    C = k.shape[1]
    qg = q.reshape(B, KH, G, D)
    s = jnp.einsum("bkgd,bckd->bkgc", qg, k).astype(jnp.float32)
    s = s / np.sqrt(D)
    cols = jnp.arange(C)[None, :]
    mask = cols <= lengths[:, None]
    if window is not None:
        mask = mask & (cols > lengths[:, None] - window)
    if win_starts is not None:
        mask = mask & ((cols < int(sink)) | (cols >= win_starts[:, None]))
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgc,bckd->bkgd", p, v)
    return out.reshape(B, H, D)
