"""Token-bucket rate limiting: per-agent and per-tool.

Reference parity (tools/src/executor.rs:52-104): 10 requests/sec per agent,
50 requests/sec per tool, refilled continuously.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

AGENT_RPS = 10.0
TOOL_RPS = 50.0


class TokenBucket:
    def __init__(self, rate: float, capacity: float | None = None):
        self.rate = rate
        self.capacity = capacity if capacity is not None else rate
        self.tokens = self.capacity
        self.updated = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self.tokens = min(self.capacity, self.tokens + (now - self.updated) * self.rate)
            self.updated = now
            if self.tokens >= n:
                self.tokens -= n
                return True
            return False


def _make_bucket(rate: float):
    """Native C++ bucket when the library is built, Python otherwise."""
    try:
        from .. import native

        if native.available():
            return native.NativeTokenBucket(rate)
    except Exception:  # noqa: BLE001 — fall back silently
        pass
    return TokenBucket(rate)


class RateLimiter:
    def __init__(self, agent_rps: float = AGENT_RPS, tool_rps: float = TOOL_RPS):
        self.agent_rps = agent_rps
        self.tool_rps = tool_rps
        self._agents: Dict[str, object] = {}
        self._tools: Dict[str, object] = {}
        self._lock = threading.Lock()
        # Pay the native-library build/load at construction (service start),
        # not inside check()'s lock on the first request — a cold g++ compile
        # there would stall every concurrent tool call for seconds.
        try:
            from .. import native

            native.load()
        except Exception:  # noqa: BLE001
            pass

    def check(self, agent_id: str, tool_name: str) -> tuple[bool, str]:
        with self._lock:
            ab = self._agents.setdefault(agent_id, _make_bucket(self.agent_rps))
            tb = self._tools.setdefault(tool_name, _make_bucket(self.tool_rps))
        if not ab.try_acquire():
            return False, f"agent {agent_id} rate limit exceeded ({self.agent_rps}/s)"
        if not tb.try_acquire():
            return False, f"tool {tool_name} rate limit exceeded ({self.tool_rps}/s)"
        return True, ""
